"""RoutingFront — the driver-side routing service for multi-worker serving.

Reference: HTTPSourceV2.scala:113-173 — the driver runs an HttpServer; every
WorkerServer POSTs its ServiceInfo{name, host, port} to register, and public
traffic is spread across registered workers. Worker loss is handled by retrying
on another worker (Spark task retry gave the reference this for free; here
it's explicit) — but unlike the pre-fault-layer build, failing workers are NOT
blacklisted forever: each worker runs a circuit breaker (closed -> open on
``max_failures`` consecutive failures), and open workers are health-probed on
a jittered backoff and re-admitted when they answer again.

Deadline contract: requests carrying ``X-MMLSpark-Deadline`` (epoch seconds)
are rejected with 504 once expired — before any forward — and the per-worker
forward timeout is capped at the remaining deadline.

TPU-native deployment note: one RoutingFront per serving cluster (typically on
the coordinator host), one ServingServer per TPU host; the pipeline inside
each worker uses that host's chips. Cross-worker replies ride the internal
endpoint (server.reply_to), so a worker group that shards a batch can answer
requests that entered elsewhere.
"""

from __future__ import annotations

import itertools
import json
import queue as queue_mod
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.error import HTTPError, URLError
from urllib.parse import urlsplit
from urllib.request import Request, urlopen

from ..core import faults
from ..core.faults import RetryPolicy, deadline_from_headers
from ..obs import bridge as obs_bridge
from ..obs.metrics import MetricsRegistry
from ..obs.trace import TRACE_HEADER, Tracer

#: circuit-breaker states (per registered worker)
CLOSED = "closed"          # healthy: receives traffic
OPEN = "open"              # tripped: excluded from routing, health-probed
HALF_OPEN = "half_open"    # probe succeeded: routed again, one failure re-opens


class _WorkerCircuit:
    __slots__ = ("state", "failures", "next_probe", "probe_attempt")

    def __init__(self):
        self.state = CLOSED
        self.failures = 0
        self.next_probe = 0.0
        self.probe_attempt = 0


class RoutingFront:
    """HTTP front: register workers, round-robin public requests, circuit-
    break dead ones and re-admit them when health probes succeed.

    Endpoints:
      POST /_mmlspark/register   {"address": "http://host:port/api"} -> 200
      GET  /_mmlspark/workers    -> {"workers": [...], "states": {...}}
      anything else              -> forwarded to a routable worker (retry
                                    across workers; ``max_failures``
                                    consecutive failures trip the worker's
                                    breaker OPEN — probed, not blacklisted)
    """

    REGISTER_PATH = "/_mmlspark/register"
    WORKERS_PATH = "/_mmlspark/workers"
    #: probed path on the worker host: constant-cost on ServingServer
    #: (healthz — the old /_mmlspark/stats probe payload scaled with the
    #: latency window and executor timeline); any HTTP answer — 404
    #: included — proves liveness elsewhere
    PROBE_PATH = "/_mmlspark/healthz"
    #: the front's own Prometheus exposition + liveness probe
    METRICS_PATH = "/_mmlspark/metrics"
    HEALTH_PATH = "/_mmlspark/healthz"
    #: buffered spans as JSON (worker parity: cross-hop exemplar lookups
    #: resolve from the front too, not just the worker that served them)
    TRACE_PATH = "/_mmlspark/trace"
    #: fleet capacity aggregation: polls every routable worker's
    #: /_mmlspark/capacity and sums the recommendations — the single
    #: endpoint a helm HPA / external scaler keys on
    CAPACITY_PATH = "/_mmlspark/capacity"
    #: fabric mode only (404-equivalent pass-through otherwise): the L1's
    #: ring summary (epoch, cells, journal tail) and the drain control
    RING_PATH = "/_mmlspark/ring"
    DRAIN_PATH = "/_mmlspark/drain"

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 forward_timeout_s: float = 70.0, max_failures: int = 3,
                 token: Optional[str] = None,
                 probe_interval_s: float = 0.5,
                 probe_timeout_s: float = 2.0,
                 probe_policy: Optional[RetryPolicy] = None,
                 obs: bool = True, tracer: Optional[Tracer] = None,
                 trace_sample_rate: float = 1.0,
                 http_mode: str = "thread", slo=None, hedge=None,
                 fabric=None, capacity_ttl_s: Optional[float] = 45.0):
        self.host = host
        self.port = port
        self.forward_timeout_s = forward_timeout_s
        self.max_failures = max_failures
        self.token = token  # when set, /register requires X-MMLSpark-Token
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        #: capacity-aggregate staleness bound: a worker plan older than
        #: this (its self-reported ``plan_age_s``) is dropped from the
        #: fleet sums and listed under ``stale_workers`` — a worker whose
        #: planning loop stalled must not steer the HPA forever. None
        #: disables the check.
        self.capacity_ttl_s = capacity_ttl_s
        # HTTP transport: "thread" = ThreadingHTTPServer + one urlopen
        # socket per forward; "async" = event-loop ingress (serving/aio.py)
        # + pooled keep-alive worker connections — the hop stops paying a
        # TCP connect per forwarded request, and frame bodies pass through
        # as the same opaque bytes (no decode/re-encode on this hop in
        # either mode)
        if http_mode not in ("thread", "async"):
            raise ValueError(f"http_mode must be 'thread' or 'async', "
                             f"got {http_mode!r}")
        self.http_mode = http_mode
        self._aio = None
        self._pool = None  # AsyncConnectionPool (async mode, loop thread)
        # hedged requests ("The Tail at Scale"): after a quantile of the
        # observed forward-latency distribution, re-issue the request to a
        # second worker, first response wins (serving/supervisor.py
        # HedgeTracker). None = off (the default — hedging deliberately
        # double-dispatches, so it is opt-in for idempotent transforms).
        from .supervisor import make_hedge

        self._hedge = make_hedge(hedge)
        # federated front fabric (serving/fabric): when set, this front is
        # an L1 — its registered "workers" are L2 fronts (cells) and route
        # order comes from consistent-hash tenant affinity instead of the
        # round-robin. None (the default) leaves the single-front path
        # byte-identical.
        from .fabric import make_fabric

        self._fabric = make_fabric(fabric)
        # probe backoff: open workers are re-probed on a jittered exponential
        # schedule (deterministic when the policy is seeded)
        self.probe_policy = probe_policy or RetryPolicy(
            max_retries=1 << 30, base_s=probe_interval_s, multiplier=2.0,
            max_backoff_s=max(probe_interval_s * 16, probe_interval_s),
            jitter=0.2, seed=0)
        self._probe_rng = self.probe_policy.make_rng()
        self._workers: List[str] = []
        self._circuits: Dict[str, _WorkerCircuit] = {}
        self._capacity: Dict[str, int] = {}
        # per-worker admitted-model lists (multimodel workers): purely
        # informational capacity lines on /_mmlspark/workers — absent from
        # the payload entirely while no worker registers models
        self._models_by_worker: Dict[str, List[str]] = {}
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        # observability: registry (worker circuit states + forward
        # outcomes) and tracer (ingress + per-attempt forward spans; the
        # trace context rides X-MMLSpark-Trace to the worker)
        self.obs_enabled = bool(obs)
        self.registry: Optional[MetricsRegistry] = None
        self.tracer: Optional[Tracer] = None
        self._forwards = None
        # front-side latency SLO (obs/perf.py): burn-rate gauges over the
        # client-observed forward latency, so the autoscaling signal exists
        # at the tier the HPA actually scales behind
        self._slo = None
        if self.obs_enabled:
            self.registry = MetricsRegistry()
            self.tracer = tracer if tracer is not None else Tracer(
                sample_rate=trace_sample_rate, service="routing-front")
            obs_bridge.fold_front(self.registry, self)
            obs_bridge.fold_tracer(self.registry, self.tracer)
            self._forwards = self.registry.counter(
                "mmlspark_front_requests_total",
                "public requests by routing outcome", ("outcome",))
            from ..obs import perf as obs_perf

            self._slo = obs_perf.make_slo(slo)
            if self._slo is not None:
                self.registry.register_collector(self._slo.families)

    def _count(self, outcome: str) -> None:
        if self._forwards is not None:
            self._forwards.labels(outcome=outcome).inc()

    def _slo_record(self, t_p0: float, status: int) -> None:
        """Feed one public-request outcome to the SLO tracker (shed/error
        statuses burn budget regardless of how fast they were written)."""
        if self._slo is not None:
            self._slo.record(time.perf_counter() - t_p0,
                             breach=True if status >= 500 else None)

    # -- worker management ------------------------------------------------
    def register(self, address: str, capacity: int = 1,
                 models: Optional[List[str]] = None) -> None:
        """``capacity`` is the worker's concurrent-batch hint (its replica
        count under the async executor — ServingServer.capacity): weighted
        round-robin sends a worker with R replicas R slots per cycle.
        ``models`` (multimodel workers) lists the worker's admitted models
        for the per-model capacity view on ``/_mmlspark/workers``."""
        with self._lock:
            if address not in self._workers:
                self._workers.append(address)
            self._circuits[address] = _WorkerCircuit()
            self._capacity[address] = max(1, int(capacity))
            if models:
                self._models_by_worker[address] = \
                    sorted({str(m) for m in models})
            else:
                self._models_by_worker.pop(address, None)
        if self._fabric is not None:
            # a journaled ring epoch (re-registration refreshes are not
            # epochs; a ring.rebalance crash is absorbed — previous epoch
            # keeps serving)
            self._fabric.note_register(address)

    def deregister(self, address: str) -> None:
        with self._lock:
            if address in self._workers:
                self._workers.remove(address)
            self._circuits.pop(address, None)
            self._capacity.pop(address, None)
            self._models_by_worker.pop(address, None)
        if self._fabric is not None:
            self._fabric.note_deregister(address)

    @property
    def workers(self) -> List[str]:
        """Routable workers (breaker closed or half-open)."""
        with self._lock:
            return [w for w in self._workers
                    if self._circuits[w].state != OPEN]

    @property
    def worker_states(self) -> Dict[str, str]:
        with self._lock:
            return {w: self._circuits[w].state for w in self._workers}

    @property
    def worker_capacities(self) -> Dict[str, int]:
        with self._lock:
            return {w: self._capacity.get(w, 1) for w in self._workers}

    def _pick_order(self) -> List[str]:
        """Capacity-weighted round-robin: a worker with capacity R (R
        replicas) occupies R slots in the rotation, so traffic matches the
        cluster's real concurrent-batch capacity. The returned order is
        deduplicated — retries still walk DISTINCT workers."""
        with self._lock:
            ws: List[str] = []
            for w in self._workers:
                if self._circuits[w].state != OPEN:
                    ws.extend([w] * self._capacity.get(w, 1))
        if not ws:
            return []
        start = next(self._rr) % len(ws)
        rotated = ws[start:] + ws[:start]
        seen = set()
        order = []
        for w in rotated:
            if w not in seen:
                seen.add(w)
                order.append(w)
        return order

    def _route_order(self, headers) -> List[str]:
        """Worker order for one public request: with the fabric on, the
        tenant's affinity cell first and the ring-walk survivors after it
        (bounded movement: only a dead/drained cell's arc re-hashes);
        otherwise the capacity-weighted round-robin, unchanged."""
        if self._fabric is None:
            return self._pick_order()
        with self._lock:
            routable = [w for w in self._workers
                        if self._circuits[w].state != OPEN]
        return self._fabric.order_for(headers, routable)

    def drain_cell(self, address: str,
                   timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Planned maintenance (fabric mode): journal a ``drain`` epoch —
        new assignments stop and the cell's arc re-hashes onto survivors —
        wait for this front's in-flight forwards to the cell to flush,
        journal the handoff epoch, then deregister the cell. Blocking
        (bounded by the fabric's drain timeout): call it from the threaded
        transport or out-of-band, not on the async loop."""
        if self._fabric is None:
            raise RuntimeError("drain_cell requires fabric mode "
                               "(RoutingFront(fabric=...))")
        result = self._fabric.drain_cell(address, timeout_s=timeout_s)
        if result.get("ok"):
            self.deregister(address)
        return result

    def _note_failure(self, address: str) -> None:
        with self._lock:
            c = self._circuits.get(address)
            if c is None:
                return
            c.failures += 1
            # a half-open worker re-opens on its first failure; a closed one
            # trips after max_failures consecutive failures
            if c.state == HALF_OPEN or c.failures >= self.max_failures:
                c.state = OPEN
                c.probe_attempt = 0
                c.next_probe = time.monotonic() + self.probe_policy.next_wait(
                    0, self._probe_rng)

    def _note_success(self, address: str) -> None:
        with self._lock:
            c = self._circuits.get(address)
            if c is not None:
                c.failures = 0
                c.state = CLOSED

    # -- health probing (re-admission instead of permanent blacklist) -----
    def _probe(self, address: str) -> bool:
        parts = urlsplit(address)
        url = f"{parts.scheme}://{parts.netloc}{self.PROBE_PATH}"
        try:
            with urlopen(Request(url, method="GET"),
                         timeout=self.probe_timeout_s):
                return True
        except HTTPError:
            return True  # the worker answered: alive, path just unsupported
        except (URLError, OSError):
            return False

    def _probe_loop(self) -> None:
        while not self._stop.wait(min(self.probe_interval_s, 0.1)):
            now = time.monotonic()
            with self._lock:
                due = [w for w in self._workers
                       if self._circuits[w].state == OPEN
                       and now >= self._circuits[w].next_probe]
            for addr in due:
                alive = self._probe(addr)
                with self._lock:
                    c = self._circuits.get(addr)
                    if c is None or c.state != OPEN:
                        continue
                    if alive:
                        c.state = HALF_OPEN
                        c.failures = 0
                    else:
                        c.probe_attempt += 1
                        c.next_probe = time.monotonic() + \
                            self.probe_policy.next_wait(
                                c.probe_attempt, self._probe_rng)

    # -- forwarding helpers (threaded transport) -----------------------------
    def _worker_url(self, addr: str, incoming, path: str) -> str:
        """Resolve the worker-side URL for one forward: "/" routes to the
        worker's registered api path; any other path+query forwards
        verbatim (proxy semantics)."""
        parts = urlsplit(addr)
        wpath = parts.path if path in ("", "/") else incoming.path
        query = f"?{incoming.query}" if incoming.query else ""
        return f"{parts.scheme}://{parts.netloc}{wpath or '/'}{query}"

    def _forward_once(self, addr: str, method: str, url: str, path: str,
                      headers: Dict[str, str], body: bytes,
                      timeout: float, tctx) -> Tuple[str, Any]:
        """One forward attempt over urlopen, with circuit-breaker notes and
        the per-attempt forward span. Returns ``(kind, payload)``:

          - ``"response"`` — payload = (status, body, content_type): the
            worker answered (any status — authoritative, never retried);
          - ``"timeout"``  — payload = error string: the request may have
            REACHED the worker (read timeout — replay only when safe);
          - ``"error"``    — payload = error string: the request never
            arrived (connect refused/reset — safe to replay elsewhere).
        """
        fwd = None
        hdrs = dict(headers)
        if tctx is not None:
            if tctx.sampled:
                fwd = self.tracer.child(tctx)
            hdrs[TRACE_HEADER] = (fwd or tctx).to_header()
        req = Request(url, data=body if body else None, method=method,
                      headers=hdrs)
        t_f0w, t_f0 = time.time(), time.perf_counter()

        def fwd_span(**attrs):
            if fwd is not None:
                self.tracer.record("forward", fwd, t_f0w,
                                   time.perf_counter() - t_f0,
                                   worker=addr, **attrs)

        if self._fabric is not None:
            # per-cell in-flight accounting: what drain_cell waits on
            self._fabric.begin(addr)
        try:
            faults.fire(faults.WORKER_FORWARD, addr=addr, path=path)
            if self._fabric is not None:
                # cell-crash chaos seam (fabric mode only): InjectedFault
                # is an OSError, so it lands in the transport-error branch
                # below as a replay-safe "error" — the retry walk re-hashes
                # the tenant onto the next ring survivor
                faults.fire(faults.FRONT_L2_CRASH, cell=addr, path=path)
            with urlopen(req, timeout=timeout) as resp:
                self._note_success(addr)
                fwd_span(status=resp.status)
                return ("response", (resp.status, resp.read(),
                                     resp.headers.get("Content-Type",
                                                      "application/json")))
        except HTTPError as e:
            # worker answered (e.g. 500 from the pipeline): authoritative
            self._note_success(addr)
            fwd_span(status=e.code)
            return ("response", (e.code, e.read() or b"",
                                 e.headers.get("Content-Type",
                                               "text/plain")))
        except (URLError, OSError) as e:
            self._note_failure(addr)
            reason = getattr(e, "reason", e)
            fwd_span(error=str(reason))
            timed_out = isinstance(reason, TimeoutError) or \
                "timed out" in str(reason).lower()
            return ("timeout" if timed_out else "error", str(reason))
        finally:
            if self._fabric is not None:
                self._fabric.end(addr)

    def _hedged_forward(self, order: List[str], attempt: Callable,
                        deadline) -> Optional[Tuple[str, Any, str]]:
        """Primary + delayed hedge over the first two routable workers
        (threaded transport): launch ``attempt(order[0])`` in a thread;
        if no outcome lands within the tracker's quantile delay, launch
        ``attempt(order[1])`` and take whichever responds FIRST (the
        loser's reply is discarded when it eventually arrives — bounded
        duplicate work, no cancellation needed). Returns ``(kind, payload,
        addr)`` for the winning response / terminal failure, or None when
        every launched attempt failed with a replay-safe transport error
        (the caller walks the remaining workers)."""
        tracker = self._hedge
        tracker.note_request()
        results: "queue_mod.Queue" = queue_mod.Queue()
        t0 = time.perf_counter()

        def run(addr: str, role: str) -> None:
            try:
                kind, payload = attempt(addr)
            except Exception as e:  # noqa: BLE001 — a lost put would deadlock
                kind, payload = "error", str(e)
            if role == "primary" and kind == "response":
                # quantile source: primary latencies only (hedge wins
                # would bias the reservoir low)
                tracker.observe(time.perf_counter() - t0)
            results.put((role, addr, kind, payload))

        threading.Thread(target=run, args=(order[0], "primary"),
                         daemon=True).start()
        delay = tracker.delay_s()
        launched, hedge_done, did_hedge = 1, False, False
        failures: List[Tuple[str, str, str, Any]] = []
        while len(failures) < launched:
            timeout = None
            if not hedge_done:
                timeout = max(0.0, t0 + delay - time.perf_counter())
            try:
                role, addr, kind, payload = results.get(timeout=timeout)
            except queue_mod.Empty:
                hedge_done = True
                if deadline is not None and deadline.expired():
                    continue  # nobody is waiting: don't spend a duplicate
                try:
                    # chaos seam: a raising FRONT_HEDGE plan suppresses
                    # this hedge; fired() records which requests hedged
                    faults.fire(faults.FRONT_HEDGE, addr=order[1])
                except Exception:  # noqa: BLE001 — injected suppression
                    tracker.note_suppressed()
                    continue
                tracker.note_hedged()
                did_hedge = True
                threading.Thread(target=run, args=(order[1], "hedge"),
                                 daemon=True).start()
                launched += 1
                continue
            if kind == "response":
                tracker.note_win(role)
                return (kind, payload, addr)
            failures.append((role, addr, kind, payload))
            if not hedge_done and kind == "error":
                # the primary failed replay-safe BEFORE the hedge delay:
                # try the second worker immediately — a sequential retry
                # (the primary is gone, so this is not duplicate work and
                # does not count as a hedge)
                hedge_done = True
                threading.Thread(target=run, args=(order[1], "retry"),
                                 daemon=True).start()
                launched += 1
        if did_hedge:
            tracker.note_both_failed()
        # a read timeout is terminal for non-idempotent requests and an
        # expired deadline is terminal outright (the caller applies the
        # rules); prefer reporting those over a replay-safe error
        for role, addr, kind, payload in failures:
            if kind in ("timeout", "deadline"):
                return (kind, payload, addr)
        return None

    # -- HTTP ---------------------------------------------------------------
    def _control(self, path: str, body: bytes, headers
                 ) -> Optional[tuple]:
        """Control-plane endpoints shared by both transports: returns
        (status, content_type, body) or None when the request should be
        forwarded to a worker."""
        if path == RoutingFront.REGISTER_PATH:
            from .server import TOKEN_HEADER
            if self.token is not None and \
                    headers.get(TOKEN_HEADER) != self.token:
                return (403, "application/json",
                        b'{"error": "bad cluster token"}')
            try:
                msg = json.loads(body.decode())
                self.register(msg["address"],
                              capacity=int(msg.get("capacity", 1)),
                              models=msg.get("models"))
                return (200, "application/json", b"{}")
            except Exception as e:  # noqa: BLE001
                return (400, "application/json",
                        json.dumps({"error": str(e)}).encode())
        if path == RoutingFront.WORKERS_PATH:
            payload = {"workers": self.workers,
                       "states": self.worker_states,
                       "capacity": self.worker_capacities}
            with self._lock:
                by_worker = {w: list(ms)
                             for w, ms in self._models_by_worker.items()}
            if by_worker:
                # per-model capacity lines (multimodel workers only — the
                # section is absent while nobody registers models): for
                # each model, which workers serve it and their summed
                # routable capacity
                per_model: Dict[str, Dict[str, Any]] = {}
                caps = self.worker_capacities
                states = self.worker_states
                for w, ms in sorted(by_worker.items()):
                    for m in ms:
                        line = per_model.setdefault(
                            m, {"workers": [], "capacity": 0})
                        line["workers"].append(w)
                        if states.get(w) != OPEN:
                            line["capacity"] += caps.get(w, 1)
                payload["models"] = per_model
            if self._hedge is not None:
                payload["hedge"] = self._hedge.summary()
            if self._fabric is not None:
                payload["fabric"] = self._fabric.summary()
            return (200, "application/json", json.dumps(payload).encode())
        if path == RoutingFront.HEALTH_PATH:
            return (200, "application/json", json.dumps(
                {"ok": True, "workers": len(self.workers)}).encode())
        if path == RoutingFront.METRICS_PATH:
            if self.registry is None:
                return (404, "application/json",
                        b'{"error": "observability disabled"}')
            return (200, MetricsRegistry.CONTENT_TYPE,
                    self.registry.exposition().encode("utf-8"))
        if path == RoutingFront.TRACE_PATH:
            # worker parity (ServingServer.TRACE_PATH): a latency-bucket
            # exemplar found in the front's exposition resolves HERE —
            # front ingress/forward spans share the worker's trace_id
            if self.tracer is None:
                return (404, "application/json",
                        b'{"error": "observability disabled"}')
            return (200, "application/json", json.dumps(
                {"stats": self.tracer.stats(),
                 "spans": self.tracer.spans()}).encode("utf-8"))
        if path == RoutingFront.CAPACITY_PATH:
            return (200, "application/json",
                    json.dumps(self._collect_capacity()).encode("utf-8"))
        if path == RoutingFront.RING_PATH and self._fabric is not None:
            # fabric off: fall through to the forward path (byte-identical
            # single-front behavior — the worker answers or 404s)
            return (200, "application/json",
                    json.dumps(self._fabric.summary()).encode("utf-8"))
        if path == RoutingFront.DRAIN_PATH and self._fabric is not None:
            from .server import TOKEN_HEADER
            if self.token is not None and \
                    headers.get(TOKEN_HEADER) != self.token:
                return (403, "application/json",
                        b'{"error": "bad cluster token"}')
            try:
                msg = json.loads(body.decode())
                result = self.drain_cell(
                    msg["cell"], timeout_s=msg.get("timeout_s"))
                return (200, "application/json",
                        json.dumps(result).encode("utf-8"))
            except Exception as e:  # noqa: BLE001
                return (400, "application/json",
                        json.dumps({"error": str(e)}).encode())
        return None

    def _collect_capacity(self) -> Dict[str, Any]:
        """Aggregate the workers' fleet recommendations on demand. Each
        worker plans for ITS OWN arrival share, so the fleet-wide
        recommendation is the SUM across responders (a balanced front
        splits traffic, so per-worker demand is total/W). Workers with
        fleet disabled (404) are counted but contribute nothing. Fetches
        fan out on short-lived threads bounded by ``probe_timeout_s`` —
        this also runs on the async transport's loop thread, so the stall
        must stay bounded."""
        addrs = list(self.workers)
        results: Dict[str, Any] = {}

        def fetch(addr: str) -> None:
            parts = urlsplit(addr)
            url = f"{parts.scheme}://{parts.netloc}{self.CAPACITY_PATH}"
            try:
                with urlopen(Request(url, method="GET"),
                             timeout=self.probe_timeout_s) as resp:
                    results[addr] = json.loads(resp.read().decode("utf-8"))
            except HTTPError as e:
                results[addr] = {"disabled": True} if e.code == 404 \
                    else {"error": f"http {e.code}"}
            except Exception as e:  # noqa: BLE001 — a dead worker is data
                results[addr] = {"error": str(e)}

        threads = [threading.Thread(target=fetch, args=(a,), daemon=True)
                   for a in addrs]
        for t in threads:
            t.start()
        deadline = time.monotonic() + self.probe_timeout_s + 0.5
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        total_rec = 0
        contributed = 0
        total_forecast = 0.0
        responding = 0
        stale: List[str] = []
        ttl = self.capacity_ttl_s
        for addr in addrs:
            r = results.get(addr)
            if not isinstance(r, dict):
                continue
            if "state" in r:
                # a worker's own fleet summary
                responding += 1
                age = r.get("plan_age_s")
                if ttl is not None and age is not None and age > ttl:
                    # staleness fix: a worker whose planning loop stalled
                    # keeps republishing its last plan forever — drop it
                    # from the sums instead of steering the HPA with it
                    stale.append(addr)
                    continue
                rec = r.get("recommended_replicas")
                if rec is not None:
                    total_rec += int(rec)
                    contributed += 1
                fc = (r.get("forecast") or {}).get("forecast_rps")
                if fc is not None:
                    total_forecast += float(fc)
            elif "workers" in r and "recommended_replicas" in r:
                # an L2 front's aggregate (fabric mode: this front's
                # "workers" are themselves fronts): fold the cell's sums —
                # the cell applied the same TTL to its own workers, so its
                # stale list propagates up
                responding += 1
                rec = r.get("recommended_replicas")
                if rec is not None:
                    total_rec += int(rec)
                    contributed += 1
                fc = r.get("forecast_rps")
                if fc is not None:
                    total_forecast += float(fc)
                stale.extend(r.get("stale_workers") or [])
        return {"workers": len(addrs), "responding": responding,
                # null (not 0) when no worker has published a plan yet —
                # an HPA must never read "scale to zero" out of cold start
                "recommended_replicas": total_rec if contributed else None,
                "forecast_rps": round(total_forecast, 4),
                "stale_workers": stale,
                "per_worker": {a: results.get(a, {"error": "no reply"})
                               for a in addrs}}

    def _make_handler(self):
        front = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _read_body(self) -> bytes:
                length = int(self.headers.get("Content-Length", 0) or 0)
                return self.rfile.read(length) if length else b""

            def _respond(self, status: int, body: bytes,
                         ctype: str = "application/json",
                         extra: Optional[Dict[str, str]] = None):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _handle(self):
                incoming = urlsplit(self.path)
                path = incoming.path.rstrip("/")
                body = self._read_body()
                ctrl = front._control(path, body, self.headers)
                if ctrl is not None:
                    status, ctype, cbody = ctrl
                    self._respond(status, cbody, ctype)
                    return
                # trace ingress: the front originates (or continues) the
                # trace; each forward attempt ships a child context to the
                # worker via X-MMLSpark-Trace, so worker spans link up
                tctx = front.tracer.ingress(self.headers) \
                    if front.tracer is not None else None
                t_w0, t_p0 = time.time(), time.perf_counter()

                def respond(status, body, ctype="application/json",
                            extra=None, outcome=None):
                    self._respond(status, body, ctype, extra)
                    if outcome is not None:
                        front._count(outcome)
                    front._slo_record(t_p0, int(status))
                    if tctx is not None and tctx.sampled:
                        front.tracer.record(
                            "ingress", tctx, t_w0,
                            time.perf_counter() - t_p0, status=int(status))

                # deadline gate: an expired request is dropped HERE, before
                # any forward burns a worker slot
                dl = deadline_from_headers(self.headers)
                if dl is not None and dl.expired():
                    respond(504, b'{"error": "deadline expired"}',
                            outcome="deadline_expired")
                    return
                # forward to a worker, retrying across the ring; a request is
                # only REPLAYED on another worker when the failure shows it
                # never reached the first one (connect refused/reset) or the
                # method is idempotent — a read timeout on a POST may mean the
                # worker is mid-compute, so replaying would double-process it.
                # With hedging ON the first two workers instead race: the
                # hedge launches after the tracker's quantile delay and the
                # first response wins (opt-in: duplicates by design).
                order = front._route_order(self.headers)
                if not order:
                    respond(503, b'{"error": "no workers registered"}',
                            extra={"Retry-After": "1"}, outcome="no_workers")
                    return
                idempotent = self.command in ("GET", "HEAD")
                # replace any incoming trace header with the per-attempt
                # context (built in _forward_once): the head decision made
                # at ingress MUST propagate, otherwise the worker would
                # re-roll sampling
                drop = {"host", "content-length"}
                if tctx is not None:
                    drop.add(TRACE_HEADER.lower())
                base_hdrs = {k: v for k, v in self.headers.items()
                             if k.lower() not in drop}

                def attempt(addr):
                    if dl is not None and dl.expired():
                        return ("deadline", None)
                    timeout = front.forward_timeout_s
                    if dl is not None:
                        timeout = max(dl.cap(timeout), 1e-3)
                    return front._forward_once(
                        addr, self.command,
                        front._worker_url(addr, incoming, path), path,
                        base_hdrs, body, timeout, tctx)

                rest = order
                if front._hedge is not None and len(order) >= 2:
                    hedged = front._hedged_forward(order[:2], attempt, dl)
                    if hedged is not None:
                        kind, payload, addr = hedged
                        if kind == "response":
                            status, rbody, ctype = payload
                            respond(status, rbody, ctype,
                                    outcome="forwarded")
                            return
                        if kind == "timeout" and not idempotent:
                            respond(504, json.dumps(
                                {"error": f"worker {addr} timed out; not "
                                          f"replayed (non-idempotent)"}
                            ).encode(), outcome="timeout_unreplayed")
                            return
                        if kind == "deadline":
                            respond(504, b'{"error": "deadline expired"}',
                                    outcome="deadline_expired")
                            return
                    rest = order[2:]
                for addr in rest:
                    kind, payload = attempt(addr)
                    if kind == "response":
                        status, rbody, ctype = payload
                        respond(status, rbody, ctype, outcome="forwarded")
                        return
                    if kind == "deadline":
                        respond(504, b'{"error": "deadline expired"}',
                                outcome="deadline_expired")
                        return
                    if kind == "timeout" and not idempotent:
                        respond(504, json.dumps(
                            {"error": f"worker {addr} timed out; not "
                                      f"replayed (non-idempotent)"}
                        ).encode(), outcome="timeout_unreplayed")
                        return
                respond(502, b'{"error": "all workers failed"}',
                        outcome="all_workers_failed")

            do_POST = _handle
            do_GET = _handle

        return Handler

    async def _aio_handle(self, req):
        """Async-transport handler (serving/aio.py): same control plane,
        circuit-breaker notes, deadline gates, trace spans, and
        idempotent-replay rules as the threaded handler — but forwards ride
        the keep-alive connection pool instead of a fresh urlopen socket,
        and request/response bodies pass through as opaque bytes."""
        import asyncio

        from .aio import HTTPResponse
        from ..obs.trace import TRACE_HEADER

        incoming = urlsplit(req.path)
        path = incoming.path.rstrip("/")
        body = req.body
        ctrl = self._control(path, body, req.headers)
        if ctrl is not None:
            status, ctype, cbody = ctrl
            return HTTPResponse(status, cbody, ctype)
        tctx = self.tracer.ingress(req.headers) \
            if self.tracer is not None else None
        t_w0, t_p0 = time.time(), time.perf_counter()

        def respond(status, rbody, ctype="application/json", extra=None,
                    outcome=None):
            if outcome is not None:
                self._count(outcome)
            self._slo_record(t_p0, int(status))
            if tctx is not None and tctx.sampled:
                self.tracer.record("ingress", tctx, t_w0,
                                   time.perf_counter() - t_p0,
                                   status=int(status))
            return HTTPResponse(status, rbody, ctype, extra)

        dl = deadline_from_headers(req.headers)
        if dl is not None and dl.expired():
            return respond(504, b'{"error": "deadline expired"}',
                           outcome="deadline_expired")
        order = self._route_order(req.headers)
        if not order:
            return respond(503, b'{"error": "no workers registered"}',
                           extra={"Retry-After": "1"}, outcome="no_workers")
        idempotent = req.method in ("GET", "HEAD")
        drop = {"host", "content-length", "connection"}
        if tctx is not None:
            # the head sampling decision made at ingress MUST propagate
            # (same rule as the threaded handler)
            drop.add(TRACE_HEADER.lower())
        base_hdrs = {k: v for k, v in req.headers.items()
                     if k.lower() not in drop}

        async def attempt(addr):
            """One pooled forward: same breaker/span/deadline taxonomy as
            the threaded _forward_once, over the keep-alive pool."""
            if dl is not None and dl.expired():
                return ("deadline", None)
            timeout = max(dl.cap(self.forward_timeout_s), 1e-3) \
                if dl is not None else self.forward_timeout_s
            url = self._worker_url(addr, incoming, path)
            fwd = None
            hdrs = dict(base_hdrs)
            if tctx is not None:
                if tctx.sampled:
                    fwd = self.tracer.child(tctx)
                hdrs[TRACE_HEADER] = (fwd or tctx).to_header()
            t_f0w, t_f0 = time.time(), time.perf_counter()

            def fwd_span(**attrs):
                if fwd is not None:
                    self.tracer.record("forward", fwd, t_f0w,
                                       time.perf_counter() - t_f0,
                                       worker=addr, **attrs)

            if self._fabric is not None:
                self._fabric.begin(addr)
            try:
                faults.fire(faults.WORKER_FORWARD, addr=addr, path=path)
                if self._fabric is not None:
                    # cell-crash chaos seam — same taxonomy as the
                    # threaded transport: replay-safe "error", re-hash
                    faults.fire(faults.FRONT_L2_CRASH, cell=addr,
                                path=path)
                status, rhdrs, rbody = await self._pool.request(
                    req.method, url, body=body, headers=hdrs,
                    timeout=timeout, deadline=dl)
            except (asyncio.TimeoutError, OSError) as e:
                # transport failure: same taxonomy as the urlopen path —
                # note the breaker, replay only when safe
                self._note_failure(addr)
                fwd_span(error=str(e))
                timed_out = isinstance(e, asyncio.TimeoutError) or \
                    isinstance(e, TimeoutError) or \
                    "timed out" in str(e).lower()
                return ("timeout" if timed_out else "error", str(e))
            finally:
                if self._fabric is not None:
                    self._fabric.end(addr)
            # ANY worker answer — 2xx or an error status — is authoritative
            # (the threaded handler's urlopen/HTTPError split, merged)
            self._note_success(addr)
            fwd_span(status=status)
            return ("response",
                    (status, rbody,
                     rhdrs.get("Content-Type", "application/json")))

        rest = order
        if self._hedge is not None and len(order) >= 2:
            hedged = await self._hedged_forward_aio(order[:2], attempt, dl)
            if hedged is not None:
                kind, payload, addr = hedged
                if kind == "response":
                    status, rbody, ctype = payload
                    return respond(status, rbody, ctype,
                                   outcome="forwarded")
                if kind == "timeout" and not idempotent:
                    return respond(504, json.dumps(
                        {"error": f"worker {addr} timed out; not "
                                  f"replayed (non-idempotent)"}
                    ).encode(), outcome="timeout_unreplayed")
                if kind == "deadline":
                    return respond(504, b'{"error": "deadline expired"}',
                                   outcome="deadline_expired")
            rest = order[2:]
        for addr in rest:
            kind, payload = await attempt(addr)
            if kind == "response":
                status, rbody, ctype = payload
                return respond(status, rbody, ctype, outcome="forwarded")
            if kind == "deadline":
                return respond(504, b'{"error": "deadline expired"}',
                               outcome="deadline_expired")
            if kind == "timeout" and not idempotent:
                return respond(504, json.dumps(
                    {"error": f"worker {addr} timed out; not "
                              f"replayed (non-idempotent)"}
                ).encode(), outcome="timeout_unreplayed")
        return respond(502, b'{"error": "all workers failed"}',
                       outcome="all_workers_failed")

    async def _hedged_forward_aio(self, order, attempt,
                                  deadline) -> Optional[Tuple[str, Any, str]]:
        """Async twin of ``_hedged_forward``: primary task + delayed hedge
        task, first response wins, losers are CANCELLED (the pool discards
        a cancelled connection rather than reusing it torn)."""
        import asyncio

        tracker = self._hedge
        tracker.note_request()
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        tasks = {asyncio.ensure_future(attempt(order[0])):
                 ("primary", order[0])}
        delay = tracker.delay_s()
        hedge_done = did_hedge = False
        failures: List[Tuple[str, str, Any]] = []
        result: Optional[Tuple[str, Any, str]] = None
        while tasks:
            timeout = None
            if not hedge_done:
                timeout = max(0.0, t0 + delay - loop.time())
            done, _pending = await asyncio.wait(
                set(tasks), timeout=timeout,
                return_when=asyncio.FIRST_COMPLETED)
            if not done:
                hedge_done = True
                if deadline is not None and deadline.expired():
                    continue
                try:
                    faults.fire(faults.FRONT_HEDGE, addr=order[1])
                except Exception:  # noqa: BLE001 — injected suppression
                    tracker.note_suppressed()
                    continue
                tracker.note_hedged()
                did_hedge = True
                tasks[asyncio.ensure_future(attempt(order[1]))] = \
                    ("hedge", order[1])
                continue
            for t in done:
                role, addr = tasks.pop(t)
                try:
                    kind, payload = await t  # done: resolves immediately
                except asyncio.CancelledError:
                    continue
                if kind == "response":
                    if role == "primary":
                        tracker.observe(loop.time() - t0)
                    tracker.note_win(role)
                    result = (kind, payload, addr)
                else:
                    failures.append((addr, kind, payload))
                    if not hedge_done and kind == "error":
                        # primary failed replay-safe before the delay:
                        # sequential retry on the second worker, not a hedge
                        hedge_done = True
                        tasks[asyncio.ensure_future(attempt(order[1]))] = \
                            ("retry", order[1])
            if result is not None:
                for t in tasks:
                    t.cancel()
                return result
        if did_hedge:
            tracker.note_both_failed()
        for addr, kind, payload in failures:
            if kind in ("timeout", "deadline"):
                return (kind, payload, addr)
        return None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "RoutingFront":
        self._stop.clear()
        if self.http_mode == "async":
            from .aio import AsyncConnectionPool, AsyncHTTPServer

            self._pool = AsyncConnectionPool()
            self._aio = AsyncHTTPServer(self.host, self.port,
                                        self._aio_handle,
                                        name="routing-front-aio")
            self._aio.start()
            self.port = self._aio.port
        else:
            self._httpd = ThreadingHTTPServer((self.host, self.port),
                                              self._make_handler())
            self.port = self._httpd.server_address[1]
            t = threading.Thread(target=self._httpd.serve_forever,
                                 daemon=True, name="routing-front")
            t.start()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True, name="routing-front-probe")
        self._probe_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
            self._probe_thread = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._aio is not None:
            if self._pool is not None and self._aio.loop is not None \
                    and self._aio.loop.is_running():
                # close pooled worker sockets on their own loop
                try:
                    self._aio.loop.call_soon_threadsafe(self._pool.close)
                except RuntimeError:
                    pass
            self._aio.stop()
            self._aio = None
        if self._fabric is not None:
            self._fabric.close()  # flush/close the durable ring journal

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}/"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def register_worker(front_address: str, worker_address: str,
                    timeout: float = 10.0, token: Optional[str] = None,
                    capacity: int = 1,
                    models: Optional[List[str]] = None) -> None:
    """Worker-side registration call (ServiceInfo POST parity).

    ``capacity``: concurrent-batch hint for weighted routing — pass the
    worker's ``ServingServer.capacity`` (replica count under async_exec).
    ``models``: the worker's admitted model list (multimodel workers) for
    the per-model capacity view on ``/_mmlspark/workers``."""
    from .server import _post_json

    parts = urlsplit(front_address)
    url = f"{parts.scheme}://{parts.netloc}{RoutingFront.REGISTER_PATH}"
    msg: Dict[str, Any] = {"address": worker_address,
                           "capacity": int(capacity)}
    if models:
        msg["models"] = [str(m) for m in models]
    _post_json(url, msg, timeout=timeout, token=token)
