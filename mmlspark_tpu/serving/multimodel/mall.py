"""ModelMall: N independent fitted pipelines behind one worker.

The reference framework's whole point is many models behind one substrate,
but a ``ServingServer`` hosts exactly ONE transform. The mall closes that
gap: it installs itself AS the server's transform (the lifecycle-plane
idiom) and routes each ingress row to one of N *per-model* lifecycle
planes by the ``X-MMLSpark-Model`` header (or an in-band ``"model"`` JSON
column; absent -> the default model). Every model keeps its own
``LifecyclePlane`` — registry, canary controller, shadow scoring, SLO
buckets — with a per-model journal namespace (``ns=<model>`` on every
registry entry), all sharing the worker's existing TransferRing/SlotPool/
CompileCache/PersistentCompileCache substrate.

Control loops (all journaled, all one-step-rollback, all off the hot
path — ticked from the server's batch heartbeat):

  - **Packing** — a ``PackingPlanner`` (serving/fleet/planner.py)
    bin-packs models onto replicas by ``predict_ms x forecast_rps``;
    uncalibrated models get a measured-probe slot, never an invented
    load number. The plan's ``idle_share`` is the AutoML budget.
  - **Eviction** — cold models (no traffic for ``evict_idle_s``) are
    parked to the persistent/object-store tier when residency exceeds
    ``max_resident`` (halved while the brownout controller has a
    degradation step applied — memory pressure sheds first); a model
    receiving traffic is never evicted while it is the last live copy.
    The next request restores it with an accounted AOT re-warm; new
    models are warmed BEFORE they become routable (warm-before-admit).
    The ``mall.evict`` chaos seam fires after the tier park and before
    the resident drop: a crash mid-evict leaves the model servable from
    the tier through the same accounted re-warm.
  - **AutoML** — an ``AutoMLScheduler`` (multimodel/automl.py) deploys
    grid candidates as shadow versions while the plan marks capacity
    idle, and sheds them instantly when traffic reclaims it. Promotion
    runs through the per-model canary ramp; the ``mall.swap`` seam fires
    before the registry swap, so a crash mid-promotion leaves the
    model's incumbent serving.

``multimodel=None`` (the server default) constructs nothing: replies and
metrics exposition stay bitwise-identical to a mall-less build —
test-enforced like every prior plane.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...core import faults
from ...core.dataframe import DataFrame
from ..tenants import MODEL_HEADER, header_lookup
from ..lifecycle.canary import CanaryConfig, LifecyclePlane
from ..fleet.planner import (ModelDemand, PackingPlanner, PlannerConfig,
                             forecast_rps)
from .automl import AutoMLScheduler, make_automl

__all__ = ["MODEL_HEADER", "MallConfig", "ModelMall", "make_multimodel"]

#: in-band body sniff cap: bodies larger than this are never parsed for a
#: ``"model"`` column (the header is the fast path; in-band is a courtesy)
_INBAND_MAX_BYTES = 65536


def model_from_body(value: Any) -> Optional[str]:
    """Best-effort in-band model extraction: a JSON object body with a
    top-level ``"model"`` string. Anything else (non-JSON, oversized,
    malformed, non-object) reads as "no in-band model" — never an error."""
    try:
        if isinstance(value, (bytes, bytearray, memoryview)):
            raw = bytes(value)
        elif isinstance(value, str):
            raw = value.encode("utf-8", "ignore")
        else:
            return None
        if len(raw) > _INBAND_MAX_BYTES:
            return None
        head = raw.lstrip()
        if not head.startswith(b"{") or b'"model"' not in raw:
            return None
        obj = json.loads(raw)
        if not isinstance(obj, dict):
            return None
        m = obj.get("model")
        m = str(m).strip() if m is not None else ""
        return m or None
    except Exception:  # noqa: BLE001 — sniffing never fails a request
        return None


@dataclasses.dataclass
class MallConfig:
    """The mall's envelope (``multimodel=`` dict keys on the server)."""

    default_model: str = "default"
    max_resident: int = 4
    evict_idle_s: float = 30.0
    check_interval_s: float = 1.0
    probe_ms: float = 25.0
    service_alpha: float = 0.3
    journal_cap: int = 512
    #: per-model lifecycle spec: None -> CanaryConfig defaults, dict ->
    #: CanaryConfig kwargs, CanaryConfig -> shared by every model
    lifecycle: Any = None
    #: AutoML spec: None -> off, dict -> AutoMLScheduler kwargs,
    #: AutoMLScheduler -> as-is (multimodel/automl.py)
    automl: Any = None
    #: packing envelope: None -> PlannerConfig defaults, dict ->
    #: PlannerConfig kwargs, PlannerConfig -> as-is
    packing: Any = None

    def __post_init__(self):
        if not str(self.default_model).strip():
            raise ValueError("default_model must be non-empty")
        if int(self.max_resident) < 1:
            raise ValueError("max_resident must be >= 1")
        if float(self.evict_idle_s) < 0:
            raise ValueError("evict_idle_s must be >= 0")

    def canary_config(self) -> CanaryConfig:
        lc = self.lifecycle
        if isinstance(lc, CanaryConfig):
            return lc
        if isinstance(lc, dict):
            return CanaryConfig(**lc)
        return CanaryConfig()

    def planner_config(self) -> PlannerConfig:
        p = self.packing
        if isinstance(p, PlannerConfig):
            return p
        if isinstance(p, dict):
            return PlannerConfig(**p)
        return PlannerConfig()


class _ModelHost:
    """The per-model stand-in for the server that a LifecyclePlane binds
    to: just a transform and a reply column. ``_executor`` is None on
    purpose — promotions inside a model mutate only that model's
    registry; the MALL stays the executor's installed transform, so no
    executor flip is needed (the plane routes via ``registry.live`` per
    batch)."""

    __slots__ = ("transform", "reply_col", "_executor")

    def __init__(self, transform: Callable, reply_col: str):
        self.transform = transform
        self.reply_col = reply_col
        self._executor = None


class _ModelEntry:
    """Mall-side bookkeeping for one admitted model."""

    __slots__ = ("name", "plane", "host", "state", "token", "admitted_s",
                 "last_used_s", "evicted_s", "requests", "service_ms",
                 "rewarms", "rewarm_seconds", "_buckets")

    def __init__(self, name: str, plane: LifecyclePlane, host: _ModelHost,
                 now: float):
        self.name = name
        self.plane: Optional[LifecyclePlane] = plane
        self.host = host
        self.state = "resident"            # "resident" | "evicted"
        self.token: Any = None             # tier park token while evicted
        self.admitted_s = now
        self.last_used_s = now
        self.evicted_s: Optional[float] = None
        self.requests = 0
        #: measured per-row service EWMA (ms) — the probe measurement that
        #: graduates an uncalibrated model into real packing
        self.service_ms: Optional[float] = None
        self.rewarms = 0
        self.rewarm_seconds = 0.0
        #: per-second (second, total, breaches) arrival triples, the
        #: forecast_rps input shape (obs SLOTracker bucket contract)
        self._buckets: List[List[float]] = []

    def note_arrival(self, rows: int, now: float,
                     max_history_s: int = 600) -> None:
        sec = int(now)
        if self._buckets and self._buckets[-1][0] == sec:
            self._buckets[-1][1] += rows
        else:
            self._buckets.append([sec, float(rows), 0.0])
            while self._buckets and sec - self._buckets[0][0] > max_history_s:
                self._buckets.pop(0)
        self.last_used_s = now
        self.requests += rows

    def observe_service(self, per_row_ms: float, alpha: float) -> None:
        if per_row_ms <= 0:
            return
        if self.service_ms is None:
            self.service_ms = per_row_ms
        else:
            self.service_ms = alpha * per_row_ms \
                + (1.0 - alpha) * self.service_ms

    def arrival_snapshot(self) -> List[Tuple[float, float, float]]:
        return [tuple(b) for b in self._buckets]


class ModelMall:
    """The model-fleet plane, installed AS the server's transform.

    Hooks (all optional):
      ``warm(model, version)``        AOT-warm a model's executables
                                      (warm-before-admit + re-warm)
      ``evict_store(model, plane)``   park a plane to the persistent /
                                      object-store tier, return a token
      ``evict_load(model, token)``    restore a parked plane
      ``predict_ms(model)``           cost model's per-row estimate (None
                                      -> the mall's own measured EWMA)
      ``replicas()``                  packing width (default: the live
                                      executor's replica count, else 1)
      ``live_copies(model)``          fleet-wide live copies of a model
                                      (default 1 — never evict a model
                                      receiving traffic on a lone worker)
      ``live_version``/``live_stage``/``live_cost``
                                      bootstrap identity of the default
                                      model (the lifecycle hook trio)
    """

    def __init__(self, config: Optional[MallConfig] = None, *,
                 hooks: Optional[Dict[str, Any]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config if config is not None else MallConfig()
        self._hooks = dict(hooks or {})
        self._clock = clock
        self._lock = threading.RLock()
        self._server: Any = None
        self._reply_col = "reply"
        self._models: Dict[str, _ModelEntry] = {}
        self.planner = PackingPlanner(self.config.planner_config(),
                                      probe_ms=self.config.probe_ms,
                                      journal_cap=self.config.journal_cap)
        self.automl: Optional[AutoMLScheduler] = \
            make_automl(self.config.automl, clock=clock)
        self._last_tick = 0.0
        self._started = False
        self.evictions = 0
        self.evict_crashes = 0
        self.rewarms = 0
        self.swaps = 0
        self.unknown_requests = 0
        self._journal_cap = max(8, int(self.config.journal_cap))
        self.journal: List[Dict[str, Any]] = []

    # -- journal (per-model namespace: every entry carries model=) -------
    def _log(self, action: str, model: Optional[str] = None,
             **info: Any) -> None:
        entry = {"action": action, "t": round(self._clock(), 3), **info}
        if model is not None:
            entry["model"] = model
        with self._lock:
            if len(self.journal) >= self._journal_cap:
                del self.journal[: self._journal_cap // 4]
            self.journal.append(entry)

    def journal_for(self, model: str, last: int = 32) -> List[Dict[str, Any]]:
        """One model's slice of the mall journal (its registry journal —
        stamped ``ns=<model>`` — lives on the plane itself)."""
        with self._lock:
            ours = [dict(e) for e in self.journal
                    if e.get("model") == model]
        return ours[-int(last):]

    # -- attribute forwarding: fleet/tuner introspection through the
    # default model (mega_k, set_mega_k, snapshot hooks, ...)
    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        models = self.__dict__.get("_models") or {}
        cfg = self.__dict__.get("config")
        entry = models.get(cfg.default_model) if cfg is not None else None
        if entry is None or entry.plane is None:
            raise AttributeError(name)
        return getattr(entry.plane, name)

    # -- wiring -----------------------------------------------------------
    def bind(self, server: Any) -> "ModelMall":
        """Adopt ``server.transform`` as the DEFAULT model and return the
        mall (the server installs the return value as its transform)."""
        self._server = server
        self._reply_col = getattr(server, "reply_col", "reply")
        if self.config.default_model not in self._models:
            self._admit(self.config.default_model, server.transform,
                        version=self._hooks.get("live_version"),
                        stage=self._hooks.get("live_stage"),
                        cost=self._hooks.get("live_cost"),
                        warm=False)  # the incumbent is already warm
        return self

    def start(self) -> None:
        with self._lock:
            self._started = True
            planes = [e.plane for e in self._models.values()
                      if e.plane is not None]
        for p in planes:
            p.start()

    def stop(self) -> None:
        with self._lock:
            self._started = False
            planes = [e.plane for e in self._models.values()
                      if e.plane is not None]
        for p in planes:
            try:
                p.stop()
            except Exception:  # noqa: BLE001 — shutdown stays best-effort
                pass

    # -- model admission ---------------------------------------------------
    def _make_plane(self, name: str, transform: Callable, *,
                    version: Optional[str], stage: Any,
                    cost: Optional[dict]
                    ) -> Tuple[LifecyclePlane, _ModelHost]:
        hooks: Dict[str, Any] = {"namespace": name,
                                 "live_version": version,
                                 "live_stage": stage,
                                 "live_cost": cost}
        warm = self._hooks.get("warm")
        if warm is not None:
            hooks["warm"] = lambda ver, _m=name: warm(_m, ver)
        plane = LifecyclePlane(self.config.canary_config(), hooks=hooks,
                               clock=self._clock)
        host = _ModelHost(transform, self._reply_col)
        plane.bind(host)
        # promotion apply: the mall's chaos seam instead of an executor
        # flip (the mall stays the executor's transform; sub-plane swaps
        # only move that model's registry.live pointer)
        plane.controller._apply_swap = \
            lambda new, old, _m=name, _h=host: \
            self._apply_model_swap(_m, _h, new, old)
        return plane, host

    def _apply_model_swap(self, model: str, host: _ModelHost,
                          new: Any, old: Any) -> None:
        """swap_live's ``apply`` for a per-model promotion: the seam fires
        BEFORE any state mutates — a raising plan aborts the swap with the
        incumbent version serving (registry.swap_live's contract)."""
        faults.fire(faults.MALL_SWAP, model=model, version=new.version,
                    incumbent=old.version if old is not None else None)
        host.transform = new.transform
        with self._lock:
            self.swaps += 1
        self._log("swap", model=model, version=new.version,
                  incumbent=old.version if old is not None else None)

    def _admit(self, name: str, transform: Callable, *,
               version: Optional[str] = None, stage: Any = None,
               cost: Optional[dict] = None,
               warm: bool = True) -> LifecyclePlane:
        plane, host = self._make_plane(name, transform, version=version,
                                       stage=stage, cost=cost)
        warm_s = 0.0
        if warm:
            # warm-before-admit: AOT-warm the executables BEFORE the model
            # becomes routable, so its first request never pays a compile
            hook = self._hooks.get("warm")
            if hook is not None:
                t0 = time.perf_counter()
                try:
                    hook(name, plane.registry.live)
                except Exception:  # noqa: BLE001 — a failed warm admits
                    # cold (accounted), it never blocks admission
                    pass
                warm_s = time.perf_counter() - t0
        now = self._clock()
        with self._lock:
            if name in self._models:
                raise ValueError(f"model {name!r} already admitted")
            entry = _ModelEntry(name, plane, host, now)
            self._models[name] = entry
            started = self._started
        if started:
            plane.start()
        self._log("admit", model=name, warm_s=round(warm_s, 6),
                  version=plane.registry.live.version
                  if plane.registry.live else None)
        self._evict_pass(now)
        return plane

    def add_model(self, name: str, transform: Callable, *,
                  version: Optional[str] = None, stage: Any = None,
                  cost: Optional[dict] = None) -> LifecyclePlane:
        """Admit a fitted pipeline under ``name`` (warm-before-admit).
        Returns that model's lifecycle plane."""
        name = str(name).strip()
        if not name:
            raise ValueError("model name must be non-empty")
        return self._admit(name, transform, version=version, stage=stage,
                           cost=cost, warm=True)

    def models(self) -> Dict[str, str]:
        """{model: state} for every admitted model."""
        with self._lock:
            return {n: e.state for n, e in self._models.items()}

    def has_model(self, name: str) -> bool:
        """Servable (resident OR parked in the tier — a request re-warms)."""
        with self._lock:
            return name in self._models

    def plane_for(self, name: str) -> Optional[LifecyclePlane]:
        with self._lock:
            e = self._models.get(name)
            return e.plane if e is not None else None

    # -- routing key -------------------------------------------------------
    def model_of(self, headers: Any, value: Any = None) -> Optional[str]:
        """The request's explicit model key: ``X-MMLSpark-Model`` header
        first, then the in-band JSON ``"model"`` column; None when the
        request names no model (-> the default model)."""
        try:
            m = header_lookup(headers, MODEL_HEADER)
        except Exception:  # noqa: BLE001 — a weird headers shape routes
            m = None       # to the default model, never errors
        if m is not None:
            return m
        return model_from_body(value)

    # -- data path ----------------------------------------------------------
    def __call__(self, df: Any) -> Any:
        if "headers" not in getattr(df, "columns", ()):
            # non-ingress frame (warmup probe, direct call): default model
            return self._dispatch(self.config.default_model, df,
                                  int(getattr(df, "count", lambda: 1)()))
        data = df.collect()
        headers = data.get("headers")
        values = data.get("value")
        n = len(headers) if headers is not None else 0
        default = self.config.default_model
        groups: Dict[str, List[int]] = {}
        unknown: List[int] = []
        for i in range(n):
            h = headers[i]
            m = self.model_of(h, values[i] if values is not None else None)
            m = m if m is not None else default
            if self.has_model(m):
                groups.setdefault(m, []).append(i)
            else:
                unknown.append(i)
        if unknown:
            self._shed_unknown(data, unknown)
        if not groups:
            return DataFrame.from_dict({"id": [], self._reply_col: []})
        if not unknown and len(groups) == 1:
            # whole batch is one model: route the frame untouched (the
            # single-model fast path — bitwise-identical to a mall-less
            # server when only the default model exists)
            (name, idxs), = groups.items()
            return self._dispatch(name, df, len(idxs))
        outs = []
        for name in sorted(groups):          # deterministic merge order
            idxs = groups[name]
            sub = DataFrame.from_dict(
                {k: [data[k][i] for i in idxs] for k in data})
            outs.append(self._dispatch(name, sub, len(idxs)))
        return self._merge(outs)

    def submit(self, df: Any):
        """Async-dispatch face: the mall declines (returns None) so the
        executor falls back to the synchronous ``run`` path — per-row
        routing needs the materialized frame."""
        return None

    def _shed_unknown(self, data: Dict[str, Any],
                      unknown: List[int]) -> None:
        srv = self._server
        ids = data.get("id")
        with self._lock:
            self.unknown_requests += len(unknown)
        if srv is None or ids is None:
            return
        body = b'{"error": "unknown model"}'
        for i in unknown:
            try:
                srv.stats.record_shed(404, "unknown_model")
                srv._fulfill(int(ids[i]), 404, body,
                             content_type="application/json")
            except Exception:  # noqa: BLE001 — shedding never kills a batch
                pass

    def _dispatch(self, name: str, df: Any, rows: int) -> Any:
        entry = self._ensure_resident(name)
        now = self._clock()
        with self._lock:
            entry.note_arrival(max(1, rows), now)
        plane = entry.plane
        t0 = time.perf_counter()
        out = plane(df)
        dur_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            entry.observe_service(dur_ms / max(1, rows),
                                  self.config.service_alpha)
        return out

    def _merge(self, outs: List[Any]) -> Any:
        cols = [o.collect() if hasattr(o, "collect") else dict(o)
                for o in outs]
        keys = set(cols[0])
        for c in cols[1:]:
            keys &= set(c)
        merged: Dict[str, List[Any]] = {k: [] for k in sorted(keys)}
        for c in cols:
            for k in merged:
                merged[k].extend(list(c[k]))
        return DataFrame.from_dict(merged)

    # -- eviction / re-warm --------------------------------------------------
    def _ensure_resident(self, name: str) -> _ModelEntry:
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise KeyError(f"unknown model {name!r}")
            if entry.state == "resident":
                return entry
            t0 = time.perf_counter()
            load = self._hooks.get("evict_load")
            plane = load(name, entry.token) if load is not None \
                else entry.token
            if plane is None:
                raise RuntimeError(f"model {name!r} lost from the tier")
            # AOT re-warm BEFORE the model takes traffic again
            warm = self._hooks.get("warm")
            if warm is not None:
                try:
                    warm(name, plane.registry.live)
                except Exception:  # noqa: BLE001 — accounted cold serve
                    pass
            entry.plane = plane
            entry.token = None
            entry.state = "resident"
            entry.evicted_s = None
            wall = time.perf_counter() - t0
            entry.rewarms += 1
            entry.rewarm_seconds += wall
            self.rewarms += 1
            started = self._started
        if started:
            plane.start()
        self._log("rewarm", model=name, wall_s=round(wall, 6))
        return entry

    def _brownout_active(self) -> bool:
        ctrl = getattr(self._server, "_brownout", None) \
            if self._server is not None else None
        if ctrl is None:
            return False
        try:
            return int(ctrl.step) > 0
        except Exception:  # noqa: BLE001 — a broken controller reads calm
            return False

    def _evict_pass(self, now: float) -> int:
        limit = int(self.config.max_resident)
        if self._brownout_active():
            # brownout-aware: a degradation step means the worker is under
            # pressure — halve residency so cold models shed memory first
            limit = max(1, limit // 2)
        with self._lock:
            resident = [e for e in self._models.values()
                        if e.state == "resident"]
            if len(resident) <= limit:
                return 0
            live_copies = self._hooks.get("live_copies")
            cands = []
            for e in resident:
                if e.name == self.config.default_model:
                    continue  # the incumbent transform is never parked
                hot = (now - e.last_used_s) < self.config.evict_idle_s
                if hot:
                    try:
                        copies = int(live_copies(e.name)) \
                            if live_copies is not None else 1
                    except Exception:  # noqa: BLE001 — unknown reads lone
                        copies = 1
                    if copies <= 1:
                        # never evict the last live copy of a model that
                        # is receiving traffic
                        continue
                cands.append(e)
            cands.sort(key=lambda e: (e.last_used_s, e.name))  # coldest 1st
            excess = len(resident) - limit
            victims = cands[:max(0, excess)]
        evicted = 0
        for e in victims:
            if self._evict(e, now):
                evicted += 1
        return evicted

    def _evict(self, entry: _ModelEntry, now: float) -> bool:
        plane = entry.plane
        if plane is None:
            return False
        store = self._hooks.get("evict_store")
        try:
            # park to the tier FIRST — the tier copy is the safety net a
            # mid-evict crash falls back on
            token = store(entry.name, plane) if store is not None else plane
        except Exception:  # noqa: BLE001 — an unwritable tier means the
            # model simply stays resident (accounted skip, PR 13 idiom)
            self._log("evict_skipped", model=entry.name,
                      reason="store_failed")
            return False
        crashed = False
        try:
            faults.fire(faults.MALL_EVICT, model=entry.name)
        except Exception:  # noqa: BLE001 — injected crash mid-evict: the
            # resident copy is gone either way; the tier copy serves
            crashed = True
        try:
            plane.stop()
        except Exception:  # noqa: BLE001 — a wedged shadow thread must
            # not block the eviction pass
            pass
        with self._lock:
            entry.plane = None
            entry.token = token
            entry.state = "evicted"
            entry.evicted_s = now
            self.evictions += 1
            if crashed:
                self.evict_crashes += 1
        self._log("evict", model=entry.name, crashed=crashed,
                  idle_s=round(now - entry.last_used_s, 3))
        return True

    # -- control loop ---------------------------------------------------------
    def tick(self, e2e_s: float) -> None:
        """The server's batch heartbeat: tick every resident plane (their
        canary controllers rate-limit internally), then — at most every
        ``check_interval_s`` — refresh the packing plan, run the eviction
        pass and give the AutoML scheduler its capacity decision."""
        with self._lock:
            planes = [e.plane for e in self._models.values()
                      if e.plane is not None]
        for p in planes:
            try:
                p.tick(e2e_s)
            except Exception:  # noqa: BLE001 — a model's controller error
                # must not stall the others
                pass
        now = self._clock()
        with self._lock:
            if now - self._last_tick < self.config.check_interval_s:
                return
            self._last_tick = now
        try:
            plan = self._plan_tick(now)
            self._evict_pass(now)
            if self.automl is not None:
                idle = self._idle_share(plan)
                target = self.automl.model or self.config.default_model
                with self._lock:
                    e = self._models.get(target)
                    plane = e.plane if e is not None \
                        and e.state == "resident" else None
                act = self.automl.tick(plane, idle)
                if act is not None:
                    self._log("automl", model=target, event=act,
                              idle_share=round(idle, 4))
        except Exception:  # noqa: BLE001 — the control loop never kills
            # the batch path it is riding
            pass

    def _replicas(self) -> int:
        hook = self._hooks.get("replicas")
        if hook is not None:
            try:
                return max(1, int(hook()))
            except Exception:  # noqa: BLE001 — fall through to the live set
                pass
        srv = self._server
        ex = getattr(srv, "_executor", None) if srv is not None else None
        if ex is not None:
            try:
                return max(1, len(ex.replicas.replicas))
            except Exception:  # noqa: BLE001 — executor mid-teardown
                pass
        return max(1, int(getattr(srv, "replicas", 1) or 1))

    def _plan_tick(self, now: float):
        predict = self._hooks.get("predict_ms")
        demands = []
        with self._lock:
            entries = list(self._models.values())
        for e in entries:
            pm = None
            if predict is not None:
                try:
                    pm = predict(e.name)
                except Exception:  # noqa: BLE001 — no estimate is "probe"
                    pm = None
            if pm is None:
                pm = e.service_ms  # the measured-probe graduation path
            fc = forecast_rps(e.arrival_snapshot(), now=now)
            demands.append(ModelDemand(model=e.name, predict_ms=pm,
                                       forecast_rps=fc["forecast_rps"]))
        plan = self.planner.plan(demands, self._replicas())
        self._log("pack", models=len(demands),
                  idle_share=round(plan.idle_share, 4),
                  probes=list(plan.probes), reason=plan.reason)
        return plan

    def _idle_share(self, plan: Any) -> float:
        """The AutoML budget: the plan's idle share, clamped by the live
        executor's own idleness when one is attached — a saturated
        executor vetoes trials even if the forecast looks calm."""
        idle = float(plan.idle_share)
        ex = getattr(self._server, "_executor", None) \
            if self._server is not None else None
        fn = getattr(ex, "idle_fraction", None)
        if callable(fn):
            try:
                idle = min(idle, float(fn()))
            except Exception:  # noqa: BLE001 — introspection best-effort
                pass
        return idle

    # -- introspection -----------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        with self._lock:
            models: Dict[str, Any] = {}
            for name, e in self._models.items():
                d: Dict[str, Any] = {
                    "state": e.state,
                    "default": name == self.config.default_model,
                    "requests": e.requests,
                    "service_ms": round(e.service_ms, 4)
                    if e.service_ms is not None else None,
                    "rewarms": e.rewarms,
                    "rewarm_seconds": round(e.rewarm_seconds, 6),
                }
                if e.plane is not None:
                    d["lifecycle"] = e.plane.summary()
                models[name] = d
            counters = {"evictions": self.evictions,
                        "evict_crashes": self.evict_crashes,
                        "rewarms": self.rewarms,
                        "swaps": self.swaps,
                        "unknown_requests": self.unknown_requests}
            journal = [dict(j) for j in self.journal[-16:]]
        out = {"default_model": self.config.default_model,
               "max_resident": self.config.max_resident,
               "models": models,
               "packing": self.planner.summary(),
               "counters": counters,
               "journal": journal}
        if self.automl is not None:
            out["automl"] = self.automl.summary()
        return out


def make_multimodel(spec: Any, hooks: Optional[Dict[str, Any]] = None,
                    clock: Callable[[], float] = time.monotonic
                    ) -> Optional[ModelMall]:
    """Coerce the server's ``multimodel=`` knob: None/False -> off (the
    bitwise-parity default), True -> MallConfig defaults, dict ->
    MallConfig kwargs, MallConfig -> configured, a ModelMall passes
    through (pre-wired malls keep their hooks)."""
    if spec is None or spec is False:
        return None
    if isinstance(spec, ModelMall):
        return spec
    if spec is True:
        return ModelMall(MallConfig(), hooks=hooks, clock=clock)
    if isinstance(spec, MallConfig):
        return ModelMall(spec, hooks=hooks, clock=clock)
    if isinstance(spec, dict):
        return ModelMall(MallConfig(**spec), hooks=hooks, clock=clock)
    raise TypeError(f"multimodel: cannot coerce {type(spec).__name__}")
