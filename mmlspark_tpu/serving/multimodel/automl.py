"""AutoML-on-serve: hyperparameter trials scheduled onto idle capacity.

``TuneHyperparameters`` (automl/tuning.py) searches a param grid offline —
fit, fold-evaluate, pick. This module runs the SAME search continuously
against live traffic: each grid point becomes a *trial candidate* deployed
as a shadow version on the target model's lifecycle plane, scored by the
existing divergence/burn gates and promoted through the canary ramp —
population-based train-on-serve, the TVM measure->select loop applied to
the model population instead of the kernel population (PAPERS.md, same
framing as the compiler-search PR).

The capacity contract (the acceptance criterion): a trial may only START
while the packing plan's ``idle_share`` is at or above ``min_idle_share``,
and it is INSTANTLY shed (``controller.rollback(..., "traffic_reclaim")``)
the moment idle capacity falls below ``shed_idle_share`` — live-model
traffic never pays for a trial. Shadow duplicates already ride the plane's
bounded drop-don't-block queue, so even a running trial adds zero serving
latency; the shed rule bounds the *compute* it may consume. Every launch,
promotion, shed and rollback is journaled (bounded, tuner idiom).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

from ..lifecycle.registry import CANARY, LIVE, ROLLED_BACK, SHADOWING

__all__ = ["AutoMLScheduler", "make_automl"]


def _param_dicts(grid: Any) -> Iterator[Dict[str, Any]]:
    """Normalize a trial source into plain ``{param: value}`` dicts:
    GridSpace/ParamSpace yield ``[(est, name, value), ...]`` lists from
    ``param_maps()``; a plain iterable of dicts passes through."""
    maps = grid.param_maps() if hasattr(grid, "param_maps") else iter(grid)
    for pm in maps:
        if isinstance(pm, dict):
            yield dict(pm)
        else:
            yield {name: value for (_est, name, value) in pm}


class AutoMLScheduler:
    """Turn a param grid into canary-gated trials on idle capacity.

    ``grid``   GridSpace / ParamSpace (automl/params.py) or an iterable of
               ``{param: value}`` dicts — the trial population.
    ``build``  callable(params) -> fitted transform for one candidate (the
               caller owns training; the scheduler owns scheduling).
    ``model``  target model name in the mall (None = the default model).

    One trial is in flight at a time (the lifecycle plane's one-rollout
    invariant); ``max_trials`` bounds the population (defaults to the
    grid's ``space_size()`` when it has one, else 8).
    """

    def __init__(self, grid: Any, build: Callable[[Dict[str, Any]], Any],
                 *, model: Optional[str] = None,
                 min_idle_share: float = 0.25,
                 shed_idle_share: float = 0.10,
                 max_trials: Optional[int] = None,
                 version_prefix: str = "trial-",
                 journal_cap: int = 256,
                 clock: Callable[[], float] = time.monotonic):
        if not callable(build):
            raise TypeError("automl build hook must be callable")
        if not 0.0 <= shed_idle_share <= min_idle_share <= 1.0:
            raise ValueError("need 0 <= shed_idle_share <= min_idle_share "
                             "<= 1")
        self.grid = grid
        self.build = build
        self.model = model
        self.min_idle_share = float(min_idle_share)
        self.shed_idle_share = float(shed_idle_share)
        if max_trials is None:
            size = getattr(grid, "space_size", None)
            max_trials = int(size()) if callable(size) else 8
        self.max_trials = int(max_trials)
        self.version_prefix = str(version_prefix)
        self._clock = clock
        self._lock = threading.Lock()
        self._params = _param_dicts(grid)
        self._active: Optional[Dict[str, Any]] = None
        self._exhausted = False
        self.trials_started = 0
        self.trials_promoted = 0
        self.trials_shed = 0
        self.trials_rolled_back = 0
        self._journal_cap = max(8, int(journal_cap))
        self.journal: List[Dict[str, Any]] = []

    def _log(self, action: str, **info: Any) -> None:
        entry = {"action": action, "t": round(self._clock(), 3), **info}
        if len(self.journal) >= self._journal_cap:
            del self.journal[: self._journal_cap // 4]
        self.journal.append(entry)

    @property
    def active(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._active) if self._active else None

    # -- the scheduling tick ---------------------------------------------
    def tick(self, plane: Any, idle_share: float) -> Optional[str]:
        """One scheduling decision against the target model's lifecycle
        plane. Returns the action taken ("launch"/"shed"/"promoted"/
        "rolled_back") or None. Never raises — a failing candidate is a
        journaled rollback, not a serving failure."""
        if plane is None:
            return None
        with self._lock:
            active = self._active
        if active is not None:
            return self._settle_or_shed(plane, active, idle_share)
        return self._maybe_launch(plane, idle_share)

    def _settle_or_shed(self, plane: Any, active: Dict[str, Any],
                        idle_share: float) -> Optional[str]:
        try:
            ver = plane.registry.get(active["version"])
        except KeyError:
            with self._lock:
                self._active = None
            return None
        wall = round(self._clock() - active["t0"], 3)
        if ver.state == LIVE:
            with self._lock:
                self._active = None
                self.trials_promoted += 1
            self._log("promoted", version=ver.version,
                      params=active["params"], wall_s=wall)
            return "promoted"
        if ver.state == ROLLED_BACK:
            with self._lock:
                self._active = None
                self.trials_rolled_back += 1
            self._log("rolled_back", version=ver.version,
                      params=active["params"], wall_s=wall)
            return "rolled_back"
        if idle_share < self.shed_idle_share and \
                ver.state in (SHADOWING, CANARY):
            # real traffic reclaimed the capacity: shed the trial NOW —
            # the plane's public rollback, with the reclaim on the record
            try:
                plane.controller.rollback(ver, "traffic_reclaim",
                                          idle_share=round(idle_share, 4))
            except Exception:  # noqa: BLE001 — shedding must not raise
                pass
            with self._lock:
                self._active = None
                self.trials_shed += 1
            self._log("shed", version=ver.version, params=active["params"],
                      idle_share=round(idle_share, 4), wall_s=wall)
            return "shed"
        return None

    def _maybe_launch(self, plane: Any, idle_share: float) -> Optional[str]:
        with self._lock:
            if self._exhausted or self.trials_started >= self.max_trials:
                return None
        if idle_share < self.min_idle_share:
            return None
        # the plane runs one rollout at a time; respect an operator rollout
        if plane.controller.active_version() is not None:
            return None
        params = next(self._params, None)
        if params is None:
            with self._lock:
                self._exhausted = True
            self._log("exhausted", trials=self.trials_started)
            return None
        with self._lock:
            self.trials_started += 1
            n = self.trials_started
        version = f"{self.version_prefix}{n}"
        try:
            transform = self.build(params)
            ver = plane.deploy(transform, version=version)
        except Exception as e:  # noqa: BLE001 — a broken candidate is
            # search evidence, not a serving failure
            self._log("launch_failed", version=version, params=params,
                      error=str(e)[:200])
            return None
        with self._lock:
            self._active = {"version": ver.version, "params": params,
                            "t0": self._clock(),
                            "idle_share": round(idle_share, 4)}
        self._log("launch", version=ver.version, params=params,
                  idle_share=round(idle_share, 4))
        return "launch"

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {"trials_started": self.trials_started,
                    "trials_promoted": self.trials_promoted,
                    "trials_shed": self.trials_shed,
                    "trials_rolled_back": self.trials_rolled_back,
                    "max_trials": self.max_trials,
                    "exhausted": self._exhausted,
                    "min_idle_share": self.min_idle_share,
                    "shed_idle_share": self.shed_idle_share,
                    "model": self.model,
                    "active": dict(self._active) if self._active else None,
                    "journal": list(self.journal[-16:])}


def make_automl(spec: Any,
                clock: Callable[[], float] = time.monotonic
                ) -> Optional[AutoMLScheduler]:
    """Coerce the mall's ``automl`` knob: None/False -> off, dict ->
    AutoMLScheduler kwargs (``grid`` + ``build`` required), a built
    scheduler passes through."""
    if spec is None or spec is False:
        return None
    if isinstance(spec, AutoMLScheduler):
        return spec
    if isinstance(spec, dict):
        return AutoMLScheduler(clock=clock, **spec)
    raise TypeError(f"automl: cannot coerce {type(spec).__name__}")
