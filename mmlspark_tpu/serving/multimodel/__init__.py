"""Dense multi-model serving: the ModelMall plane (docs/multimodel.md).

One worker, N independent fitted pipelines behind the existing substrate:
per-model lifecycle planes routed by ``X-MMLSpark-Model``, cost-packed
onto replicas, brownout-aware eviction to the persistent tier, and AutoML
trials scheduled onto idle capacity.
"""

from .mall import (MODEL_HEADER, MallConfig, ModelMall, make_multimodel,
                   model_from_body)
from .automl import AutoMLScheduler, make_automl

__all__ = ["MODEL_HEADER", "MallConfig", "ModelMall", "make_multimodel",
           "model_from_body", "AutoMLScheduler", "make_automl"]
