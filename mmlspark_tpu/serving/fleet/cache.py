"""Persistent, content-addressed compile cache — the cross-process tier
under the in-process LRU ``CompileCache`` (core/device_stage.py).

Why: every new replica/pod recompiles every (segment, bucket) signature
from scratch, so at fleet scale every scale-out event is a self-inflicted
compile-latency storm. TVM's answer (PAPERS.md) is to ship the tuned,
compiled artifact to new workers instead of re-learning it per worker;
this module is that answer for fused XLA executables.

Entry format (one file per signature, ``<digest>.mmlc``)::

    MAGIC (6 bytes) | header length (8 bytes, big-endian) | header JSON
    | payload (pickled ``serialize_executable.serialize`` triple, or
      empty for cost-only entries)

The content key (``content_key``) is a sha256 over the canonical repr of
the in-process cache key — (segment graph key, shape-bucket signature,
dtypes) — joined with the environment fingerprint (jax version, backend,
format version). Anything that changes what XLA would compile changes the
digest, so a foreign-version entry is simply never looked up AND is
rejected again at load time by the header fingerprint (defense in depth:
a digest collision or a hand-copied file still can't smuggle a stale
executable in).

Degradation contract (chaos-tested, tests/test_faults.py):

  - a truncated / corrupted / foreign-version / unpicklable entry
    degrades to an accounted recompile (``load_errors`` counter, never a
    crash);
  - a store failure (full volume, readonly mount, injected fault) never
    blocks or fails the serving path (``store_errors`` counter);
  - an executable that this jax cannot serialize falls back to persisting
    only the harvested cost record and the live tuner knobs
    (``kind="costs"``), which still warm the cost model — the planner and
    tuner start calibrated even when the executable itself can't travel.

Fault points: ``compilecache.load`` / ``compilecache.store``
(core/faults.py) fire before the read and the atomic write respectively.
"""

from __future__ import annotations

import ast
import errno
import hashlib
import io
import json
import logging
import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...core import faults
from . import objstore as _objstore

_LOG = logging.getLogger(__name__)

#: on-disk format version — bump on any layout change; mismatched entries
#: are skipped (never parsed further)
FORMAT = 1
MAGIC = b"MMLC1\n"
_HEADER_LEN_BYTES = 8
#: entry file suffix (mmlspark compiled)
SUFFIX = ".mmlc"


def _canon(obj: Any) -> str:
    """Deterministic textual form of a cache key: primitives and (nested)
    tuples/lists render via repr, anything else via its type+repr — stable
    across processes for the primitive-only keys fusion actually builds."""
    return repr(obj)


def env_fingerprint(mesh: Any = None) -> Dict[str, Any]:
    """What must match for a persisted executable to be loadable here:
    jax/jaxlib version, the default backend, the device count, and the
    MESH TOPOLOGY (axis names + sizes + device kind) executables shard
    over. A GSPMD-partitioned executable hard-codes its mesh shape — warm
    loading one onto a different mesh would dispatch garbage, so the
    topology is part of the content address: a mismatched entry is simply
    never found (clean miss -> recompile), not detected after the fact.
    Import-gated — without jax the fingerprint still exists (cost-only
    entries remain usable).

    ``mesh``: the mesh the owning model shards over; when None the ambient
    ``MeshContext`` (parallel/mesh.py) is consulted, falling back to
    ``"none"`` (the single-device fingerprint, unchanged semantics)."""
    fp: Dict[str, Any] = {"format": FORMAT}
    try:
        import jax

        fp["jax"] = str(jax.__version__)
        fp["backend"] = str(jax.default_backend())
        fp["devices"] = int(jax.device_count())
    except Exception:  # noqa: BLE001 — host-only installs still fingerprint
        fp["jax"] = "none"
        fp["backend"] = "none"
        fp["devices"] = 0
    try:
        if mesh is None:
            from ...parallel.mesh import MeshContext

            mesh = MeshContext.current()
        from ...parallel.shardplan import mesh_topology

        fp["mesh"] = mesh_topology(mesh)
    except Exception:  # noqa: BLE001 — no mesh machinery: single-device
        fp["mesh"] = "none"
    try:
        shape = dict(getattr(mesh, "shape", {}) or {})
        p = int(shape.get("pipe", 1))
        if p > 1:
            # pipelined executables compile per-STAGE on a pipe sub-mesh
            # (parallel/pipeplan.py pipe_submeshes): a stage keeps every
            # non-pipe axis and owns a slice of the pipe axis, so the
            # layout a stage executable hard-codes is (non-pipe shape,
            # pipe extent). Folding that in makes a different pipe layout
            # a clean counted miss. The key exists ONLY when the mesh has
            # a pipe axis to split: every non-pipe fingerprint — and so
            # every pre-pipeline content address — stays byte-identical.
            fp["pipe_submesh"] = ";".join(
                f"{a}={int(shape.get(a, 1))}"
                for a in ("data", "fsdp", "tensor", "seq", "expert")
            ) + f";pipe={p}"
    except Exception:  # noqa: BLE001 — shape-less mesh object
        pass
    return fp


def content_key(key: Any, fp: Optional[Dict[str, Any]] = None) -> str:
    """sha256 content hash of (cache key, environment fingerprint) — the
    entry's filename stem. The in-process key already encodes the segment
    graph identity, the bucketed batch shape, and the dtypes (core/fusion
    ``(seg.key, sig)``); the fingerprint folds in jax/backend/format."""
    fp = fp if fp is not None else env_fingerprint()
    h = hashlib.sha256()
    h.update(_canon(key).encode("utf-8"))
    h.update(json.dumps(fp, sort_keys=True).encode("utf-8"))
    return h.hexdigest()


def _serialize_executable(fn: Any) -> Optional[bytes]:
    """Pickle the AOT executable's portable triple, or None when this jax
    (or this executable — e.g. the lazy ``jitted`` fallback the builder
    returns when ``lower().compile()`` is unavailable) can't serialize."""
    try:
        from jax.experimental import serialize_executable as se
    except Exception:  # noqa: BLE001 — older/stripped jax: cost-only tier
        return None
    try:
        triple = se.serialize(fn)
        return pickle.dumps(triple)
    except Exception:  # noqa: BLE001 — unserializable executable
        return None


def _deserialize_executable(payload: bytes) -> Any:
    from jax.experimental import serialize_executable as se

    serialized, in_tree, out_tree = pickle.loads(payload)
    return se.deserialize_and_load(serialized, in_tree, out_tree)


class PersistentCompileCache:
    """Directory-backed second tier for ``CompileCache`` (one file per
    signature; the directory is the shared volume / object-store mount).

    ``write=False`` makes the tier read-only (consume a fleet-shared
    cache without contributing — e.g. canary pods). ``knobs_provider``
    (a zero-arg callable returning a dict) snapshots the live tuner knobs
    into every stored entry, so a cost-only entry still carries the tuned
    configuration to the next pod.

    Thread contract: counters live under ``_lock``; file I/O and
    (de)serialization always run OUTSIDE it.
    """

    def __init__(self, path: str, write: bool = True,
                 knobs_provider: Optional[Callable[[], dict]] = None,
                 mesh: Any = None, store: Any = None):
        self.path = str(path)
        self.write = bool(write)
        self.knobs_provider = knobs_provider
        #: optional object-store backend (fleet/objstore.py): entry and
        #: snapshot I/O route through ``store.put``/``store.get`` instead
        #: of the local directory — same format, same degrade contract
        self._store = _objstore.make_store(store)
        # ``mesh`` pins the topology the fingerprint carries (the owning
        # model's shard mesh); default resolves the ambient MeshContext
        self._fp = env_fingerprint(mesh=mesh)
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.store_skips = 0      # already present / not serializable+empty
        self.costs_only = 0       # entries persisted/loaded without payload
        self.load_errors = 0
        self.store_errors = 0
        self.write_degrades = 0   # ENOSPC flips to accounted read-only
        self.snapshots = 0        # knob-shipping snapshots written
        self._enospc_logged = False
        self._last_snapshot_blob: Optional[bytes] = None
        self.load_s = 0.0
        self.store_s = 0.0
        #: cost records recovered from cost-only entries at warm time:
        #: {label: {shape: record}} — SegmentCostModel.ingest_costs shape
        self._cost_records: Dict[str, Dict[str, Dict[str, Any]]] = {}
        #: last knobs dict seen in a warmed entry (newest mtime wins)
        self.loaded_knobs: Optional[Dict[str, Any]] = None
        if self.write and self._store is None:
            try:
                os.makedirs(self.path, exist_ok=True)
            except OSError:
                # unwritable mount: degrade to read-only, don't crash the
                # server constructor
                self.write = False

    # -- entry I/O ---------------------------------------------------------

    def _file_for(self, digest: str) -> str:
        return os.path.join(self.path, digest + SUFFIX)

    def _load_blob(self, name: str) -> Optional[bytes]:
        """One object's raw bytes by flat name (``<digest>.mmlc`` or the
        snapshot key) — via the object store when attached, else the local
        directory. ``None`` when absent; backend errors raise (accounted
        by the caller, degrading to recompile)."""
        if self._store is not None:
            return self._store.get(name)
        try:
            with open(os.path.join(self.path, name), "rb") as fh:
                return fh.read()
        except FileNotFoundError:
            return None

    def _write_blob(self, name: str, blob: bytes) -> None:
        if self._store is not None:
            self._store.put(name, blob)
        else:
            faults.atomic_write_bytes(os.path.join(self.path, name), blob)

    def _has_entry(self, name: str) -> bool:
        if self._store is not None:
            return self._store.has(name)
        return os.path.exists(os.path.join(self.path, name))

    def _entry_names(self) -> List[str]:
        if self._store is not None:
            try:
                return sorted(self._store.list(SUFFIX))
            except Exception:  # noqa: BLE001 — unlistable remote tier
                return []
        try:
            return sorted(n for n in os.listdir(self.path)
                          if n.endswith(SUFFIX))
        except OSError:
            return []

    def _read_entry(self, path: str
                    ) -> Tuple[Dict[str, Any], Optional[bytes]]:
        """Parse one entry by path -> (header, payload or None). Raises on
        any corruption; callers account and degrade."""
        blob = self._load_blob(os.path.basename(path))
        if blob is None:
            raise FileNotFoundError(path)
        return self._parse_entry(blob)

    def _parse_entry(self, blob: bytes
                     ) -> Tuple[Dict[str, Any], Optional[bytes]]:
        buf = io.BytesIO(blob)
        if buf.read(len(MAGIC)) != MAGIC:
            raise ValueError("bad magic")
        hlen = int.from_bytes(buf.read(_HEADER_LEN_BYTES), "big")
        if hlen <= 0 or hlen > len(blob):
            raise ValueError("bad header length")
        header = json.loads(buf.read(hlen).decode("utf-8"))
        payload = buf.read()
        if header.get("kind") == "exec":
            want = header.get("payload_sha256")
            if want != hashlib.sha256(payload).hexdigest():
                raise ValueError("payload digest mismatch (truncated?)")
        else:
            payload = None
        for k, v in self._fp.items():
            if header.get("env", {}).get(k) != v:
                raise ValueError(
                    f"environment mismatch on {k!r}: entry "
                    f"{header.get('env', {}).get(k)!r} != local {v!r}")
        return header, payload

    def _write_entry(self, path: str, header: Dict[str, Any],
                     payload: bytes) -> None:
        hjson = json.dumps(header, sort_keys=True).encode("utf-8")
        blob = MAGIC + len(hjson).to_bytes(_HEADER_LEN_BYTES, "big") \
            + hjson + payload
        self._write_blob(os.path.basename(path), blob)

    def _note_write_failure(self, e: BaseException) -> None:
        """Account one failed write; ENOSPC additionally flips the tier to
        read-only (logged once) — a full cache volume must never crash or
        spam the serving loop (docs/faults.md disk-full contract)."""
        with self._lock:
            self.store_errors += 1
            if getattr(e, "errno", None) != errno.ENOSPC:
                return
            self.write = False
            self.write_degrades += 1
            logged = self._enospc_logged
            self._enospc_logged = True
        if not logged:
            _LOG.warning("persistent compile-cache volume full (ENOSPC): "
                         "degrading to read-only mode")

    # -- the CompileCache tier protocol ------------------------------------

    def load(self, key: Any, label: Optional[str] = None,
             shape: Optional[str] = None
             ) -> Optional[Tuple[Any, Optional[Dict[str, Any]]]]:
        """Look the live key up in the persistent tier. Returns
        ``(executable, cost_record)`` on a hit, None on miss OR any error
        (corruption, version skew, injected fault) — the caller recompiles
        and the failure is an accounted counter, never an exception."""
        digest = content_key(key, self._fp)
        name = digest + SUFFIX
        t0 = time.perf_counter()
        try:
            faults.fire(faults.COMPILECACHE_LOAD, key=digest, label=label)
            blob = self._load_blob(name)
            if blob is None:
                with self._lock:
                    self.misses += 1
                return None
            header, payload = self._parse_entry(blob)
            if header.get("kind") != "exec" or payload is None:
                # cost-only entry: nothing to execute, but the harvested
                # cost still warms the model
                self._absorb_costs(header)
                with self._lock:
                    self.costs_only += 1
                    self.misses += 1
                return None
            fn = _deserialize_executable(payload)
        except Exception as e:  # noqa: BLE001 — degrade to recompile
            _LOG.warning("persistent compile-cache load failed for %s: %s",
                         digest[:12], e)
            with self._lock:
                self.load_errors += 1
                self.misses += 1
            return None
        dt = time.perf_counter() - t0
        with self._lock:
            self.hits += 1
            self.load_s += dt
        return fn, header.get("cost")

    def store(self, key: Any, fn: Any,
              cost: Optional[Dict[str, Any]] = None,
              label: Optional[str] = None,
              shape: Optional[str] = None) -> bool:
        """Persist one freshly-compiled executable (or, when it can't
        serialize, its cost record + live knobs). Fire-and-forget: every
        failure is a counter, never an exception into the serving path."""
        if not self.write:
            return False
        digest = content_key(key, self._fp)
        name = digest + SUFFIX
        t0 = time.perf_counter()
        try:
            faults.fire(faults.COMPILECACHE_STORE, key=digest, label=label)
            if self._has_entry(name):
                with self._lock:
                    self.store_skips += 1
                return False
            payload = _serialize_executable(fn)
            kind = "exec" if payload is not None else "costs"
            knobs = None
            if self.knobs_provider is not None:
                try:
                    knobs = self.knobs_provider()
                except Exception:  # noqa: BLE001 — knobs are best-effort
                    knobs = None
            header = {
                "kind": kind,
                "env": dict(self._fp),
                "key_repr": _canon(key),
                "label": label,
                "shape": shape,
                "cost": dict(cost or {}) or None,
                "knobs": knobs,
                "payload_sha256": hashlib.sha256(
                    payload).hexdigest() if payload is not None else None,
            }
            self._write_entry(self._file_for(digest), header, payload or b"")
        except Exception as e:  # noqa: BLE001 — never block serving
            _LOG.warning("persistent compile-cache store failed for %s: %s",
                         digest[:12], e)
            self._note_write_failure(e)
            return False
        dt = time.perf_counter() - t0
        with self._lock:
            self.stores += 1
            self.store_s += dt
            if kind == "costs":
                self.costs_only += 1
        return True

    # -- pod-start AOT warm -------------------------------------------------

    def warm(self, cache: Any, limit: Optional[int] = None
             ) -> Dict[str, int]:
        """Preload every compatible persisted executable into the
        in-process ``CompileCache`` (``cache.preload`` — no miss/compile
        accounting), so a fresh replica's first request for a
        previously-seen signature is a plain memory hit with zero jit
        compiles. Cost-only entries warm ``harvested_costs()`` /
        ``loaded_knobs`` instead. Every per-entry failure is counted and
        skipped — a corrupted fleet cache can only make warm-up smaller,
        never fail pod start."""
        out = {"warmed": 0, "costs_only": 0, "skipped": 0, "errors": 0}
        names = self._entry_names()
        for name in names:
            if limit is not None and out["warmed"] >= limit:
                break
            try:
                faults.fire(faults.COMPILECACHE_LOAD, key=name)
                blob = self._load_blob(name)
                if blob is None:
                    out["skipped"] += 1
                    continue
                header, payload = self._parse_entry(blob)
                if header.get("kind") != "exec" or payload is None:
                    self._absorb_costs(header)
                    out["costs_only"] += 1
                    continue
                key = self._key_of(header)
                if key is None:
                    # non-literal key: not warmable by name, but still
                    # lazily loadable at get() time (digest from live key)
                    out["skipped"] += 1
                    continue
                fn = _deserialize_executable(payload)
                if cache.preload(key, fn, label=header.get("label"),
                                 shape=header.get("shape"),
                                 cost=header.get("cost")):
                    out["warmed"] += 1
                else:
                    out["skipped"] += 1
                self._absorb_costs(header)
            except Exception as e:  # noqa: BLE001 — warm must not fail start
                _LOG.warning("skipping persisted entry %s: %s", name, e)
                with self._lock:
                    self.load_errors += 1
                out["errors"] += 1
        return out

    @staticmethod
    def _key_of(header: Dict[str, Any]) -> Optional[Any]:
        """Reconstruct the in-process cache key from its stored canonical
        repr. Only literal keys (tuples/strings/numbers — what fusion
        builds) round-trip; anything else returns None."""
        try:
            key = ast.literal_eval(header.get("key_repr") or "")
        except (ValueError, SyntaxError):
            return None
        return key

    def _absorb_costs(self, header: Dict[str, Any]) -> None:
        """Fold one entry's cost record / knobs into the warm-time side
        channels the cost model and tuner consume."""
        label, shape = header.get("label"), header.get("shape")
        cost = header.get("cost")
        with self._lock:
            if label and shape and isinstance(cost, dict):
                self._cost_records.setdefault(
                    str(label), {})[str(shape)] = dict(cost)
            knobs = header.get("knobs")
            if isinstance(knobs, dict) and knobs:
                self.loaded_knobs = dict(knobs)

    def harvested_costs(self) -> Dict[str, Dict[str, Dict[str, Any]]]:
        """{label: {shape: cost record}} recovered from persisted entries
        — the ``SegmentCostModel.ingest_costs`` shape, so a fresh pod's
        cost model starts calibrated from the fleet's measurements."""
        with self._lock:
            return {lab: {shp: dict(rec) for shp, rec in by.items()}
                    for lab, by in self._cost_records.items()}

    # -- knob shipping (fleet/objstore.py snapshot format) ------------------

    def put_snapshot(self, knobs: Optional[Dict[str, Any]] = None,
                     capacity_plan: Optional[Dict[str, Any]] = None) -> bool:
        """Ship the live tuning state: one canonical-JSON snapshot of the
        tuner's ``KnobSet`` and the controller's capacity plan, stored
        alongside the executables. Byte-identical snapshots are skipped
        (safe to call on every plan tick); failures degrade exactly like
        entry stores — accounted, ENOSPC flips read-only, never a raise."""
        if not self.write:
            return False
        blob = _objstore.snapshot_blob(knobs=knobs,
                                       capacity_plan=capacity_plan,
                                       env=dict(self._fp))
        with self._lock:
            if blob == self._last_snapshot_blob:
                return False
        try:
            self._write_blob(_objstore.SNAPSHOT_KEY, blob)
        except Exception as e:  # noqa: BLE001 — never block serving
            _LOG.warning("knob-snapshot store failed: %s", e)
            self._note_write_failure(e)
            return False
        with self._lock:
            self._last_snapshot_blob = blob
            self.snapshots += 1
        return True

    def load_snapshot(self) -> Optional[Dict[str, Any]]:
        """The shipped tuning snapshot (``{"knobs": ..., "capacity_plan":
        ..., "env": ...}``), or None when absent/corrupt/foreign-format —
        the pod then simply relearns, the PR 13 degrade contract."""
        try:
            blob = self._load_blob(_objstore.SNAPSHOT_KEY)
        except Exception as e:  # noqa: BLE001 — degrade to relearning
            _LOG.warning("knob-snapshot load failed: %s", e)
            with self._lock:
                self.load_errors += 1
            return None
        snap = _objstore.parse_snapshot(blob)
        if blob is not None and snap is None:
            with self._lock:
                self.load_errors += 1
        return snap

    # -- introspection ------------------------------------------------------

    def entry_count(self) -> int:
        return len(self._entry_names())

    def stats(self) -> Dict[str, Any]:
        entries = self.entry_count()  # listdir outside the counter lock
        with self._lock:
            total = self.hits + self.misses
            return {
                "path": self.path,
                "write": self.write,
                "entries": entries,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else None,
                "stores": self.stores,
                "store_skips": self.store_skips,
                "costs_only": self.costs_only,
                "load_errors": self.load_errors,
                "store_errors": self.store_errors,
                "write_degrades": self.write_degrades,
                "snapshots": self.snapshots,
                "load_s": round(self.load_s, 6),
                "store_s": round(self.store_s, 6),
                "env": dict(self._fp),
                "store": (self._store.stats()
                          if self._store is not None else None),
            }
