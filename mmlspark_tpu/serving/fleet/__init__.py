"""Fleet control plane: everything a POD FLEET needs that one process
doesn't (ROADMAP "Planet-scale serving").

Three cooperating parts, each usable alone:

  - ``cache``      persistent, content-addressed compile cache: a second
                   tier under the in-process ``CompileCache`` that ships
                   serialized AOT executables between pods, so a fresh
                   replica answers its first request with zero jit
                   compiles for previously-seen (segment, bucket)
                   signatures (the TVM move: ship the tuned artifact, do
                   not re-learn it per worker).
  - ``planner``    capacity planner: inverts the calibrated
                   SegmentCostModel — arrival-rate forecast in, the
                   (replicas, inflight, bucket, mega_k) config that meets
                   the SLO at minimum capacity out. Pure and journaled.
  - ``controller`` autoscale controller: one loop consuming the
                   multi-window SLO burn rates; BrownoutController is the
                   fast path (degrade in-place NOW), the planner's
                   scale-out/in recommendation the slow path (hysteretic,
                   journaled, one-step rollback like the Tuner). Publishes
                   the cross-pod recommendation at ``/_mmlspark/capacity``
                   for helm HPA / an external scaler.
  - ``objstore``   object-store artifact tier under the persistent cache:
                   put/get backends (local-dir reference impl + injectable
                   remote stub) that detach executable survival from the
                   pod-local disk, and the knob-shipping snapshot format
                   (KnobSet + capacity plan) that lets a fresh pod start
                   tuned with zero relearning.

See docs/fleet.md for the cache key contract, the planner math, and the
controller state machine; docs/front_fabric.md for the object-store
interface and the knob-shipping format.
"""

from .cache import PersistentCompileCache, content_key
from .controller import FleetController, FleetSpec, make_fleet
from .objstore import (CallbackStore, LocalDirStore, ObjectStore,
                       make_store)
from .planner import (CapacityPlan, CapacityPlanner, ModelDemand,
                      PackingPlan, PackingPlanner, PlannerConfig,
                      forecast_rps, pack_models, plan_capacity)

__all__ = [
    "PersistentCompileCache", "content_key",
    "CapacityPlan", "CapacityPlanner", "PlannerConfig",
    "ModelDemand", "PackingPlan", "PackingPlanner", "pack_models",
    "forecast_rps", "plan_capacity",
    "FleetController", "FleetSpec", "make_fleet",
    "ObjectStore", "LocalDirStore", "CallbackStore", "make_store",
]
