"""Object-store artifact tier: put/get backends under the persistent cache.

PR 13's ``PersistentCompileCache`` ties compiled-executable survival to the
pod-local disk; this module detaches it. The cache's entries are already
content-addressed (``<sha256>.mmlc``), which is exactly an object-store
key space, so the tier is a minimal put/get interface:

  - ``LocalDirStore``  — the reference implementation (atomic writes into
    one directory); doubles as the test double for remote stores.
  - ``CallbackStore``  — the injectable remote stub: wrap any client's
    callables (GCS/S3/...) without this framework importing their SDKs.

Both fire the ``store.put`` / ``store.get`` fault points before touching
the backend, so chaos plans exercise the real degrade paths: a failing put
flips the cache to accounted read-only mode; a failing or corrupted get is
an accounted recompile — serving never stops for the artifact tier.

The tier also ships tuning state: :func:`put_snapshot` / :func:`get_snapshot`
store a JSON snapshot of the live ``KnobSet`` and capacity plan alongside
the executables, so a fresh pod warm-starts on the tuned buckets / mega-K /
sharding / kernel variants with zero relearning (docs/front_fabric.md,
"Knob shipping").
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Callable, Dict, List, Optional

from ...core import faults

logger = logging.getLogger(__name__)

#: the well-known key carrying the shipped KnobSet + capacity plan
SNAPSHOT_KEY = "knobs-snapshot.json"
#: snapshot wire format version (bump on incompatible change)
SNAPSHOT_FORMAT = 1


class ObjectStore:
    """Minimal put/get artifact store. Subclasses implement ``_do_*``; the
    public methods fire the fault points and keep op/error/byte counters
    (the ``mmlspark_store_*`` metric families). Errors re-raise so the
    caller (the persistent cache) applies its own degrade accounting."""

    name = "objstore"

    def __init__(self) -> None:
        self.puts = 0
        self.gets = 0
        self.put_errors = 0
        self.get_errors = 0
        self.bytes_put = 0
        self.bytes_got = 0
        self._lock = threading.Lock()

    # -- public API ---------------------------------------------------------

    def put(self, key: str, data: bytes) -> None:
        try:
            faults.fire(faults.STORE_PUT, key=key, store=self.name,
                        n_bytes=len(data))
            self._do_put(key, bytes(data))
        except Exception:
            with self._lock:
                self.put_errors += 1
            raise
        with self._lock:
            self.puts += 1
            self.bytes_put += len(data)

    def get(self, key: str) -> Optional[bytes]:
        """The object's bytes, or ``None`` when absent. Backend errors and
        injected ``store.get`` faults raise (accounted, then degraded to
        recompile by the cache)."""
        try:
            faults.fire(faults.STORE_GET, key=key, store=self.name)
            blob = self._do_get(key)
        except Exception:
            with self._lock:
                self.get_errors += 1
            raise
        if blob is not None:
            with self._lock:
                self.gets += 1
                self.bytes_got += len(blob)
        return blob

    def has(self, key: str) -> bool:
        return self._do_has(key)

    def list(self, suffix: str = "") -> List[str]:
        return self._do_list(suffix)

    def delete(self, key: str) -> None:
        self._do_delete(key)

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {"store": self.name, "puts": self.puts, "gets": self.gets,
                    "put_errors": self.put_errors,
                    "get_errors": self.get_errors,
                    "bytes_put": self.bytes_put,
                    "bytes_got": self.bytes_got}

    # -- backend seams ------------------------------------------------------

    def _do_put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def _do_get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def _do_has(self, key: str) -> bool:
        return self._do_get(key) is not None

    def _do_list(self, suffix: str) -> List[str]:
        raise NotImplementedError

    def _do_delete(self, key: str) -> None:
        raise NotImplementedError


def _safe_key(key: str) -> str:
    if not key or os.sep in key or key.startswith("."):
        raise ValueError("object keys are flat names, got %r" % (key,))
    return key


class LocalDirStore(ObjectStore):
    """Reference backend: one flat directory, atomic durable writes (tmp +
    fsync + rename, the journal compactor's idiom) so a crashed put never
    leaves a torn object for a later get to trip on."""

    name = "localdir"

    def __init__(self, root: str):
        super().__init__()
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, _safe_key(key))

    def _do_put(self, key: str, data: bytes) -> None:
        faults.atomic_write_bytes(self._path(key), data)

    def _do_get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as fh:
                return fh.read()
        except FileNotFoundError:
            return None

    def _do_has(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def _do_list(self, suffix: str) -> List[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(n for n in names if n.endswith(suffix))

    def _do_delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass


class CallbackStore(ObjectStore):
    """The injectable remote stub: adapt any object-store client by passing
    its callables. ``get_fn`` must return ``None`` for a missing key;
    ``has_fn``/``list_fn``/``delete_fn`` are optional (``has`` falls back
    to a get, ``list`` to empty — a remote tier that cannot enumerate still
    serves point lookups, and ``warm()`` simply finds nothing to preload)."""

    name = "callback"

    def __init__(self, put_fn: Callable[[str, bytes], None],
                 get_fn: Callable[[str], Optional[bytes]],
                 list_fn: Optional[Callable[[str], List[str]]] = None,
                 delete_fn: Optional[Callable[[str], None]] = None,
                 has_fn: Optional[Callable[[str], bool]] = None):
        super().__init__()
        self._put_fn = put_fn
        self._get_fn = get_fn
        self._list_fn = list_fn
        self._delete_fn = delete_fn
        self._has_fn = has_fn

    def _do_put(self, key: str, data: bytes) -> None:
        self._put_fn(key, data)

    def _do_get(self, key: str) -> Optional[bytes]:
        return self._get_fn(key)

    def _do_has(self, key: str) -> bool:
        if self._has_fn is not None:
            return bool(self._has_fn(key))
        return self._do_get(key) is not None

    def _do_list(self, suffix: str) -> List[str]:
        if self._list_fn is None:
            return []
        return [n for n in self._list_fn(suffix) if n.endswith(suffix)]

    def _do_delete(self, key: str) -> None:
        if self._delete_fn is not None:
            self._delete_fn(key)


def make_store(store) -> Optional[ObjectStore]:
    """Coerce a ``store=`` argument: ``None`` off, a path string becomes a
    ``LocalDirStore``, a ready ``ObjectStore`` passes through."""
    if store is None:
        return None
    if isinstance(store, ObjectStore):
        return store
    if isinstance(store, str):
        return LocalDirStore(store)
    raise TypeError("store must be None/path/ObjectStore, got %r" % (store,))


# ---------------------------------------------------------------------------
# Knob shipping: KnobSet + capacity plan snapshots
# ---------------------------------------------------------------------------

def snapshot_blob(knobs: Optional[Dict[str, object]] = None,
                  capacity_plan: Optional[Dict[str, object]] = None,
                  env: Optional[Dict[str, object]] = None) -> bytes:
    """Serialize a knob-shipping snapshot (canonical JSON: byte-stable for
    the change-detection skip in ``PersistentCompileCache.put_snapshot``)."""
    payload = {"format": SNAPSHOT_FORMAT, "knobs": knobs or None,
               "capacity_plan": capacity_plan or None, "env": env or None}
    return json.dumps(payload, sort_keys=True).encode("utf-8")


def parse_snapshot(blob: Optional[bytes]) -> Optional[Dict[str, object]]:
    """Decode a snapshot blob; ``None`` on absence, corruption or a foreign
    format version (degrade to relearning, never raise)."""
    if blob is None:
        return None
    try:
        payload = json.loads(blob.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(payload, dict):
        return None
    if payload.get("format") != SNAPSHOT_FORMAT:
        return None
    return payload
