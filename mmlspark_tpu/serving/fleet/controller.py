"""Autoscale controller: one coordinated loop over burn rate and plan.

The ROADMAP's complaint was that every layer reacts on its own — brownout
degrades, the tuner retunes, and nothing SCALES. This controller is the
coordination point:

  fast path   the BrownoutController (serving/supervisor.py) keeps
              degrading in-place the moment the short burn window fires —
              this controller never blocks or duplicates it, it only
              OBSERVES brownout state (scaling decisions freeze while a
              brownout is active: capacity math measured during
              degradation is polluted).
  slow path   multi-window burn rates + the arrival forecast feed the
              CapacityPlanner; the plan's in-process knobs (inflight,
              mega_k) apply LIVE through hooks onto the executor / fused
              model, and the cross-pod knob (replicas) publishes as a
              recommendation at ``/_mmlspark/capacity`` for helm HPA /
              an external scaler — this process cannot start pods.

State machine (docs/fleet.md "Controller state machine")::

    steady --plan wants more, N_out consecutive--> scale_out --apply-->
        watch --regression--> rollback --> cooldown --> steady
              --clean-------> steady
    steady --plan wants less, N_in consecutive + hold--> scale_in (same
        watch/rollback path; scale-in is deliberately slower than
        scale-out: under-capacity burns SLO, over-capacity burns money)
    any    --brownout active--> degraded (observe only) --> steady

Apply semantics mirror the Tuner (core/tune.py): every apply journals
{before, after, plan}, keeps exactly one ``_prev`` snapshot, and a
measured e2e regression beyond ``regress_pct`` during the watch window
rolls back one step and enters a veto cooldown.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .planner import CapacityPlanner, PlannerConfig, forecast_rps


class FleetSpec:
    """Controller knobs (coerced from the ``fleet=`` dict by
    ``make_fleet``). Defaults are deliberately conservative: plan every
    5s, two agreeing plans to scale out, five + a hold to scale in."""

    def __init__(self, tick_s: float = 1.0, plan_every_s: float = 5.0,
                 consecutive_out: int = 2, consecutive_in: int = 5,
                 hold_s: float = 30.0, regress_pct: float = 0.15,
                 watch_batches: int = 20, cooldown_s: float = 30.0,
                 forecast_horizon_s: float = 60.0,
                 journal_cap: int = 256):
        self.tick_s = float(tick_s)
        self.plan_every_s = float(plan_every_s)
        self.consecutive_out = max(1, int(consecutive_out))
        self.consecutive_in = max(1, int(consecutive_in))
        self.hold_s = float(hold_s)
        self.regress_pct = float(regress_pct)
        self.watch_batches = max(1, int(watch_batches))
        self.cooldown_s = float(cooldown_s)
        self.forecast_horizon_s = float(forecast_horizon_s)
        self.journal_cap = int(journal_cap)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)


class FleetController:
    """The coordinated autoscale loop. ``tick(e2e_s)`` is the per-batch
    heartbeat (rate-limited internally, cheap no-op on the hot path);
    ``summary()`` is the ``/_mmlspark/capacity`` payload.

    ``hooks`` late-bind the live layers (set in server.start()):
      - ``live_config()``      -> {replicas, inflight, mega_k, bucket}
      - ``set_inflight(n)``    pipelined executor depth, applied live
      - ``set_mega_k(k)``      fused model's K-step dispatch factor
      - ``arrival_buckets()``  SLOTracker per-second (sec, total, bad)
                               triples feeding the forecast

    Lock contract: controller state under ``_lock``; hooks ALWAYS run
    outside it (they take executor/model locks of their own — the same
    C002 hygiene the brownout steps follow)."""

    def __init__(self, planner: CapacityPlanner,
                 spec: Optional[FleetSpec] = None,
                 slo: Any = None, brownout: Any = None,
                 hooks: Optional[Dict[str, Callable]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.planner = planner
        self.spec = spec if spec is not None else FleetSpec()
        self.slo = slo
        self.brownout = brownout
        self.hooks: Dict[str, Callable] = dict(hooks or {})
        self._clock = clock
        self._lock = threading.Lock()
        self.state = "steady"
        self._last_tick = 0.0
        self._last_plan = 0.0
        self._last_apply = 0.0
        self._cooldown_until = 0.0
        self._agree_out = 0
        self._agree_in = 0
        self._e2e_ewma: Optional[float] = None
        # regression watch (Tuner idiom): baseline EWMA at apply time,
        # batches seen since; one _prev snapshot = one-step rollback
        self._watch: Optional[Dict[str, Any]] = None
        self._prev: Optional[Dict[str, Any]] = None
        self._last_forecast: Dict[str, float] = {
            "level_rps": 0.0, "trend_rps_s": 0.0, "forecast_rps": 0.0,
            "seconds": 0}
        self._recommended: Optional[Dict[str, Any]] = None
        self._recommended_t: Optional[float] = None
        self.decisions = {"scale_out": 0, "scale_in": 0, "rollback": 0,
                          "held_degraded": 0, "warm_start": 0}
        self.journal: List[Dict[str, Any]] = []

    # -- journal ------------------------------------------------------------

    def _log_locked(self, action: str, **fields: Any) -> None:
        entry = {"action": action, "t": round(self._clock(), 3),
                 "state": self.state}
        entry.update(fields)
        self.journal.append(entry)
        if len(self.journal) > self.spec.journal_cap:
            del self.journal[: self.spec.journal_cap // 4]

    # -- live-layer access (hooks, outside the lock) ------------------------

    def _live_config(self) -> Dict[str, Any]:
        fn = self.hooks.get("live_config")
        if fn is None:
            return {}
        try:
            return dict(fn() or {})
        except Exception:  # noqa: BLE001 — a broken hook reads as unknown
            return {}

    def _apply_knobs(self, inflight: Optional[int],
                     mega_k: Optional[int]) -> Dict[str, Any]:
        """Run the in-process apply hooks; returns what actually applied
        (a hook that is missing or raises simply doesn't apply — the
        journal records the delta honestly)."""
        applied: Dict[str, Any] = {}
        if inflight is not None:
            fn = self.hooks.get("set_inflight")
            if fn is not None:
                try:
                    fn(int(inflight))
                    applied["inflight"] = int(inflight)
                except Exception:  # noqa: BLE001 — apply is best-effort
                    pass
        if mega_k is not None:
            fn = self.hooks.get("set_mega_k")
            if fn is not None:
                try:
                    fn(int(mega_k))
                    applied["mega_k"] = int(mega_k)
                except Exception:  # noqa: BLE001 — apply is best-effort
                    pass
        return applied

    def _forecast(self) -> Dict[str, float]:
        fn = self.hooks.get("arrival_buckets")
        buckets: List = []
        now = None
        if fn is not None:
            try:
                raw = fn() or []
                if isinstance(raw, dict):
                    # SLOTracker.arrival_buckets form: the tracker's own
                    # clock rides along (its buckets are monotonic-stamped,
                    # so wall-time "now" would misdate every second)
                    now = raw.get("now")
                    buckets = list(raw.get("buckets") or [])
                else:
                    buckets = list(raw)
            except Exception:  # noqa: BLE001 — no buckets = zero forecast
                buckets = []
        return forecast_rps(buckets, now=now,
                            horizon_s=self.spec.forecast_horizon_s)

    def _brownout_active(self) -> bool:
        b = self.brownout
        if b is None:
            return False
        try:
            return bool(getattr(b, "step", 0))
        except Exception:  # noqa: BLE001 — unreadable = assume inactive
            return False

    # -- the loop -----------------------------------------------------------

    def tick(self, e2e_s: Optional[float] = None) -> Optional[str]:
        """One heartbeat (called per served batch alongside the tuner
        tick). Returns the action taken ("scale_out"/"scale_in"/
        "rollback") or None. Never raises."""
        try:
            return self._tick(e2e_s)
        except Exception:  # noqa: BLE001 — fleet must never kill serving
            return None

    def _tick(self, e2e_s: Optional[float]) -> Optional[str]:
        now = self._clock()
        with self._lock:
            if e2e_s is not None:
                self._e2e_ewma = float(e2e_s) if self._e2e_ewma is None \
                    else 0.8 * self._e2e_ewma + 0.2 * float(e2e_s)
                if self._watch is not None:
                    self._watch["batches"] += 1
            if now - self._last_tick < self.spec.tick_s:
                return None
            self._last_tick = now
            watch = dict(self._watch) if self._watch is not None else None
            ewma = self._e2e_ewma
        # regression watch resolves before anything else: a bad apply
        # must unwind even while degraded or cooling down
        if watch is not None and watch["batches"] >= \
                self.spec.watch_batches and ewma is not None:
            base = watch["baseline_e2e"]
            if base and ewma > base * (1.0 + self.spec.regress_pct):
                return self._rollback(ewma, base)
            with self._lock:
                if self._watch is not None:
                    self._log_locked("watch_clear",
                                     baseline_s=round(base or 0.0, 6),
                                     e2e_s=round(ewma, 6))
                    self._watch = None
                    self.state = "steady"
        if self._brownout_active():
            # fast path owns the situation: hold every scaling decision,
            # count the held tick once per plan interval for visibility
            with self._lock:
                if now - self._last_plan >= self.spec.plan_every_s:
                    self._last_plan = now
                    self.state = "degraded"
                    self.decisions["held_degraded"] += 1
                    self._log_locked("held_degraded")
                self._agree_out = self._agree_in = 0
            return None
        with self._lock:
            if now < self._cooldown_until:
                return None
            if self.state == "degraded":
                self.state = "steady"
            if now - self._last_plan < self.spec.plan_every_s:
                return None
            self._last_plan = now
        return self._plan_and_maybe_apply(now)

    def _plan_and_maybe_apply(self, now: float) -> Optional[str]:
        forecast = self._forecast()
        live = self._live_config()
        plan = self.planner.plan(forecast["forecast_rps"],
                                 live_replicas=live.get("replicas"))
        rec = plan.to_dict()
        with self._lock:
            self._last_forecast = forecast
            self._recommended = rec
            self._recommended_t = now
        # knob shipping (fleet/objstore.py): refresh the shipped snapshot
        # on every plan — the hook reads the live tuner knobs at call time
        # and the tier dedups byte-identical snapshots, so this is cheap
        snap = self.hooks.get("snapshot")
        if snap is not None:
            try:
                snap(rec)
            except Exception:  # noqa: BLE001 — shipping is best-effort
                pass
        if plan.meets_slo is None:
            # uncalibrated: recommendation published, nothing applied
            with self._lock:
                self._agree_out = self._agree_in = 0
            return None
        live_replicas = int(live.get("replicas") or 1)
        direction = None
        if plan.replicas > live_replicas:
            direction = "scale_out"
        elif plan.replicas < live_replicas:
            direction = "scale_in"
        elif plan.inflight != live.get("inflight") \
                or plan.mega_k != live.get("mega_k"):
            # same replica count, different in-process knobs: treat as
            # the (cheap) out direction so it applies on the fast quorum
            direction = "scale_out"
        with self._lock:
            if direction == "scale_out":
                self._agree_out += 1
                self._agree_in = 0
                ready = self._agree_out >= self.spec.consecutive_out
            elif direction == "scale_in":
                self._agree_in += 1
                self._agree_out = 0
                ready = self._agree_in >= self.spec.consecutive_in \
                    and now - self._last_apply >= self.spec.hold_s
            else:
                self._agree_out = self._agree_in = 0
                return None
            if not ready or self._watch is not None:
                return None
        return self._apply(direction, plan, live, now)

    def _apply(self, direction: str, plan, live: Dict[str, Any],
               now: float) -> str:
        applied = self._apply_knobs(plan.inflight, plan.mega_k)
        with self._lock:
            self._prev = {"live": dict(live), "applied_keys": list(applied)}
            self._watch = {"baseline_e2e": self._e2e_ewma, "batches": 0,
                           "direction": direction}
            self._last_apply = now
            self._agree_out = self._agree_in = 0
            self.state = direction
            self.decisions[direction] += 1
            self._log_locked("apply", direction=direction,
                             plan=plan.to_dict(), live=dict(live),
                             applied=applied)
        return direction

    def _rollback(self, ewma: float, base: float) -> str:
        """One-step rollback of the most recent apply (Tuner semantics):
        restore the snapshotted in-process knobs, veto further scaling
        for ``cooldown_s``."""
        with self._lock:
            prev = self._prev
            self._prev = None
            self._watch = None
            self.state = "cooldown"
            self._cooldown_until = self._clock() + self.spec.cooldown_s
            # agreement restarts from zero: plans counted while the bad
            # apply was live must not fast-track the next apply the
            # moment the cooldown expires
            self._agree_out = self._agree_in = 0
            self.decisions["rollback"] += 1
            self._log_locked("rollback",
                             baseline_s=round(base, 6),
                             e2e_s=round(ewma, 6),
                             restored=dict(prev["live"]) if prev else None)
        if prev is not None:
            live = prev["live"]
            self._apply_knobs(live.get("inflight"), live.get("mega_k"))
        return "rollback"

    def warm_start(self, plan: Dict[str, Any]) -> bool:
        """Adopt a shipped capacity plan (fleet/objstore.py knob shipping)
        as the published recommendation before the first local plan runs:
        a fresh pod's ``/_mmlspark/capacity`` answers calibrated from tick
        zero instead of opening a relearning window. Journaled; the first
        LOCAL plan replaces it (nothing is applied to live knobs here —
        the tuner's own warm start owns that)."""
        if not isinstance(plan, dict) or not plan:
            return False
        with self._lock:
            if self._recommended is not None:
                return False  # a live plan always outranks a shipped one
            self._recommended = dict(plan)
            self._recommended_t = self._clock()
            self.decisions["warm_start"] += 1
            self._log_locked("warm_start", plan=dict(plan))
        return True

    def rollback(self) -> bool:
        """Manual one-step rollback (ops hatch, Tuner parity). False when
        there is nothing to roll back."""
        with self._lock:
            has_prev = self._prev is not None
        if not has_prev:
            return False
        with self._lock:
            ewma = self._e2e_ewma or 0.0
        self._rollback(ewma, ewma)
        return True

    # -- the /_mmlspark/capacity payload ------------------------------------

    def summary(self) -> Dict[str, Any]:
        live = self._live_config()
        brown = None
        if self.brownout is not None:
            try:
                brown = {"active": self._brownout_active(),
                         "step": int(getattr(self.brownout, "step", 0))}
            except Exception:  # noqa: BLE001 — summary must not raise
                brown = {"active": False, "step": 0}
        with self._lock:
            rec = dict(self._recommended) if self._recommended else None
            age = None
            if rec is not None and self._recommended_t is not None:
                age = round(max(0.0, self._clock() - self._recommended_t), 3)
            return {
                "state": self.state,
                "forecast": dict(self._last_forecast),
                "recommended": rec,
                "recommended_replicas": rec["replicas"] if rec else None,
                # self-reported plan freshness: the front's capacity
                # aggregation drops plans older than its TTL (a stalled
                # planning loop must not steer the HPA forever)
                "plan_age_s": age,
                "live": live,
                "brownout": brown,
                "decisions": dict(self.decisions),
                "spec": self.spec.to_dict(),
                "planner": self.planner.summary(),
                "journal": list(self.journal[-16:]),
            }


def make_fleet(spec: Any, *, predict_ms: Callable[[int], Optional[float]],
               slo: Any = None, brownout: Any = None,
               hooks: Optional[Dict[str, Callable]] = None,
               planner_cfg: Optional[PlannerConfig] = None
               ) -> Optional[FleetController]:
    """Coerce a server's ``fleet`` knob (the make_brownout idiom):
    None/False -> off, True -> defaults, dict -> configured
    (FleetSpec kwargs + optional ``planner`` sub-dict = PlannerConfig
    kwargs), FleetController -> as-is."""
    if spec is None or spec is False:
        return None
    if isinstance(spec, FleetController):
        return spec
    if spec is True:
        fspec = FleetSpec()
    elif isinstance(spec, FleetSpec):
        fspec = spec
    elif isinstance(spec, dict):
        kw = dict(spec)
        kw.pop("cache_path", None)  # consumed by serve_pipeline
        kw.pop("cache_write", None)
        kw.pop("cache_store", None)  # object-store backend (objstore.py)
        pcfg = kw.pop("planner", None)
        if pcfg is not None and planner_cfg is None:
            planner_cfg = PlannerConfig(**pcfg)
        fspec = FleetSpec(**kw)
    else:
        raise ValueError(
            f"fleet must be None/bool/dict/FleetSpec/FleetController, "
            f"got {spec!r}")
    planner = CapacityPlanner(predict_ms, planner_cfg)
    return FleetController(planner, spec=fspec, slo=slo,
                           brownout=brownout, hooks=hooks)
