"""Capacity planner: invert the calibrated cost model.

PR 7 built the SLO burn-rate gauges ("the HPA signal") and PR 9 built the
SegmentCostModel that predicts per-batch compute from batch size. This
module closes the loop the ROADMAP asks for: instead of reacting to CPU
pressure, PLAN capacity — given an arrival-rate forecast, emit the
(replicas, inflight, bucket, mega_k) configuration that meets the latency
objective at minimum capacity ("A Learned Performance Model for TPUs",
PAPERS.md, used in reverse).

The math (docs/fleet.md "Planner math"):

  service_ms(B)   cost model's predicted per-batch wall for bucket B
  mu(B)           per-replica service rate = B / service_ms(B) rows/ms
  demand          forecast rows/s x ``headroom`` safety factor
  rho             utilization = demand / (R x mu) — capped at
                  ``utilization_cap`` so queueing delay stays bounded
  latency(B, R)   wait + service x (1 + rho / (1 - rho)); wait is the
                  adaptive window's steady state (~alpha x service); the
                  M/M/1-flavored inflation term is deliberately
                  pessimistic (real batching smooths arrivals)

Feasible = rho <= cap AND latency <= objective. Among feasible configs
the planner minimizes replicas first (capacity is the expensive axis),
then maximizes bucket (bigger batches amortize dispatch better at equal
replica count). ``inflight`` deepens with utilization (pipeline overlap
only pays when there is queue to hide) and ``mega_k`` engages when the
per-replica dispatch rate crosses ``dispatch_floor_hz`` — the PR 11
mega-dispatch criterion, applied predictively.

Everything here is pure (inputs in, plan out, no live objects), so the
sweep tests in tests/test_fleet.py can prove "emitted config meets the
SLO" across a simulated arrival sweep without a server.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class PlannerConfig:
    """Planning envelope. ``objective_ms``/``target`` mirror the serving
    SLO (obs/perf.py SLOConfig); the rest bound the search space."""

    objective_ms: float = 250.0
    target: float = 0.99
    utilization_cap: float = 0.7
    headroom: float = 1.15
    min_replicas: int = 1
    max_replicas: int = 64
    max_inflight: int = 8
    bucket_candidates: Tuple[int, ...] = (8, 16, 32, 64, 128, 256)
    mega_k_candidates: Tuple[int, ...] = (1, 2, 4)
    #: per-replica dispatches/s above which K-step mega-dispatch engages
    dispatch_floor_hz: float = 150.0
    #: adaptive window steady state as a fraction of service time
    window_alpha: float = 0.5

    def __post_init__(self):
        if self.objective_ms <= 0:
            raise ValueError("objective_ms must be positive")
        if not 0.0 < self.utilization_cap < 1.0:
            raise ValueError(
                f"utilization_cap must be in (0,1), got "
                f"{self.utilization_cap}")
        if self.headroom < 1.0:
            raise ValueError("headroom must be >= 1.0")
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError("bad replica bounds")


@dataclasses.dataclass(frozen=True)
class CapacityPlan:
    """One planning decision — the knob vector plus the evidence for it."""

    replicas: int
    inflight: int
    bucket: int
    mega_k: int
    demand_rps: float
    service_ms: Optional[float]
    wait_ms: Optional[float]
    predicted_latency_ms: Optional[float]
    utilization: Optional[float]
    capacity_rps: Optional[float]
    meets_slo: Optional[bool]
    reason: str

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        for k, v in d.items():
            if isinstance(v, float):
                d[k] = round(v, 4)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CapacityPlan":
        """Rehydrate a shipped plan (fleet/objstore.py knob shipping).
        Unknown keys are dropped, missing ones defaulted — a snapshot from
        a slightly older build still warm-starts the controller."""
        names = {f.name for f in dataclasses.fields(cls)}
        kw: Dict[str, Any] = {"replicas": 1, "inflight": 1, "bucket": 1,
                              "mega_k": 1, "demand_rps": 0.0,
                              "service_ms": None, "wait_ms": None,
                              "predicted_latency_ms": None,
                              "utilization": None, "capacity_rps": None,
                              "meets_slo": None, "reason": "shipped"}
        kw.update({k: v for k, v in dict(d).items() if k in names})
        return cls(**kw)


def forecast_rps(buckets: Iterable, now: Optional[float] = None,
                 alpha: float = 0.35, trend_alpha: float = 0.15,
                 horizon_s: float = 60.0,
                 max_history_s: int = 600) -> Dict[str, float]:
    """EWMA level + short-horizon trend (Holt's linear method) over the
    SLOTracker's per-second ``(second, total, breaches)`` buckets.

    Seconds with no bucket are zero-traffic seconds and count as 0 — an
    idle gap must pull the forecast DOWN, not freeze it. The current
    (partial) second is excluded. Returns level, trend, and the
    ``horizon_s``-ahead forecast (floored at 0)."""
    pts: Dict[int, float] = {}
    for rec in buckets:
        try:
            sec, total = int(rec[0]), float(rec[1])
        except (TypeError, ValueError, IndexError):
            continue
        pts[sec] = pts.get(sec, 0.0) + total
    if not pts:
        return {"level_rps": 0.0, "trend_rps_s": 0.0,
                "forecast_rps": 0.0, "seconds": 0}
    now_s = int(now if now is not None else time.time())
    first = max(min(pts), now_s - int(max_history_s))
    last = max(max(pts), now_s - 1)
    level: Optional[float] = None
    trend = 0.0
    n = 0
    for sec in range(first, last + 1):
        if sec >= now_s:  # current second is partially filled — skip
            continue
        x = pts.get(sec, 0.0)
        n += 1
        if level is None:
            level = x
            continue
        prev = level
        level = alpha * x + (1.0 - alpha) * (level + trend)
        trend = trend_alpha * (level - prev) + (1.0 - trend_alpha) * trend
    level = level if level is not None else 0.0
    return {"level_rps": round(level, 4),
            "trend_rps_s": round(trend, 6),
            "forecast_rps": round(max(0.0, level + trend * horizon_s), 4),
            "seconds": n}


def _latency_ms(service_ms: float, rho: float, cfg: PlannerConfig
                ) -> Tuple[float, float]:
    """(wait_ms, predicted latency) for one config: adaptive-window wait
    plus service inflated by the queueing factor rho/(1-rho)."""
    wait = cfg.window_alpha * service_ms
    queue_factor = rho / max(1e-9, 1.0 - rho) if rho < 1.0 else math.inf
    return wait, wait + service_ms * (1.0 + queue_factor)


def plan_capacity(demand_rps: float,
                  predict_ms: Callable[[int], Optional[float]],
                  cfg: Optional[PlannerConfig] = None,
                  live_replicas: Optional[int] = None) -> CapacityPlan:
    """The pure planning function: forecast demand (rows/s) + the cost
    model's ``predict_ms(bucket)`` in, minimum-capacity SLO-meeting plan
    out.

    An uncalibrated model (``predict_ms`` returns None for every bucket)
    yields a hold-steady plan (``meets_slo=None``) — the planner NEVER
    invents capacity numbers it has no evidence for, mirroring the
    Tuner's "uncalibrated changes nothing" contract."""
    cfg = cfg if cfg is not None else PlannerConfig()
    demand = max(0.0, float(demand_rps)) * cfg.headroom

    def rank(p: CapacityPlan) -> Tuple:
        # preference order: feasible beats infeasible; then fewer
        # replicas (capacity is the expensive axis); then bigger bucket
        # (dispatch amortization); then lower predicted latency
        return (0 if p.meets_slo else 1, p.replicas, -p.bucket,
                p.predicted_latency_ms
                if p.predicted_latency_ms is not None else math.inf)

    best: Optional[CapacityPlan] = None
    calibrated = False
    for bucket in sorted(set(int(b) for b in cfg.bucket_candidates)):
        if bucket <= 0:
            continue
        try:
            service_ms = predict_ms(bucket)
        except Exception:  # noqa: BLE001 — a model error is "no estimate"
            service_ms = None
        if service_ms is None or service_ms <= 0:
            continue
        calibrated = True
        mu_rps = bucket * 1000.0 / service_ms  # rows/s per replica
        if demand <= 0:
            replicas = cfg.min_replicas
        else:
            replicas = max(cfg.min_replicas, int(math.ceil(
                demand / (mu_rps * cfg.utilization_cap))))
        if replicas > cfg.max_replicas:
            # even the full fleet can't meet the cap with this bucket:
            # record the saturated plan as a candidate of last resort
            replicas = cfg.max_replicas
        rho = demand / (replicas * mu_rps) if demand > 0 else 0.0
        wait, latency = _latency_ms(service_ms, min(rho, 0.999), cfg)
        feasible = rho <= cfg.utilization_cap \
            and latency <= cfg.objective_ms
        # inflight: overlap only pays once there is queue to hide; deepen
        # with utilization, bounded by the envelope
        inflight = 1 if rho < 0.25 else (2 if rho < 0.6 else 3)
        inflight = min(cfg.max_inflight, inflight)
        # mega_k: per-replica dispatch rate (batches/s) above the floor
        # means fixed dispatch cost dominates -> amortize K-fold
        dispatch_hz = demand / (replicas * bucket) if demand > 0 else 0.0
        mega_k = 1
        for k in sorted(set(int(k) for k in cfg.mega_k_candidates)):
            if k >= 1 and dispatch_hz / k > cfg.dispatch_floor_hz:
                continue
            if k >= 1:
                mega_k = k
                break
        cand = CapacityPlan(
            replicas=replicas, inflight=inflight, bucket=bucket,
            mega_k=mega_k, demand_rps=round(demand, 4),
            service_ms=round(service_ms, 4), wait_ms=round(wait, 4),
            predicted_latency_ms=round(latency, 4)
            if math.isfinite(latency) else None,
            utilization=round(rho, 4),
            capacity_rps=round(replicas * mu_rps, 2),
            meets_slo=feasible,
            reason="planned")
        if best is None or rank(cand) < rank(best):
            best = cand
    if not calibrated or best is None:
        hold = max(cfg.min_replicas, int(live_replicas or cfg.min_replicas))
        return CapacityPlan(
            replicas=hold, inflight=2, bucket=0, mega_k=1,
            demand_rps=round(demand, 4), service_ms=None, wait_ms=None,
            predicted_latency_ms=None, utilization=None,
            capacity_rps=None, meets_slo=None, reason="uncalibrated")
    return best


class CapacityPlanner:
    """Journaled wrapper: every ``plan()`` call appends (demand, plan) to
    a bounded decision journal, so ``/_mmlspark/capacity`` and the perf
    report can show WHY the current recommendation is what it is."""

    def __init__(self, predict_ms: Callable[[int], Optional[float]],
                 cfg: Optional[PlannerConfig] = None,
                 journal_cap: int = 256):
        self.cfg = cfg if cfg is not None else PlannerConfig()
        self._predict_ms = predict_ms
        self._lock = threading.Lock()
        self._journal: "deque[Dict[str, Any]]" = deque(maxlen=journal_cap)
        self.plans_total = 0

    def plan(self, demand_rps: float,
             live_replicas: Optional[int] = None) -> CapacityPlan:
        p = plan_capacity(demand_rps, self._predict_ms, self.cfg,
                          live_replicas=live_replicas)
        with self._lock:
            self.plans_total += 1
            self._journal.append({"t": round(time.time(), 3),
                                  "demand_rps": round(demand_rps, 4),
                                  "plan": p.to_dict()})
        return p

    def journal(self, last: int = 20) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._journal)[-int(last):]

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            latest = self._journal[-1] if self._journal else None
            return {"plans_total": self.plans_total,
                    "config": dataclasses.asdict(self.cfg),
                    "latest": dict(latest) if latest else None}


# ---------------------------------------------------------------------------
# Model packing (serving/multimodel): bin-pack N models onto R replicas
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ModelDemand:
    """One model's claim on worker capacity. ``predict_ms`` is the cost
    model's per-request service estimate (None = uncalibrated — the model
    gets a measured-probe slot, never an invented load number);
    ``forecast_rps`` is the Holt forecast over that model's own traffic."""

    model: str
    predict_ms: Optional[float]
    forecast_rps: float

    @property
    def calibrated(self) -> bool:
        return self.predict_ms is not None and self.predict_ms > 0

    @property
    def load(self) -> Optional[float]:
        """Demanded compute, ms of service per wall second — the packing
        key ``predict_ms x forecast_rps`` from the issue/ROADMAP."""
        if not self.calibrated:
            return None
        return float(self.predict_ms) * max(0.0, float(self.forecast_rps))


@dataclasses.dataclass(frozen=True)
class PackingPlan:
    """One packing decision: model -> replica placements plus the idle
    share the AutoML scheduler is allowed to spend on trials."""

    placements: Tuple[Tuple[str, int], ...]   # (model, replica) pairs
    replica_load: Tuple[float, ...]           # ms/s packed per replica
    probes: Tuple[str, ...]                   # uncalibrated models probing
    idle_replicas: Tuple[int, ...]            # replicas below probe load
    idle_share: float                         # 0..1 of total capacity free
    capacity_ms: float                        # per-replica ms/s budget
    reason: str

    def to_dict(self) -> Dict[str, Any]:
        return {"placements": [list(p) for p in self.placements],
                "replica_load": [round(x, 4) for x in self.replica_load],
                "probes": list(self.probes),
                "idle_replicas": list(self.idle_replicas),
                "idle_share": round(self.idle_share, 4),
                "capacity_ms": round(self.capacity_ms, 2),
                "reason": self.reason}

    def replica_of(self, model: str) -> Optional[int]:
        for m, r in self.placements:
            if m == model:
                return r
        return None


def pack_models(demands: Iterable[ModelDemand], replicas: int,
                cfg: Optional[PlannerConfig] = None,
                probe_ms: float = 25.0) -> PackingPlan:
    """Pure deterministic first-fit-decreasing bin-pack of models onto
    replicas by ``predict_ms x forecast_rps`` (ms of demanded service per
    wall second against a per-replica budget of ``1000 x
    utilization_cap``).

    Ties and ordering are fully deterministic: models sort by
    (-load, name), so the same demands always produce the same plan (the
    determinism tests diff plans byte-for-byte). Uncalibrated models are
    NOT packed by a guessed load — each is placed on the currently
    least-loaded replica with a nominal ``probe_ms`` reservation and
    listed in ``probes``; the mall measures them there and the next plan
    packs them for real ("uncalibrated changes nothing" applied to
    placement). When every replica overflows its budget the plan still
    places every model (serving beats purity) with
    ``reason="saturated"``."""
    cfg = cfg if cfg is not None else PlannerConfig()
    replicas = max(1, int(replicas))
    budget = 1000.0 * cfg.utilization_cap
    calibrated = sorted(
        (d for d in demands if d.calibrated),
        key=lambda d: (-(d.load or 0.0), d.model))
    probing = sorted((d for d in demands if not d.calibrated),
                     key=lambda d: d.model)
    loads = [0.0] * replicas
    placements: List[Tuple[str, int]] = []
    saturated = False
    for d in calibrated:
        want = d.load or 0.0
        slot = None
        for r in range(replicas):          # first fit over replica order
            if loads[r] + want <= budget:
                slot = r
                break
        if slot is None:                   # overflow: least-loaded replica
            slot = min(range(replicas), key=lambda r: (loads[r], r))
            saturated = True
        loads[slot] += want
        placements.append((d.model, slot))
    for d in probing:
        slot = min(range(replicas), key=lambda r: (loads[r], r))
        loads[slot] += probe_ms
        placements.append((d.model, slot))
    total = budget * replicas
    used = sum(loads)
    idle = [r for r in range(replicas) if loads[r] <= probe_ms]
    return PackingPlan(
        placements=tuple(placements),
        replica_load=tuple(round(x, 4) for x in loads),
        probes=tuple(d.model for d in probing),
        idle_replicas=tuple(idle),
        idle_share=max(0.0, 1.0 - used / total) if total > 0 else 0.0,
        capacity_ms=budget,
        reason="saturated" if saturated else "packed")


class PackingPlanner:
    """Journaled wrapper around ``pack_models`` with the tuner-style
    one-step rollback: every plan is appended to a bounded journal, and
    ``rollback()`` restores exactly the previous placement (the mall
    re-applies it) — the same contract as CapacityPlanner/Tuner."""

    def __init__(self, cfg: Optional[PlannerConfig] = None,
                 probe_ms: float = 25.0, journal_cap: int = 256):
        self.cfg = cfg if cfg is not None else PlannerConfig()
        self.probe_ms = float(probe_ms)
        self._lock = threading.Lock()
        self._journal: "deque[Dict[str, Any]]" = deque(maxlen=journal_cap)
        self._current: Optional[PackingPlan] = None
        self._prev: Optional[PackingPlan] = None
        self.plans_total = 0
        self.rollbacks = 0

    @property
    def current(self) -> Optional[PackingPlan]:
        with self._lock:
            return self._current

    def plan(self, demands: Iterable[ModelDemand],
             replicas: int) -> PackingPlan:
        demands = list(demands)
        p = pack_models(demands, replicas, self.cfg, probe_ms=self.probe_ms)
        with self._lock:
            self.plans_total += 1
            self._prev = self._current
            self._current = p
            self._journal.append({
                "t": round(time.time(), 3), "action": "pack",
                "demands": [{"model": d.model,
                             "predict_ms": d.predict_ms,
                             "forecast_rps": round(d.forecast_rps, 4)}
                            for d in demands],
                "replicas": int(replicas),
                "plan": p.to_dict()})
        return p

    def rollback(self, reason: str = "rollback") -> Optional[PackingPlan]:
        """Restore the previous plan (one step, like the Tuner). Returns
        the restored plan, or None when there is no prior decision."""
        with self._lock:
            if self._prev is None:
                return None
            restored, self._current, self._prev = \
                self._prev, self._prev, None
            self.rollbacks += 1
            self._journal.append({"t": round(time.time(), 3),
                                  "action": "rollback", "reason": reason,
                                  "plan": restored.to_dict()})
            return restored

    def journal(self, last: int = 20) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._journal)[-int(last):]

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {"plans_total": self.plans_total,
                    "rollbacks": self.rollbacks,
                    "probe_ms": self.probe_ms,
                    "current": self._current.to_dict()
                    if self._current else None}
