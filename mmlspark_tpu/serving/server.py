"""ServingServer: HTTP ingress + continuous batching loop + reply routing.

Reference mapping (SURVEY §3.4, HTTPSourceV2.scala):
  - WorkerServer public handler       -> ThreadingHTTPServer ingress
  - request id + epoch bookkeeping    -> per-request reply slots (Event + holder)
  - micro-batch/continuous trigger    -> drain loop: wait <= max_wait_ms for up
    to max_batch_size requests, one pipeline.transform per drained batch
  - ServingUDFs.sendReplyUDF          -> reply slot fulfillment by request id
  - driver routing / multi-worker     -> ServingServer instances are per-host;
    a front proxy (or DNS) spreads load, replies always come from the host that
    accepted the request (no cross-machine replyTo hop needed)

The batching loop keeps the pipeline's jitted stages warm: after the first
batch, steady-state latency is queue wait + one compiled forward.
"""

from __future__ import annotations

import json
import threading
import time
import queue as queue_mod
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame


class _ReplySlot:
    __slots__ = ("event", "status", "body", "content_type")

    def __init__(self):
        self.event = threading.Event()
        self.status = 500
        self.body = b""
        self.content_type = "application/json"


class ServingServer:
    """Serve a DataFrame->DataFrame function over HTTP.

    The transform receives a DataFrame with columns:
      - ``id``:      request ids (opaque ints)
      - ``value``:   raw request body bytes
      - ``headers``: per-row dict of request headers
    and must return a DataFrame containing ``id`` and a reply column
    (default "reply") holding str/bytes/dict per row.
    """

    def __init__(self, transform: Callable[[DataFrame], DataFrame],
                 host: str = "127.0.0.1", port: int = 8898,
                 api_path: str = "/", reply_col: str = "reply",
                 max_batch_size: int = 64, max_wait_ms: float = 5.0,
                 name: str = "serving"):
        self.transform = transform
        self.host = host
        self.port = port
        self.api_path = api_path.rstrip("/") or "/"
        self.reply_col = reply_col
        self.max_batch_size = max_batch_size
        self.max_wait_ms = max_wait_ms
        self.name = name
        self._queue: "queue_mod.Queue" = queue_mod.Queue()
        self._slots: Dict[int, _ReplySlot] = {}
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._stop = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []
        self.requests_served = 0

    # -- ingress ---------------------------------------------------------
    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _handle(self):
                path = self.path.rstrip("/") or "/"
                if path != server.api_path:
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0) or 0)
                body = self.rfile.read(length) if length else b""
                slot = _ReplySlot()
                with server._id_lock:
                    rid = server._next_id
                    server._next_id += 1
                    server._slots[rid] = slot
                server._queue.put((rid, body, dict(self.headers.items())))
                ok = slot.event.wait(timeout=60.0)
                with server._id_lock:
                    server._slots.pop(rid, None)
                if not ok:
                    self.send_error(504, "batch timeout")
                    return
                self.send_response(slot.status)
                self.send_header("Content-Type", slot.content_type)
                self.send_header("Content-Length", str(len(slot.body)))
                self.end_headers()
                self.wfile.write(slot.body)

            do_POST = _handle
            do_GET = _handle

        return Handler

    # -- batching loop (the continuous query) ----------------------------
    def _drain_batch(self):
        """Block for the first request, then gather up to max_batch_size within
        max_wait_ms (DynamicBatcher semantics, stages/Batchers.scala)."""
        try:
            first = self._queue.get(timeout=0.2)
        except queue_mod.Empty:
            return None
        batch = [first]
        deadline = time.perf_counter() + self.max_wait_ms / 1000.0
        while len(batch) < self.max_batch_size:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                batch.append(self._queue.get(timeout=remaining))
            except queue_mod.Empty:
                break
        return batch

    def _loop(self):
        while not self._stop.is_set():
            batch = self._drain_batch()
            if not batch:
                continue
            ids = np.array([b[0] for b in batch], dtype=np.int64)
            bodies = np.empty(len(batch), dtype=object)
            headers = np.empty(len(batch), dtype=object)
            for i, (_, body, hdrs) in enumerate(batch):
                bodies[i] = body
                headers[i] = hdrs
            df = DataFrame([{"id": ids, "value": bodies, "headers": headers}])
            try:
                out = self.transform(df)
                data = out.collect()
                out_ids = data["id"]
                replies = data[self.reply_col]
                for rid, reply in zip(out_ids, replies):
                    self._fulfill(int(rid), 200, reply)
                answered = {int(r) for r in out_ids}
                for rid in ids:
                    if int(rid) not in answered:
                        self._fulfill(int(rid), 204, b"")
            except Exception as e:  # failed batch -> 500s, keep serving
                for rid in ids:
                    self._fulfill(int(rid), 500, json.dumps(
                        {"error": str(e)}).encode("utf-8"))

    def _fulfill(self, rid: int, status: int, reply: Any):
        slot = self._slots.get(rid)
        if slot is None:
            return
        if isinstance(reply, (dict, list)):
            body = json.dumps(reply, default=_json_default).encode("utf-8")
            ctype = "application/json"
        elif isinstance(reply, (bytes, bytearray)):
            body, ctype = bytes(reply), "application/octet-stream"
        elif isinstance(reply, np.ndarray):
            body = json.dumps(reply.tolist()).encode("utf-8")
            ctype = "application/json"
        elif reply is None:
            body, ctype = b"", "text/plain"
        else:
            body, ctype = str(reply).encode("utf-8"), "text/plain"
        slot.status = status
        slot.body = body
        slot.content_type = ctype
        slot.event.set()
        self.requests_served += 1

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ServingServer":
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_address[1]  # resolve port 0
        t_http = threading.Thread(target=self._httpd.serve_forever, daemon=True,
                                  name=f"{self.name}-http")
        t_loop = threading.Thread(target=self._loop, daemon=True,
                                  name=f"{self.name}-batcher")
        t_http.start()
        t_loop.start()
        self._threads = [t_http, t_loop]
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(type(o))


def serve_pipeline(stage, input_col: str, reply_col: str = "reply",
                   parse: str = "json", host: str = "127.0.0.1", port: int = 0,
                   api_path: str = "/", max_batch_size: int = 64,
                   max_wait_ms: float = 5.0) -> ServingServer:
    """Serve a fitted Transformer: request body -> ``input_col`` -> stage ->
    ``reply_col`` (IOImplicits fluent sugar parity, io/IOImplicits.scala:182-213).

    parse: 'json' (body -> dict/array) | 'text' | 'bytes'.
    """
    from .stages import parse_request

    def transform(df: DataFrame) -> DataFrame:
        parsed = parse_request(df, input_col, parse=parse)
        out = stage.transform(parsed)
        if reply_col not in out.schema:
            for pname in ("outputCol", "predictionCol"):
                if stage.has_param(pname) and stage.get(pname) in out.schema:
                    out = out.with_column(reply_col,
                                          lambda p, _c=stage.get(pname): p[_c])
                    break
        return out

    return ServingServer(transform, host=host, port=port, api_path=api_path,
                         reply_col=reply_col, max_batch_size=max_batch_size,
                         max_wait_ms=max_wait_ms)
