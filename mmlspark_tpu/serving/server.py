"""ServingServer: HTTP ingress + continuous batching loop + reply routing.

Reference mapping (SURVEY §3.4, HTTPSourceV2.scala):
  - WorkerServer public handler       -> ThreadingHTTPServer ingress
  - request id + epoch bookkeeping    -> per-request reply slots (Event + holder)
  - micro-batch/continuous trigger    -> drain loop: wait <= max_wait_ms for up
    to max_batch_size requests, one pipeline.transform per drained batch
  - ServingUDFs.sendReplyUDF          -> reply slot fulfillment by request id;
    a peer process can answer via the internal reply endpoint + ``reply_to``
    (the cross-machine replyTo hop, HTTPSourceV2.scala:516-545)
  - driver routing / multi-worker     -> RoutingFront (routing.py): workers
    register, the front load-balances public traffic and retries/evicts dead
    workers (driver routing service, HTTPSourceV2.scala:113-173)

The batching loop keeps the pipeline's jitted stages warm: after the first
batch, steady-state latency is queue wait + one compiled forward.

Two execution modes share the same ingress, journal, deadline-gate, and
reply machinery (so replies are bitwise-identical between them):

  - ``async_exec=False`` (default): the serial ``_loop`` above — drain ->
    transform -> fulfill -> drain.
  - ``async_exec=True``: the pipelined executor (serving/executor.py) —
    batch N+1 drains/journals/stages while batch N computes, ``replicas``
    copies dispatch round-robin across local devices, a dedicated readback
    thread fulfills reply slots, and the coalescing window self-tunes
    (``adaptive_batching``).

Orthogonally, TWO HTTP transports share the same admission, slot, and
fulfillment helpers (``_handle_control`` / ``_preflight`` / ``_enqueue`` /
``_finish``), so replies are also bitwise-identical between them:

  - ``http_mode="thread"``: the legacy ``ThreadingHTTPServer`` — one thread
    per connection, blocking reply-slot waits.
  - ``http_mode="async"``: the event-loop transport (serving/aio.py) — one
    thread for every connection, keep-alive pooling, pipelined reads, reply
    slots bridged to asyncio futures.

The wire is negotiated per request via Content-Type: binary column frames
(``application/x-mmlspark-frame``, io/binary.py) are header-validated at
ingress (malformed frames 400 before burning a batch slot) and ride the
batch rows as raw bytes — no JSON parse, no base64 — while JSON clients keep
the legacy path. ``tenants`` maps ``X-MMLSpark-Tenant`` to weighted-fair
admission classes (serving/tenants.py): overload sheds proportionally
instead of a global 503.
"""

from __future__ import annotations

import base64
import json
import random
import threading
import time
import queue as queue_mod
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..core.faults import deadline_from_headers
from ..io.binary import FRAME_CONTENT_TYPE, FrameError, frame_info
from ..obs import bridge as obs_bridge
from ..obs import perf as obs_perf
from ..obs import trace as obs_trace
from ..obs.metrics import SERVING_LATENCY_BUCKETS, MetricsRegistry
from ..obs.trace import Tracer
from .tenants import TenantAdmission

#: header carrying the shared cluster secret for internal endpoints
TOKEN_HEADER = "X-MMLSpark-Token"


def _post_json(url: str, payload: dict, timeout: float = 10.0,
               token: Optional[str] = None,
               policy: Optional["RetryPolicy"] = None,
               transport: Optional[Callable] = None) -> None:
    """POST a JSON payload through the shared retry stack
    (``io.http.send_with_retries`` + ``core.faults.RetryPolicy``) like every
    other network path: transient transport failures and retryable statuses
    back off and retry; a definitive error raises ``HTTPError`` (the legacy
    urlopen contract callers rely on) and an exhausted connection failure
    raises ``URLError``. ``transport`` overrides the per-attempt send
    (``(req, timeout[, deadline]) -> HTTPResponseData``) so tests stay
    offline while still exercising the retry loop."""
    import io as io_mod
    from urllib.error import HTTPError, URLError

    from ..core.faults import RetryPolicy
    from ..io.http import HTTPRequestData, send_with_retries

    headers = {"Content-Type": "application/json"}
    if token is not None:
        headers[TOKEN_HEADER] = token
    req = HTTPRequestData(url=url, method="POST", headers=headers,
                          entity=json.dumps(payload).encode("utf-8"))
    if policy is None:
        # the reply hop is latency-sensitive: short backoffs, bounded budget
        policy = RetryPolicy(max_retries=3, base_s=0.05, budget_s=5.0)
    resp = send_with_retries(req, timeout=timeout, policy=policy,
                             send=transport)
    if resp.statusCode == 0:
        raise URLError(resp.statusLine or f"POST {url} failed")
    if not 200 <= resp.statusCode < 300:
        raise HTTPError(url, resp.statusCode, resp.statusLine or "error",
                        resp.headers or {},
                        io_mod.BytesIO(resp.entity or b""))


class _ReplySlot:
    __slots__ = ("event", "status", "body", "content_type", "t_in", "t_drain",
                 "t_done", "batch", "waiter", "tenant")

    def __init__(self):
        self.event = threading.Event()
        self.status = 500
        self.body = b""
        self.content_type = "application/json"
        # latency decomposition timestamps (perf_counter seconds):
        # t_in = ingress enqueue, t_drain = batch formed (queue wait ends),
        # t_done = reply fulfilled (compute + reply routing ends)
        self.t_in = 0.0
        self.t_drain = 0.0
        self.t_done = 0.0
        self.batch = 0
        # async-transport bridge: called (threadsafe) after event.set() so
        # the event loop wakes the awaiting connection coroutine
        self.waiter: Optional[Callable[[], None]] = None
        # admission class (X-MMLSpark-Tenant); in-flight share released when
        # the slot is popped
        self.tenant: Optional[str] = None


class LatencyStats:
    """Bounded rolling window of per-request component latencies.

    The decomposition the round-2 verdict asked for: ``queue`` (ingress to
    batch-drain), ``compute`` (batch-drain to reply fulfillment — the
    pipeline transform incl. any device dispatch), and ``overhead`` =
    total - compute - queue (slot wakeup + HTTP write). The reference's
    sub-ms serving claim (docs/mmlspark-serving.md:10-11) is about the
    serving framework, not the model — ``queue + overhead`` is the
    framework's share."""

    def __init__(self, cap: int = 4096):
        self._lock = threading.Lock()
        self._cap = cap
        self._rows: List[tuple] = []  # (queue_s, compute_s, total_s, batch)
        # load-shed visibility: (status, reason) -> count, so the adaptive
        # controller's effect on shed rate is observable next to the
        # latency percentiles (503 = admission/drain sheds, 504 = deadline
        # gates and slot timeouts)
        self._shed: Dict[tuple, int] = {}

    def record(self, queue_s: float, compute_s: float, total_s: float,
               batch: int) -> None:
        with self._lock:
            if len(self._rows) >= self._cap:
                del self._rows[: self._cap // 4]
            self._rows.append((queue_s, compute_s, total_s, batch))

    def record_shed(self, status: int, reason: str,
                    tenant: Optional[str] = None) -> None:
        """Count one load-shed/drop: status is the HTTP code returned
        (400/503/504), reason a short slug (queue_full, tenant_over_share,
        bad_frame, draining, deadline_ingress, deadline_queue,
        deadline_inflight, slot_timeout); ``tenant`` labels the admission
        class when tenancy is on."""
        with self._lock:
            key = (int(status), str(reason),
                   str(tenant) if tenant is not None else None)
            self._shed[key] = self._shed.get(key, 0) + 1

    def shed_summary(self) -> Dict[str, Any]:
        with self._lock:
            shed = dict(self._shed)
        by_status: Dict[str, int] = {}
        by_reason: Dict[str, int] = {}
        by_tenant: Dict[str, int] = {}
        for (status, reason, tenant), n in shed.items():
            by_status[str(status)] = by_status.get(str(status), 0) + n
            by_reason[reason] = by_reason.get(reason, 0) + n
            if tenant is not None:
                by_tenant[tenant] = by_tenant.get(tenant, 0) + n
        out = {"total": sum(shed.values()), "by_status": by_status,
               "by_reason": by_reason}
        if by_tenant:
            out["by_tenant"] = by_tenant
        return out

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            rows = list(self._rows)
        if not rows:
            return {"n": 0, "shed": self.shed_summary()}
        arr = np.asarray(rows)
        q, c, t = arr[:, 0] * 1e3, arr[:, 1] * 1e3, arr[:, 2] * 1e3
        o = t - q - c

        def pct(x):
            return {"p50": round(float(np.percentile(x, 50)), 3),
                    "p95": round(float(np.percentile(x, 95)), 3),
                    "mean": round(float(np.mean(x)), 3)}

        return {"n": len(rows),
                "queue_ms": pct(q), "compute_ms": pct(c),
                "overhead_ms": pct(o), "total_ms": pct(t),
                "mean_batch": round(float(np.mean(arr[:, 3])), 2),
                "shed": self.shed_summary()}


class _Prepared:
    """One drained batch, deadline-gated, stamped, and journaled — the unit
    that flows through the sync loop and the async executor's stages."""

    __slots__ = ("rows", "ids", "df", "epoch", "queue_s", "n", "seq", "ctxs",
                 "wd_gen", "wd_tries", "wd_expiries")

    def __init__(self, rows, ids, df, epoch, queue_s, ctxs=None):
        self.rows = rows        # [(rid, body, headers), ...]
        self.ids = ids          # np.int64 array
        self.df = df            # ingress DataFrame (id/value/headers/origin)
        self.epoch = epoch      # journal epoch (None when journaling is off)
        self.queue_s = queue_s  # mean ingress->drain wait of the batch
        self.n = len(rows)
        self.seq = 0            # executor pipeline sequence number
        # rid -> sampled SpanContext for traced requests in this batch
        self.ctxs = ctxs if ctxs is not None else {}
        # hung-dispatch watchdog bookkeeping (executor lock guards all
        # three): generation claims stale-ify a wedged dispatch's late
        # return, tries bound re-dispatches, expiries bound budget doubling
        self.wd_gen = 0
        self.wd_tries = 0
        self.wd_expiries = 0


class ServingServer:
    """Serve a DataFrame->DataFrame function over HTTP.

    The transform receives a DataFrame with columns:
      - ``id``:      request ids (opaque ints)
      - ``value``:   raw request body bytes
      - ``headers``: per-row dict of request headers
    and must return a DataFrame containing ``id`` and a reply column
    (default "reply") holding str/bytes/dict per row. Returning an EMPTY
    DataFrame means "answered elsewhere": rows stay pending for the
    cross-worker replyTo hop. A non-empty output without the reply column is
    a configuration error and fails the batch with 500s.

    ``token``: optional shared cluster secret. When set, the internal reply
    endpoint requires the ``X-MMLSpark-Token`` header — set the same token on
    every worker and the RoutingFront. The public API is the intended open
    surface; the internal endpoints are cluster-internal (the reference's
    equivalents sit inside the Spark cluster's network boundary,
    HTTPSourceV2.scala:516-545).
    """

    # internal reply endpoint (cross-machine replyTo, HTTPSourceV2.scala:516-545)
    INTERNAL_REPLY_PATH = "/_mmlspark/reply"
    #: Prometheus text-format exposition (obs/metrics.py registry + bridge)
    METRICS_PATH = "/_mmlspark/metrics"
    #: constant-cost liveness probe (the RoutingFront's PROBE_PATH): a tiny
    #: fixed payload instead of the full /_mmlspark/stats summary, whose
    #: cost scales with the latency window / executor timeline sizes
    HEALTH_PATH = "/_mmlspark/healthz"
    #: buffered spans as JSON (debug surface; exporters write JSONL/Perfetto)
    TRACE_PATH = "/_mmlspark/trace"
    #: fleet controller's capacity recommendation (serving/fleet): the
    #: cross-pod scaling signal an external scaler / helm HPA consumes
    CAPACITY_PATH = "/_mmlspark/capacity"
    #: model-lifecycle registry view (serving/lifecycle): versions, states,
    #: rollout journal — 404 when the lifecycle plane is off
    MODELS_PATH = "/_mmlspark/models"
    #: batched labeled-feedback ingress for train-on-serve (POST
    #: {"rows": [...], "labels": [...]}) — 404 when the plane is off
    FEEDBACK_PATH = "/_mmlspark/feedback"
    #: model-mall view (serving/multimodel): admitted models, residency,
    #: packing plan, AutoML trials — 404 when the multimodel plane is off
    MALL_PATH = "/_mmlspark/mall"

    def __init__(self, transform: Callable[[DataFrame], DataFrame],
                 host: str = "127.0.0.1", port: int = 8898,
                 api_path: str = "/", reply_col: str = "reply",
                 max_batch_size: int = 64, max_wait_ms: float = 5.0,
                 slot_timeout_s: float = 60.0, token: Optional[str] = None,
                 journal_path: Optional[str] = None,
                 name: str = "serving",
                 ingest_stats: Optional[Callable[[], Optional[dict]]] = None,
                 fusion_stats: Optional[Callable[[], Optional[dict]]] = None,
                 max_queue: int = 0, drain_timeout_s: float = 5.0,
                 async_exec: bool = False, inflight: int = 2,
                 replicas: int = 1, adaptive_batching: bool = True,
                 batch_alpha: float = 0.5, batch_min_wait_ms: float = 0.0,
                 batch_max_wait_ms: Optional[float] = None,
                 devices: Optional[list] = None, controller=None,
                 tuner=None,
                 obs: bool = True, tracer: Optional[Tracer] = None,
                 trace_sample_rate: float = 1.0,
                 http_mode: str = "thread",
                 wire_binary: bool = True,
                 tenants=None, slo=None,
                 metrics_exemplars: bool = False,
                 supervise: bool = True,
                 watchdog_budget_s: Optional[float] = None,
                 watchdog_k: float = 8.0,
                 watchdog_min_budget_s: float = 1.0,
                 probe_fn: Optional[Callable] = None,
                 brownout=None, brownout_hooks=None,
                 fleet=None, fleet_hooks=None,
                 lifecycle=None, lifecycle_hooks=None,
                 multimodel=None, multimodel_hooks=None):
        self.transform = transform
        # optional provider of the device-ingest decomposition (queue/h2d/
        # compute/readback — parallel/ingest.IngestStats.summary) merged into
        # the /_mmlspark/stats payload; serve_pipeline wires it automatically
        # for stages that expose last_ingest_stats
        self.ingest_stats = ingest_stats
        # optional provider of the pipeline-fusion report (segment layout,
        # per-segment compute, compile-cache hit rate — core/fusion.py
        # fusion_stats()); serve_pipeline wires it for fused pipelines
        self.fusion_stats = fusion_stats
        self.host = host
        self.port = port
        self.slot_timeout_s = slot_timeout_s
        self.api_path = api_path.rstrip("/") or "/"
        self.reply_col = reply_col
        self.max_batch_size = max_batch_size
        self.max_wait_ms = max_wait_ms
        self.name = name
        self.token = token
        # bounded admission: above max_queue pending requests, new arrivals
        # load-shed with 503 + Retry-After instead of growing latency without
        # bound (0 = unbounded, the legacy behavior)
        self.max_queue = max_queue
        self.drain_timeout_s = drain_timeout_s
        self._draining = threading.Event()
        # write-ahead journal => epoch/commit semantics (journal.py): each
        # drained batch is an epoch, committed once every request is answered
        self._journal = None
        self._epoch = 0
        self._epoch_rids: Dict[int, set] = {}
        self._journal_lock = threading.Lock()  # serializes epoch bookkeeping
        if journal_path:
            from .journal import RequestJournal

            self._journal = RequestJournal(journal_path)
        # async pipelined executor knobs (serving/executor.py): when
        # async_exec is set, start() runs the drain/compute/readback pipeline
        # instead of the serial loop — same batch semantics, same replies
        self.async_exec = bool(async_exec)
        self.inflight = max(1, int(inflight))
        self.replicas = max(1, int(replicas))
        self.adaptive_batching = bool(adaptive_batching)
        # adaptive-controller knobs (previously constructor-only defaults on
        # AdaptiveBatchController, invisible at runtime): target queue/
        # compute ratio and the window clamp — live values surface in
        # /_mmlspark/stats async.controller
        self.batch_alpha = float(batch_alpha)
        self.batch_min_wait_ms = float(batch_min_wait_ms)
        self.batch_max_wait_ms = batch_max_wait_ms
        self._devices = devices
        self._controller = controller
        # cost-model auto-tuner (core/tune.py): when set, both serving
        # loops tick it per batch (refit/apply every tuner.every batches,
        # one-step rollback on measured e2e regression); its state is the
        # ``tuner`` section of /_mmlspark/stats and the mmlspark_tuner_*
        # families. serve_pipeline(autotune=...) wires it for fused models.
        self._tuner = tuner
        # supervision layer (serving/supervisor.py): with async_exec, a
        # ReplicaSupervisor ejects/probes/readmits unhealthy replicas and a
        # DispatchWatchdog re-dispatches wedged batches. Passive when
        # healthy — replies are bitwise-identical to supervise=False.
        self.supervise = bool(supervise)
        self.watchdog_budget_s = watchdog_budget_s
        self.watchdog_k = float(watchdog_k)
        self.watchdog_min_budget_s = float(watchdog_min_budget_s)
        self._probe_fn = probe_fn
        # brownout controller (serving/supervisor.py BrownoutController):
        # staged graceful degradation on SLO burn — None/False = off (the
        # default; enabling requires the slo knob). Built in start() so the
        # steps can capture the live controller/executor.
        self._brownout_spec = brownout
        # extra degradation hooks from serve_pipeline: {step name:
        # (apply_fn, revert_fn)} — e.g. the fusion planner's host-fallback
        # demotion for optional segments
        self._brownout_hooks = dict(brownout_hooks or {})
        self._brownout = None
        # fleet control plane (serving/fleet): persistent-cache-aware
        # capacity planner + autoscale controller. None/False = off (the
        # default — fleet=False stays bitwise-identical). Built in start()
        # so the hooks can capture the live executor/SLO tracker; extra
        # hooks (set_mega_k, predict_ms) arrive from serve_pipeline.
        self._fleet_spec = fleet
        self._fleet_hooks = dict(fleet_hooks or {})
        self._fleet = None
        # model lifecycle plane (serving/lifecycle): versioned registry +
        # shadow-scored canary rollout + train-on-serve. None/False = off
        # (the default — lifecycle=False stays bitwise-identical). Built in
        # start() BEFORE the replica set, so replicas capture the plane as
        # their transform; hooks (warm, live_stage, ...) arrive from
        # serve_pipeline.
        self._lifecycle_spec = lifecycle
        self._lifecycle_hooks = dict(lifecycle_hooks or {})
        self._lifecycle = None
        # model mall (serving/multimodel): N independent fitted pipelines
        # routed by X-MMLSpark-Model through per-model lifecycle planes,
        # cost-packed onto replicas, with idle-capacity AutoML trials.
        # None/False = off (the default — multimodel=None stays
        # bitwise-identical in replies AND metrics exposition). Built in
        # start() BEFORE the replica set, like the lifecycle plane; when
        # both knobs are set the mall owns the per-model planes and the
        # lifecycle spec becomes every model's canary config.
        self._multimodel_spec = multimodel
        self._multimodel_hooks = dict(multimodel_hooks or {})
        self._multimodel = None
        self._executor = None
        self._queue: "queue_mod.Queue" = queue_mod.Queue()
        # wake latch: set on every enqueue and on stop(), so the batcher's
        # first-request wait is event-driven instead of a 0.2s poll
        self._wake = threading.Event()
        self._slots: Dict[int, _ReplySlot] = {}
        # random start: ids are routing handles that ride to peer workers, so
        # don't make them guessable from zero (defense alongside `token`)
        self._next_id = random.SystemRandom().randrange(1 << 48)
        self._id_lock = threading.Lock()
        self._stop = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []
        self.requests_served = 0
        self.stats = LatencyStats()
        # HTTP transport: "thread" = ThreadingHTTPServer (legacy, one thread
        # per connection), "async" = event-loop transport (serving/aio.py,
        # keep-alive pooling + pipelined reads on one thread)
        if http_mode not in ("thread", "async"):
            raise ValueError(f"http_mode must be 'thread' or 'async', "
                             f"got {http_mode!r}")
        self.http_mode = http_mode
        self._aio = None  # AsyncHTTPServer when http_mode == "async"
        # binary wire (io/binary.py frames): validate + account frame bodies
        # at ingress; False treats frames as opaque bytes (no negotiation)
        self.wire_binary = bool(wire_binary)
        # per-wire-format request/byte counters (obs bridge exports them)
        self._wire_lock = threading.Lock()
        self.wire_counts: Dict[str, int] = {"json": 0, "binary": 0}
        self.wire_bytes: Dict[str, int] = {"json": 0, "binary": 0}
        # per-tenant weighted-fair admission (serving/tenants.py): a dict of
        # weights or a TenantAdmission; None = legacy global queue shed
        if tenants is not None and not isinstance(tenants, TenantAdmission):
            tenants = TenantAdmission(dict(tenants))
        self._tenants: Optional[TenantAdmission] = tenants
        self.warmup_ok: Optional[bool] = None  # None until warmup() runs
        # observability (obs/): per-server metrics registry with bridge
        # collectors over the existing stats surfaces + a tracer whose
        # head-based sampling decision rides X-MMLSpark-Trace across hops.
        # ``obs=False`` strips the whole layer (the bench A/B baseline).
        self.obs_enabled = bool(obs)
        self.registry: Optional[MetricsRegistry] = None
        self.tracer: Optional[Tracer] = None
        self._traces: Dict[int, obs_trace.SpanContext] = {}
        # perf attribution layer (obs/perf.py): a latency HISTOGRAM whose
        # buckets carry trace-id exemplars (the metrics->traces link), a
        # declarative latency SLO with multi-window burn-rate gauges (the
        # HPA signal), and the device-memory collector. ``slo`` accepts an
        # SLOConfig/dict, False to disable, or None for the default
        # objective; ``metrics_exemplars`` gates the OpenMetrics exemplar
        # syntax on /_mmlspark/metrics (always present in /_mmlspark/stats).
        self.metrics_exemplars = bool(metrics_exemplars)
        self._slo: Optional[obs_perf.SLOTracker] = None
        self._lat_hist = None
        if self.obs_enabled:
            self.registry = MetricsRegistry()
            self.tracer = tracer if tracer is not None else Tracer(
                sample_rate=trace_sample_rate, service=name)
            obs_bridge.fold_server(self.registry, self)
            obs_bridge.fold_tracer(self.registry, self.tracer)
            self._slo = obs_perf.make_slo(slo)
            if self._slo is not None:
                self.registry.register_collector(self._slo.families)
            self._lat_hist = self.registry.histogram(
                "mmlspark_request_duration_seconds",
                "end-to-end request latency (ingress to reply write)",
                buckets=SERVING_LATENCY_BUCKETS)
            obs_perf.fold_device_memory(self.registry)

    # -- ingress (transport-agnostic request handling) -------------------
    #
    # Both HTTP transports route through the same four helpers, so replies
    # are bitwise-identical between http_mode="thread" and "async":
    #   _handle_control -> control-plane endpoints (None = the api path)
    #   _preflight      -> admission (drain/deadline/frame/tenant gates)
    #   _enqueue        -> reply slot + ingress queue
    #   _finish         -> response bytes + stats/trace stamping

    def _handle_control(self, path: str, body: bytes, headers
                        ) -> Optional[Tuple[int, str, bytes,
                                            Optional[Dict[str, str]]]]:
        """Answer a control-plane request: (status, content_type, body,
        extra_headers), or None when ``path`` is the public api path."""
        if path == ServingServer.INTERNAL_REPLY_PATH:
            # peer worker answering a request that entered here
            # (sendReplyUDF -> replyTo hop, ServingUDFs.scala:36-48)
            if self.token is not None and \
                    headers.get(TOKEN_HEADER) != self.token:
                return (403, "application/json",
                        b'{"error": "bad or missing cluster token"}', None)
            try:
                msg = json.loads(body.decode("utf-8"))
                self._fulfill(
                    int(msg["id"]), int(msg.get("status", 200)),
                    base64.b64decode(msg["body_b64"]),
                    content_type=msg.get("content_type"))
                self._maybe_commit_epochs()
                return (200, "application/json", b"", None)
            except Exception as e:  # noqa: BLE001
                return (400, "application/json", json.dumps(
                    {"error": str(e)}).encode("utf-8"), None)
        if path == "/_mmlspark/stats":
            # latency decomposition endpoint (verdict item: prove the
            # framework's share of serving latency is sub-ms); with a
            # device pipeline behind the transform, "compute" further
            # decomposes into the ingest stages (queue/h2d/compute/
            # readback per batch)
            summary = self.stats.summary()
            if self._executor is not None:
                try:
                    summary["async"] = self._executor.stats()
                except Exception as e:  # noqa: BLE001
                    summary["async"] = {"error": str(e)}
            if self.ingest_stats is not None:
                try:
                    summary["ingest"] = self.ingest_stats()
                except Exception as e:  # noqa: BLE001
                    summary["ingest"] = {"error": str(e)}
            if self.fusion_stats is not None:
                try:
                    summary["fusion"] = self.fusion_stats()
                except Exception as e:  # noqa: BLE001
                    summary["fusion"] = {"error": str(e)}
            with self._wire_lock:
                summary["wire"] = {"requests": dict(self.wire_counts),
                                   "bytes": dict(self.wire_bytes)}
            if self._tenants is not None:
                summary["tenants"] = self._tenants.summary()
            if self._tuner is not None:
                try:
                    summary["tuner"] = self._tuner.stats()
                except Exception as e:  # noqa: BLE001
                    summary["tuner"] = {"error": str(e)}
            if self._aio is not None:
                summary["http"] = self._aio.stats()
            if self._slo is not None:
                summary["slo"] = self._slo.summary()
            if self._brownout is not None:
                summary["brownout"] = self._brownout.summary()
            if self._fleet is not None:
                try:
                    summary["fleet"] = self._fleet.summary()
                except Exception as e:  # noqa: BLE001
                    summary["fleet"] = {"error": str(e)}
            if self._lifecycle is not None:
                try:
                    summary["lifecycle"] = self._lifecycle.summary()
                except Exception as e:  # noqa: BLE001
                    summary["lifecycle"] = {"error": str(e)}
            if self._multimodel is not None:
                try:
                    summary["multimodel"] = self._multimodel.summary()
                except Exception as e:  # noqa: BLE001
                    summary["multimodel"] = {"error": str(e)}
            if self._lat_hist is not None:
                # bucket counts + trace-id exemplars, ALWAYS here (the
                # exposition carries them only behind metrics_exemplars)
                summary["latency_histogram"] = self._lat_hist.snapshot()
            return (200, "application/json",
                    json.dumps(summary).encode("utf-8"), None)
        if path == ServingServer.HEALTH_PATH:
            # constant-cost liveness probe: payload size does not
            # scale with the stats window (the old PROBE_PATH did)
            return (200, "application/json", json.dumps(
                {"ok": True,
                 "draining": self._draining.is_set()}).encode("utf-8"), None)
        if path == ServingServer.METRICS_PATH:
            if self.registry is None:
                return (404, "application/json",
                        b'{"error": "observability disabled"}', None)
            ex = self.metrics_exemplars
            ctype = MetricsRegistry.OPENMETRICS_CONTENT_TYPE if ex \
                else MetricsRegistry.CONTENT_TYPE
            return (200, ctype,
                    self.registry.exposition(exemplars=ex).encode("utf-8"),
                    None)
        if path == ServingServer.TRACE_PATH:
            if self.tracer is None:
                return (404, "application/json",
                        b'{"error": "observability disabled"}', None)
            return (200, "application/json", json.dumps(
                {"stats": self.tracer.stats(),
                 "spans": self.tracer.spans()}).encode("utf-8"), None)
        if path == ServingServer.CAPACITY_PATH:
            # fleet capacity recommendation (serving/fleet): the external
            # scaler / helm HPA polls this for recommended_replicas
            if self._fleet is None:
                return (404, "application/json",
                        b'{"error": "fleet disabled"}', None)
            try:
                payload = json.dumps(self._fleet.summary()).encode("utf-8")
            except Exception as e:  # noqa: BLE001
                return (500, "application/json", json.dumps(
                    {"error": str(e)}).encode("utf-8"), None)
            return (200, "application/json", payload, None)
        if path == ServingServer.MODELS_PATH:
            # model-lifecycle registry view (serving/lifecycle): versions,
            # states, traffic shares, and the rollout decision journal
            if self._lifecycle is None:
                return (404, "application/json",
                        b'{"error": "lifecycle disabled"}', None)
            try:
                payload = json.dumps(
                    self._lifecycle.summary()).encode("utf-8")
            except Exception as e:  # noqa: BLE001
                return (500, "application/json", json.dumps(
                    {"error": str(e)}).encode("utf-8"), None)
            return (200, "application/json", payload, None)
        if path == ServingServer.MALL_PATH:
            # model-mall view (serving/multimodel): admitted models,
            # residency state, the current packing plan, and AutoML trials
            if self._multimodel is None:
                return (404, "application/json",
                        b'{"error": "multimodel disabled"}', None)
            try:
                payload = json.dumps(
                    self._multimodel.summary()).encode("utf-8")
            except Exception as e:  # noqa: BLE001
                return (500, "application/json", json.dumps(
                    {"error": str(e)}).encode("utf-8"), None)
            return (200, "application/json", payload, None)
        if path == ServingServer.FEEDBACK_PATH:
            # batched labeled feedback for train-on-serve: journaled
            # write-ahead, so a 200 means the examples will survive a crash
            if self._lifecycle is None:
                return (404, "application/json",
                        b'{"error": "lifecycle disabled"}', None)
            try:
                msg = json.loads(body.decode("utf-8"))
                n = self._lifecycle.feed_feedback(
                    msg["rows"], msg["labels"])
                return (200, "application/json", json.dumps(
                    {"journaled": n}).encode("utf-8"), None)
            except Exception as e:  # noqa: BLE001
                return (400, "application/json", json.dumps(
                    {"error": str(e)}).encode("utf-8"), None)
        if path != self.api_path:
            return (404, "application/json", b'{"error": "not found"}', None)
        return None

    def _preflight(self, headers, body: bytes):
        """Admission control for one public request. Returns
        ``(None, tenant, wire, tctx, t_wall_in)`` when admitted, or
        ``((status, ctype, body, extra), ...)`` with the shed response.

        Gate order (cheapest rejection first, matching the legacy handler):
        draining -> ingress deadline -> frame header validation -> queue /
        tenant weighted-fair admission. The frame gate means a malformed or
        hostile-length binary frame 400s HERE — before a slot, a journal
        write, or any transform work is spent on it."""
        tenant = TenantAdmission.tenant_of(headers) \
            if self._tenants is not None else None
        if self._draining.is_set():
            # graceful drain: stop accepting, finish what's in flight
            self.stats.record_shed(503, "draining", tenant=tenant)
            return ((503, "application/json", b'{"error": "server draining"}',
                     {"Retry-After": "1"}), None, None, None, 0.0)
        dl = deadline_from_headers(headers)
        if dl is not None and dl.expired():
            # already dead on arrival: never burns a batch slot
            self.stats.record_shed(504, "deadline_ingress", tenant=tenant)
            return ((504, "application/json", b'{"error": "deadline expired"}',
                     None), None, None, None, 0.0)
        # wire negotiation: binary frames are validated (bounded header
        # parse, hostile length fields rejected) before admission
        ctype = str(headers.get("Content-Type", "") or "")
        wire = "json"
        frame_dur = 0.0
        if self.wire_binary and ctype.split(";")[0].strip().lower() == \
                FRAME_CONTENT_TYPE:
            wire = "binary"
            t0 = time.perf_counter()
            try:
                frame_info(body)
            except FrameError as e:
                self.stats.record_shed(400, "bad_frame", tenant=tenant)
                return ((400, "application/json", json.dumps(
                    {"error": f"bad frame: {e}"}).encode("utf-8"), None),
                    None, None, None, 0.0)
            frame_dur = time.perf_counter() - t0
        if self._multimodel is not None:
            # unknown-model 404 BEFORE admission: a request naming a model
            # the mall never admitted must not burn a queue slot or a
            # tenant's weighted-fair share
            m = self._multimodel.model_of(headers, body)
            if m is not None and not self._multimodel.has_model(m):
                self.stats.record_shed(404, "unknown_model", tenant=tenant)
                return ((404, "application/json",
                         b'{"error": "unknown model"}', None),
                        None, None, None, 0.0)
        if self._tenants is not None:
            if not self._tenants.try_admit(
                    tenant, self._queue.qsize(), self.max_queue):
                # weighted-fair shed: THIS tenant is over its share of a
                # full queue (light tenants within share still get in)
                self.stats.record_shed(503, "tenant_over_share",
                                       tenant=tenant)
                return ((503, "application/json",
                         b'{"error": "tenant over admission share"}',
                         {"Retry-After": "1"}), None, None, None, 0.0)
        elif self.max_queue and self._queue.qsize() >= self.max_queue:
            self.stats.record_shed(503, "queue_full", tenant=tenant)
            return ((503, "application/json",
                     b'{"error": "admission queue full"}',
                     {"Retry-After": "1"}), None, None, None, 0.0)
        with self._wire_lock:
            self.wire_counts[wire] += 1
            self.wire_bytes[wire] += len(body)
        # trace ingress: continue the hop in X-MMLSpark-Trace or
        # originate one (head-based sampling decides HERE; batch
        # stages only ever see sampled contexts)
        tctx = None
        t_wall_in = time.time()
        if self.tracer is not None:
            tctx = self.tracer.ingress(headers)
            if not tctx.sampled:
                tctx = None
            elif wire == "binary":
                # frame span: header-validation cost + wire bytes, so the
                # binary path's ingress share is visible per traced request
                self.tracer.record("frame", tctx, t_wall_in, frame_dur,
                                   bytes=len(body))
        return (None, tenant, wire, tctx, t_wall_in)

    def _enqueue(self, body: bytes, headers: Dict[str, str],
                 tenant: Optional[str], tctx,
                 waiter: Optional[Callable[[], None]] = None
                 ) -> Tuple[int, _ReplySlot]:
        """Register a reply slot and put the request on the batch queue.
        ``waiter`` (async transport) is attached BEFORE the enqueue so a
        fulfillment can never race past it."""
        slot = _ReplySlot()
        slot.t_in = time.perf_counter()
        slot.tenant = tenant
        slot.waiter = waiter
        with self._id_lock:
            rid = self._next_id
            self._next_id += 1
            self._slots[rid] = slot
            if tctx is not None:
                self._traces[rid] = tctx
        self._queue.put((rid, body, dict(headers.items())))
        self._wake.set()
        return rid, slot

    def _pop_slot(self, rid: int) -> Optional[_ReplySlot]:
        """Remove a slot (idempotent) and release its tenant share exactly
        once — whichever of _fulfill / the transport cleanup pops first."""
        with self._id_lock:
            slot = self._slots.pop(rid, None)
            self._traces.pop(rid, None)
        if slot is not None and slot.tenant is not None \
                and self._tenants is not None:
            self._tenants.release(slot.tenant)
        return slot

    def _finish(self, rid: int, slot: _ReplySlot, tctx, ok: bool,
                t_wall_in: float):
        """Build the response for a waited-on slot: returns ((status, ctype,
        body, extra), after_write) — ``after_write()`` stamps the latency row
        and ingress span and must run after the transport writes the reply
        (so overhead = total - queue - compute includes the reply write)."""
        self._pop_slot(rid)
        if not ok:
            self.stats.record_shed(504, "slot_timeout", tenant=slot.tenant)
            total_s = time.perf_counter() - slot.t_in
            if self._slo is not None:
                # a timed-out slot burns error budget regardless of how
                # fast the 504 itself was written
                self._slo.record(total_s, breach=True)
            if self._lat_hist is not None:
                self._lat_hist.observe(
                    total_s, exemplar={"trace_id": tctx.trace_id}
                    if tctx is not None else None)
            if tctx is not None:
                self.tracer.record(
                    "ingress", tctx, t_wall_in,
                    time.perf_counter() - slot.t_in, status=504)
            return ((504, "application/json", b'{"error": "batch timeout"}',
                     None), None)

        def after_write():
            # stamp the total HERE (post wakeup + HTTP write) so
            # overhead = total - queue - compute measures the slot
            # wakeup and response write, not zero by construction
            t_end = time.perf_counter()
            total_s = t_end - slot.t_in
            if slot.t_in and slot.t_drain and slot.t_done:
                self.stats.record(slot.t_drain - slot.t_in,
                                  slot.t_done - slot.t_drain,
                                  total_s, slot.batch)
            if self._slo is not None:
                self._slo.record(total_s)
            if self._lat_hist is not None:
                # the exemplar pins THIS request's trace_id to the latency
                # bucket it landed in: a p99 spike in the scrape is one
                # click from its Perfetto timeline
                self._lat_hist.observe(
                    total_s, exemplar={"trace_id": tctx.trace_id}
                    if tctx is not None else None)
            if tctx is not None:
                # the request's root span on this hop: covers queue wait,
                # batch stages (its children), and the reply write
                self.tracer.record(
                    "ingress", tctx, t_wall_in,
                    time.perf_counter() - slot.t_in,
                    status=slot.status, batch=slot.batch)

        return ((slot.status, slot.content_type, slot.body, None),
                after_write)

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _respond(self, status, ctype, body, extra):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _handle(self):
                path = self.path.rstrip("/") or "/"
                length = int(self.headers.get("Content-Length", 0) or 0)
                body = self.rfile.read(length) if length else b""
                ctrl = server._handle_control(path, body, self.headers)
                if ctrl is not None:
                    self._respond(*ctrl)
                    return
                shed, tenant, _wire, tctx, t_wall_in = \
                    server._preflight(self.headers, body)
                if shed is not None:
                    self._respond(*shed)
                    return
                rid, slot = server._enqueue(body, self.headers, tenant, tctx)
                ok = slot.event.wait(timeout=server.slot_timeout_s)
                resp, after_write = server._finish(rid, slot, tctx, ok,
                                                   t_wall_in)
                self._respond(*resp)
                if after_write is not None:
                    after_write()

            do_POST = _handle
            do_GET = _handle

        return Handler

    async def _aio_handle(self, req):
        """The async transport's request handler (serving/aio.py): same
        helpers as the threaded path, with the reply-slot wait bridged to
        the event loop via the slot's threadsafe ``waiter`` callback."""
        import asyncio

        from .aio import HTTPResponse

        path = req.path.split("?", 1)[0].rstrip("/") or "/"
        ctrl = self._handle_control(path, req.body, req.headers)
        if ctrl is not None:
            status, ctype, body, extra = ctrl
            return HTTPResponse(status, body, ctype, extra)
        shed, tenant, _wire, tctx, t_wall_in = \
            self._preflight(req.headers, req.body)
        if shed is not None:
            status, ctype, body, extra = shed
            return HTTPResponse(status, body, ctype, extra)
        loop = asyncio.get_running_loop()
        done = asyncio.Event()

        def waiter():  # called from the batcher/executor thread
            try:
                loop.call_soon_threadsafe(done.set)
            except RuntimeError:  # loop closing mid-shutdown
                pass

        rid, slot = self._enqueue(req.body, req.headers, tenant, tctx,
                                  waiter=waiter)
        try:
            await asyncio.wait_for(done.wait(), timeout=self.slot_timeout_s)
            ok = True
        except asyncio.TimeoutError:
            ok = slot.event.is_set()  # lost-wakeup safety: trust the slot
        resp, after_write = self._finish(rid, slot, tctx, ok, t_wall_in)
        status, ctype, body, extra = resp
        out = HTTPResponse(status, body, ctype, extra)
        if after_write is not None:
            # the event loop writes the response after returning; the stamp
            # lands post-render here (the threaded path stamps post-write)
            after_write()
        return out

    # -- batching loop (the continuous query) ----------------------------
    def _next_request(self):
        """Stop-aware wait for the first queued request: wakes immediately
        on a new arrival or on stop() via the ``_wake`` latch (the old fixed
        0.2s poll burned 5 idle wakeups/sec and held shutdown up to 200ms).
        Returns None when stopping."""
        while True:
            try:
                return self._queue.get_nowait()
            except queue_mod.Empty:
                pass
            if self._stop.is_set():
                return None
            self._wake.clear()
            # re-check after clear: an enqueue between get_nowait and clear
            # would otherwise be a lost wakeup
            if not self._queue.empty():
                continue
            self._wake.wait(timeout=1.0)  # timeout = lost-wakeup safety net

    def _coalesce(self, first, max_wait_ms: float):
        """Gather up to max_batch_size requests within ``max_wait_ms`` after
        ``first`` (DynamicBatcher semantics, stages/Batchers.scala)."""
        batch = [first]
        deadline = time.perf_counter() + max_wait_ms / 1000.0
        while len(batch) < self.max_batch_size:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                batch.append(self._queue.get(timeout=remaining))
            except queue_mod.Empty:
                break
        return batch

    def _drain_batch(self, max_wait_ms: Optional[float] = None):
        """Block for the first request, then gather up to max_batch_size
        within the coalescing window (``max_wait_ms`` overrides the static
        knob — the async executor passes the adaptive controller's window)."""
        first = self._next_request()
        if first is None:
            return None
        return self._coalesce(
            first, self.max_wait_ms if max_wait_ms is None else max_wait_ms)

    def _gate_deadlines(self, batch, stage: str):
        """Answer 504 for requests whose deadline expired while queued or
        staged (pre-journal for the queue gate, pre-dispatch for the
        in-flight gate) so a backed-up server never spends compute on
        replies nobody is waiting for. Returns the live rows."""
        live = []
        for rid, body, hdrs in batch:
            dl = deadline_from_headers(hdrs)
            if dl is not None and dl.expired():
                self.stats.record_shed(504, f"deadline_{stage}")
                self._fulfill(
                    rid, 504,
                    b'{"error": "deadline expired in %s"}' %
                    (b"queue" if stage == "queue" else b"flight"),
                    content_type="application/json")
            else:
                live.append((rid, body, hdrs))
        return live

    def _build_df(self, batch):
        """Ingress rows -> (ids array, transform input DataFrame)."""
        ids = np.array([b[0] for b in batch], dtype=np.int64)
        bodies = np.empty(len(batch), dtype=object)
        headers = np.empty(len(batch), dtype=object)
        for i, (_, body, hdrs) in enumerate(batch):
            bodies[i] = body
            headers[i] = hdrs
        origin = np.empty(len(batch), dtype=object)
        origin[:] = self.address
        df = DataFrame([{"id": ids, "value": bodies, "headers": headers,
                         "origin": origin}])
        return ids, df

    def _prepare_batch(self, batch) -> Optional[_Prepared]:
        """Deadline-gate, stamp, journal, and build the transform input for
        one drained batch — shared by the sync loop and the async executor
        so both modes have identical epoch/journal/gate semantics. Returns
        None when every request expired while queued."""
        batch = self._gate_deadlines(batch, "queue")
        if not batch:
            return None
        t_drain = time.perf_counter()
        waits = []
        ctxs = {}
        with self._id_lock:
            for rid, _, _ in batch:
                s = self._slots.get(rid)
                if s is not None:
                    s.t_drain = t_drain
                    s.batch = len(batch)
                    waits.append(t_drain - s.t_in)
                ctx = self._traces.get(rid)
                if ctx is not None:
                    ctxs[rid] = ctx
        ids, df = self._build_df(batch)
        epoch = None
        if self._journal is not None:
            with self._journal_lock:
                self._epoch += 1
                epoch = self._epoch
                self._epoch_rids[epoch] = {int(r) for r in ids}
            try:
                self._journal.append_many(epoch, batch)
            except Exception:  # noqa: BLE001 — serve degraded, not dead
                # a journal WRITE failure must not take serving down: the
                # batch is still answered below, so the only loss window is
                # a crash mid-transform of this one epoch
                pass
        queue_s = float(sum(waits) / len(waits)) if waits else 0.0
        return _Prepared(batch, ids, df, epoch, queue_s, ctxs=ctxs)

    def _regate_inflight(self, prep: _Prepared) -> Optional[_Prepared]:
        """Re-run the deadline gate on a staged batch just before dispatch
        (async executor: a request can expire while its batch waits in the
        submit queue). Returns the surviving _Prepared or None."""
        live = self._gate_deadlines(prep.rows, "inflight")
        if len(live) == len(prep.rows):
            return prep
        if not live:
            return None
        ids, df = self._build_df(live)
        keep = {rid for rid, _, _ in live}
        ctxs = {rid: c for rid, c in prep.ctxs.items() if rid in keep}
        out = _Prepared(live, ids, df, prep.epoch, prep.queue_s, ctxs=ctxs)
        out.seq = prep.seq
        out.wd_tries = prep.wd_tries
        out.wd_expiries = prep.wd_expiries
        return out

    def _trace_batch(self, name: str, prep: "_Prepared", t0_wall: float,
                     dur_s: float, **attrs) -> None:
        """Record one batch-stage span per traced request in ``prep``
        (no-op when obs is off or nothing in the batch is sampled)."""
        if self.tracer is not None and prep.ctxs:
            self.tracer.record_batch(name, list(prep.ctxs.values()),
                                     t0_wall, dur_s, rows=prep.n, **attrs)

    def _apply_output(self, ids, out) -> None:
        """Fulfill reply slots from a transform output DataFrame (errors
        degrade to 500s for the whole batch, never kill the loop)."""
        try:
            data = out.collect()
            has_rows = any(len(v) for v in data.values())
            if "id" in data and self.reply_col in data:
                out_ids, replies = data["id"], data[self.reply_col]
            elif not has_rows:
                # empty output => nothing answered locally (handoff)
                out_ids, replies = (), ()
            else:
                # rows but no id/reply column: a misconfigured transform,
                # not a handoff — fail fast instead of letting every
                # client hang to the slot timeout
                raise KeyError(
                    f"transform output has rows but no 'id' + "
                    f"'{self.reply_col}' columns (got {list(data)})")
            for rid, reply in zip(out_ids, replies):
                if reply is None:
                    self._fulfill(int(rid), 204, b"")
                else:
                    self._fulfill(int(rid), 200, reply)
            # rows ABSENT from the output stay pending: another worker may
            # answer them via the internal replyTo endpoint; otherwise the
            # slot times out with 504 (HTTPSourceV2 leaves unanswered
            # requests to the epoch timeout the same way)
        except Exception as e:  # noqa: BLE001 — failed batch -> 500s
            self._fail_batch(ids, e)

    def _fail_batch(self, ids, e: BaseException) -> None:
        for rid in ids:
            self._fulfill(int(rid), 500, json.dumps(
                {"error": str(e)}).encode("utf-8"))

    def _loop(self):
        while not self._stop.is_set():
            batch = self._drain_batch()
            if not batch:
                continue
            tw, tp = time.time(), time.perf_counter()
            t_b0 = tp
            prep = self._prepare_batch(batch)
            if prep is None:
                continue
            self._trace_batch("drain", prep, tw, time.perf_counter() - tp)
            tw, tp = time.time(), time.perf_counter()
            try:
                # batch_context makes the traced requests visible to deep
                # layers (TransferRing H2D staging, fused segments)
                with obs_trace.batch_context(self.tracer,
                                             list(prep.ctxs.values())):
                    out = self.transform(prep.df)
            except Exception as e:  # noqa: BLE001 — keep serving
                self._trace_batch("dispatch", prep, tw,
                                  time.perf_counter() - tp, error=str(e))
                self._fail_batch(prep.ids, e)
            else:
                self._trace_batch("dispatch", prep, tw,
                                  time.perf_counter() - tp)
                tw, tp = time.time(), time.perf_counter()
                self._apply_output(prep.ids, out)
                self._trace_batch("readback", prep, tw,
                                  time.perf_counter() - tp)
            self._maybe_commit_epochs()
            self._tuner_tick(prep.queue_s + time.perf_counter() - t_b0)

    def _tuner_tick(self, e2e_s: float) -> None:
        """Per-batch auto-tuner heartbeat — shared by the sync loop and the
        pipelined executor's readback thread. No-op without a tuner; a
        tuner failure degrades to untuned serving, never a dead loop."""
        if self._tuner is not None:
            try:
                self._tuner.on_epoch(e2e_s)
            except Exception:  # noqa: BLE001 — tuning must never kill serving
                pass
        if self._brownout is not None:
            try:
                self._brownout.check()
            except Exception:  # noqa: BLE001 — brownout must never kill serving
                pass
        if self._fleet is not None:
            try:
                self._fleet.tick(e2e_s)
            except Exception:  # noqa: BLE001 — scaling must never kill serving
                pass
        if self._lifecycle is not None:
            try:
                self._lifecycle.tick(e2e_s)
            except Exception:  # noqa: BLE001 — rollout control must never
                pass           # kill serving
        if self._multimodel is not None:
            try:
                self._multimodel.tick(e2e_s)
            except Exception:  # noqa: BLE001 — packing/eviction/trials must
                pass           # never kill serving

    def _fleet_live_config(self) -> Dict[str, Any]:
        """The fleet controller's view of the live knob vector (its
        ``live_config`` hook): what is ACTUALLY running, against which a
        plan's recommendation is diffed before any apply."""
        cfg: Dict[str, Any] = {"replicas": self.capacity,
                               "inflight": None, "mega_k": None}
        ex = self._executor
        if ex is not None:
            cfg["inflight"] = int(ex.inflight)
        mk = getattr(self.transform, "mega_k", None)
        if mk is not None:
            try:
                cfg["mega_k"] = int(mk() or 1)
            except Exception:  # noqa: BLE001 — unknown reads as None
                cfg["mega_k"] = None
        return cfg

    def _brownout_steps(self) -> list:
        """Declared degradation ladder, in escalation order. Each step is a
        reversible knob change; restoring walks back the stack:

          1. ``batch_window`` — collapse the coalescing window (adaptive
             clamp + the sync loop's ``max_wait_ms``): stop spending
             latency budget on batching when the budget is already burning.
          2. ``demote_segments`` (serve_pipeline hook, fused pipelines) —
             demote optional light segments to the host path via the fusion
             planner's host-fallback overrides, freeing device time for the
             heavy segment.
          3. ``tighten_admission`` — halve the bounded-admission queue and
             scale per-tenant quotas by 0.5: shed earlier, shed fairly.
        """
        from .supervisor import BrownoutStep

        steps = []
        window_state: Dict[str, Any] = {}

        def window_apply():
            window_state["max_wait_ms"] = self.max_wait_ms
            self.max_wait_ms = 0.0
            if self._controller is not None:
                clamp = getattr(self._controller, "set_window_clamp", None)
                if callable(clamp):
                    window_state["clamp"] = clamp(
                        self._controller.min_wait_ms)

        def window_revert():
            self.max_wait_ms = window_state.pop("max_wait_ms",
                                                self.max_wait_ms)
            if self._controller is not None and "clamp" in window_state:
                self._controller.set_window_clamp(window_state.pop("clamp"))

        steps.append(BrownoutStep("batch_window", window_apply,
                                  window_revert))
        for name, (apply_fn, revert_fn) in self._brownout_hooks.items():
            steps.append(BrownoutStep(name, apply_fn, revert_fn))
        adm_state: Dict[str, Any] = {}

        def adm_apply():
            adm_state["max_queue"] = self.max_queue
            if self.max_queue:
                self.max_queue = max(1, self.max_queue // 2)
            if self._tenants is not None:
                pressure = getattr(self._tenants, "set_pressure", None)
                if callable(pressure):
                    adm_state["pressure"] = pressure(0.5)

        def adm_revert():
            self.max_queue = adm_state.pop("max_queue", self.max_queue)
            if self._tenants is not None and "pressure" in adm_state:
                self._tenants.set_pressure(adm_state.pop("pressure"))

        steps.append(BrownoutStep("tighten_admission", adm_apply,
                                  adm_revert))
        return steps

    def _maybe_commit_epochs(self, force: bool = False) -> None:
        """Commit every epoch whose requests are all answered or abandoned
        (their slots are gone) — HTTPSourceV2 commit() parity. Called from
        the batcher thread and peer-reply handler threads; _journal_lock
        serializes the check-commit-delete so an epoch commits exactly once.

        A commit WRITE failure (disk error, injected fault) must not kill the
        serving loop: the epoch stays pending and the commit retries on the
        next call — uncommitted epochs replay after a crash, which is exactly
        the at-least-once contract. ``force`` commits during shutdown (after
        ``_stop`` is set but before the journal closes)."""
        if self._journal is None or (self._stop.is_set() and not force):
            return
        with self._id_lock:
            live = set(self._slots)
        with self._journal_lock:
            for epoch in sorted(self._epoch_rids):
                if not (self._epoch_rids[epoch] & live):
                    try:
                        self._journal.commit(epoch)
                    except Exception:  # noqa: BLE001 — retried next round
                        continue
                    del self._epoch_rids[epoch]

    def _fulfill(self, rid: int, status: int, reply: Any,
                 content_type: Optional[str] = None):
        # pop-to-claim: the batcher thread and peer replyTo handler threads can
        # race on the same rid; exactly one wins the slot, so the waiting
        # client never sees a torn status/body pair (the pop also releases
        # the tenant's admission share exactly once)
        slot = self._pop_slot(rid)
        if slot is None:
            return
        if content_type is not None and isinstance(reply, (bytes, bytearray)):
            body, ctype = bytes(reply), content_type
        elif isinstance(reply, (dict, list)):
            body = json.dumps(reply, default=_json_default).encode("utf-8")
            ctype = "application/json"
        elif isinstance(reply, (bytes, bytearray)):
            body, ctype = bytes(reply), "application/octet-stream"
        elif isinstance(reply, np.ndarray):
            body = json.dumps(reply.tolist()).encode("utf-8")
            ctype = "application/json"
        elif reply is None:
            body, ctype = b"", "text/plain"
        else:
            body, ctype = str(reply).encode("utf-8"), "text/plain"
        slot.status = status
        slot.body = body
        slot.content_type = ctype
        # compute ends here; the REQUEST thread stamps the true total (after
        # event wakeup + HTTP write) and records the stats row — recording
        # here would make overhead = total - queue - compute identically 0
        slot.t_done = time.perf_counter()
        slot.event.set()
        if slot.waiter is not None:
            # async transport: wake the awaiting connection coroutine
            # (threadsafe; set AFTER event so the coroutine sees a final slot)
            try:
                slot.waiter()
            except Exception:  # noqa: BLE001 — loop gone mid-shutdown
                pass
        with self._id_lock:
            self.requests_served += 1

    def warmup(self, example_body: bytes,
               headers: Optional[Dict[str, str]] = None,
               sizes: Optional[List[int]] = None) -> "ServingServer":
        """Pre-compile the pipeline for the given batch sizes (default: 1 and
        max_batch_size) by pushing synthetic batches straight through the
        transform. After this, a lone request takes the already-compiled
        batch-1 executable — no first-hit compile, no padding to a bigger
        bucket (the warm batch-1 fast path of verdict item 4).

        Returns self; ``warmup_ok`` records whether every synthetic batch
        transformed cleanly (a failed warmup is logged, not raised — serving
        must start regardless, but the operator can see the first real
        request will still pay compile)."""
        import logging

        self.warmup_ok = True
        sizes = sizes or [1, self.max_batch_size]
        hdrs = dict(headers or {})
        for size in sizes:
            ids = np.arange(size, dtype=np.int64) - (1 << 60)  # never live ids
            bodies = np.empty(size, dtype=object)
            hs = np.empty(size, dtype=object)
            origin = np.empty(size, dtype=object)
            for i in range(size):
                bodies[i] = example_body
                hs[i] = hdrs
                origin[i] = self.address \
                    if (self._httpd is not None or self._aio is not None) \
                    else ""
            try:
                self.transform(DataFrame(
                    [{"id": ids, "value": bodies, "headers": hs,
                      "origin": origin}])).collect()
            except Exception:  # warmup must never block serving
                self.warmup_ok = False
                logging.getLogger("mmlspark_tpu.serving").warning(
                    "warmup batch of size %d failed — the first real request "
                    "at this size will pay compile", size, exc_info=True)
        return self

    @property
    def capacity(self) -> int:
        """Concurrent-batch capacity hint for the RoutingFront: the number
        of whole batches this worker can have in flight at once."""
        return self.replicas if self.async_exec else 1

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ServingServer":
        if self._multimodel_spec and self._multimodel is None:
            from .multimodel import make_multimodel

            # built FIRST (even before the lifecycle plane): the mall owns
            # one LifecyclePlane PER model and replaces the transform with
            # its router, so the replica set below captures the mall. A
            # standalone lifecycle= spec folds in as every model's canary
            # config rather than building a second, competing plane.
            spec = self._multimodel_spec
            if self._lifecycle_spec and self._lifecycle_spec is not True:
                if spec is True:
                    spec = {"lifecycle": self._lifecycle_spec}
                elif isinstance(spec, dict) and "lifecycle" not in spec:
                    spec = dict(spec, lifecycle=self._lifecycle_spec)
            mall = make_multimodel(spec, hooks=self._multimodel_hooks)
            if mall is not None:
                self.transform = mall.bind(self)
                mall.start()
                self._multimodel = mall
        if self._lifecycle_spec and self._lifecycle is None \
                and self._multimodel is None:
            from .lifecycle import make_lifecycle

            # built FIRST: the plane adopts the configured transform as the
            # live version and replaces it, so the replica set below (and
            # the sync loop) capture the plane — every batch then routes
            # through the version registry
            plane = make_lifecycle(self._lifecycle_spec,
                                   hooks=self._lifecycle_hooks)
            if plane is not None:
                self.transform = plane.bind(self)
                plane.start()
                self._lifecycle = plane
        if self.http_mode == "async":
            from .aio import AsyncHTTPServer

            self._aio = AsyncHTTPServer(self.host, self.port,
                                        self._aio_handle,
                                        name=f"{self.name}-aio")
            self._aio.start()
            self.port = self._aio.port  # resolve port 0
            self._threads = []
        else:
            self._httpd = ThreadingHTTPServer((self.host, self.port),
                                              self._make_handler())
            self.port = self._httpd.server_address[1]  # resolve port 0
            t_http = threading.Thread(
                target=lambda: self._httpd.serve_forever(poll_interval=0.05),
                daemon=True, name=f"{self.name}-http")
            t_http.start()
            self._threads = [t_http]
        if self.async_exec:
            from .executor import (AdaptiveBatchController, PipelinedExecutor,
                                   ReplicaSet)

            ctrl = self._controller
            if ctrl is None and self.adaptive_batching:
                max_wait = self.batch_max_wait_ms \
                    if self.batch_max_wait_ms is not None \
                    else max(self.max_wait_ms * 4, 50.0)
                ctrl = AdaptiveBatchController(
                    alpha=self.batch_alpha,
                    min_wait_ms=self.batch_min_wait_ms,
                    init_wait_ms=self.max_wait_ms,
                    max_wait_ms=max_wait)
                self._controller = ctrl
            rset = ReplicaSet(self.transform, n=self.replicas,
                              devices=self._devices)
            supervisor = watchdog = None
            if self.supervise:
                from .supervisor import DispatchWatchdog, ReplicaSupervisor

                # supervisor records track the PLACED replica indices
                # (placement skips can leave gaps)
                supervisor = ReplicaSupervisor(
                    [r.index for r in rset.replicas],
                    probe_fn=self._probe_fn)
                predict = None
                if self._tuner is not None:
                    predict = getattr(self._tuner, "predict_batch_ms", None)
                watchdog = DispatchWatchdog(
                    k=self.watchdog_k,
                    min_budget_s=self.watchdog_min_budget_s,
                    fixed_s=self.watchdog_budget_s,
                    predict_ms_fn=predict)
            self._executor = PipelinedExecutor(
                self, rset, controller=ctrl, inflight=self.inflight,
                supervisor=supervisor, watchdog=watchdog)
            self._executor.start()
            self._threads.extend(self._executor.threads)
        else:
            t_loop = threading.Thread(target=self._loop, daemon=True,
                                      name=f"{self.name}-batcher")
            t_loop.start()
            self._threads.append(t_loop)
        if self._brownout_spec:
            from .supervisor import make_brownout

            self._brownout = make_brownout(
                self._brownout_spec, self._slo, self._brownout_steps())
        if self._tuner is not None:
            # late-bind the layers the tuner steers: the adaptive window
            # seed and the live in-flight depth exist only after start()
            if getattr(self._tuner, "controller", None) is None:
                self._tuner.controller = self._controller
            if getattr(self._tuner, "executor", None) is None:
                self._tuner.executor = self._executor
        if self._fleet_spec:
            from .fleet import make_fleet

            hooks = dict(self._fleet_hooks)
            predict = hooks.pop("predict_ms", None)
            if predict is None and self._tuner is not None:
                # the tuner's calibrated cost model doubles as the
                # planner's service-time oracle
                predict = getattr(self._tuner, "predict_batch_ms", None)
            if predict is None:
                def predict(_rows):
                    return None  # uncalibrated: the planner holds steady
            hooks.setdefault("live_config", self._fleet_live_config)
            if self._executor is not None:
                hooks.setdefault("set_inflight", self._executor.set_inflight)
            if self._slo is not None:
                hooks.setdefault("arrival_buckets",
                                 self._slo.arrival_buckets)
            warm_plan = hooks.pop("warm_plan", None)
            self._fleet = make_fleet(
                self._fleet_spec, predict_ms=predict, slo=self._slo,
                brownout=self._brownout, hooks=hooks)
            if warm_plan and self._fleet is not None:
                # shipped capacity plan (knob-shipping snapshot): publish
                # it at /_mmlspark/capacity until the first local plan
                # outranks it, so a fresh pod advertises tuned capacity
                # from its first scrape
                try:
                    self._fleet.warm_start(warm_plan)
                except Exception:  # noqa: BLE001 — warm start best-effort
                    pass
        return self

    def stop(self, drain: bool = True) -> None:
        """Graceful by default: stop ACCEPTING (new requests get 503 +
        Retry-After), flush the in-flight epochs (queued requests still get
        answered), then shut down and commit/close the journal. ``drain=False``
        is the old hard stop (chaos tests use it to simulate a crash)."""
        started = self._httpd is not None or self._aio is not None
        if drain and started and not self._stop.is_set():
            self._draining.set()
            deadline = time.perf_counter() + self.drain_timeout_s
            while time.perf_counter() < deadline:
                with self._id_lock:
                    pending = bool(self._slots)
                if self._queue.empty() and not pending:
                    break
                time.sleep(0.01)
        self._stop.set()
        self._wake.set()  # release a batcher blocked on the first-get wait
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._aio is not None:
            self._aio.stop()
        # join the batcher/pipeline before closing the journal: an in-flight
        # batch must finish its append/commit on an open file
        if self._executor is not None:
            self._executor.stop()
        if self._lifecycle is not None:
            try:
                self._lifecycle.stop()
            except Exception:  # noqa: BLE001 — shutdown stays best-effort
                pass
        if self._multimodel is not None:
            try:
                self._multimodel.stop()
            except Exception:  # noqa: BLE001 — shutdown stays best-effort
                pass
        for t in self._threads:
            if t.name.endswith("-batcher"):
                t.join(timeout=5)
        if self._journal is not None:
            # final commit sweep: fully-answered epochs are committed even
            # though _stop is set, so a clean shutdown leaves nothing to replay
            try:
                self._maybe_commit_epochs(force=True)
            except Exception:  # noqa: BLE001 — closing anyway
                pass
            self._journal.close()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def reply_to(origin_address: str, rid: int, reply: Any, status: int = 200,
             timeout: float = 10.0, token: Optional[str] = None,
             policy: Optional["RetryPolicy"] = None,
             transport: Optional[Callable] = None) -> None:
    """Answer a request pending on another worker (sendReplyUDF/replyTo parity,
    ServingUDFs.scala:36-48): POST the reply to ``origin``'s internal handler,
    which responds on the cached exchange. The hop rides the shared retry
    stack (``send_with_retries`` + ``RetryPolicy``) — transient network
    failures back off and retry instead of dropping the reply.

    ``origin_address``: the ``origin`` column value the request carried
    (http://host:port/api); the internal endpoint lives on the same server.
    ``token``: the cluster secret, when the origin server was started with one.
    ``policy``/``transport``: retry policy override and injectable
    per-attempt send (tests stay offline).
    """
    from urllib.parse import urlsplit

    if isinstance(reply, (bytes, bytearray)):
        body, ctype = bytes(reply), "application/octet-stream"
    elif isinstance(reply, str):
        body, ctype = reply.encode("utf-8"), "text/plain"
    else:
        body = json.dumps(reply, default=_json_default).encode("utf-8")
        ctype = "application/json"
    parts = urlsplit(origin_address)
    url = f"{parts.scheme}://{parts.netloc}{ServingServer.INTERNAL_REPLY_PATH}"
    _post_json(url, {"id": int(rid), "status": int(status),
                     "content_type": ctype,
                     "body_b64": base64.b64encode(body).decode("ascii")},
               timeout=timeout, token=token, policy=policy,
               transport=transport)


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(type(o))


def serve_pipeline(stage, input_col: str, reply_col: str = "reply",
                   parse: str = "json", host: str = "127.0.0.1", port: int = 0,
                   api_path: str = "/", max_batch_size: int = 64,
                   max_wait_ms: float = 5.0, token: Optional[str] = None,
                   journal_path: Optional[str] = None,
                   max_queue: int = 0, fused: bool = False,
                   async_exec: bool = False, inflight: int = 2,
                   replicas: int = 1, adaptive_batching: bool = True,
                   batch_alpha: float = 0.5,
                   batch_min_wait_ms: float = 0.0,
                   batch_max_wait_ms: Optional[float] = None,
                   autotune: bool = False, tune_every: int = 50,
                   obs: bool = True,
                   trace_sample_rate: float = 1.0,
                   http_mode: str = "thread", wire_binary: bool = True,
                   tenants=None, slo=None,
                   metrics_exemplars: bool = False,
                   supervise: bool = True,
                   watchdog_budget_s: Optional[float] = None,
                   brownout=None, fleet=False,
                   lifecycle=False, multimodel=False) -> ServingServer:
    """Serve a fitted Transformer: request body -> ``input_col`` -> stage ->
    ``reply_col`` (IOImplicits fluent sugar parity, io/IOImplicits.scala:182-213).

    parse: 'json' (body -> dict/array) | 'text' | 'bytes'.

    ``fused=True`` compiles a PipelineModel's device-capable stages into
    shared XLA programs (``PipelineModel.fuse()``, core/fusion.py): the
    batch loop then executes the fused executables, and
    ``/_mmlspark/stats`` reports the segment layout, compile-cache hit
    rate, and per-segment compute alongside the ingest decomposition.

    ``async_exec=True`` serves through the pipelined executor
    (serving/executor.py): batch N+1 drains/journals while batch N computes
    (``inflight`` bounds staged-but-unfulfilled batches), ``replicas``
    copies of the pipeline dispatch round-robin across local devices, and
    the coalescing window self-tunes (``adaptive_batching``). With
    ``fused=True`` the executor additionally splits dispatch from readback
    via the fused pipeline's non-blocking ``transform_submit``.

    ``batch_alpha`` / ``batch_min_wait_ms`` / ``batch_max_wait_ms`` expose
    the adaptive controller's target ratio and window clamp (previously
    constructor-only defaults); the live tuned values read back through
    ``/_mmlspark/stats`` ``async.controller``. ``autotune=True`` (fused
    pipelines) attaches a cost-model ``Tuner`` (core/tune.py) that refits
    from measured per-segment stats every ``tune_every`` batches and
    applies bucket/fuse/window/inflight knobs with journaled decisions and
    one-step rollback — the ``tuner`` section of ``/_mmlspark/stats`` and
    the ``mmlspark_tuner_*`` metric families show its state. An
    uncalibrated tuner changes nothing (cold-start replies are
    bitwise-identical to static knobs).

    ``http_mode="async"`` swaps the thread-per-connection ingress for the
    event-loop transport (serving/aio.py: keep-alive pooling, pipelined
    reads, one thread for all connections). ``wire_binary`` negotiates the
    binary frame wire on Content-Type ``application/x-mmlspark-frame``
    (io/binary.py; ``parse_request`` decodes frame rows zero-copy whatever
    ``parse`` mode JSON clients use). ``tenants`` (weights dict or
    TenantAdmission) switches bounded admission to per-tenant weighted-fair
    shedding on the ``X-MMLSpark-Tenant`` header. ``slo`` declares the
    latency objective behind the ``mmlspark_slo_burn_rate`` gauges
    (SLOConfig/dict; None = the default 250ms @ p99; False = off), and
    ``metrics_exemplars=True`` renders trace-id exemplars on
    ``/_mmlspark/metrics`` in OpenMetrics syntax (obs/perf.py — always
    present in ``/_mmlspark/stats`` regardless).

    ``supervise`` (default on, async_exec only) runs the self-healing
    layer (serving/supervisor.py): per-replica health scores with
    quarantine/probe/readmit and a hung-dispatch watchdog that
    re-dispatches wedged batches on a healthy replica
    (``watchdog_budget_s`` pins a fixed wall budget; the default derives
    one from the cost model / measured EWMA). ``brownout`` (off by
    default; requires ``slo``) enables staged graceful degradation on SLO
    burn — shrink the batch window, demote optional fused segments to
    host, tighten admission — restored hysteretically; see
    docs/serving.md.

    ``fleet`` (off by default — disabled serving stays bitwise-identical)
    enables the fleet control plane (serving/fleet, docs/fleet.md):
    ``True`` for defaults or a dict of FleetSpec kwargs, plus two
    cache keys consumed here — ``cache_path`` mounts a persistent
    compile-cache tier under the in-process CompileCache (fused pipelines:
    serialized AOT executables shared across pods, warmed at start so a
    fresh replica's first request pays zero jit compiles for
    previously-seen signatures) and ``cache_write`` (default True) gates
    the store path. The capacity planner + autoscale controller publish
    at ``/_mmlspark/capacity`` and apply inflight/mega_k live.

    ``lifecycle`` (off by default — disabled serving stays
    bitwise-identical) enables the model lifecycle plane
    (serving/lifecycle, docs/lifecycle.md): ``True`` for defaults or a
    dict of CanaryConfig kwargs. The configured stage becomes the live
    version; candidates registered at runtime roll out shadow-scored and
    burn-gated (``/_mmlspark/models``), and with a fleet ``cache_path``
    mounted the promotion warm hook stages a candidate's executables into
    the persistent compile cache BEFORE it takes traffic (zero-compile
    promotion).

    ``multimodel`` (off by default — disabled serving stays
    bitwise-identical in replies AND metrics exposition) enables the
    model mall (serving/multimodel, docs/multimodel.md): ``True`` for
    defaults or a dict of MallConfig kwargs. The configured stage becomes
    the DEFAULT model; further fitted pipelines admitted via
    ``server.transform.add_model(name, fn)`` route by the
    ``X-MMLSpark-Model`` header (or in-band ``"model"`` JSON column),
    each behind its own per-model lifecycle plane. Models are cost-packed
    onto replicas (``/_mmlspark/mall`` shows the plan), cold models park
    to the tier with accounted re-warm, and an ``automl`` spec schedules
    grid trials on idle capacity. A standalone ``lifecycle`` spec folds
    in as every model's canary config.
    """
    from ..core.pipeline import PipelineModel
    from .stages import parse_request

    if fused and isinstance(stage, PipelineModel):
        stage = stage.fuse()

    def _map_reply(out: DataFrame) -> DataFrame:
        if reply_col not in out.schema:
            for pname in ("outputCol", "predictionCol"):
                if stage.has_param(pname) and stage.get(pname) in out.schema:
                    out = out.with_column(reply_col,
                                          lambda p, _c=stage.get(pname): p[_c])
                    break
        return out

    def transform(df: DataFrame) -> DataFrame:
        parsed = parse_request(df, input_col, parse=parse)
        return _map_reply(stage.transform(parsed))

    if hasattr(stage, "transform_submit"):
        # submit protocol: dispatch without readback, hand the pending
        # device-resident result to the executor's readback thread
        def _submit(df: DataFrame):
            parsed = parse_request(df, input_col, parse=parse)
            pend = stage.transform_submit(parsed)
            return lambda: _map_reply(pend())

        transform.submit = _submit

    if hasattr(stage, "mega_k_max"):
        # watchdog hint: one Python-level dispatch may cover up to K queued
        # micro-batches once the Tuner applies a mega-dispatch knob
        transform.mega_k = lambda: stage.mega_k_max

    ingest = None
    if hasattr(stage, "last_ingest_stats"):
        def ingest():
            s = stage.last_ingest_stats
            return s.summary() if s is not None else None

    fusion = None
    if hasattr(stage, "fusion_stats"):
        fusion = stage.fusion_stats

    tuner = None
    if autotune and hasattr(stage, "set_tuning"):
        from ..core.costmodel import SegmentCostModel
        from ..core.tune import Tuner

        model = getattr(stage, "cost_model", None)
        if model is None:
            model = SegmentCostModel()
            stage.set_tuning(cost_model=model)
        tuner = Tuner(fused=stage, model=model, every=tune_every)

    brownout_hooks = None
    if brownout and hasattr(stage, "set_tuning"):
        # brownout step 2, wired only for fused pipelines: demote the
        # OPTIONAL (non-heavy) fused segments to the host path via the
        # fusion planner's fuse-override hook — under overload the device
        # serves the heavy segment only; restore puts the old overrides
        # back verbatim
        demote_state: Dict[str, Any] = {}

        def demote_apply(_stage=stage, _st=demote_state):
            plan_nodes = getattr(_stage, "_last_plan", None) or []
            light = [n.label for n in plan_nodes
                     if getattr(n, "label", None) is not None
                     and not getattr(n, "heavy", True)]
            _st["prev"] = dict(getattr(_stage, "_fuse_overrides", {}) or {})
            if light:
                overrides = dict(_st["prev"])
                overrides.update({lab: False for lab in light})
                _stage.set_tuning(fuse=overrides)

        def demote_revert(_stage=stage, _st=demote_state):
            if "prev" in _st:
                _stage.set_tuning(fuse=_st.pop("prev"))

        brownout_hooks = {"demote_segments": (demote_apply, demote_revert)}

    fleet_hooks = None
    tier = None
    if fleet:
        fleet_hooks = {}
        cache_path = None
        cache_write = True
        cache_store = None
        if isinstance(fleet, dict):
            cache_path = fleet.get("cache_path")
            cache_write = bool(fleet.get("cache_write", True))
            # object-store backend (fleet/objstore.py): a directory path
            # or an ObjectStore instance — entries and the knob-shipping
            # snapshot ride the store instead of the pod-local cache_path
            cache_store = fleet.get("cache_store")
        if (cache_path or cache_store) \
                and hasattr(stage, "attach_persistent_cache"):
            from .fleet import PersistentCompileCache

            def _knobs(_t=tuner):
                # persisted alongside cost-only entries so a fresh pod can
                # seed its knobs from the fleet's tuned state
                if _t is not None:
                    try:
                        return _t.knobs.to_dict()
                    except Exception:  # noqa: BLE001 — knobs best-effort
                        return {}
                return {}

            tier = PersistentCompileCache(cache_path or "",
                                          write=cache_write,
                                          knobs_provider=_knobs,
                                          store=cache_store)
            # attach + AOT-warm: deserialize previously-seen executables
            # into the in-process cache BEFORE the first request arrives
            stage.attach_persistent_cache(tier)
            # knob shipping (docs/front_fabric.md): adopt the fleet's
            # shipped KnobSet NOW — journaled "warm_start" with one-step
            # rollback — and hand the capacity plan to the controller, so
            # the pod serves tuned from its first request (zero
            # relearning, the zero-compile warm's control-plane twin)
            snap = tier.load_snapshot()
            if snap:
                if tuner is not None and snap.get("knobs"):
                    try:
                        tuner.warm_start(snap["knobs"])
                    except Exception:  # noqa: BLE001 — just relearn
                        pass
                if snap.get("capacity_plan"):
                    fleet_hooks["warm_plan"] = dict(snap["capacity_plan"])

            def _snapshot(plan=None, _tier=tier, _t=tuner):
                # refreshed by the controller on every plan; byte-identical
                # snapshots dedup inside the tier
                knobs = None
                if _t is not None:
                    try:
                        knobs = _t.knobs.to_dict()
                    except Exception:  # noqa: BLE001
                        knobs = None
                _tier.put_snapshot(knobs=knobs, capacity_plan=plan)

            fleet_hooks["snapshot"] = _snapshot
        if hasattr(stage, "set_tuning"):
            def _set_mega_k(k, _stage=stage):
                # the controller's single K fans out to the heavy planned
                # segments (mega-dispatch only pays where dispatch rate
                # dominates — the PR 11 criterion)
                nodes = getattr(_stage, "_last_plan", None) or []
                labels = [n.label for n in nodes
                          if getattr(n, "label", None) is not None
                          and getattr(n, "heavy", False)]
                if labels:
                    _stage.set_tuning(
                        mega_k={lab: int(k) for lab in labels})

            fleet_hooks["set_mega_k"] = _set_mega_k
        if tuner is not None:
            fleet_hooks["predict_ms"] = tuner.predict_batch_ms

    lifecycle_hooks = None
    if lifecycle:
        # the plane adopts the configured stage as the live version; the
        # warm hook runs at promotion time, BEFORE the candidate takes
        # traffic: with a persistent compile-cache tier mounted (fleet
        # cache_path), attaching it AOT-warms the candidate's previously
        # serialized executables — the zero-compile promotion criterion
        lifecycle_hooks = {"live_stage": stage}

        def _warm(ver, _tier=tier):
            st = ver.stage
            if st is None or not hasattr(st, "attach_persistent_cache"):
                return "no stage cache"
            if _tier is None:
                return "no persistent tier"
            st.attach_persistent_cache(_tier)
            return "warmed"

        lifecycle_hooks["warm"] = _warm

    multimodel_hooks = None
    if multimodel:
        # the mall adopts the configured stage as the DEFAULT model. Its
        # warm hook is the per-model twin of the lifecycle one: with a
        # persistent compile-cache tier mounted, admitting / re-warming a
        # model AOT-stages its executables BEFORE it takes traffic
        # (warm-before-admit). The cost hook feeds the packing planner the
        # tuner's calibrated per-row estimate for the default model; other
        # models graduate through the mall's measured-probe EWMA.
        multimodel_hooks = {"live_stage": stage}

        def _mm_warm(model, ver, _tier=tier):
            st = getattr(ver, "stage", None)
            if st is None or not hasattr(st, "attach_persistent_cache"):
                return "no stage cache"
            if _tier is None:
                return "no persistent tier"
            st.attach_persistent_cache(_tier)
            return "warmed"

        multimodel_hooks["warm"] = _mm_warm
        if tuner is not None:
            _default = "default"
            if isinstance(multimodel, dict):
                _default = str(multimodel.get("default_model", "default"))

            def _mm_predict(model, _t=tuner, _d=_default):
                return _t.predict_row_ms() if model == _d else None

            multimodel_hooks["predict_ms"] = _mm_predict

    return ServingServer(transform, host=host, port=port, api_path=api_path,
                         reply_col=reply_col, max_batch_size=max_batch_size,
                         max_wait_ms=max_wait_ms, token=token,
                         journal_path=journal_path, ingest_stats=ingest,
                         fusion_stats=fusion, max_queue=max_queue,
                         async_exec=async_exec, inflight=inflight,
                         replicas=replicas,
                         adaptive_batching=adaptive_batching,
                         batch_alpha=batch_alpha,
                         batch_min_wait_ms=batch_min_wait_ms,
                         batch_max_wait_ms=batch_max_wait_ms,
                         tuner=tuner, obs=obs,
                         trace_sample_rate=trace_sample_rate,
                         http_mode=http_mode, wire_binary=wire_binary,
                         tenants=tenants, slo=slo,
                         metrics_exemplars=metrics_exemplars,
                         supervise=supervise,
                         watchdog_budget_s=watchdog_budget_s,
                         brownout=brownout,
                         brownout_hooks=brownout_hooks,
                         fleet=fleet, fleet_hooks=fleet_hooks,
                         lifecycle=lifecycle,
                         lifecycle_hooks=lifecycle_hooks,
                         multimodel=multimodel,
                         multimodel_hooks=multimodel_hooks)
