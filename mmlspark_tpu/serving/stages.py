"""Request/reply column sugar (reference ServingImplicits parseRequest/makeReply,
io/IOImplicits.scala:182-213 + ServingUDFs.scala:16-50).

Wire negotiation happens HERE, per row: a request whose Content-Type is
``application/x-mmlspark-frame`` (io/binary.py) decodes as a binary column
frame — numpy views over the body bytes, zero-copy, no JSON parse, no
base64 — regardless of the ``parse`` mode JSON clients use, so one endpoint
serves both wires and replies stay bitwise-identical between them."""

from __future__ import annotations

import json
from typing import Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..io.binary import FRAME_CONTENT_TYPE, FrameError, decode_frame, is_frame


def _row_content_type(headers) -> str:
    if not headers:
        return ""
    get = getattr(headers, "get", None)
    v = get("Content-Type") if get is not None else None
    if v is None:
        low = "content-type"
        for k in headers:
            if str(k).lower() == low:
                v = headers[k]
                break
    return str(v or "").split(";")[0].strip().lower()


def _decode_frame_row(raw: bytes):
    """Frame body -> parsed value: single-column frames unwrap to the bare
    array (mirroring the JSON single-'data'-key unwrap), multi-column frames
    stay a {name: array} dict. Views over ``raw`` — zero-copy."""
    cols = decode_frame(raw)
    if len(cols) == 1:
        return next(iter(cols.values()))
    return cols


def parse_request(df: DataFrame, output_col: str, parse: str = "json",
                  value_col: str = "value",
                  headers_col: Optional[str] = "headers") -> DataFrame:
    """Decode the raw request-body column: json -> dict/list (dict payloads with
    a single 'data'/'value' key unwrap to the value), text -> str, bytes -> raw.
    Rows negotiated as binary frames (Content-Type + magic) decode to numpy
    views whatever ``parse`` says; a frame that fails validation parses to
    None (the ingress already 400s malformed frames — this covers journal
    replay and direct DataFrame use)."""
    use_headers = headers_col if headers_col in (df.schema or []) else None

    def fn(p):
        col = p[value_col]
        hdrs = p[use_headers] if use_headers else None
        out = np.empty(len(col), dtype=object)
        for i, body in enumerate(col):
            if body is None:
                out[i] = None
                continue
            raw = bytes(body)
            if is_frame(raw) and (
                    hdrs is None
                    or _row_content_type(hdrs[i]) == FRAME_CONTENT_TYPE):
                try:
                    out[i] = _decode_frame_row(raw)
                except FrameError:
                    out[i] = None
                continue
            if parse == "bytes":
                out[i] = raw
            elif parse == "text":
                out[i] = raw.decode("utf-8", errors="replace")
            else:
                try:
                    obj = json.loads(raw.decode("utf-8"))
                except Exception:
                    out[i] = None
                    continue
                if isinstance(obj, dict) and len(obj) == 1 and \
                        next(iter(obj)) in ("data", "value"):
                    obj = next(iter(obj.values()))
                out[i] = np.asarray(obj, dtype=np.float64) \
                    if isinstance(obj, list) and obj \
                    and isinstance(obj[0], (int, float)) else obj
        return out

    return df.with_column(output_col, fn)


def make_reply(df: DataFrame, input_col: str, reply_col: str = "reply"
               ) -> DataFrame:
    """Copy/coerce a column into the reply column (makeReplyUDF parity)."""
    return df.with_column(reply_col, lambda p: p[input_col])
