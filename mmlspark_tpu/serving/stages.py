"""Request/reply column sugar (reference ServingImplicits parseRequest/makeReply,
io/IOImplicits.scala:182-213 + ServingUDFs.scala:16-50)."""

from __future__ import annotations

import json
from typing import Any, Optional

import numpy as np

from ..core.dataframe import DataFrame


def parse_request(df: DataFrame, output_col: str, parse: str = "json",
                  value_col: str = "value") -> DataFrame:
    """Decode the raw request-body column: json -> dict/list (dict payloads with
    a single 'data'/'value' key unwrap to the value), text -> str, bytes -> raw."""

    def fn(p):
        col = p[value_col]
        out = np.empty(len(col), dtype=object)
        for i, body in enumerate(col):
            if body is None:
                out[i] = None
                continue
            raw = bytes(body)
            if parse == "bytes":
                out[i] = raw
            elif parse == "text":
                out[i] = raw.decode("utf-8", errors="replace")
            else:
                try:
                    obj = json.loads(raw.decode("utf-8"))
                except Exception:
                    out[i] = None
                    continue
                if isinstance(obj, dict) and len(obj) == 1 and \
                        next(iter(obj)) in ("data", "value"):
                    obj = next(iter(obj.values()))
                out[i] = np.asarray(obj, dtype=np.float64) \
                    if isinstance(obj, list) and obj \
                    and isinstance(obj[0], (int, float)) else obj
        return out

    return df.with_column(output_col, fn)


def make_reply(df: DataFrame, input_col: str, reply_col: str = "reply"
               ) -> DataFrame:
    """Copy/coerce a column into the reply column (makeReplyUDF parity)."""
    return df.with_column(reply_col, lambda p: p[input_col])
