"""SSH reverse port forwarding for serving workers behind a gateway.

Reference: io/http/PortForwarding.scala:1-86 — workers open a JSch SSH
session to a gateway host and reverse-forward a remote port to their local
ServingServer, retrying across a remote port range until a free one binds.
Here the tunnel rides the system ``ssh`` client (OpenSSH is the fleet-
standard transport; no JVM, no bundled SSH implementation): ``ssh -N -R
remote:...:local`` runs as a supervised subprocess, with the same retry-
across-ports behavior and identity-file support.

Typical use: a RoutingFront on a public gateway, ServingServers on TPU
hosts inside a private network — each worker forwards
``gateway:port -> localhost:server.port`` then registers
``http://gateway:port/`` with the front.
"""

from __future__ import annotations

import shlex
import subprocess
import time
from typing import List, Optional, Tuple


def build_ssh_command(username: str, ssh_host: str, ssh_port: int,
                      bind_address: str, remote_port: int, local_host: str,
                      local_port: int,
                      key_file: Optional[str] = None,
                      extra_opts: Optional[List[str]] = None) -> List[str]:
    """The argv for one reverse-forward attempt (unit-testable seam;
    forwardPortToRemote's JSch setRemoteForwarding equivalent)."""
    cmd = ["ssh", "-N",
           "-o", "StrictHostKeyChecking=no",
           "-o", "ExitOnForwardFailure=yes",
           "-o", "ServerAliveInterval=30",
           "-p", str(ssh_port)]
    if key_file:
        cmd += ["-i", key_file]
    cmd += ["-R", f"{bind_address}:{remote_port}:{local_host}:{local_port}",
            f"{username}@{ssh_host}"]
    if extra_opts:
        cmd += list(extra_opts)
    return cmd


class PortForwarder:
    """Supervised reverse SSH tunnel (forwardPortToRemote parity).

    ``start()`` tries remote ports ``remote_port_start..+max_retries`` until
    one binds (ExitOnForwardFailure makes a taken port exit immediately, the
    JSch retry-loop behavior); the winning port is ``.remote_port``.
    """

    def __init__(self, username: str, ssh_host: str, ssh_port: int = 22,
                 bind_address: str = "0.0.0.0", remote_port_start: int = 8898,
                 local_host: str = "127.0.0.1", local_port: int = 8898,
                 key_file: Optional[str] = None, max_retries: int = 10,
                 settle_s: float = 1.0):
        self.username = username
        self.ssh_host = ssh_host
        self.ssh_port = ssh_port
        self.bind_address = bind_address
        self.remote_port_start = remote_port_start
        self.local_host = local_host
        self.local_port = local_port
        self.key_file = key_file
        self.max_retries = max_retries
        self.settle_s = settle_s
        self.remote_port: Optional[int] = None
        self._proc: Optional[subprocess.Popen] = None

    def _spawn(self, remote_port: int) -> subprocess.Popen:
        cmd = build_ssh_command(self.username, self.ssh_host, self.ssh_port,
                                self.bind_address, remote_port,
                                self.local_host, self.local_port,
                                key_file=self.key_file)
        return subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    def start(self) -> Tuple[subprocess.Popen, int]:
        last_err: Optional[str] = None
        for attempt in range(self.max_retries + 1):
            port = self.remote_port_start + attempt
            proc = self._spawn(port)
            time.sleep(self.settle_s)
            if proc.poll() is None:  # still running => forward bound
                self._proc, self.remote_port = proc, port
                return proc, port
            last_err = f"ssh exited rc={proc.returncode} for port {port}"
        cmd = shlex.join(build_ssh_command(
            self.username, self.ssh_host, self.ssh_port, self.bind_address,
            self.remote_port_start, self.local_host, self.local_port))
        raise RuntimeError(
            f"could not establish reverse forward after "
            f"{self.max_retries + 1} attempts: {last_err} (cmd: {cmd})")

    @property
    def remote_address(self) -> str:
        if self.remote_port is None:
            raise RuntimeError("forwarder not started")
        return f"http://{self.ssh_host}:{self.remote_port}/"

    def stop(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        self._proc = None

    def __enter__(self) -> "PortForwarder":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
