"""Low-latency serving: any pipeline as a web service (reference Spark Serving).

The reference turns a structured-streaming query into an HTTP service with
embedded per-executor servers and driver-side routing
(org/apache/spark/sql/execution/streaming/*, SURVEY §3.4). Here the equivalent:
a per-host ingress server feeding a continuous micro-batching loop — queue ->
pad/batch -> pipeline.transform (jitted stages reuse their compile cache) ->
reply routing keyed by request id.
"""

from .server import ServingServer, reply_to, serve_pipeline
from .routing import RoutingFront, register_worker
from .port_forwarding import PortForwarder, build_ssh_command
from .journal import RequestJournal
from .stages import parse_request, make_reply
from .executor import (AdaptiveBatchController, PipelinedExecutor, Replica,
                       ReplicaSet)
from .aio import AsyncConnectionPool, AsyncHTTPServer
from .tenants import TENANT_HEADER, TenantAdmission, tenants_from_spec
from .supervisor import (BrownoutController, BrownoutStep, DispatchWatchdog,
                         HedgeConfig, HedgeTracker, ReplicaSupervisor)
from .lifecycle import (CanaryConfig, CanaryController, LifecyclePlane,
                        ModelRegistry, ModelVersion, OnlineTrainer,
                        make_lifecycle)
from .multimodel import (MODEL_HEADER, AutoMLScheduler, MallConfig,
                         ModelMall, make_multimodel)

__all__ = ["AdaptiveBatchController", "AsyncConnectionPool",
           "AsyncHTTPServer", "AutoMLScheduler", "BrownoutController",
           "BrownoutStep",
           "CanaryConfig", "CanaryController", "DispatchWatchdog",
           "HedgeConfig", "HedgeTracker", "LifecyclePlane",
           "MODEL_HEADER", "MallConfig", "ModelMall", "ModelRegistry",
           "ModelVersion", "OnlineTrainer",
           "PipelinedExecutor", "PortForwarder",
           "Replica", "ReplicaSet", "ReplicaSupervisor", "RequestJournal",
           "RoutingFront", "ServingServer", "TENANT_HEADER",
           "TenantAdmission", "build_ssh_command", "make_lifecycle",
           "make_multimodel",
           "make_reply", "parse_request", "register_worker", "reply_to",
           "serve_pipeline", "tenants_from_spec"]
