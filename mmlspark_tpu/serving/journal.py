"""Write-ahead request journal: epoch/commit semantics for the serving loop.

Reference: HTTPSourceV2's epoch machinery (HTTPSourceV2.scala:575-640 —
per-epoch request queues, history kept until the epoch commits, recovered
partitions replayed to retried tasks). The TPU-native serving loop has no
Spark task retry, so the equivalent durability contract is a write-ahead
journal: every drained batch is an *epoch*; its requests are journaled
BEFORE the transform runs, and the epoch commits once every request in it
has been answered (or abandoned by its client). After a crash, ``recover``
returns the uncommitted requests so a supervisor can re-submit them to a
fresh server — at-least-once processing for side-effecting pipelines.

Format: JSONL with an optional length-prefixed binary record variant.
JSON records (one op per line):
    {"op": "entry", "epoch": E, "id": rid, "body_b64": ..., "headers": {...}}
    {"op": "commit", "epoch": E}
Binary records (bodies that are wire frames — io/binary.py magic — would
pay a 33% base64 inflation as JSON; instead the header line carries the
byte count and the raw body follows verbatim):
    {"op": "entry_bin", "epoch": E, "id": rid, "nbytes": N, "headers": ...}
    <N raw body bytes>\\n
Readers handle both variants in one file, so a journal written before the
binary wire existed replays unchanged. ``compact`` rewrites the file
dropping committed epochs, preserving each entry's record variant.
"""

from __future__ import annotations

import base64
import errno
import json
import logging
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..core import faults
from ..core.faults import fsync_dir
from ..io.binary import is_frame

_LOG = logging.getLogger(__name__)


class RequestJournal:
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        #: disk-full degrade (docs/faults.md): ENOSPC on an append flips
        #: the journal to accounted read-only mode — durability is lost
        #: (logged once, counted) but the serving loop never crashes
        self.degraded = False
        self.write_errors = 0
        self.skipped_writes = 0
        self._enospc_logged = False
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "ab")

    def _note_write_error(self, e: OSError) -> None:
        """ENOSPC degrades (read-only, log once); anything else re-raises —
        an unexpected I/O failure should surface, a full volume should not
        take serving down."""
        if getattr(e, "errno", None) != errno.ENOSPC:
            raise e
        with self._lock:
            self.write_errors += 1
            self.degraded = True
            logged = self._enospc_logged
            self._enospc_logged = True
        if not logged:
            _LOG.warning("request journal volume full (ENOSPC): degrading "
                         "to read-only mode — epochs are no longer durable")

    def _skip_write(self) -> bool:
        if not self.degraded:
            return False
        with self._lock:
            self.skipped_writes += 1
        return True

    # -- write side (server) ----------------------------------------------
    @staticmethod
    def _record(epoch: int, rid: int, body: bytes,
                headers: Optional[Dict[str, str]]) -> bytes:
        """One journal record, variant chosen by the body: wire frames are
        stored raw behind a length-prefixed header line (no base64
        inflation); everything else stays a plain JSONL entry."""
        body = bytes(body)
        if is_frame(body):
            head = json.dumps({
                "op": "entry_bin", "epoch": int(epoch), "id": int(rid),
                "nbytes": len(body), "headers": dict(headers or {})})
            return head.encode("utf-8") + b"\n" + body + b"\n"
        return (json.dumps({
            "op": "entry", "epoch": int(epoch), "id": int(rid),
            "body_b64": base64.b64encode(body).decode("ascii"),
            "headers": dict(headers or {})}) + "\n").encode("utf-8")

    def append(self, epoch: int, rid: int, body: bytes,
               headers: Optional[Dict[str, str]] = None) -> None:
        if self._skip_write():
            return
        rec = self._record(epoch, rid, body, headers)
        try:
            faults.fire(faults.JOURNAL_WRITE, epoch=epoch, n=1)
            with self._lock:
                self._fh.write(rec)
                self._fh.flush()
                os.fsync(self._fh.fileno())
        except OSError as e:
            self._note_write_error(e)

    def append_many(self, epoch: int, entries) -> None:
        """Journal a whole epoch with ONE flush+fsync (the hot batch path:
        durability is per-epoch, so per-request fsyncs buy nothing).
        ``entries``: iterable of (rid, body, headers)."""
        recs = [self._record(epoch, rid, body, headers)
                for rid, body, headers in entries]
        if self._skip_write():
            return
        try:
            faults.fire(faults.JOURNAL_WRITE, epoch=epoch, n=len(recs))
            with self._lock:
                self._fh.write(b"".join(recs))
                self._fh.flush()
                os.fsync(self._fh.fileno())
        except OSError as e:
            self._note_write_error(e)

    def commit(self, epoch: int) -> None:
        if self._skip_write():
            return
        try:
            faults.fire(faults.JOURNAL_COMMIT, epoch=epoch)
            with self._lock:
                self._fh.write((json.dumps({"op": "commit",
                                            "epoch": int(epoch)}) +
                                "\n").encode("utf-8"))
                self._fh.flush()
                os.fsync(self._fh.fileno())
        except OSError as e:
            self._note_write_error(e)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"degraded": int(self.degraded),
                    "write_errors": self.write_errors,
                    "skipped_writes": self.skipped_writes}

    def close(self) -> None:
        with self._lock:
            self._fh.close()

    # -- read side (recovery) ---------------------------------------------
    @staticmethod
    def _pending_by_epoch(path: str
                          ) -> Dict[int, List[Tuple[int, bytes, Dict[str, str]]]]:
        if not os.path.exists(path):
            return {}
        entries: Dict[int, List[Tuple[int, bytes, Dict[str, str]]]] = {}
        committed = set()
        with open(path, "rb") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line.decode("utf-8"))
                except (json.JSONDecodeError, UnicodeDecodeError):
                    # torn final line from a crash mid-append — exactly the
                    # case recovery exists for; skip it (that request never
                    # reached the transform)
                    continue
                if not isinstance(rec, dict) or "op" not in rec:
                    continue
                if rec["op"] == "commit":
                    committed.add(rec["epoch"])
                elif rec["op"] == "entry_bin":
                    # length-prefixed raw body follows the header line
                    body = fh.read(int(rec["nbytes"]))
                    if len(body) != int(rec["nbytes"]):
                        continue  # torn binary tail: crash mid-append
                    fh.read(1)  # trailing newline
                    entries.setdefault(rec["epoch"], []).append(
                        (rec["id"], body, rec.get("headers", {})))
                else:
                    entries.setdefault(rec["epoch"], []).append(
                        (rec["id"], base64.b64decode(rec["body_b64"]),
                         rec.get("headers", {})))
        return {e: v for e, v in entries.items() if e not in committed}

    @staticmethod
    def recover(path: str) -> List[Tuple[int, bytes, Dict[str, str]]]:
        """(rid, body, headers) of every request in an UNcommitted epoch —
        what a supervisor re-submits after a crash."""
        pending = RequestJournal._pending_by_epoch(path)
        out: List[Tuple[int, bytes, Dict[str, str]]] = []
        for epoch in sorted(pending):
            out.extend(pending[epoch])
        return out

    def compact(self) -> None:
        """Rewrite the journal keeping only uncommitted epochs, preserving
        their epoch numbers (a late commit of a live epoch must still match).

        Atomic AND durable: the replacement is fully written + fsynced before
        the rename, and the directory is fsynced after — a crash at any point
        mid-compact leaves either the complete old journal or the complete
        new one, never a torn file that loses uncommitted epochs."""
        with self._lock:
            self._fh.close()
            try:
                pending = self._pending_by_epoch(self.path)
                tmp = self.path + ".tmp"
                try:
                    with open(tmp, "wb") as fh:
                        for epoch in sorted(pending):
                            for rid, body, headers in pending[epoch]:
                                fh.write(self._record(epoch, rid, body,
                                                      headers))
                        fh.flush()
                        os.fsync(fh.fileno())
                    os.replace(tmp, self.path)
                except BaseException:
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
                    raise
                fsync_dir(os.path.dirname(os.path.abspath(self.path)))
            finally:
                # reopen even on failure: the journal must stay writable
                # (the old complete file is still in place)
                self._fh = open(self.path, "ab")
