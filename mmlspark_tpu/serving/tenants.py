"""Per-tenant admission classes: weighted-fair load shedding.

Bounded admission (PR 2) sheds with a GLOBAL 503 once the ingress queue hits
``max_queue`` — one heavy tenant saturating the queue starves every light
tenant behind the same front. This module maps the ``X-MMLSpark-Tenant``
header to admission classes with configured weights, so overload sheds
PROPORTIONALLY:

  - while the global queue is below ``max_queue``, every tenant is admitted
    (work-conserving — unused share is never wasted);
  - once the queue is full, a tenant is admitted only while its in-flight
    share (admitted and not yet answered) is below its weighted quota
    ``max_queue * weight / sum(active weights)`` — the heavy tenant that
    filled the queue sheds first, a light tenant within its share still
    gets in (total admission stays bounded by ~2x ``max_queue``: the global
    cap plus the sum of quotas).

Requests without the header share the ``default`` class. The admission
object is transport-agnostic: ``ServingServer`` consults it at ingress in
both the threaded and async HTTP modes.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Mapping, Optional

__all__ = ["TENANT_HEADER", "MODEL_HEADER", "TenantAdmission",
           "tenants_from_spec", "header_lookup"]

#: request header naming the admission class (absent -> "default")
TENANT_HEADER = "X-MMLSpark-Tenant"
DEFAULT_TENANT = "default"
#: request header naming the target model in a multi-model worker
#: (serving/multimodel; absent -> the mall's default model). Lives here —
#: next to the other identity header — so the fabric's affinity fold and
#: the mall share one constant without an import cycle.
MODEL_HEADER = "X-MMLSpark-Model"


def header_lookup(headers: Optional[Mapping[str, str]],
                  name: str) -> Optional[str]:
    """Case-insensitive single-header lookup (the ``tenant_of`` /
    ``deadline_from_headers`` convention, factored out): exact and
    lowercase keys first, then a linear scan; empty values read as
    absent."""
    if not headers:
        return None
    get = getattr(headers, "get", None)
    v = None
    if get is not None:
        v = get(name) or get(name.lower())
    if v is None:
        low = name.lower()
        for k in headers:
            if str(k).lower() == low:
                v = headers[k]
                break
    v = str(v).strip() if v is not None else ""
    return v or None


class TenantAdmission:
    """Weighted-fair admission over named tenant classes.

    ``weights``: tenant -> relative weight (unknown tenants get
    ``default_weight``). State tracked per tenant: in-flight count
    (admitted, not yet answered — released by the server when the reply
    slot resolves), admitted/shed totals for the stats surface.
    """

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0):
        self.weights = {str(k): float(v) for k, v in (weights or {}).items()}
        if any(w <= 0 for w in self.weights.values()):
            raise ValueError("tenant weights must be positive")
        self.default_weight = float(default_weight)
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {}
        self._admitted: Dict[str, int] = {}
        self._shed: Dict[str, int] = {}
        # brownout pressure: every quota is scaled by this factor while a
        # degradation step has tightened admission (1.0 = full quotas)
        self._pressure = 1.0

    def set_pressure(self, factor: float) -> float:
        """Scale every tenant quota by ``factor`` (the brownout
        controller's tighten-admission knob); returns the previous factor
        so the step can restore it."""
        if factor <= 0:
            raise ValueError("pressure factor must be positive")
        with self._lock:
            prev = self._pressure
            self._pressure = float(factor)
            return prev

    @staticmethod
    def tenant_of(headers: Optional[Mapping[str, str]]) -> str:
        """Case-insensitive ``X-MMLSpark-Tenant`` lookup (same convention as
        ``deadline_from_headers``); absent or empty -> ``default``."""
        return header_lookup(headers, TENANT_HEADER) or DEFAULT_TENANT

    def weight(self, tenant: str) -> float:
        return self.weights.get(tenant, self.default_weight)

    def quota(self, tenant: str, max_queue: int) -> int:
        """This tenant's fair share of a FULL queue: ``max_queue`` split by
        weight over the currently-active tenants (inflight > 0, plus the
        asking tenant). At least 1 — a configured tenant is never starved
        outright."""
        with self._lock:
            return self._quota_locked(tenant, max_queue)

    def _quota_locked(self, tenant: str, max_queue: int) -> int:
        active = {t for t, n in self._inflight.items() if n > 0}
        active.add(tenant)
        total_w = sum(self.weight(t) for t in active)
        if total_w <= 0:
            return max(1, int(max_queue * self._pressure))
        return max(1, int(max_queue * self._pressure
                          * self.weight(tenant) / total_w))

    def try_admit(self, tenant: str, queue_depth: int,
                  max_queue: int) -> bool:
        """One admission decision; on True the tenant's in-flight count is
        taken (pair with ``release`` when the request resolves)."""
        with self._lock:
            if max_queue <= 0 or queue_depth < max_queue:
                ok = True  # global queue not full: work-conserving admit
            else:
                ok = self._inflight.get(tenant, 0) < \
                    self._quota_locked(tenant, max_queue)
            if ok:
                self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
                self._admitted[tenant] = self._admitted.get(tenant, 0) + 1
            else:
                self._shed[tenant] = self._shed.get(tenant, 0) + 1
            return ok

    def release(self, tenant: str) -> None:
        with self._lock:
            n = self._inflight.get(tenant, 0) - 1
            if n > 0:
                self._inflight[tenant] = n
            else:
                self._inflight.pop(tenant, None)

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            tenants = sorted(set(self._admitted) | set(self._shed)
                             | set(self._inflight) | set(self.weights))
            return {t: {"weight": self.weight(t),
                        "inflight": self._inflight.get(t, 0),
                        "admitted": self._admitted.get(t, 0),
                        "shed": self._shed.get(t, 0)}
                    for t in tenants}


def tenants_from_spec(spec: Optional[str]) -> Optional[TenantAdmission]:
    """Parse the deploy-surface encoding (helm env plumbing):
    ``"teamA=3,teamB=1"`` -> TenantAdmission with those weights; ``"1"`` /
    ``"true"`` -> enabled with uniform weights; empty/None/"0"/"false" ->
    None (tenancy off, legacy global shed)."""
    if spec is None:
        return None
    spec = spec.strip()
    if not spec or spec.lower() in ("0", "false", "off", "no"):
        return None
    if spec.lower() in ("1", "true", "on", "yes"):
        return TenantAdmission()
    weights: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, w = part.partition("=")
        if not sep:
            raise ValueError(f"bad tenant spec entry {part!r} "
                             f"(want name=weight)")
        weights[name.strip()] = float(w)
    return TenantAdmission(weights)
