"""Self-healing serving runtime: supervision, watchdogs, hedging, brownout.

"The Tail at Scale" (Dean & Barroso, CACM 2013) catalogs the standard cures
for stragglers and wedged components in a serving fleet: detect outliers,
eject and probe them, hedge slow requests, and degrade gracefully instead of
falling over. PR 2 gave this stack retries, circuit breakers, bounded
admission, and journaled epochs — machinery that *reacts to errors*. This
module adds the layer that *detects and repairs silent failure*: a dispatch
that hangs (device wedge, runaway host stage) burns its deadline without
ever raising, and nothing ejects the replica it wedged on.

Four cooperating pieces, all wired by ``ServingServer`` / ``RoutingFront``:

  - ``ReplicaSupervisor`` — per-replica health accounting for the pipelined
    executor (serving/executor.py): successes, errors, wall-clock latency
    outliers, and wedges feed a decayed health score; a replica that wedges
    (or fails ``max_failures`` consecutive dispatches) is QUARANTINED —
    excluded from the submit queue — and re-admitted only after a PROBE
    succeeds, on a backoff schedule. Mirrors the front's worker circuit
    breaker (serving/routing.py closed/open/half_open), one level down.
  - ``DispatchWatchdog`` — a wall-clock budget per in-flight dispatch,
    derived from the cost model's ``predict_ms`` when calibrated (the
    tuner's model, core/costmodel.py) and from a compute EWMA otherwise;
    an expired dispatch is marked wedged and its batch re-dispatched on a
    healthy replica (the executor owns the requeue mechanics).
  - ``HedgeTracker`` — hedged-request policy for the RoutingFront: after a
    delay set to a configured quantile of observed forward latency, the
    front re-issues the request to a second worker and the first response
    wins. Duplicate work is bounded by construction: only requests slower
    than the quantile hedge at all.
  - ``BrownoutController`` — declared degradation steps driven by the SLO
    burn rate (obs/perf.py SLOTracker): when the error budget burns past
    ``enter_burn``, apply the next step (shrink the batch window, demote
    optional fused segments to host, tighten admission quotas); restore
    hysteretically when the burn drops below ``exit_burn``. Every
    transition is journaled like a tuner decision (core/tune.py) with
    one-step rollback.

Everything here is OFF-path when idle: with no faults injected and brownout
disabled, plans, batch windows, and serving replies are bitwise-identical
to the unsupervised build (enforced by the parity tests).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["BrownoutController", "BrownoutStep", "DispatchWatchdog",
           "HedgeConfig", "HedgeTracker", "ReplicaSupervisor", "make_hedge"]

#: replica health states (supervisor mirror of the routing circuit breaker)
HEALTHY = "healthy"          # admitted: pulls batches from the submit queue
QUARANTINED = "quarantined"  # ejected: wedged or error-scored out; waiting
PROBING = "probing"          # probe in flight: one success re-admits

REPLICA_STATES = (HEALTHY, QUARANTINED, PROBING)


class _ReplicaHealth:
    """Mutable per-replica record (guarded by the supervisor's lock)."""

    __slots__ = ("state", "successes", "errors", "timeouts", "outliers",
                 "consecutive", "score", "compute_ewma", "quarantined_at",
                 "probe_attempt", "ejections", "readmissions", "last_reason")

    def __init__(self):
        self.state = HEALTHY
        self.successes = 0
        self.errors = 0
        self.timeouts = 0      # wedged dispatches (watchdog expiries)
        self.outliers = 0      # completions past outlier_k x the EWMA
        self.consecutive = 0   # consecutive failures (resets on success)
        self.score = 1.0       # decayed health score in [0, 1]
        self.compute_ewma: Optional[float] = None
        self.quarantined_at = 0.0
        self.probe_attempt = 0
        self.ejections = 0
        self.readmissions = 0
        self.last_reason: Optional[str] = None


class ReplicaSupervisor:
    """Health scores + eject/probe/readmit state machine over the executor's
    replicas.

    ``probe_fn(replica) -> bool`` (optional) runs a real synthetic dispatch
    during re-admission; the default probe is a LIVENESS probe — for a
    wedged replica the only possible evidence is its stuck thread returning
    at all, so a clean late return after the quarantine cooldown counts as
    probe success. ``quarantine_s`` is the base cooldown; repeated probe
    failures back off exponentially (capped at 16x).
    """

    def __init__(self, replicas: Any, max_failures: int = 3,
                 quarantine_s: float = 1.0, outlier_k: float = 4.0,
                 decay: float = 0.85,
                 probe_fn: Optional[Callable[[Any], bool]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.max_failures = max(1, int(max_failures))
        self.quarantine_s = float(quarantine_s)
        self.outlier_k = float(outlier_k)
        self.decay = float(decay)
        self.probe_fn = probe_fn
        self._clock = clock
        self._lock = threading.Lock()
        # ``replicas``: a replica count, or the iterable of PLACED replica
        # indices (placement skips can leave gaps — a ghost record for a
        # never-placed replica would inflate healthy_peers)
        if isinstance(replicas, int):
            indices = range(max(1, replicas))
        else:
            indices = [int(i) for i in replicas]
        self._replicas: Dict[int, _ReplicaHealth] = {
            int(i): _ReplicaHealth() for i in indices}
        # mesh awareness (parallel/shardplan.py shard_groups): index ->
        # the frozen group of indices that fail TOGETHER (one mesh slice).
        # Empty = every replica is its own group (the pre-mesh behavior).
        self._groups: Dict[int, Tuple[int, ...]] = {}
        # pipeline awareness (parallel/pipeplan.py PipeSupervision): the
        # registered stage device groups, in stage order. Empty until a
        # pipe plan registers — nothing changes for unpipelined serving.
        self._pipe_stages: List[Tuple[int, ...]] = []

    def set_shard_groups(self, groups) -> None:
        """Register the mesh's shard groups (a list of index lists): when a
        member wedges or ejects, its WHOLE group quarantines — partial
        results from a broken mesh slice are lost regardless of which chip
        in the slice failed. Call with () to clear (back to per-replica)."""
        with self._lock:
            self._groups = {}
            for grp in groups or ():
                members = tuple(int(i) for i in grp)
                for i in members:
                    self._groups[i] = members

    def shard_group(self, index: int) -> Tuple[int, ...]:
        with self._lock:
            return self._groups.get(int(index), (int(index),))

    def set_pipe_stages(self, stages) -> None:
        """Register a pipe plan's stage device groups (a list of index
        lists, parallel/pipeplan.py PipeSupervision.register): the same
        fail-together semantics as shard groups — a wedged stage loses
        its whole sub-mesh, so every member quarantines with it. Stage
        groups are kept alongside any shard groups; ``pipe_stage(i)``
        reads them back and ``note_stage_wedged`` quarantines one whole
        stage. Call with () to clear."""
        with self._lock:
            self._pipe_stages = [tuple(int(i) for i in grp)
                                 for grp in stages or ()]
            for members in self._pipe_stages:
                for i in members:
                    # a stage IS a fail-together group: reuse the shard-
                    # group ejection fabric for its members
                    self._groups.setdefault(i, members)

    def pipe_stage(self, stage_index: int) -> Tuple[int, ...]:
        with self._lock:
            stages = getattr(self, "_pipe_stages", [])
            if 0 <= int(stage_index) < len(stages):
                return stages[int(stage_index)]
            return ()

    def note_stage_wedged(self, stage_index: int) -> None:
        """A pipeline stage's whole sub-mesh wedged mid-stream: every
        member device index quarantines NOW (the stage's devices fail
        together — the pipe-stage analogue of ``note_wedged``'s
        shard-group ejection). Unknown stage indices are a no-op."""
        members = self.pipe_stage(stage_index)
        with self._lock:
            for i in members:
                h = self._get(i)
                h.timeouts += 1
                h.consecutive += 1
                self._score(h, 0.0)
                if h.state == HEALTHY:
                    self._eject(h, f"pipe_stage:{int(stage_index)}")

    def _eject_peers(self, index: int, reason: str) -> None:
        """Quarantine the healthy remainder of ``index``'s shard group
        (already under self._lock). Peers carry a ``shard_group:`` reason
        so the stats surface shows WHY a chip that never failed is out."""
        for peer in self._groups.get(int(index), ()):
            if peer == int(index):
                continue
            ph = self._get(peer)
            if ph.state == HEALTHY:
                self._eject(ph, f"shard_group:{reason}")

    def _get(self, index: int) -> _ReplicaHealth:
        return self._replicas.setdefault(int(index), _ReplicaHealth())

    def _score(self, h: _ReplicaHealth, outcome: float) -> None:
        h.score = self.decay * h.score + (1.0 - self.decay) * outcome

    # -- event feed (executor compute loop / watchdog) -------------------
    def note_success(self, index: int, compute_s: float) -> None:
        with self._lock:
            h = self._get(index)
            h.successes += 1
            h.consecutive = 0
            if h.compute_ewma is not None and \
                    compute_s > self.outlier_k * h.compute_ewma:
                # slow-but-completed: a latency outlier dings the score
                # without counting as a failure
                h.outliers += 1
                self._score(h, 0.5)
            else:
                self._score(h, 1.0)
            h.compute_ewma = compute_s if h.compute_ewma is None else \
                0.75 * h.compute_ewma + 0.25 * compute_s

    def note_failure(self, index: int, reason: str = "error") -> None:
        with self._lock:
            h = self._get(index)
            h.errors += 1
            h.consecutive += 1
            self._score(h, 0.0)
            if h.state == HEALTHY and h.consecutive >= self.max_failures:
                self._eject(h, reason)
                self._eject_peers(index, reason)

    def note_wedged(self, index: int) -> None:
        """A watchdog-expired dispatch: immediate quarantine — a wedged
        replica must stop receiving traffic NOW, not after max_failures."""
        with self._lock:
            h = self._get(index)
            h.timeouts += 1
            h.consecutive += 1
            self._score(h, 0.0)
            if h.state == HEALTHY:
                self._eject(h, "wedged")
            # a wedged chip invalidates its whole mesh slice even when the
            # record was already quarantined (late watchdog expiry)
            self._eject_peers(index, "wedged")

    def _eject(self, h: _ReplicaHealth, reason: str) -> None:
        h.state = QUARANTINED
        h.quarantined_at = self._clock()
        h.probe_attempt = 0
        h.ejections += 1
        h.last_reason = reason

    # -- admission / probing (executor compute loop) ---------------------
    def admitted(self, index: int) -> bool:
        with self._lock:
            return self._get(index).state == HEALTHY

    def probe_due(self, index: int) -> bool:
        """True once the quarantine cooldown (with probe backoff) elapsed."""
        with self._lock:
            h = self._get(index)
            if h.state != QUARANTINED:
                return False
            backoff = self.quarantine_s * min(16, 2 ** h.probe_attempt)
            return self._clock() - h.quarantined_at >= backoff

    def begin_probe(self, index: int) -> None:
        with self._lock:
            h = self._get(index)
            if h.state == QUARANTINED:
                h.state = PROBING

    def run_probe(self, replica: Any) -> bool:
        """Execute the configured probe (liveness default: True — the
        replica's thread being free to probe IS the liveness evidence)."""
        if self.probe_fn is None:
            return True
        try:
            return bool(self.probe_fn(replica))
        except Exception:  # noqa: BLE001 — a raising probe is a failed probe
            return False

    def note_probe(self, index: int, ok: bool) -> None:
        with self._lock:
            h = self._get(index)
            if ok:
                h.state = HEALTHY
                h.consecutive = 0
                h.readmissions += 1
                # re-admitted on probation: mid score, one wedge re-ejects
                h.score = max(h.score, 0.5)
            else:
                h.state = QUARANTINED
                h.quarantined_at = self._clock()
                h.probe_attempt += 1

    def healthy_peers(self, excluding: int) -> int:
        with self._lock:
            return sum(1 for i, h in self._replicas.items()
                       if i != excluding and h.state == HEALTHY)

    # -- stats surface ---------------------------------------------------
    def describe(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for i in sorted(self._replicas):
                h = self._replicas[i]
                out.append({
                    "replica": i, "state": h.state,
                    "score": round(h.score, 4),
                    "successes": h.successes, "errors": h.errors,
                    "timeouts": h.timeouts, "outliers": h.outliers,
                    "consecutive_failures": h.consecutive,
                    "ejections": h.ejections,
                    "readmissions": h.readmissions,
                    "last_reason": h.last_reason,
                    "compute_ewma_ms": None if h.compute_ewma is None
                    else round(h.compute_ewma * 1e3, 3)})
            return out

    def summary(self) -> Dict[str, Any]:
        rows = self.describe()
        return {"replicas": rows,
                "healthy": sum(1 for r in rows if r["state"] == HEALTHY),
                "quarantined": sum(1 for r in rows
                                   if r["state"] != HEALTHY),
                "ejections": sum(r["ejections"] for r in rows),
                "readmissions": sum(r["readmissions"] for r in rows)}


# ---------------------------------------------------------------------------
# Hung-dispatch watchdog (budget policy; the executor owns the scan thread)
# ---------------------------------------------------------------------------


class DispatchWatchdog:
    """Wall-clock budget policy for in-flight dispatches.

    Budget per batch = ``k`` x the best estimate of its compute time:
    the cost model's ``predict_ms`` when calibrated (``predict_ms_fn``,
    wired from the serving tuner), else a measured compute EWMA — floored
    at ``min_budget_s`` so scheduling jitter never trips it. ``fixed_s``
    overrides everything (the chaos tests' deterministic knob). UNARMED
    (budget None) until either estimate exists: a fresh server's first
    compile can take arbitrarily long and must not read as a wedge.

    On expiry the executor re-dispatches the batch on a healthy replica
    (``max_redispatch`` bounds duplicates). With no healthy peer the budget
    doubles in place up to ``abandon_after`` expiries, then the batch is
    abandoned with an accounted 504 — a single-replica wedge degrades to a
    fast, attributed failure instead of a silent slot-timeout.
    """

    def __init__(self, k: float = 8.0, min_budget_s: float = 1.0,
                 fixed_s: Optional[float] = None,
                 predict_ms_fn: Optional[Callable[[int],
                                                  Optional[float]]] = None,
                 max_redispatch: int = 1, abandon_after: int = 3,
                 poll_s: float = 0.01):
        self.k = float(k)
        self.min_budget_s = float(min_budget_s)
        self.fixed_s = None if fixed_s is None else float(fixed_s)
        self.predict_ms_fn = predict_ms_fn
        self.max_redispatch = max(0, int(max_redispatch))
        self.abandon_after = max(1, int(abandon_after))
        self.poll_s = float(poll_s)
        self._lock = threading.Lock()
        self._ewma: Optional[float] = None
        self.trips = 0
        self.requeues = 0
        self.abandons = 0

    def observe(self, compute_s: float) -> None:
        """Feed one healthy dispatch's wall seconds (EWMA fallback source)."""
        with self._lock:
            self._ewma = compute_s if self._ewma is None else \
                0.75 * self._ewma + 0.25 * compute_s

    def budget_s(self, rows: int, batches: int = 1) -> Optional[float]:
        """Wall budget for a batch of ``rows``, or None while unarmed.

        ``batches`` covers K-step mega-dispatch (core/fusion.py): one
        Python-level dispatch may execute up to K queued micro-batches, so
        the measured-EWMA fallback — calibrated on single dispatches —
        scales by K. The cost-model prediction path already prices the
        actual row count and needs no scaling."""
        batches = max(1, int(batches or 1))
        if self.fixed_s is not None:
            return self.fixed_s
        pred_ms = None
        if self.predict_ms_fn is not None:
            try:
                pred_ms = self.predict_ms_fn(int(rows))
            except Exception:  # noqa: BLE001 — model failure != unarmed crash
                pred_ms = None
        with self._lock:
            ewma = self._ewma
        est = pred_ms / 1e3 if pred_ms is not None else \
            (ewma * batches if ewma is not None else None)
        if est is None:
            return None
        return max(self.min_budget_s, self.k * est)

    def note_trip(self, kind: str) -> None:
        with self._lock:
            self.trips += 1
            if kind == "requeue":
                self.requeues += 1
            elif kind == "abandon":
                self.abandons += 1

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            ewma = self._ewma
            trips, requeues, abandons = \
                self.trips, self.requeues, self.abandons
        return {"k": self.k, "min_budget_s": self.min_budget_s,
                "fixed_s": self.fixed_s,
                "armed": self.fixed_s is not None or ewma is not None
                or self.predict_ms_fn is not None,
                "compute_ewma_ms": None if ewma is None
                else round(ewma * 1e3, 3),
                "trips": trips, "requeues": requeues, "abandons": abandons}


# ---------------------------------------------------------------------------
# Hedged requests (RoutingFront policy + accounting)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HedgeConfig:
    """Hedging policy: after ``quantile`` of the observed forward-latency
    distribution (clamped to [min_delay_ms, max_delay_ms]), re-issue the
    request to ONE other worker; first response wins. Until ``min_samples``
    latencies are observed the delay is ``init_delay_ms``. Only requests
    slower than the quantile hedge at all, so duplicate work is bounded at
    ~(1 - quantile) of traffic by construction.

    Hedging deliberately double-dispatches: enable it only for idempotent
    serving transforms (pure inference — the normal case). Each worker
    journals and commits its own epoch exactly once either way; the losing
    reply is discarded at the front.
    """

    quantile: float = 0.95
    init_delay_ms: float = 50.0
    min_delay_ms: float = 1.0
    max_delay_ms: float = 5000.0
    min_samples: int = 20
    window: int = 512

    def __post_init__(self):
        if not 0.5 <= self.quantile < 1.0:
            raise ValueError(f"hedge quantile must be in [0.5, 1), "
                             f"got {self.quantile}")
        if self.min_delay_ms < 0 or self.max_delay_ms < self.min_delay_ms:
            raise ValueError("bad hedge delay clamp")


class HedgeTracker:
    """Latency reservoir + hedge accounting for the RoutingFront."""

    def __init__(self, config: Optional[HedgeConfig] = None):
        self.config = config if config is not None else HedgeConfig()
        self._lock = threading.Lock()
        self._lat: "deque[float]" = deque(maxlen=self.config.window)
        self.requests = 0
        self.hedged = 0
        self.suppressed = 0       # hedge launch blocked (injected fault)
        self.wins_primary = 0
        self.wins_hedge = 0
        self.wins_retry = 0       # non-hedged retry walk won the race
        self.both_failed = 0

    def observe(self, latency_s: float) -> None:
        with self._lock:
            self._lat.append(float(latency_s))

    def delay_s(self) -> float:
        """Current hedge trigger delay in seconds."""
        cfg = self.config
        with self._lock:
            lat = sorted(self._lat)
        if len(lat) < cfg.min_samples:
            ms = cfg.init_delay_ms
        else:
            idx = min(len(lat) - 1, int(cfg.quantile * len(lat)))
            ms = lat[idx] * 1e3
        return min(cfg.max_delay_ms, max(cfg.min_delay_ms, ms)) / 1e3

    def note_request(self) -> None:
        with self._lock:
            self.requests += 1

    def note_hedged(self) -> None:
        with self._lock:
            self.hedged += 1

    def note_suppressed(self) -> None:
        with self._lock:
            self.suppressed += 1

    def note_win(self, role: str) -> None:
        with self._lock:
            if role == "hedge":
                self.wins_hedge += 1
            elif role == "retry":
                self.wins_retry += 1
            else:
                self.wins_primary += 1

    def note_both_failed(self) -> None:
        with self._lock:
            self.both_failed += 1

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._lat)
            out = {"quantile": self.config.quantile,
                   "delay_ms": None, "samples": n,
                   "requests": self.requests, "hedged": self.hedged,
                   "suppressed": self.suppressed,
                   "wins_primary": self.wins_primary,
                   "wins_hedge": self.wins_hedge,
                   "wins_retry": self.wins_retry,
                   "both_failed": self.both_failed,
                   "hedge_fraction": round(
                       self.hedged / self.requests, 4)
                   if self.requests else 0.0}
        out["delay_ms"] = round(self.delay_s() * 1e3, 3)
        return out


def make_hedge(hedge: Any) -> Optional[HedgeTracker]:
    """Coerce the front's ``hedge`` knob: None/False -> off, True -> default
    config, HedgeConfig/dict -> configured, HedgeTracker -> as-is."""
    if hedge is None or hedge is False:
        return None
    if hedge is True:
        return HedgeTracker()
    if isinstance(hedge, HedgeTracker):
        return hedge
    if isinstance(hedge, HedgeConfig):
        return HedgeTracker(hedge)
    if isinstance(hedge, dict):
        return HedgeTracker(HedgeConfig(**hedge))
    raise ValueError(f"hedge must be None/bool/HedgeConfig/dict, "
                     f"got {hedge!r}")


# ---------------------------------------------------------------------------
# Brownout: staged graceful degradation on SLO burn
# ---------------------------------------------------------------------------


class BrownoutStep:
    """One declared degradation: ``apply()`` engages it, ``revert()``
    restores the pre-step state (closures capture whatever knob state they
    need). Steps are applied in declaration order and reverted in reverse —
    a stack of reversible knob changes."""

    __slots__ = ("name", "_apply", "_revert")

    def __init__(self, name: str, apply: Callable[[], None],
                 revert: Callable[[], None]):
        self.name = str(name)
        self._apply = apply
        self._revert = revert

    def apply(self) -> None:
        self._apply()

    def revert(self) -> None:
        self._revert()


class BrownoutController:
    """Hysteretic staged degradation driven by SLO burn rate.

    ``check()`` is the per-batch tick (rate-limited to ``check_interval_s``
    internally, so it is a cheap no-op on the hot path): read the burn rate
    for ``window_s`` from the SLO tracker; above ``enter_burn`` and after
    ``hold_s`` since the last transition, apply the next step; below
    ``exit_burn`` for ``2 * hold_s`` (hysteresis — restoring is slower than
    degrading), revert the most recent step. Transitions are journaled like
    tuner decisions (bounded list, ``rollback()`` reverts exactly the most
    recent step)."""

    def __init__(self, slo: Any, steps: List[BrownoutStep],
                 enter_burn: float = 2.0, exit_burn: float = 0.5,
                 window_s: int = 60, hold_s: float = 5.0,
                 check_interval_s: float = 0.25, journal_cap: int = 256,
                 clock: Callable[[], float] = time.monotonic):
        if slo is None:
            raise ValueError("brownout requires an SLO tracker (slo= knob)")
        if exit_burn >= enter_burn:
            raise ValueError("exit_burn must be below enter_burn "
                             "(hysteresis band)")
        self.slo = slo
        self.steps = list(steps)
        self.enter_burn = float(enter_burn)
        self.exit_burn = float(exit_burn)
        self.window_s = int(window_s)
        self.hold_s = float(hold_s)
        self.check_interval_s = float(check_interval_s)
        self._journal_cap = int(journal_cap)
        self._clock = clock
        self._lock = threading.Lock()
        self._step = 0          # applied step count (0 = full service)
        self._last_check = 0.0
        self._last_change = 0.0
        self._below_since: Optional[float] = None
        self.transitions = {"degrade": 0, "restore": 0, "rollback": 0}
        self.journal: List[Dict[str, Any]] = []

    @property
    def step(self) -> int:
        with self._lock:
            return self._step

    def _log(self, action: str, step_name: str, burn: float) -> None:
        entry = {"action": action, "step": step_name,
                 "level": self._step, "burn": round(burn, 4),
                 "t": round(self._clock(), 3)}
        self.journal.append(entry)
        if len(self.journal) > self._journal_cap:
            del self.journal[: self._journal_cap // 4]

    def _burn(self) -> float:
        try:
            rates = self.slo.burn_rates()
        except Exception:  # noqa: BLE001 — a broken tracker must not degrade
            return 0.0
        return float(rates.get(self.window_s, 0.0))

    def check(self) -> Optional[str]:
        """One controller tick. Returns the transition taken ("degrade" /
        "restore") or None. Rate-limited; safe to call per batch."""
        now = self._clock()
        with self._lock:
            if now - self._last_check < self.check_interval_s:
                return None
            self._last_check = now
        burn = self._burn()
        action: Optional[str] = None
        step: Optional[BrownoutStep] = None
        with self._lock:
            if burn > self.enter_burn:
                self._below_since = None
                if self._step < len(self.steps) and \
                        now - self._last_change >= self.hold_s:
                    step = self.steps[self._step]
                    self._step += 1
                    self._last_change = now
                    self.transitions["degrade"] += 1
                    self._log("degrade", step.name, burn)
                    action = "degrade"
            elif burn < self.exit_burn and self._step > 0:
                # hysteresis: the burn must stay below exit_burn for
                # 2 * hold_s before a step restores (degrading is fast,
                # restoring is deliberate)
                if self._below_since is None:
                    self._below_since = now
                elif now - self._below_since >= 2 * self.hold_s:
                    step = self.steps[self._step - 1]
                    self._step -= 1
                    self._last_change = now
                    self._below_since = now
                    self.transitions["restore"] += 1
                    self._log("restore", step.name, burn)
                    action = "restore"
            else:
                self._below_since = None
        if action is None or step is None:
            return None
        # knob closures run OUTSIDE the controller lock: a step may take
        # server/controller locks of its own (lock-order hygiene, C002)
        self._run_step(action, step)
        return action

    @staticmethod
    def _run_step(action: str, step: BrownoutStep) -> None:
        try:
            if action == "degrade":
                step.apply()
            else:
                step.revert()
        except Exception:  # noqa: BLE001 — a failing knob must not kill serving
            pass

    def rollback(self) -> bool:
        """Revert exactly the most recent applied step (the tuner-style
        one-step rollback). Returns False at full service."""
        with self._lock:
            if self._step == 0:
                return False
            step = self.steps[self._step - 1]
            self._step -= 1
            self._last_change = self._clock()
            self.transitions["rollback"] += 1
            self._log("rollback", step.name, 0.0)
        self._run_step("restore", step)
        return True

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {"step": self._step,
                    "max_steps": len(self.steps),
                    "active": self._step > 0,
                    "steps": [s.name for s in self.steps],
                    "enter_burn": self.enter_burn,
                    "exit_burn": self.exit_burn,
                    "window_s": self.window_s,
                    "transitions": dict(self.transitions),
                    "journal": list(self.journal[-16:])}


def make_brownout(spec: Any, slo: Any,
                  steps: List[BrownoutStep]) -> Optional[BrownoutController]:
    """Coerce a server's ``brownout`` knob: None/False -> off, True ->
    default thresholds, dict -> configured (keys = BrownoutController
    kwargs), BrownoutController -> as-is."""
    if spec is None or spec is False:
        return None
    if isinstance(spec, BrownoutController):
        return spec
    if spec is True:
        return BrownoutController(slo, steps)
    if isinstance(spec, dict):
        return BrownoutController(slo, steps, **spec)
    raise ValueError(f"brownout must be None/bool/dict/BrownoutController, "
                     f"got {spec!r}")
