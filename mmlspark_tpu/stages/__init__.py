"""Utility pipeline stages (reference stages/ package, SURVEY §2.4).

Column/row plumbing, batching, timing, summarization, text preprocessing —
the ~25 wide-but-shallow stages every pipeline leans on.
"""

from .basic import (
    Cacher,
    ClassBalancer,
    ClassBalancerModel,
    DropColumns,
    EnsembleByKey,
    Explode,
    Lambda,
    MultiColumnAdapter,
    PartitionCoalesce,
    RenameColumn,
    Repartition,
    SelectColumns,
    StratifiedRepartition,
    SummarizeData,
    Timer,
    TimerModel,
    UDFTransformer,
)
from .minibatch import (
    DynamicMiniBatchTransformer,
    FixedMiniBatchTransformer,
    FlattenBatch,
    TimeIntervalMiniBatchTransformer,
)
from .text import TextPreprocessor, UnicodeNormalize
from .udfs import get_value_at, to_vector

__all__ = [
    "Cacher", "ClassBalancer", "ClassBalancerModel", "DropColumns",
    "DynamicMiniBatchTransformer", "EnsembleByKey", "Explode",
    "FixedMiniBatchTransformer", "FlattenBatch", "Lambda", "MultiColumnAdapter",
    "PartitionCoalesce", "RenameColumn", "Repartition", "SelectColumns",
    "StratifiedRepartition", "SummarizeData", "TextPreprocessor",
    "TimeIntervalMiniBatchTransformer", "Timer", "TimerModel", "UDFTransformer",
    "UnicodeNormalize", "get_value_at", "to_vector",
]
