"""Minibatching stages: rows -> array-valued batch rows and back.

Reference: stages/MiniBatchTransformer.scala:14-200 (Fixed/Dynamic/TimeInterval
variants + FlattenBatch) and stages/Batchers.scala:12-160 (the iterator machinery).
Batch rows hold per-column lists; downstream device stages (DNNModel) consume them
as padded static-shape arrays via parallel/batching.py.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame, Partition, _partition_len
from ..core.params import Param
from ..core.pipeline import Transformer


def _slice_to_batch_rows(p: Partition, bounds: List[int]) -> Partition:
    out: Partition = {}
    for name, col in p.items():
        vals = np.empty(len(bounds) - 1, dtype=object)
        for bi in range(len(bounds) - 1):
            chunk = col[bounds[bi]:bounds[bi + 1]]
            vals[bi] = list(chunk)
        out[name] = vals
    return out


class FixedMiniBatchTransformer(Transformer):
    """Group every ``batchSize`` consecutive rows into one batch row
    (FixedMiniBatchTransformer, MiniBatchTransformer.scala:29-38)."""

    batchSize = Param("batchSize", "Rows per batch", 10, lambda v: v > 0, int)
    maxBufferSize = Param("maxBufferSize", "Buffering bound (parity; eager here)",
                          2147483647, ptype=int)
    buffered = Param("buffered", "Background buffering (parity; eager here)", False,
                     ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        b = self.get("batchSize")

        def fn(p: Partition) -> Partition:
            n = _partition_len(p)
            bounds = sorted(set(list(range(0, n, b)) + [n])) or [0, 0]
            return _slice_to_batch_rows(p, bounds)

        return df.map_partitions(fn)


class DynamicMiniBatchTransformer(Transformer):
    """Batch = whatever is available now (DynamicMiniBatchTransformer parity).

    In streaming, dynamic batching drains the queue; on a materialized partition the
    drain is the whole partition, capped by ``maxBatchSize``.
    """

    maxBatchSize = Param("maxBatchSize", "Upper bound on batch size", 2147483647,
                         lambda v: v > 0, int)

    def transform(self, df: DataFrame) -> DataFrame:
        cap = self.get("maxBatchSize")

        def fn(p: Partition) -> Partition:
            n = _partition_len(p)
            bounds = sorted(set(list(range(0, n, cap)) + [n])) or [0, 0]
            return _slice_to_batch_rows(p, bounds)

        return df.map_partitions(fn)


class TimeIntervalMiniBatchTransformer(Transformer):
    """Batch rows arriving within a time window (TimeIntervalMiniBatchTransformer).

    On a materialized partition all rows are 'already arrived': one batch per
    partition (capped by maxBatchSize) — matching the reference's semantics when
    the source outruns the interval.
    """

    millisToWait = Param("millisToWait", "Window length in ms", 1000,
                         lambda v: v > 0, int)
    maxBatchSize = Param("maxBatchSize", "Upper bound on batch size", 2147483647,
                         lambda v: v > 0, int)

    def transform(self, df: DataFrame) -> DataFrame:
        return DynamicMiniBatchTransformer(
            maxBatchSize=self.get("maxBatchSize")).transform(df)


class FlattenBatch(Transformer):
    """Inverse of minibatching: explode array-valued batch rows back to scalar rows
    (FlattenBatch, MiniBatchTransformer.scala:174+)."""

    def transform(self, df: DataFrame) -> DataFrame:
        def fn(p: Partition) -> Partition:
            names = list(p)
            n_batches = _partition_len(p)
            lengths = []
            for bi in range(n_batches):
                ls = {len(p[name][bi]) for name in names
                      if isinstance(p[name][bi], (list, tuple, np.ndarray))}
                lengths.append(max(ls) if ls else 1)
            total = int(sum(lengths))
            out: Partition = {}
            for name in names:
                vals = np.empty(total, dtype=object)
                k = 0
                for bi in range(n_batches):
                    v = p[name][bi]
                    if isinstance(v, (list, tuple, np.ndarray)):
                        for item in list(v)[:lengths[bi]]:
                            vals[k] = item
                            k += 1
                        k += lengths[bi] - min(lengths[bi], len(v))
                    else:  # scalar: replicate across the batch (non-batched col)
                        for _ in range(lengths[bi]):
                            vals[k] = v
                            k += 1
                out[name] = vals
            return out

        return df.map_partitions(fn)
