"""Text preprocessing stages (reference stages/TextPreprocessor.scala:15-130,
stages/UnicodeNormalize.scala)."""

from __future__ import annotations

import unicodedata
from typing import Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer


class _Trie:
    """Longest-match replacement trie (TextPreprocessor's Trie, :15-60)."""

    __slots__ = ("children", "value")

    def __init__(self):
        self.children: Dict[str, "_Trie"] = {}
        self.value: Optional[str] = None

    def put(self, key: str, value: str) -> None:
        node = self
        for ch in key:
            node = node.children.setdefault(ch, _Trie())
        node.value = value

    def longest_match(self, text: str, start: int):
        """(match_length, replacement) of the longest key matching at ``start``."""
        node = self
        best = (0, None)
        i = start
        while i < len(text):
            node = node.children.get(text[i])
            if node is None:
                break
            i += 1
            if node.value is not None:
                best = (i - start, node.value)
        return best


class TextPreprocessor(Transformer, HasInputCol, HasOutputCol):
    """Trie-based string normalization and phrase replacement
    (stages/TextPreprocessor.scala:15-130): normFunc first, then greedy
    longest-match replacement over the normalized text."""

    map = Param("map", "Phrase -> replacement dict", None, ptype=dict)
    normFunc = Param("normFunc", "Normalization: identity|lowerCase|removePunctuation",
                     "identity",
                     lambda v: v in ("identity", "lowerCase", "removePunctuation"), str)

    _PUNCT = set(".,!?;:'\"()[]{}<>-_/\\|@#$%^&*+=~`")

    def _normalize(self, text: str) -> str:
        kind = self.get("normFunc")
        if kind == "lowerCase":
            return text.lower()
        if kind == "removePunctuation":
            return "".join(ch for ch in text if ch not in self._PUNCT)
        return text

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        trie = _Trie()
        for k, v in (self.get("map") or {}).items():
            trie.put(k, v)

        def process(text):
            if text is None:
                return None
            text = self._normalize(str(text))
            out = []
            i = 0
            while i < len(text):
                length, repl = trie.longest_match(text, i)
                if length:
                    out.append(repl)
                    i += length
                else:
                    out.append(text[i])
                    i += 1
            return "".join(out)

        return df.with_column(out_col,
                              lambda p: [process(v) for v in p[in_col]])


class UnicodeNormalize(Transformer, HasInputCol, HasOutputCol):
    """Unicode normal-form + optional lowercase (stages/UnicodeNormalize.scala)."""

    form = Param("form", "Normal form: NFC|NFD|NFKC|NFKD", "NFKD",
                 lambda v: v in ("NFC", "NFD", "NFKC", "NFKD"), str)
    lower = Param("lower", "Lowercase after normalizing", True, ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        form = self.get("form")
        lower = self.get("lower")

        def process(v):
            if v is None:
                return None
            s = unicodedata.normalize(form, str(v))
            return s.lower() if lower else s

        return df.with_column(out_col, lambda p: [process(v) for v in p[in_col]])
