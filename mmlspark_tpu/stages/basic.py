"""Column/row plumbing stages (reference stages/ package).

Parity targets per class are cited inline; behavior mirrors the reference, the
substrate is the partitioned columnar DataFrame.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.dataframe import DataFrame, Partition, _partition_len
from ..core.params import (
    ComplexParam,
    HasInputCol,
    HasLabelCol,
    HasOutputCol,
    HasSeed,
    Param,
)
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import ColType, Schema


class Lambda(Transformer):
    """Arbitrary DataFrame->DataFrame function as a stage (stages/Lambda.scala:21).

    The function is a ComplexParam (persisted by pickle), so Lambdas of module-level
    functions round-trip through save/load; closures don't (same limitation as the
    reference's UDF serialization).
    """

    transformFunc = ComplexParam("transformFunc", "DataFrame -> DataFrame function")

    def __init__(self, transform_func: Optional[Callable] = None, **kwargs):
        super().__init__(**kwargs)
        if transform_func is not None:
            self.set("transformFunc", transform_func)

    def transform(self, df: DataFrame) -> DataFrame:
        return self.get_or_throw("transformFunc")(df)


class UDFTransformer(Transformer, HasInputCol, HasOutputCol):
    """Apply a per-row (or per-partition-column) function to a column
    (stages/UDFTransformer.scala).

    ``udf`` maps one input value -> output value; ``vectorizedUdf`` maps a whole
    column array -> column array (preferred: one call per partition).
    """

    udf = ComplexParam("udf", "Per-row value function")
    vectorizedUdf = ComplexParam("vectorizedUdf", "Whole-column function")
    deviceUdf = ComplexParam(
        "deviceUdf",
        "Optional jittable batched mirror of the udf: [B, ...] array -> "
        "[B, ...] array, row-independent and BITWISE-equal to the host "
        "udf on its accepted dtypes. When set, pipeline fusion "
        "(core/fusion.py) can compile this stage into a shared XLA "
        "program with its neighbors; the host udf remains the fallback "
        "and the parity oracle.")
    inputCols = Param("inputCols", "Multiple input columns (udf gets one arg each)",
                      None, ptype=(list, tuple))

    def device_fn(self, schema):
        from ..core.device_stage import DeviceFn

        dev = self.get("deviceUdf")
        if dev is None or self.get("inputCols"):
            return None
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")

        def fn(params, env):
            return {out_col: dev(env[in_col])}

        return DeviceFn(
            key=("UDFTransformer", in_col, out_col, id(dev)),
            in_cols=(in_col,), out_cols=(out_col,), fn=fn)

    def transform(self, df: DataFrame) -> DataFrame:
        out_col = self.get_or_throw("outputCol")
        vec = self.get("vectorizedUdf")
        row_fn = self.get("udf")
        in_cols = self.get("inputCols")
        if vec is not None:
            in_col = self.get_or_throw("inputCol")
            return df.with_column(out_col, lambda p: vec(p[in_col]))
        if row_fn is None:
            raise ValueError("UDFTransformer needs udf or vectorizedUdf")
        if in_cols:
            def fn(p: Partition):
                n = _partition_len(p)
                return [row_fn(*(p[c][i] for c in in_cols)) for i in range(n)]
            return df.with_column(out_col, fn)
        in_col = self.get_or_throw("inputCol")
        return df.with_column(out_col, lambda p: [row_fn(v) for v in p[in_col]])


class MultiColumnAdapter(Transformer):
    """Apply a 1-in/1-out base stage across many column pairs
    (stages/MultiColumnAdapter.scala)."""

    baseStage = ComplexParam("baseStage", "Stage with inputCol/outputCol params")
    inputCols = Param("inputCols", "Input column names", None, ptype=(list, tuple))
    outputCols = Param("outputCols", "Output column names", None, ptype=(list, tuple))

    def transform(self, df: DataFrame) -> DataFrame:
        base = self.get_or_throw("baseStage")
        ins, outs = self.get_or_throw("inputCols"), self.get_or_throw("outputCols")
        if len(ins) != len(outs):
            raise ValueError("inputCols and outputCols must align")
        for i, o in zip(ins, outs):
            stage = base.copy()
            stage.set("inputCol", i).set("outputCol", o)
            df = stage.transform(df)
        return df


class Explode(Transformer, HasInputCol, HasOutputCol):
    """Expand an array column into one row per element (stages/Explode.scala)."""

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get("outputCol") or in_col

        def explode_part(p: Partition) -> Partition:
            col = p[in_col]
            reps = np.array([0 if v is None else len(np.atleast_1d(v)) for v in col])
            idx = np.repeat(np.arange(len(col)), reps)
            out: Partition = {}
            for name, vals in p.items():
                if name == in_col and name == out_col:
                    continue
                out[name] = vals[idx]
            flat = np.empty(int(reps.sum()), dtype=object)
            k = 0
            for v in col:
                if v is None:
                    continue
                for item in np.atleast_1d(v):
                    flat[k] = item
                    k += 1
            out[out_col] = flat
            return out

        return df.map_partitions(explode_part)


class Cacher(Transformer):
    """Materialization point (stages/Cacher.scala). Eager substrate => no-op marker."""

    disable = Param("disable", "Skip caching", False, ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        return df if self.get("disable") else df.cache()


class Repartition(Transformer):
    """Shuffle rows into n even partitions (stages/Repartition.scala)."""

    n = Param("n", "Target partition count", None, lambda v: v > 0, int)
    disable = Param("disable", "Pass through unchanged", False, ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        if self.get("disable"):
            return df
        return df.repartition(self.get_or_throw("n"))


class PartitionCoalesce(Transformer):
    """Merge adjacent partitions without a shuffle (reference uses df.coalesce)."""

    n = Param("n", "Target partition count", None, lambda v: v > 0, int)

    def transform(self, df: DataFrame) -> DataFrame:
        return df.coalesce(self.get_or_throw("n"))


class StratifiedRepartition(Transformer, HasLabelCol, HasSeed):
    """Label-balanced repartition: every partition sees every class
    (stages/StratifiedRepartition.scala:26-73 — needed so distributed GBDT
    multiclass training has all classes on all workers)."""

    mode = Param("mode", "'equal' or 'original' (preserve class ratios)", "original",
                 lambda v: v in ("equal", "original"), str)

    def transform(self, df: DataFrame) -> DataFrame:
        label = self.get_or_throw("labelCol")
        n_parts = df.num_partitions
        data = df.collect()
        labels = data[label]
        rng = np.random.default_rng(self.get("seed"))
        n = len(labels)
        part_of = np.zeros(n, dtype=np.int64)
        # round-robin rows of each class across partitions -> every partition gets
        # ~count/n_parts of each class (both modes; 'equal' additionally truncates
        # classes to the same per-partition count)
        classes, inverse = np.unique(labels.astype(str), return_inverse=True)
        keep = np.ones(n, dtype=bool)
        min_count = None
        if self.get("mode") == "equal":
            counts = np.bincount(inverse)
            min_count = counts.min()
        for ci in range(len(classes)):
            idx = np.where(inverse == ci)[0]
            idx = idx[rng.permutation(len(idx))]
            if min_count is not None:
                keep[idx[min_count:]] = False
                idx = idx[:min_count]
            part_of[idx] = np.arange(len(idx)) % n_parts
        parts = []
        for pi in range(n_parts):
            mask = (part_of == pi) & keep
            parts.append({k: v[mask] for k, v in data.items()})
        return DataFrame(parts, df.schema.copy())


class DropColumns(Transformer):
    """stages/DropColumns.scala."""

    cols = Param("cols", "Columns to drop", None, ptype=(list, tuple))

    def transform(self, df: DataFrame) -> DataFrame:
        return df.drop(*self.get_or_throw("cols"))


class SelectColumns(Transformer):
    """stages/SelectColumns.scala."""

    cols = Param("cols", "Columns to keep", None, ptype=(list, tuple))

    def transform(self, df: DataFrame) -> DataFrame:
        return df.select(*self.get_or_throw("cols"))


class RenameColumn(Transformer, HasInputCol, HasOutputCol):
    """stages/RenameColumn.scala."""

    def transform(self, df: DataFrame) -> DataFrame:
        return df.with_column_renamed(self.get_or_throw("inputCol"),
                                      self.get_or_throw("outputCol"))


class EnsembleByKey(Transformer):
    """Group rows by key column(s) and average score columns — incl. elementwise
    vector averaging (stages/EnsembleByKey.scala, VectorAvg UDAF at :155)."""

    keys = Param("keys", "Key column names", None, ptype=(list, tuple))
    cols = Param("cols", "Columns to aggregate", None, ptype=(list, tuple))
    newCols = Param("newCols", "Output column names (default: mean(col))", None,
                    ptype=(list, tuple))
    strategy = Param("strategy", "Aggregation strategy", "mean",
                     lambda v: v == "mean", str)
    collapseGroup = Param("collapseGroup", "One row per key (else broadcast back)",
                          True, ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        keys = list(self.get_or_throw("keys"))
        cols = list(self.get_or_throw("cols"))
        new_cols = list(self.get("newCols") or [f"mean({c})" for c in cols])
        data = df.collect()
        n = len(next(iter(data.values()))) if data else 0
        key_tuples = [tuple(np.asarray(data[k][i]).item() if isinstance(data[k][i], np.generic)
                            else data[k][i] for k in keys) for i in range(n)]
        groups: Dict[tuple, List[int]] = {}
        for i, kt in enumerate(key_tuples):
            groups.setdefault(kt, []).append(i)

        def mean_of(col: np.ndarray, idxs: List[int]):
            vals = [col[i] for i in idxs if col[i] is not None]
            if not vals:
                return None
            arrs = [np.asarray(v, dtype=np.float64) for v in vals]
            m = np.mean(np.stack(arrs), axis=0)
            return float(m) if m.ndim == 0 else m

        def obj_col(values: List[Any]) -> np.ndarray:
            col = np.empty(len(values), dtype=object)
            for i, v in enumerate(values):
                col[i] = v
            return col

        if self.get("collapseGroup"):
            out: Partition = {k: obj_col([kt[j] for kt in groups])
                              for j, k in enumerate(keys)}
            for c, nc in zip(cols, new_cols):
                out[nc] = obj_col([mean_of(data[c], idxs) for idxs in groups.values()])
            return DataFrame([out])
        per_row: Dict[str, np.ndarray] = {}
        for c, nc in zip(cols, new_cols):
            vals = np.empty(n, dtype=object)
            for kt, idxs in groups.items():
                m = mean_of(data[c], idxs)
                for i in idxs:
                    vals[i] = m
            per_row[nc] = vals
        out_df = df
        for nc, vals in per_row.items():
            out_df = out_df.with_column(nc, vals)
        return out_df


class ClassBalancer(Estimator, HasInputCol, HasOutputCol):
    """Compute per-class weights = maxCount/count (stages/ClassBalancer.scala)."""

    broadcastJoin = Param("broadcastJoin", "Unused on this substrate (kept for parity)",
                          True, ptype=bool)

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "weight")
        super().__init__(**kwargs)

    def fit(self, df: DataFrame) -> "ClassBalancerModel":
        col = df.column(self.get_or_throw("inputCol"))
        classes, counts = np.unique(col.astype(str), return_counts=True)
        weights = counts.max() / counts.astype(np.float64)
        table = {c: float(w) for c, w in zip(classes, weights)}
        return ClassBalancerModel(inputCol=self.get("inputCol"),
                                  outputCol=self.get("outputCol"),
                                  weights=table)


class ClassBalancerModel(Model, HasInputCol, HasOutputCol):
    weights = Param("weights", "class -> weight map", None, ptype=dict)

    def transform(self, df: DataFrame) -> DataFrame:
        table = self.get_or_throw("weights")
        in_col = self.get_or_throw("inputCol")
        return df.with_column(
            self.get_or_throw("outputCol"),
            lambda p: np.array([table.get(str(v), 1.0) for v in p[in_col]],
                               dtype=np.float64))


class Timer(Estimator):
    """Time an inner stage's fit/transform, logging durations
    (stages/Timer.scala:57-110)."""

    stage = ComplexParam("stage", "The stage to time")
    logToScala = Param("logToScala", "Log via the framework logger (vs print)", True,
                       ptype=bool)
    disableMaterialization = Param("disableMaterialization",
                                   "Don't force evaluation when timing", False,
                                   ptype=bool)

    def _log(self, msg: str) -> None:
        if self.get("logToScala"):
            import logging
            logging.getLogger("mmlspark_tpu").info(msg)
        else:
            print(msg)

    def fit(self, df: DataFrame) -> "TimerModel":
        stage = self.get_or_throw("stage")
        if isinstance(stage, Estimator):
            t0 = time.perf_counter()
            model = stage.fit(df)
            self._log(f"{type(stage).__name__}.fit took {time.perf_counter() - t0:.3f}s")
        else:
            model = stage
        return TimerModel(stage=model, logToScala=self.get("logToScala"))


class TimerModel(Model):
    stage = ComplexParam("stage", "The fitted/wrapped transformer")
    logToScala = Param("logToScala", "Log via the framework logger", True, ptype=bool)

    def _log(self, msg: str) -> None:
        if self.get("logToScala"):
            import logging
            logging.getLogger("mmlspark_tpu").info(msg)
        else:
            print(msg)

    def transform(self, df: DataFrame) -> DataFrame:
        stage = self.get_or_throw("stage")
        t0 = time.perf_counter()
        out = stage.transform(df)
        self._log(f"{type(stage).__name__}.transform took {time.perf_counter() - t0:.3f}s")
        return out


class SummarizeData(Transformer):
    """Dataset statistics as a DataFrame: counts, missing, quantiles, basic moments
    (stages/SummarizeData.scala:100+)."""

    counts = Param("counts", "Include count stats", True, ptype=bool)
    basic = Param("basic", "Include basic moments", True, ptype=bool)
    sample = Param("sample", "Include quantiles", True, ptype=bool)
    percentiles = Param("percentiles", "Quantiles to compute",
                        [0.005, 0.01, 0.05, 0.25, 0.5, 0.75, 0.95, 0.99, 0.995],
                        ptype=list)
    errorThreshold = Param("errorThreshold", "Quantile error (exact here; parity)", 0.0,
                           ptype=float)

    def transform(self, df: DataFrame) -> DataFrame:
        data = df.collect()
        n = df.count()
        rows = []
        for name in df.columns:
            col = data[name]
            stats: Dict[str, Any] = {"Feature": name}
            if col.dtype == object:
                numeric = np.array([v for v in col if isinstance(v, (int, float, np.number))],
                                   dtype=np.float64)
                missing = sum(1 for v in col if v is None)
            else:
                numeric = col.astype(np.float64) if col.dtype.kind in "bifc" else np.array([])
                missing = int(np.isnan(numeric).sum()) if numeric.size else 0
                numeric = numeric[~np.isnan(numeric)] if numeric.size else numeric
            if self.get("counts"):
                stats["Count"] = float(n)
                stats["Unique Value Count"] = float(len(set(
                    str(v) for v in col)))
                stats["Missing Value Count"] = float(missing)
            if self.get("basic"):
                has = numeric.size > 0
                stats["Mean"] = float(numeric.mean()) if has else None
                stats["Standard Deviation"] = (
                    float(numeric.std(ddof=1)) if numeric.size > 1 else None)
                stats["Min"] = float(numeric.min()) if has else None
                stats["Max"] = float(numeric.max()) if has else None
            if self.get("sample"):
                for q in self.get("percentiles"):
                    stats[f"Quantile_{q}"] = (float(np.quantile(numeric, q))
                                              if numeric.size else None)
            rows.append(stats)
        return DataFrame.from_rows(rows)
