"""Column helper functions (reference stages/udfs.scala: get_value_at, to_vector)."""

from __future__ import annotations

import numpy as np


def get_value_at(col: np.ndarray, index: int) -> np.ndarray:
    """Extract element ``index`` from each per-row vector (udfs.scala get_value_at)."""
    if col.dtype != object:
        return np.ascontiguousarray(col[:, index])
    return np.array([None if v is None else float(np.asarray(v)[index]) for v in col])


def to_vector(col: np.ndarray) -> np.ndarray:
    """Coerce a column of lists/arrays/scalars into per-row float64 vectors
    (udfs.scala to_vector)."""
    out = np.empty(len(col), dtype=object)
    for i, v in enumerate(col):
        out[i] = None if v is None else np.asarray(v, dtype=np.float64).reshape(-1)
    return out
