"""Missing-data imputation and type conversion stages.

Reference: featurize/CleanMissingData.scala (mean/median/custom impute per
column) and featurize/DataConversion.scala (cast columns across primitive types,
date rendering).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasInputCols, HasOutputCols, Param
from ..core.pipeline import Estimator, Model, Transformer


class CleanMissingData(Estimator, HasInputCols, HasOutputCols):
    """Impute NaN/None in numeric columns (CleanMissingData.scala)."""

    cleaningMode = Param("cleaningMode", "Mean|Median|Custom", "Mean",
                         lambda v: v in ("Mean", "Median", "Custom"), str)
    customValue = Param("customValue", "Fill value for Custom mode", None, ptype=float)

    def fit(self, df: DataFrame) -> "CleanMissingDataModel":
        in_cols = list(self.get_or_throw("inputCols"))
        out_cols = list(self.get("outputCols") or in_cols)
        mode = self.get("cleaningMode")
        fills: Dict[str, float] = {}
        data = df.collect()
        for c in in_cols:
            col = data[c]
            if col.dtype == object:
                vals = np.array([float(v) for v in col if v is not None], dtype=np.float64)
            else:
                vals = col.astype(np.float64)
                vals = vals[~np.isnan(vals)]
            if mode == "Custom":
                fills[c] = float(self.get_or_throw("customValue"))
            elif mode == "Median":
                fills[c] = float(np.median(vals)) if len(vals) else 0.0
            else:
                fills[c] = float(vals.mean()) if len(vals) else 0.0
        return CleanMissingDataModel(inputCols=in_cols, outputCols=out_cols,
                                     fillValues=fills)


class CleanMissingDataModel(Model, HasInputCols, HasOutputCols):
    fillValues = Param("fillValues", "column -> fill value", None, ptype=dict)

    def transform(self, df: DataFrame) -> DataFrame:
        fills = self.get_or_throw("fillValues")
        in_cols = list(self.get_or_throw("inputCols"))
        out_cols = list(self.get("outputCols") or in_cols)
        out = df
        for ic, oc in zip(in_cols, out_cols):
            fill = fills[ic]

            def fn(p, _ic=ic, _fill=fill):
                col = p[_ic]
                if col.dtype == object:
                    return np.array([_fill if v is None or
                                     (isinstance(v, float) and np.isnan(v))
                                     else float(v) for v in col], dtype=np.float64)
                vals = col.astype(np.float64)
                return np.where(np.isnan(vals), _fill, vals)

            out = out.with_column(oc, fn)
        return out


_CONVERTERS = {
    "boolean": lambda col: np.array([bool(float(v)) if v is not None else None
                                     for v in col], dtype=object),
    "byte": lambda col: col.astype(np.float64).astype(np.int32),
    "short": lambda col: col.astype(np.float64).astype(np.int32),
    "integer": lambda col: col.astype(np.float64).astype(np.int32),
    "long": lambda col: col.astype(np.float64).astype(np.int64),
    "float": lambda col: col.astype(np.float32),
    "double": lambda col: col.astype(np.float64),
    "string": lambda col: np.array([None if v is None else str(v) for v in col],
                                   dtype=object),
    "toCategorical": None,   # handled via ValueIndexer semantics
    "clearCategorical": None,
    "date": None,
}


class DataConversion(Transformer):
    """Cast columns to a target type (featurize/DataConversion.scala)."""

    cols = Param("cols", "Columns to convert", None, ptype=(list, tuple))
    convertTo = Param("convertTo", "Target type", None,
                      lambda v: v in _CONVERTERS, str)
    dateTimeFormat = Param("dateTimeFormat", "Format for date conversion",
                           "yyyy-MM-dd HH:mm:ss", ptype=str)

    def transform(self, df: DataFrame) -> DataFrame:
        target = self.get_or_throw("convertTo")
        out = df
        for c in self.get_or_throw("cols"):
            if target == "date":
                out = out.with_column(c, self._to_date(c))
            elif target == "toCategorical":
                from .indexers import ValueIndexer
                out = ValueIndexer(inputCol=c, outputCol=c).fit(out).transform(out)
            elif target == "clearCategorical":
                out.schema.metadata.pop(c, None)
            else:
                conv = _CONVERTERS[target]
                out = out.with_column(c, lambda p, _c=c, _f=conv: _f(p[_c]))
        return out

    def _to_date(self, c):
        import datetime

        # translate the Java-style format the reference uses to strptime
        fmt = (self.get("dateTimeFormat")
               .replace("yyyy", "%Y").replace("MM", "%m").replace("dd", "%d")
               .replace("HH", "%H").replace("mm", "%M").replace("ss", "%S"))

        def fn(p):
            col = p[c]
            out = np.empty(len(col), dtype=object)
            for i, v in enumerate(col):
                if v is None:
                    out[i] = None
                elif isinstance(v, datetime.datetime):
                    out[i] = v
                elif isinstance(v, (int, float, np.integer, np.floating)):
                    out[i] = datetime.datetime.fromtimestamp(float(v) / 1000.0)
                else:
                    out[i] = datetime.datetime.strptime(str(v), fmt)
            return out

        return fn
