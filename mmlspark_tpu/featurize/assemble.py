"""Auto-featurization: heterogeneous columns -> one numeric feature vector.

Reference: featurize/Featurize.scala:25-110 + featurize/AssembleFeatures.scala —
per output column, build a sub-pipeline that casts numerics, indexes (or hashes
when high-cardinality) strings, one-hot encodes categoricals, imputes missing
values, and assembles everything into a single vector column. TrainClassifier /
TrainRegressor lean on this for their auto-featurize step, and the reference's
LightGBM featurize helper (LightGBMUtils.scala:44-57) is the same machinery.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasInputCols, HasOutputCol, Param
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import ColType, Schema
from ..ops.hashing import hash_string


class FastVectorAssembler(Transformer, HasInputCols, HasOutputCol):
    """Concatenate numeric/vector columns into one dense vector per row,
    skipping per-slot metadata bookkeeping — the reference's metadata-light
    VectorAssembler replacement (org/apache/spark/ml/feature/
    FastVectorAssembler.scala:1-151). Null scalars become NaN; null vectors
    raise (their width is unknowable row-locally, same as the reference)."""

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_cols = list(self.get_or_throw("inputCols"))
        out_col = self.get_or_throw("outputCol")
        # Vector-typed columns have row-locally-unknowable width when null,
        # so nulls there must raise (FastVectorAssembler.scala:143-144).
        vector_typed = {
            c for c in in_cols
            if df.schema.types.get(c) in (ColType.VECTOR, ColType.TENSOR)
        }

        def fn(p):
            n = len(next(iter(p.values()))) if p else 0
            # Columns not schema-marked VECTOR can still carry arrays (OBJECT
            # dtype); detect from the partition's first non-null value.
            holds_vectors = set(vector_typed)
            for c in in_cols:
                if c not in holds_vectors:
                    for v in p[c]:
                        if v is not None:
                            if isinstance(v, (np.ndarray, list, tuple)):
                                holds_vectors.add(c)
                            break
            out = np.empty(n, dtype=object)
            for i in range(n):
                parts = []
                for c in in_cols:
                    v = p[c][i]
                    if v is None:
                        if c in holds_vectors:
                            raise ValueError(
                                f"Values to assemble cannot be null: column "
                                f"'{c}' holds a null vector")
                        parts.append(np.array([np.nan]))
                    elif isinstance(v, (np.ndarray, list, tuple)):
                        arr = np.asarray(v, dtype=np.float64).ravel()
                        parts.append(arr)
                    else:
                        parts.append(np.array([float(v)], dtype=np.float64))
                out[i] = np.concatenate(parts)
            return out

        return df.with_column(out_col, fn)

    def transform_schema(self, schema: Schema) -> Schema:
        for c in self.get_or_throw("inputCols"):
            schema.require(c)
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.VECTOR
        return out


class AssembleFeatures(Estimator, HasInputCols, HasOutputCol):
    """Fit per-column encoders; produce a single dense vector column."""

    oneHotEncodeCategoricals = Param("oneHotEncodeCategoricals",
                                     "One-hot low-cardinality strings", True,
                                     ptype=bool)
    numberOfFeatures = Param("numberOfFeatures",
                             "Hash bucket count for high-cardinality strings", 262144,
                             ptype=int)
    allowImages = Param("allowImages", "Allow image columns (unrolled)", False,
                        ptype=bool)
    maxCategoricalLevels = Param("maxCategoricalLevels",
                                 "Cardinality cutoff for one-hot vs hashing", 100,
                                 ptype=int)

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)

    def fit(self, df: DataFrame) -> "AssembleFeaturesModel":
        in_cols = list(self.get_or_throw("inputCols"))
        data = df.collect()
        encoders: List[Dict[str, Any]] = []
        for c in in_cols:
            col = data[c]
            kind = _column_kind(col)
            if kind == "numeric":
                vals = _as_float(col)
                mean = float(np.nanmean(vals)) if len(vals) else 0.0
                encoders.append({"col": c, "kind": "numeric", "fill": mean})
            elif kind == "vector":
                dim = 0
                for v in col:
                    if v is not None:
                        dim = len(np.asarray(v).reshape(-1))
                        break
                encoders.append({"col": c, "kind": "vector", "dim": dim})
            elif kind == "sparse":
                from ..parallel.batching import sparse_width

                encoders.append({"col": c, "kind": "sparse",
                                 "dim": sparse_width(col)})
            elif kind == "string":
                levels = sorted({str(v) for v in col if v is not None})
                if (self.get("oneHotEncodeCategoricals")
                        and len(levels) <= self.get("maxCategoricalLevels")):
                    encoders.append({"col": c, "kind": "onehot", "levels": levels})
                else:
                    encoders.append({"col": c, "kind": "hash",
                                     "buckets": min(self.get("numberOfFeatures"),
                                                    1 << 18)})
            else:
                continue  # unsupported columns silently skipped (reference behavior)
        return AssembleFeaturesModel(
            inputCols=in_cols, outputCol=self.get("outputCol"), encoders=encoders)


class AssembleFeaturesModel(Model, HasInputCols, HasOutputCol):
    encoders = ComplexParam("encoders", "Per-column encoder specs")

    def transform(self, df: DataFrame) -> DataFrame:
        encoders = self.get_or_throw("encoders")
        out_col = self.get_or_throw("outputCol")

        def fn(p):
            n = len(next(iter(p.values()))) if p else 0
            pieces: List[np.ndarray] = []
            for enc in encoders:
                col = p[enc["col"]]
                kind = enc["kind"]
                if kind == "numeric":
                    vals = _as_float(col)
                    vals = np.where(np.isnan(vals), enc["fill"], vals)
                    pieces.append(vals.reshape(n, 1))
                elif kind == "vector":
                    dim = enc["dim"]
                    block = np.zeros((n, dim))
                    for i, v in enumerate(col):
                        if v is not None:
                            block[i] = np.asarray(v, dtype=np.float64).reshape(-1)[:dim]
                    pieces.append(block)
                elif kind == "sparse":
                    from ..parallel.batching import densify_sparse

                    pieces.append(densify_sparse(col, enc["dim"]))
                elif kind == "onehot":
                    levels = enc["levels"]
                    index = {v: i for i, v in enumerate(levels)}
                    block = np.zeros((n, len(levels)))
                    for i, v in enumerate(col):
                        j = index.get(str(v)) if v is not None else None
                        if j is not None:
                            block[i, j] = 1.0
                    pieces.append(block)
                elif kind == "hash":
                    # single hashed slot per string (compact; collisions sum)
                    block = np.zeros((n, 1))
                    for i, v in enumerate(col):
                        if v is not None:
                            block[i, 0] = hash_string(str(v)) % enc["buckets"]
                    pieces.append(block)
            full = np.concatenate(pieces, axis=1) if pieces else np.zeros((n, 0))
            out = np.empty(n, dtype=object)
            for i in range(n):
                out[i] = full[i]
            return out

        res = df.with_column(out_col, fn)
        names = self.slot_names()
        if names is not None:
            res.schema.meta(out_col)["slot_names"] = names
        return res

    def slot_names(self) -> Optional[List[str]]:
        """Per-slot names of the assembled vector (the reference keeps these
        in Spark ML column metadata; consumers like categoricalSlotNames
        resolve against them). None when a block has no stable naming or the
        vector is too wide to enumerate."""
        names: List[str] = []
        for enc in self.get_or_throw("encoders"):
            c, kind = enc["col"], enc["kind"]
            if kind == "numeric":
                names.append(c)
            elif kind == "onehot":
                names.extend(f"{c}_{lv}" for lv in enc["levels"])
            elif kind == "hash":
                names.append(c)
            elif kind in ("vector", "sparse"):
                if enc["dim"] > 10_000:
                    return None
                names.extend(f"{c}_{i}" for i in range(enc["dim"]))
        return names

    def transform_schema(self, schema: Schema) -> Schema:
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.VECTOR
        names = self.slot_names()
        if names is not None:
            out.meta(self.get_or_throw("outputCol"))["slot_names"] = names
        return out


class Featurize(Estimator):
    """Map of output col -> input cols, each assembled independently
    (featurize/Featurize.scala:25-110)."""

    featureColumns = Param("featureColumns", "outputCol -> [inputCols] map", None,
                           ptype=dict)
    oneHotEncodeCategoricals = Param("oneHotEncodeCategoricals", "One-hot strings",
                                     True, ptype=bool)
    numberOfFeatures = Param("numberOfFeatures", "Hash buckets", 262144, ptype=int)
    allowImages = Param("allowImages", "Allow image columns", False, ptype=bool)

    def fit(self, df: DataFrame) -> "Model":
        from ..core.pipeline import PipelineModel

        fitted = []
        for out_col, in_cols in self.get_or_throw("featureColumns").items():
            stage = AssembleFeatures(
                inputCols=list(in_cols), outputCol=out_col,
                oneHotEncodeCategoricals=self.get("oneHotEncodeCategoricals"),
                numberOfFeatures=self.get("numberOfFeatures"),
                allowImages=self.get("allowImages"))
            fitted.append(stage.fit(df))
        return PipelineModel(fitted)


def _column_kind(col: np.ndarray) -> str:
    if col.dtype.kind in "biufc":
        return "numeric"
    for v in col:
        if v is None:
            continue
        if isinstance(v, str):
            return "string"
        from ..parallel.batching import is_sparse_row

        if is_sparse_row(v):
            return "sparse"  # TextFeaturizer/VW sparse-row struct
        if isinstance(v, (np.ndarray, list, tuple)):
            return "vector"
        if isinstance(v, (int, float, np.integer, np.floating, bool)):
            return "numeric"
        return "other"
    return "other"


def _as_float(col: np.ndarray) -> np.ndarray:
    if col.dtype == object:
        return np.array([np.nan if v is None else float(v) for v in col],
                        dtype=np.float64)
    return col.astype(np.float64)
