"""Auto-featurization: heterogeneous columns -> one numeric feature vector.

Reference: featurize/Featurize.scala:25-110 + featurize/AssembleFeatures.scala —
per output column, build a sub-pipeline that casts numerics, indexes (or hashes
when high-cardinality) strings, one-hot encodes categoricals, imputes missing
values, and assembles everything into a single vector column. TrainClassifier /
TrainRegressor lean on this for their auto-featurize step, and the reference's
LightGBM featurize helper (LightGBMUtils.scala:44-57) is the same machinery.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..core.device_stage import DeviceFn, FusionUnsupported
from ..core.params import ComplexParam, HasInputCols, HasOutputCol, Param
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import ColType, Schema
from ..ops.hashing import hash_string

#: dtypes that widen to float64 EXACTLY through a float32 device compute —
#: the precondition for a fused assembler to reproduce the host's f64
#: feature vectors bitwise (f64 inputs would narrow lossily on the wire)
_F32_EXACT_DTYPES = frozenset(
    np.dtype(t) for t in (np.float32, np.bool_, np.uint8, np.int8,
                          np.uint16, np.int16))


def _f32_exact_accepts(in_cols):
    def accepts(probes):
        for c in in_cols:
            p = probes.get(c)
            if p is None or p["dtype"] is None:
                continue
            if p["sparse"] or p["dtype"] not in _F32_EXACT_DTYPES:
                return False
        return True
    return accepts


def _vector_f64_finalize(out_col):
    """f32 device batch -> f64 per-row vectors: exact widening, matching
    the host assembler's float64 output for f32-exact inputs."""

    def finalize(outs, ctx):
        arr = np.asarray(outs[out_col], dtype=np.float64)
        obj = np.empty(len(arr), dtype=object)
        for i in range(len(arr)):
            obj[i] = arr[i]
        return {out_col: obj}

    return finalize


class FastVectorAssembler(Transformer, HasInputCols, HasOutputCol):
    """Concatenate numeric/vector columns into one dense vector per row,
    skipping per-slot metadata bookkeeping — the reference's metadata-light
    VectorAssembler replacement (org/apache/spark/ml/feature/
    FastVectorAssembler.scala:1-151). Null scalars become NaN; null vectors
    raise (their width is unknowable row-locally, same as the reference)."""

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_cols = list(self.get_or_throw("inputCols"))
        out_col = self.get_or_throw("outputCol")
        # Vector-typed columns have row-locally-unknowable width when null,
        # so nulls there must raise (FastVectorAssembler.scala:143-144).
        vector_typed = {
            c for c in in_cols
            if df.schema.types.get(c) in (ColType.VECTOR, ColType.TENSOR)
        }

        def fn(p):
            n = len(next(iter(p.values()))) if p else 0
            # Columns not schema-marked VECTOR can still carry arrays (OBJECT
            # dtype); detect from the partition's first non-null value.
            holds_vectors = set(vector_typed)
            for c in in_cols:
                if c not in holds_vectors:
                    for v in p[c]:
                        if v is not None:
                            if isinstance(v, (np.ndarray, list, tuple)):
                                holds_vectors.add(c)
                            break
            out = np.empty(n, dtype=object)
            for i in range(n):
                parts = []
                for c in in_cols:
                    v = p[c][i]
                    if v is None:
                        if c in holds_vectors:
                            raise ValueError(
                                f"Values to assemble cannot be null: column "
                                f"'{c}' holds a null vector")
                        parts.append(np.array([np.nan]))
                    elif isinstance(v, (np.ndarray, list, tuple)):
                        arr = np.asarray(v, dtype=np.float64).ravel()
                        parts.append(arr)
                    else:
                        parts.append(np.array([float(v)], dtype=np.float64))
                out[i] = np.concatenate(parts)
            return out

        return df.with_column(out_col, fn)

    def transform_schema(self, schema: Schema) -> Schema:
        for c in self.get_or_throw("inputCols"):
            schema.require(c)
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.VECTOR
        return out

    def device_fn(self, schema: Schema):
        """Fusion contract: concatenation is pure value movement, so the
        assembled vector computes on device in f32 and widens to the host's
        f64 exactly — gated (accepts) to f32-exact input dtypes; nulls take
        the host path (their NaN-fill semantics aren't null-propagation)."""
        in_cols = tuple(self.get_or_throw("inputCols"))
        out_col = self.get_or_throw("outputCol")

        def fn(params, env):
            import jax.numpy as jnp

            parts = []
            for c in in_cols:
                v = env[c].astype(jnp.float32)
                parts.append(v.reshape(v.shape[0], -1))
            return {out_col: jnp.concatenate(parts, axis=1)}

        return DeviceFn(
            key=("FastVectorAssembler", in_cols, out_col),
            in_cols=in_cols, out_cols=(out_col,), fn=fn,
            finalize=_vector_f64_finalize(out_col),
            accepts=_f32_exact_accepts(in_cols), null_policy="fallback")


class AssembleFeatures(Estimator, HasInputCols, HasOutputCol):
    """Fit per-column encoders; produce a single dense vector column."""

    oneHotEncodeCategoricals = Param("oneHotEncodeCategoricals",
                                     "One-hot low-cardinality strings", True,
                                     ptype=bool)
    numberOfFeatures = Param("numberOfFeatures",
                             "Hash bucket count for high-cardinality strings", 262144,
                             ptype=int)
    allowImages = Param("allowImages", "Allow image columns (unrolled)", False,
                        ptype=bool)
    maxCategoricalLevels = Param("maxCategoricalLevels",
                                 "Cardinality cutoff for one-hot vs hashing", 100,
                                 ptype=int)

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)

    def fit(self, df: DataFrame) -> "AssembleFeaturesModel":
        in_cols = list(self.get_or_throw("inputCols"))
        data = df.collect()
        encoders: List[Dict[str, Any]] = []
        for c in in_cols:
            col = data[c]
            kind = _column_kind(col)
            if kind == "numeric":
                vals = _as_float(col)
                mean = float(np.nanmean(vals)) if len(vals) else 0.0
                encoders.append({"col": c, "kind": "numeric", "fill": mean})
            elif kind == "vector":
                dim = 0
                for v in col:
                    if v is not None:
                        dim = len(np.asarray(v).reshape(-1))
                        break
                encoders.append({"col": c, "kind": "vector", "dim": dim})
            elif kind == "sparse":
                from ..parallel.batching import sparse_width

                encoders.append({"col": c, "kind": "sparse",
                                 "dim": sparse_width(col)})
            elif kind == "string":
                levels = sorted({str(v) for v in col if v is not None})
                if (self.get("oneHotEncodeCategoricals")
                        and len(levels) <= self.get("maxCategoricalLevels")):
                    encoders.append({"col": c, "kind": "onehot", "levels": levels})
                else:
                    encoders.append({"col": c, "kind": "hash",
                                     "buckets": min(self.get("numberOfFeatures"),
                                                    1 << 18)})
            else:
                continue  # unsupported columns silently skipped (reference behavior)
        return AssembleFeaturesModel(
            inputCols=in_cols, outputCol=self.get("outputCol"), encoders=encoders)


class AssembleFeaturesModel(Model, HasInputCols, HasOutputCol):
    encoders = ComplexParam("encoders", "Per-column encoder specs")

    def transform(self, df: DataFrame) -> DataFrame:
        encoders = self.get_or_throw("encoders")
        out_col = self.get_or_throw("outputCol")

        def fn(p):
            n = len(next(iter(p.values()))) if p else 0
            pieces: List[np.ndarray] = []
            for enc in encoders:
                col = p[enc["col"]]
                kind = enc["kind"]
                if kind == "numeric":
                    vals = _as_float(col)
                    vals = np.where(np.isnan(vals), enc["fill"], vals)
                    pieces.append(vals.reshape(n, 1))
                elif kind == "vector":
                    dim = enc["dim"]
                    block = np.zeros((n, dim))
                    for i, v in enumerate(col):
                        if v is not None:
                            block[i] = np.asarray(v, dtype=np.float64).reshape(-1)[:dim]
                    pieces.append(block)
                elif kind == "sparse":
                    from ..parallel.batching import densify_sparse

                    pieces.append(densify_sparse(col, enc["dim"]))
                elif kind == "onehot":
                    levels = enc["levels"]
                    index = {v: i for i, v in enumerate(levels)}
                    block = np.zeros((n, len(levels)))
                    for i, v in enumerate(col):
                        j = index.get(str(v)) if v is not None else None
                        if j is not None:
                            block[i, j] = 1.0
                    pieces.append(block)
                elif kind == "hash":
                    # single hashed slot per string (compact; collisions sum)
                    block = np.zeros((n, 1))
                    for i, v in enumerate(col):
                        if v is not None:
                            block[i, 0] = hash_string(str(v)) % enc["buckets"]
                    pieces.append(block)
            full = np.concatenate(pieces, axis=1) if pieces else np.zeros((n, 0))
            out = np.empty(n, dtype=object)
            for i in range(n):
                out[i] = full[i]
            return out

        res = df.with_column(out_col, fn)
        names = self.slot_names()
        if names is not None:
            res.schema.meta(out_col)["slot_names"] = names
        return res

    def slot_names(self) -> Optional[List[str]]:
        """Per-slot names of the assembled vector (the reference keeps these
        in Spark ML column metadata; consumers like categoricalSlotNames
        resolve against them). None when a block has no stable naming or the
        vector is too wide to enumerate."""
        names: List[str] = []
        for enc in self.get_or_throw("encoders"):
            c, kind = enc["col"], enc["kind"]
            if kind == "numeric":
                names.append(c)
            elif kind == "onehot":
                names.extend(f"{c}_{lv}" for lv in enc["levels"])
            elif kind == "hash":
                names.append(c)
            elif kind in ("vector", "sparse"):
                if enc["dim"] > 10_000:
                    return None
                names.extend(f"{c}_{i}" for i in range(enc["dim"]))
        return names

    def transform_schema(self, schema: Schema) -> Schema:
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.VECTOR
        names = self.slot_names()
        if names is not None:
            out.meta(self.get_or_throw("outputCol"))["slot_names"] = names
        return out

    def device_fn(self, schema: Schema):
        """Fusion contract: numeric (NaN -> mean fill) and vector encoders
        mirror exactly on device; string one-hot/hash encoders are host-only
        (the whole stage stays host when any is present). Numeric encoders
        additionally require an f32-representable fill — the host imputes in
        f64, and a non-representable mean cannot round-trip the f32 wire."""
        encoders = self.get("encoders")
        if not encoders:
            return None
        if any(e["kind"] not in ("numeric", "vector") for e in encoders):
            return None
        for e in encoders:
            if e["kind"] == "numeric" and \
                    float(np.float32(e["fill"])) != float(e["fill"]):
                return None
        in_cols = tuple(e["col"] for e in encoders)
        out_col = self.get_or_throw("outputCol")

        def fn(params, env):
            import jax.numpy as jnp

            parts = []
            for e in encoders:
                v = env[e["col"]].astype(jnp.float32)
                if e["kind"] == "numeric":
                    if v.ndim != 1:
                        raise FusionUnsupported("numeric encoder expects scalars")
                    v = jnp.where(jnp.isnan(v), jnp.float32(e["fill"]), v)
                    v = v.reshape(-1, 1)
                else:
                    if v.ndim != 2 or v.shape[1] != e["dim"]:
                        raise FusionUnsupported(
                            f"vector dim {v.shape} != fitted {e['dim']}")
                parts.append(v)
            return {out_col: jnp.concatenate(parts, axis=1)}

        return DeviceFn(
            key=("AssembleFeaturesModel", in_cols, out_col,
                 tuple(tuple(sorted((k, v) for k, v in e.items()
                                    if not isinstance(v, (list, np.ndarray))))
                       for e in encoders)),
            in_cols=in_cols, out_cols=(out_col,), fn=fn,
            finalize=_vector_f64_finalize(out_col),
            accepts=_f32_exact_accepts(in_cols), null_policy="fallback")


class Featurize(Estimator):
    """Map of output col -> input cols, each assembled independently
    (featurize/Featurize.scala:25-110)."""

    featureColumns = Param("featureColumns", "outputCol -> [inputCols] map", None,
                           ptype=dict)
    oneHotEncodeCategoricals = Param("oneHotEncodeCategoricals", "One-hot strings",
                                     True, ptype=bool)
    numberOfFeatures = Param("numberOfFeatures", "Hash buckets", 262144, ptype=int)
    allowImages = Param("allowImages", "Allow image columns", False, ptype=bool)

    def fit(self, df: DataFrame) -> "Model":
        from ..core.pipeline import PipelineModel

        fitted = []
        for out_col, in_cols in self.get_or_throw("featureColumns").items():
            stage = AssembleFeatures(
                inputCols=list(in_cols), outputCol=out_col,
                oneHotEncodeCategoricals=self.get("oneHotEncodeCategoricals"),
                numberOfFeatures=self.get("numberOfFeatures"),
                allowImages=self.get("allowImages"))
            fitted.append(stage.fit(df))
        return PipelineModel(fitted)


def _column_kind(col: np.ndarray) -> str:
    if col.dtype.kind in "biufc":
        return "numeric"
    for v in col:
        if v is None:
            continue
        if isinstance(v, str):
            return "string"
        from ..parallel.batching import is_sparse_row

        if is_sparse_row(v):
            return "sparse"  # TextFeaturizer/VW sparse-row struct
        if isinstance(v, (np.ndarray, list, tuple)):
            return "vector"
        if isinstance(v, (int, float, np.integer, np.floating, bool)):
            return "numeric"
        return "other"
    return "other"


def _as_float(col: np.ndarray) -> np.ndarray:
    if col.dtype == object:
        return np.array([np.nan if v is None else float(v) for v in col],
                        dtype=np.float64)
    return col.astype(np.float64)
