"""Typed distinct-value indexing with categorical metadata.

Reference: featurize/ValueIndexer.scala (fit collects ordered distinct values,
model maps value -> index, storing categorical levels in column metadata) and
featurize/IndexToValue.scala (inverse via that metadata).
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasInputCol, HasOutputCol, Param
from ..core.pipeline import Estimator, Model
from ..core.schema import ColType, Schema, get_categorical_levels, set_categorical_levels


class ValueIndexer(Estimator, HasInputCol, HasOutputCol):
    """Fit: collect sorted distinct values; nulls get the last index."""

    def fit(self, df: DataFrame) -> "ValueIndexerModel":
        col = df.column(self.get_or_throw("inputCol"))
        vals = [v for v in col if v is not None]
        try:
            levels = sorted(set(vals))
        except TypeError:
            levels = sorted(set(str(v) for v in vals))
        return ValueIndexerModel(
            inputCol=self.get("inputCol"), outputCol=self.get("outputCol"),
            levels=list(levels))


class ValueIndexerModel(Model, HasInputCol, HasOutputCol):
    levels = ComplexParam("levels", "Ordered distinct values")

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        levels = list(self.get_or_throw("levels"))
        index = {v: i for i, v in enumerate(levels)}
        null_index = len(levels)

        def fn(p):
            col = p[in_col]
            out = np.empty(len(col), dtype=np.float64)
            for i, v in enumerate(col):
                if v is None:
                    out[i] = null_index
                else:
                    out[i] = index.get(v, index.get(str(v), null_index))
            return out

        result = df.with_column(out_col, fn)
        set_categorical_levels(result.schema, out_col, levels)
        return result

    def transform_schema(self, schema: Schema) -> Schema:
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.FLOAT64
        set_categorical_levels(out, self.get_or_throw("outputCol"),
                               list(self.get_or_throw("levels")))
        return out


class IndexToValue(Model, HasInputCol, HasOutputCol):
    """Inverse of ValueIndexerModel using categorical metadata
    (featurize/IndexToValue.scala)."""

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        levels = get_categorical_levels(df.schema, in_col)
        if levels is None:
            raise ValueError(f"Column {in_col!r} has no categorical levels metadata")

        def fn(p):
            col = p[in_col]
            out = np.empty(len(col), dtype=object)
            for i, v in enumerate(col):
                iv = int(v)
                out[i] = levels[iv] if 0 <= iv < len(levels) else None
            return out

        return df.with_column(out_col, fn)
