"""Featurization library (reference featurize/ package, SURVEY §2.4).

Auto-featurization (Featurize/AssembleFeatures), typed value indexing
(ValueIndexer/IndexToValue), missing-data imputation (CleanMissingData), type
coercion (DataConversion), and text featurization (TextFeaturizer, MultiNGram,
PageSplitter).
"""

from .indexers import IndexToValue, ValueIndexer, ValueIndexerModel
from .clean import CleanMissingData, CleanMissingDataModel, DataConversion
from .assemble import AssembleFeatures, FastVectorAssembler, Featurize
from .text import MultiNGram, PageSplitter, TextFeaturizer, TextFeaturizerModel
from .word2vec import Word2Vec, Word2VecModel

__all__ = [
    "AssembleFeatures", "CleanMissingData", "CleanMissingDataModel",
    "DataConversion", "FastVectorAssembler", "Featurize", "IndexToValue",
    "MultiNGram", "PageSplitter",
    "TextFeaturizer", "TextFeaturizerModel", "ValueIndexer", "ValueIndexerModel",
    "Word2Vec", "Word2VecModel",
]
