"""Word2Vec: skip-gram embeddings trained on-device.

Reference parity: the "TextAnalytics - Amazon Book Reviews with Word2Vec"
notebook leans on Spark ML's Word2Vec (an L0 dependency of the reference's
text journeys). TPU-native redesign: skip-gram with negative sampling as
one jitted scan over (center, context, negatives) minibatches — embedding
gathers + a dot-product logistic loss ride the MXU/VPU, host code only
builds the vocabulary and the pair table. ``transform`` averages word
vectors per document (Spark Word2Vec.transform semantics);
``find_synonyms`` does cosine top-k like the Spark API.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasInputCol, HasOutputCol, Param
from ..core.pipeline import Estimator, Model
from ..core.schema import ColType, Schema


def _tokens_of(v) -> List[str]:
    if v is None:
        return []
    if isinstance(v, str):
        return v.lower().split()
    return [str(t).lower() for t in v]


class Word2Vec(Estimator, HasInputCol, HasOutputCol):
    """Fit skip-gram word vectors over a column of texts/token lists."""

    vectorSize = Param("vectorSize", "Embedding dimension", 32, lambda v: v > 0, int)
    windowSize = Param("windowSize", "Context window radius", 3, lambda v: v > 0, int)
    minCount = Param("minCount", "Min word frequency kept in the vocab", 2,
                     lambda v: v >= 1, int)
    numIterations = Param("numIterations", "Passes over the pair table", 3,
                          lambda v: v > 0, int)
    numNegatives = Param("numNegatives", "Negative samples per pair", 4,
                         lambda v: v >= 1, int)
    stepSize = Param("stepSize", "SGD learning rate", 0.1, lambda v: v > 0,
                     float)
    batchSize = Param("batchSize", "Pairs per jitted step", 1024,
                      lambda v: v >= 1, int)
    seed = Param("seed", "RNG seed", 0, ptype=int)

    def fit(self, df: DataFrame) -> "Word2VecModel":
        import jax
        import jax.numpy as jnp

        col = df.column(self.get_or_throw("inputCol"))
        docs = [_tokens_of(v) for v in col]

        # vocabulary (host)
        counts: Dict[str, int] = {}
        for doc in docs:
            for t in doc:
                counts[t] = counts.get(t, 0) + 1
        vocab = sorted(w for w, c in counts.items()
                       if c >= self.get("minCount"))
        if not vocab:
            raise ValueError("Word2Vec: empty vocabulary "
                             "(all words below minCount)")
        index = {w: i for i, w in enumerate(vocab)}
        V, D = len(vocab), self.get("vectorSize")

        # skip-gram pair table (host)
        win = self.get("windowSize")
        centers, contexts = [], []
        for doc in docs:
            ids = [index[t] for t in doc if t in index]
            for i, c in enumerate(ids):
                for j in range(max(0, i - win), min(len(ids), i + win + 1)):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
        if not centers:
            raise ValueError("Word2Vec: no training pairs "
                             "(documents too short for the window)")
        centers_np = np.asarray(centers, dtype=np.int32)
        contexts_np = np.asarray(contexts, dtype=np.int32)

        # unigram^0.75 negative-sampling distribution (word2vec convention)
        freq = np.asarray([counts[w] for w in vocab], dtype=np.float64) ** 0.75
        neg_p = freq / freq.sum()

        rng = np.random.default_rng(self.get("seed"))
        B = min(self.get("batchSize"), len(centers_np))
        K = self.get("numNegatives")
        lr = self.get("stepSize")

        key = jax.random.key(self.get("seed"))
        k_in, k_out = jax.random.split(key)
        w_in = jax.random.normal(k_in, (V, D), dtype=jnp.float32) * 0.1
        w_out = jnp.zeros((V, D), dtype=jnp.float32)

        @jax.jit
        def step(w_in, w_out, cen, pos, neg):
            """One SGD step on a [B] batch; neg: [B, K]."""
            def loss_fn(params):
                wi, wo = params
                e = wi[cen]                           # [B, D]
                p = wo[pos]                           # [B, D]
                n = wo[neg]                           # [B, K, D]
                pos_logit = jnp.sum(e * p, axis=-1)
                neg_logit = jnp.einsum("bd,bkd->bk", e, n)
                loss = -jnp.mean(
                    jax.nn.log_sigmoid(pos_logit)
                    + jnp.sum(jax.nn.log_sigmoid(-neg_logit), axis=-1))
                return loss
            loss, grads = jax.value_and_grad(loss_fn)((w_in, w_out))
            gi, go = grads
            return w_in - lr * gi, w_out - lr * go, loss

        n_pairs = len(centers_np)
        steps_per_pass = max(1, n_pairs // B)
        for _ in range(self.get("numIterations")):
            order = rng.permutation(n_pairs)
            for s in range(steps_per_pass):
                # steps_per_pass = n_pairs // B, so every slice is exactly B
                # pairs (static shapes; the ragged tail is dropped)
                sel = order[s * B:(s + 1) * B]
                negs = rng.choice(V, size=(B, K), p=neg_p).astype(np.int32)
                w_in, w_out, _ = step(w_in, w_out,
                                      jnp.asarray(centers_np[sel]),
                                      jnp.asarray(contexts_np[sel]),
                                      jnp.asarray(negs))

        vectors = np.asarray(w_in, dtype=np.float32)
        return Word2VecModel(
            inputCol=self.get("inputCol"), outputCol=self.get("outputCol"),
            vocab=list(vocab), vectors=vectors)


class Word2VecModel(Model, HasInputCol, HasOutputCol):
    """Average-of-word-vectors document embedding + synonym lookup."""

    vocab = ComplexParam("vocab", "Vocabulary (index order)")
    vectors = ComplexParam("vectors", "[V, D] embedding matrix")

    def _index(self) -> Dict[str, int]:
        return {w: i for i, w in enumerate(self.get_or_throw("vocab"))}

    def transform(self, df: DataFrame) -> DataFrame:
        index = self._index()
        vecs = np.asarray(self.get_or_throw("vectors"))
        dim = vecs.shape[1]

        def fn(p):
            col = p[self.get_or_throw("inputCol")]
            out = np.empty(len(col), dtype=object)
            for i, v in enumerate(col):
                ids = [index[t] for t in _tokens_of(v) if t in index]
                out[i] = (vecs[ids].mean(axis=0) if ids
                          else np.zeros(dim, dtype=np.float32))
            return out

        return df.with_column(self.get_or_throw("outputCol"), fn)

    def transform_schema(self, schema: Schema) -> Schema:
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.VECTOR
        return out

    def find_synonyms(self, word: str, num: int = 5) -> List[tuple]:
        """Cosine top-k neighbors (Spark Word2VecModel.findSynonyms)."""
        index = self._index()
        if word.lower() not in index:
            raise KeyError(word)
        vecs = np.asarray(self.get_or_throw("vectors"), dtype=np.float64)
        norms = np.linalg.norm(vecs, axis=1) + 1e-12
        q = vecs[index[word.lower()]]
        sims = vecs @ q / (norms * (np.linalg.norm(q) + 1e-12))
        vocab = self.get_or_throw("vocab")
        order = np.argsort(-sims)
        out = [(vocab[i], float(sims[i])) for i in order
               if vocab[i] != word.lower()][:num]
        return out
