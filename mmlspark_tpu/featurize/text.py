"""Text featurization: tokenize -> ngrams -> hashTF -> IDF.

Reference: featurize/text/TextFeaturizer.scala (a configurable sub-pipeline over
Spark's Tokenizer/NGram/HashingTF/IDF), featurize/text/MultiNGram.scala
(concatenated n-gram ranges), featurize/text/PageSplitter.scala (split strings
into bounded-length pages for downstream services).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasInputCol, HasOutputCol, Param
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import ColType, Schema
from ..ops.hashing import hash_string

_DEFAULT_STOPWORDS = {
    "a", "an", "and", "are", "as", "at", "be", "by", "for", "from", "has", "he",
    "in", "is", "it", "its", "of", "on", "that", "the", "to", "was", "were",
    "will", "with",
}


def tokenize(text: str, pattern: str = r"\s+", to_lower: bool = True,
             min_token_length: int = 1) -> List[str]:
    if to_lower:
        text = text.lower()
    toks = [t for t in re.split(pattern, text) if len(t) >= min_token_length]
    return toks


def ngrams(tokens: List[str], n: int) -> List[str]:
    return [" ".join(tokens[i:i + n]) for i in range(len(tokens) - n + 1)]


def hash_tf(tokens: List[str], num_features: int) -> Dict[str, np.ndarray]:
    counts: Dict[int, float] = {}
    for t in tokens:
        j = hash_string(t) % num_features
        counts[j] = counts.get(j, 0.0) + 1.0
    idx = np.array(sorted(counts), dtype=np.int64)
    return {"indices": idx,
            "values": np.array([counts[i] for i in idx], dtype=np.float32)}


class TextFeaturizer(Estimator, HasInputCol, HasOutputCol):
    """Tokenize -> stopwords -> n-grams -> hashing TF -> IDF, in one stage
    (featurize/text/TextFeaturizer.scala)."""

    useTokenizer = Param("useTokenizer", "Tokenize input", True, ptype=bool)
    tokenizerPattern = Param("tokenizerPattern", "Split regex", r"\s+", ptype=str)
    toLowercase = Param("toLowercase", "Lowercase before tokenizing", True, ptype=bool)
    minTokenLength = Param("minTokenLength", "Drop shorter tokens", 1, ptype=int)
    useStopWordsRemover = Param("useStopWordsRemover", "Remove stopwords", False,
                                ptype=bool)
    useNGram = Param("useNGram", "Emit n-grams instead of unigrams", False, ptype=bool)
    nGramLength = Param("nGramLength", "n-gram length", 2, ptype=int)
    numFeatures = Param("numFeatures", "Hashing TF buckets", 1 << 18, ptype=int)
    useIDF = Param("useIDF", "Rescale by inverse document frequency", True, ptype=bool)
    minDocFreq = Param("minDocFreq", "Min docs for IDF term", 1, ptype=int)

    def _tokens(self, text: Optional[str]) -> List[str]:
        if text is None:
            return []
        toks = (tokenize(text, self.get("tokenizerPattern"),
                         self.get("toLowercase"), self.get("minTokenLength"))
                if self.get("useTokenizer") else [text])
        if self.get("useStopWordsRemover"):
            toks = [t for t in toks if t not in _DEFAULT_STOPWORDS]
        if self.get("useNGram"):
            toks = ngrams(toks, self.get("nGramLength"))
        return toks

    def fit(self, df: DataFrame) -> "TextFeaturizerModel":
        nf = self.get("numFeatures")
        idf = None
        if self.get("useIDF"):
            col = df.column(self.get_or_throw("inputCol"))
            n_docs = len(col)
            doc_freq = np.zeros(nf, dtype=np.float64)
            for text in col:
                sparse = hash_tf(self._tokens(text), nf)
                doc_freq[sparse["indices"]] += 1.0
            min_df = self.get("minDocFreq")
            idf = np.where(doc_freq >= min_df,
                           np.log((n_docs + 1.0) / (doc_freq + 1.0)), 0.0)
        return TextFeaturizerModel(
            inputCol=self.get("inputCol"), outputCol=self.get("outputCol"),
            numFeatures=nf, idfValues=idf, config=self.simple_params())


class TextFeaturizerModel(Model, HasInputCol, HasOutputCol):
    numFeatures = Param("numFeatures", "Hashing TF buckets", 1 << 18, ptype=int)
    idfValues = ComplexParam("idfValues", "IDF weights (None = TF only)")
    config = Param("config", "Tokenization config from the estimator", None, ptype=dict)

    def _tokens(self, text: Optional[str]) -> List[str]:
        cfg = self.get("config") or {}
        helper = TextFeaturizer(**{k: v for k, v in cfg.items()
                                   if TextFeaturizer.has_param(k)})
        return helper._tokens(text)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        nf = self.get("numFeatures")
        idf = self.get("idfValues")

        def fn(p):
            col = p[in_col]
            out = np.empty(len(col), dtype=object)
            for i, text in enumerate(col):
                sparse = hash_tf(self._tokens(text), nf)
                values = sparse["values"]
                if idf is not None:
                    values = (values * idf[sparse["indices"]]).astype(np.float32)
                # "size" makes the row densifiable downstream (stack_rows)
                out[i] = {"size": nf, "indices": sparse["indices"],
                          "values": values}
            return out

        return df.with_column(out_col, fn)

    def transform_schema(self, schema: Schema) -> Schema:
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.STRUCT
        return out


class MultiNGram(Transformer, HasInputCol, HasOutputCol):
    """Concatenate n-grams for several lengths (featurize/text/MultiNGram.scala).
    Input: token-array column; output: array of n-gram strings."""

    lengths = Param("lengths", "N-gram lengths to emit", [1, 2, 3], ptype=list)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        lengths = self.get("lengths")

        def fn(p):
            col = p[in_col]
            out = np.empty(len(col), dtype=object)
            for i, toks in enumerate(col):
                if toks is None:
                    out[i] = None
                    continue
                toks = list(toks)
                grams: List[str] = []
                for n in lengths:
                    grams.extend(ngrams(toks, n))
                out[i] = grams
            return out

        return df.with_column(out_col, fn)


class PageSplitter(Transformer, HasInputCol, HasOutputCol):
    """Split strings into pages within [minimumPageLength, maximumPageLength],
    preferring whitespace boundaries (featurize/text/PageSplitter.scala)."""

    maximumPageLength = Param("maximumPageLength", "Max chars per page", 5000,
                              lambda v: v > 0, int)
    minimumPageLength = Param("minimumPageLength", "Preferred min chars per page",
                              4500, lambda v: v > 0, int)
    boundaryRegex = Param("boundaryRegex", "Preferred break pattern", r"\s", ptype=str)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        max_len = self.get("maximumPageLength")
        min_len = min(self.get("minimumPageLength"), max_len)
        boundary = re.compile(self.get("boundaryRegex"))

        def split(text: Optional[str]) -> Optional[List[str]]:
            if text is None:
                return None
            pages = []
            start = 0
            while start < len(text):
                end = min(start + max_len, len(text))
                if end < len(text):
                    # prefer the last boundary in [min_len, max_len)
                    window = text[start + min_len:end]
                    matches = [m.start() for m in boundary.finditer(window)]
                    if matches:
                        end = start + min_len + matches[-1] + 1
                pages.append(text[start:end])
                start = end
            return pages

        return df.with_column(out_col,
                              lambda p: [split(v) for v in p[in_col]])
