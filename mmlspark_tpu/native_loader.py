"""NativeLoader: build/discover/load the C++ runtime, with graceful fallback.

Reference: core/env/NativeLoader.java:28-140 — extracts .so files from jar
resources and System.load()s them on each executor. Here: the .so is built
from in-repo C++ source (native/src/) on first use (g++ is in the image),
cached under native/build/, and loaded via ctypes. Every consumer falls back
to the numpy implementation when the library is unavailable, so the Python
surface never hard-depends on the toolchain.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

log = logging.getLogger("mmlspark_tpu.native")

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_PKG_DIR)
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")


_ABI_VERSION = 5


def _host_tag() -> str:
    """Short CPU-identity tag for the cache filename: the build uses
    -march=native, so a cached .so is only valid on a CPU with the same
    feature set — a shared cache dir (NFS home, baked image) must rebuild
    on a different host instead of dying with SIGILL mid-call."""
    import hashlib
    import platform

    ident = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    ident += line
                    break
    except OSError:
        pass
    return hashlib.md5(ident.encode()).hexdigest()[:8]


def _so_path() -> str:
    """Repo build dir when the repo layout is present (dev checkout); else a
    user cache dir (pip-installed: site-packages may be read-only). The ABI
    version AND a host-CPU tag are part of the filename so co-installed
    package versions (or hosts with different CPU features — the build is
    -march=native) sharing a cache dir never load each other's build."""
    name = f"libmmlspark_native.v{_ABI_VERSION}.{_host_tag()}.so"
    if os.path.isdir(_NATIVE_DIR):
        return os.path.join(_NATIVE_DIR, "build", name)
    cache = os.environ.get("XDG_CACHE_HOME",
                           os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(cache, "mmlspark_tpu", name)


_SO_PATH = _so_path()

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def _build() -> bool:
    # dev checkout first; the wheel ships the same source as package data
    # (native_src/ is a symlink to native/src/ in the repo; wheel builds
    # materialize it as a real file)
    candidates = [os.path.join(_NATIVE_DIR, "src", "mmlspark_native.cpp"),
                  os.path.join(_PKG_DIR, "native_src", "mmlspark_native.cpp")]
    src = next((c for c in candidates if os.path.exists(c)), None)
    if src is None:
        return False
    os.makedirs(os.path.dirname(_SO_PATH), exist_ok=True)
    # build to a temp path + atomic rename: concurrent processes (e.g. the
    # two-OS-process tests) may race the build — a reader must never dlopen
    # a half-written .so, and a process that mmapped the old file must not
    # have its inode rewritten under it (rename unlinks, not overwrites)
    tmp = f"{_SO_PATH}.tmp.{os.getpid()}"
    # -ffp-contract=off: no FMA contraction — the predict paths are
    # documented (and test-gated) bit-equal to the numpy references, and
    # contraction changes their rounding by 1 ulp
    cmd = ["g++", "-O3", "-march=native", "-ffp-contract=off",
           "-funroll-loops", "-fPIC", "-shared", "-std=c++17", "-o", tmp,
           src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO_PATH)
        return True
    except Exception as e:  # toolchain missing / compile error -> fallback
        log.warning("native build failed (%s); using numpy fallbacks", e)
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _try_load() -> Optional[ctypes.CDLL]:
    """dlopen + ABI check; None on any failure (caller decides rebuild)."""
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError as e:
        log.warning("native load failed (%s)", e)
        return None
    try:
        lib.mml_version.restype = ctypes.c_int32
        got = lib.mml_version()
    except (OSError, AttributeError) as e:
        # loadable .so without the symbol (foreign or truncated-but-
        # linkable file) must trigger the rebuild path, not crash load()
        log.warning("native ABI probe failed (%s)", e)
        return None
    if got != _ABI_VERSION:
        log.warning("native ABI v%s != expected v%s", got, _ABI_VERSION)
        return None
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable.

    ANY first-load failure — absent, corrupted/half-written, or a stale
    ABI from older source — gets exactly one rebuild attempt (dlopen
    failures must rebuild too: build-on-absent alone left a corrupt file
    permanently wedging the process into numpy fallbacks)."""
    global _lib, _build_attempted
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = _try_load() if os.path.exists(_SO_PATH) else None
        if lib is None:
            if _build_attempted:
                return None
            _build_attempted = True
            try:
                os.remove(_SO_PATH)
            except OSError:
                pass
            if not _build():
                return None
            lib = _try_load()
            if lib is None:
                log.warning("native library unusable after rebuild; using "
                            "numpy fallbacks")
                return None
        _declare(lib)
        _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def _declare(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    f32p = ctypes.POINTER(ctypes.c_float)
    f64p = ctypes.POINTER(ctypes.c_double)

    lib.mml_version.restype = ctypes.c_int32
    lib.mml_murmur3_32.restype = ctypes.c_uint32
    lib.mml_murmur3_32.argtypes = [u8p, ctypes.c_int32, ctypes.c_uint32]
    lib.mml_murmur3_batch.argtypes = [u8p, i64p, ctypes.c_int64,
                                      ctypes.c_uint32, u32p]
    lib.mml_resize_bilinear_f32.argtypes = [f32p, ctypes.c_int32, ctypes.c_int32,
                                            ctypes.c_int32, f32p,
                                            ctypes.c_int32, ctypes.c_int32]
    lib.mml_resize_bilinear_u8.argtypes = [u8p, ctypes.c_int32, ctypes.c_int32,
                                           ctypes.c_int32, u8p,
                                           ctypes.c_int32, ctypes.c_int32]
    lib.mml_unroll_chw_f64.argtypes = [u8p, ctypes.c_int32, ctypes.c_int32,
                                       ctypes.c_int32, f64p, ctypes.c_int32]
    lib.mml_histogram.argtypes = [i32p, f32p, f32p, u8p, ctypes.c_int64,
                                  ctypes.c_int32, ctypes.c_int32, f32p]
    lib.mml_forest_predict.argtypes = [f32p, ctypes.c_int64, ctypes.c_int32,
                                       i32p, f32p, u8p, i32p, i32p, f32p,
                                       ctypes.c_int32, ctypes.c_int32, i32p,
                                       ctypes.c_int32, f64p]
    lib.mml_csr_forest_predict.argtypes = [
        i64p, i64p, f64p, ctypes.c_int64,
        i32p, f64p, i32p, i32p, f64p,
        i64p, f64p, i32p, ctypes.c_int32, ctypes.c_int32, f64p]
    lib.mml_forest_predict_f64.argtypes = [
        f64p, ctypes.c_int64, ctypes.c_int32,
        i32p, f64p, u8p, i32p, i32p, f64p,
        ctypes.c_int32, ctypes.c_int32, i32p, ctypes.c_int32, f64p]
    lib.mml_bin_column_f64.argtypes = [f64p, ctypes.c_int64, f64p,
                                       ctypes.c_int32, i32p]
    lib.mml_bin_matrix_f64_u8.argtypes = [f64p, ctypes.c_int64,
                                          ctypes.c_int32, f64p, i64p, u8p]
    lib.mml_bin_matrix_f64_i32.argtypes = [f64p, ctypes.c_int64,
                                           ctypes.c_int32, f64p, i64p, i32p]
    lib.mml_vw_train_pass.argtypes = [
        i32p, f32p, f32p, f32p, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_float, ctypes.c_int32,
        f32p, f32p, f32p, f64p]
    lib.mml_gbdt_grow_tree.restype = ctypes.c_int32
    lib.mml_gbdt_grow_tree.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        f32p, f32p, u8p, u8p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_double, ctypes.c_double,
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        i32p, i32p, u8p, i32p, i32p, f64p, f32p, i32p, f64p, i32p]


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


# ---------------------------------------------------------------------------
# Typed wrappers (None lib -> caller should use its numpy fallback)
# ---------------------------------------------------------------------------


def murmur3_batch(strings: List[str], seed: int = 0) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    encoded = [s.encode("utf-8") for s in strings]
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    buf = np.frombuffer(b"".join(encoded), dtype=np.uint8) if encoded else \
        np.empty(0, dtype=np.uint8)
    buf = np.ascontiguousarray(buf)
    out = np.zeros(len(encoded), dtype=np.uint32)
    lib.mml_murmur3_batch(_ptr(buf, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
                          len(encoded), seed & 0xFFFFFFFF,
                          _ptr(out, ctypes.c_uint32))
    return out.astype(np.int64)


def resize_bilinear(img: np.ndarray, oh: int, ow: int) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    img = np.ascontiguousarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    h, w, c = img.shape
    if img.dtype == np.uint8:
        dst = np.empty((oh, ow, c), dtype=np.uint8)
        lib.mml_resize_bilinear_u8(_ptr(img, ctypes.c_uint8), h, w, c,
                                   _ptr(dst, ctypes.c_uint8), oh, ow)
        return dst
    src = np.ascontiguousarray(img, dtype=np.float32)
    dst = np.empty((oh, ow, c), dtype=np.float32)
    lib.mml_resize_bilinear_f32(_ptr(src, ctypes.c_float), h, w, c,
                                _ptr(dst, ctypes.c_float), oh, ow)
    return dst


def unroll_chw(img: np.ndarray, normalize: bool = False) -> Optional[np.ndarray]:
    lib = load()
    if lib is None or img.dtype != np.uint8:
        return None
    img = np.ascontiguousarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    h, w, c = img.shape
    out = np.empty(c * h * w, dtype=np.float64)
    lib.mml_unroll_chw_f64(_ptr(img, ctypes.c_uint8), h, w, c,
                           _ptr(out, ctypes.c_double), int(normalize))
    return out


def histogram(bins: np.ndarray, grad: np.ndarray, hess: np.ndarray,
              mask: np.ndarray, num_bins: int) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    bins = np.ascontiguousarray(bins, dtype=np.int32)
    grad = np.ascontiguousarray(grad, dtype=np.float32)
    hess = np.ascontiguousarray(hess, dtype=np.float32)
    mask8 = np.ascontiguousarray(mask, dtype=np.uint8)
    n, f = bins.shape
    out = np.zeros((f, num_bins, 3), dtype=np.float32)
    lib.mml_histogram(_ptr(bins, ctypes.c_int32), _ptr(grad, ctypes.c_float),
                      _ptr(hess, ctypes.c_float), _ptr(mask8, ctypes.c_uint8),
                      n, f, num_bins, _ptr(out, ctypes.c_float))
    return out


def forest_predict(X: np.ndarray, feature: np.ndarray, threshold: np.ndarray,
                   default_left: np.ndarray, left: np.ndarray,
                   right: np.ndarray, value: np.ndarray,
                   class_of_tree: np.ndarray, num_class: int
                   ) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    X = np.ascontiguousarray(X, dtype=np.float32)
    n, num_feat = X.shape
    t, m = feature.shape
    feature = np.ascontiguousarray(feature, dtype=np.int32)
    threshold = np.ascontiguousarray(threshold, dtype=np.float32)
    dl = np.ascontiguousarray(default_left, dtype=np.uint8)
    left = np.ascontiguousarray(left, dtype=np.int32)
    right = np.ascontiguousarray(right, dtype=np.int32)
    value = np.ascontiguousarray(value, dtype=np.float32)
    cot = np.ascontiguousarray(class_of_tree, dtype=np.int32)
    out = np.zeros((n, num_class), dtype=np.float64)
    lib.mml_forest_predict(
        _ptr(X, ctypes.c_float), n, num_feat, _ptr(feature, ctypes.c_int32),
        _ptr(threshold, ctypes.c_float), _ptr(dl, ctypes.c_uint8),
        _ptr(left, ctypes.c_int32), _ptr(right, ctypes.c_int32),
        _ptr(value, ctypes.c_float), t, m, _ptr(cot, ctypes.c_int32),
        num_class, _ptr(out, ctypes.c_double))
    return out


def csr_forest_predict(indptr: np.ndarray, indices: np.ndarray,
                       values: np.ndarray, feature: np.ndarray,
                       threshold: np.ndarray, left: np.ndarray,
                       right: np.ndarray, value: np.ndarray,
                       tree_offset: np.ndarray, shrinkage: np.ndarray,
                       class_of_tree: np.ndarray, num_class: int
                       ) -> Optional[np.ndarray]:
    """Flattened-forest traversal over CSR rows (numeric splits only; the
    caller keeps categorical forests on the numpy path). Node arrays are
    the per-tree arrays concatenated; ``tree_offset`` is the [T+1] node
    base of each tree; left/right stay tree-local ids."""
    lib = load()
    if lib is None:
        return None
    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    values = np.ascontiguousarray(values, dtype=np.float64)
    feature = np.ascontiguousarray(feature, dtype=np.int32)
    threshold = np.ascontiguousarray(threshold, dtype=np.float64)
    left = np.ascontiguousarray(left, dtype=np.int32)
    right = np.ascontiguousarray(right, dtype=np.int32)
    value = np.ascontiguousarray(value, dtype=np.float64)
    tree_offset = np.ascontiguousarray(tree_offset, dtype=np.int64)
    shrinkage = np.ascontiguousarray(shrinkage, dtype=np.float64)
    cot = np.ascontiguousarray(class_of_tree, dtype=np.int32)
    n = len(indptr) - 1
    n_trees = len(shrinkage)
    out = np.zeros((n, num_class), dtype=np.float64)
    lib.mml_csr_forest_predict(
        _ptr(indptr, ctypes.c_int64), _ptr(indices, ctypes.c_int64),
        _ptr(values, ctypes.c_double), n,
        _ptr(feature, ctypes.c_int32), _ptr(threshold, ctypes.c_double),
        _ptr(left, ctypes.c_int32), _ptr(right, ctypes.c_int32),
        _ptr(value, ctypes.c_double),
        _ptr(tree_offset, ctypes.c_int64), _ptr(shrinkage, ctypes.c_double),
        _ptr(cot, ctypes.c_int32), n_trees, num_class,
        _ptr(out, ctypes.c_double))
    return out


def bin_column(vals: np.ndarray, edges: np.ndarray) -> Optional[np.ndarray]:
    """Numeric-column quantile binning: lower_bound(edges)+1, NaN -> 0."""
    lib = load()
    if lib is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.float64)
    edges = np.ascontiguousarray(edges, dtype=np.float64)
    out = np.empty(len(vals), dtype=np.int32)
    lib.mml_bin_column_f64(_ptr(vals, ctypes.c_double), len(vals),
                           _ptr(edges, ctypes.c_double), len(edges),
                           _ptr(out, ctypes.c_int32))
    return out


_LOSS_IDS = {"squared": 0, "logistic": 1, "hinge": 2, "quantile": 3}


def vw_train_pass(indices: np.ndarray, values: np.ndarray,
                  labels: np.ndarray, weights: np.ndarray,
                  w: np.ndarray, g2: np.ndarray, t: float, *,
                  loss: str, tau: float, lr: float, power_t: float,
                  initial_t: float, l2: float, adaptive: bool):
    """One sequential learning pass IN PLACE over ``w``/``g2`` (padded
    sparse examples). Returns (new_t, loss_sum) or None when unavailable.
    Mirrors vw/learner.make_scan_pass's f32 update exactly."""
    lib = load()
    if lib is None or loss not in _LOSS_IDS:
        return None
    indices = np.ascontiguousarray(indices, dtype=np.int32)
    values = np.ascontiguousarray(values, dtype=np.float32)
    labels = np.ascontiguousarray(labels, dtype=np.float32)
    weights = np.ascontiguousarray(weights, dtype=np.float32)
    if w.dtype != np.float32 or g2.dtype != np.float32:
        # in-place C++ update needs f32 buffers; a bare assert here would
        # vanish under `python -O` and hand the kernel mistyped pointers —
        # degrade to the scan engine instead (the None contract above)
        return None
    n, k = indices.shape
    t_box = np.array([t], dtype=np.float32)
    loss_out = np.zeros(1, dtype=np.float64)
    lib.mml_vw_train_pass(
        _ptr(indices, ctypes.c_int32), _ptr(values, ctypes.c_float),
        _ptr(labels, ctypes.c_float), _ptr(weights, ctypes.c_float),
        n, k, _LOSS_IDS[loss], float(tau), float(lr), float(power_t),
        float(initial_t), float(l2), int(adaptive),
        _ptr(w, ctypes.c_float), _ptr(g2, ctypes.c_float),
        _ptr(t_box, ctypes.c_float), _ptr(loss_out, ctypes.c_double))
    return float(t_box[0]), float(loss_out[0])


def bin_matrix(X: np.ndarray, edges_list, dtype=np.int32
               ) -> Optional[np.ndarray]:
    """Row-major [N, F] floats -> feature-major [F, N] bins in ONE blocked
    pass (numeric features only; NaN -> bin 0)."""
    lib = load()
    if lib is None or dtype not in (np.uint8, np.int32):
        return None
    X = np.ascontiguousarray(X, dtype=np.float64)
    n, num_f = X.shape
    offsets = np.zeros(num_f + 1, dtype=np.int64)
    np.cumsum([len(e) for e in edges_list], out=offsets[1:])
    flat = (np.concatenate([np.asarray(e, dtype=np.float64)
                            for e in edges_list])
            if offsets[-1] else np.empty(0, dtype=np.float64))
    flat = np.ascontiguousarray(flat)
    out = np.empty((num_f, n), dtype=dtype)
    if dtype == np.uint8:
        lib.mml_bin_matrix_f64_u8(
            _ptr(X, ctypes.c_double), n, num_f, _ptr(flat, ctypes.c_double),
            _ptr(offsets, ctypes.c_int64), _ptr(out, ctypes.c_uint8))
    else:
        lib.mml_bin_matrix_f64_i32(
            _ptr(X, ctypes.c_double), n, num_f, _ptr(flat, ctypes.c_double),
            _ptr(offsets, ctypes.c_int64), _ptr(out, ctypes.c_int32))
    return out


def gbdt_grow_tree(bins_fm: np.ndarray, grad: np.ndarray, hess: np.ndarray,
                   row_mask: Optional[np.ndarray],
                   feature_mask: Optional[np.ndarray], *,
                   num_bins: int, num_leaves: int, max_depth: int,
                   min_data_in_leaf: float, min_sum_hessian: float,
                   min_gain_to_split: float, lambda_l1: float,
                   lambda_l2: float, max_delta_step: float):
    """Grow one leaf-wise tree on the host (LightGBM serial learner
    equivalent; numeric splits only). Returns a dict of flat node arrays
    (length = node count) + ``leaf_of_row`` [N], or None when the native
    library is unavailable.

    ``bins_fm``: [F, N] uint8 feature-major bins (0 = missing)."""
    lib = load()
    if lib is None or num_bins > 256:
        return None
    bins_fm = np.ascontiguousarray(bins_fm, dtype=np.uint8)
    num_f, n = bins_fm.shape
    grad = np.ascontiguousarray(grad, dtype=np.float32)
    hess = np.ascontiguousarray(hess, dtype=np.float32)
    rm = (np.ascontiguousarray(row_mask, dtype=np.uint8)
          if row_mask is not None else None)
    fm = (np.ascontiguousarray(feature_mask, dtype=np.uint8)
          if feature_mask is not None else None)
    cap = 2 * num_leaves - 1
    feature = np.empty(cap, dtype=np.int32)
    tbin = np.empty(cap, dtype=np.int32)
    dleft = np.empty(cap, dtype=np.uint8)
    left = np.empty(cap, dtype=np.int32)
    right = np.empty(cap, dtype=np.int32)
    value = np.empty(cap, dtype=np.float64)
    gain = np.empty(cap, dtype=np.float32)
    count = np.empty(cap, dtype=np.int32)
    weight = np.empty(cap, dtype=np.float64)
    leaf_of_row = np.empty(n, dtype=np.int32)
    null_u8 = ctypes.POINTER(ctypes.c_uint8)()
    n_nodes = lib.mml_gbdt_grow_tree(
        _ptr(bins_fm, ctypes.c_uint8), n, num_f, num_bins,
        _ptr(grad, ctypes.c_float), _ptr(hess, ctypes.c_float),
        _ptr(rm, ctypes.c_uint8) if rm is not None else null_u8,
        _ptr(fm, ctypes.c_uint8) if fm is not None else null_u8,
        num_leaves, max_depth, float(min_data_in_leaf),
        float(min_sum_hessian), float(min_gain_to_split),
        float(lambda_l1), float(lambda_l2), float(max_delta_step),
        _ptr(feature, ctypes.c_int32), _ptr(tbin, ctypes.c_int32),
        _ptr(dleft, ctypes.c_uint8), _ptr(left, ctypes.c_int32),
        _ptr(right, ctypes.c_int32), _ptr(value, ctypes.c_double),
        _ptr(gain, ctypes.c_float), _ptr(count, ctypes.c_int32),
        _ptr(weight, ctypes.c_double), _ptr(leaf_of_row, ctypes.c_int32))
    m = int(n_nodes)
    return {"feature": feature[:m], "threshold_bin": tbin[:m],
            "default_left": dleft[:m].astype(bool), "left": left[:m],
            "right": right[:m], "value": value[:m], "gain": gain[:m],
            "count": count[:m], "weight": weight[:m],
            "leaf_of_row": leaf_of_row}


def forest_predict_f64(X: np.ndarray, feature: np.ndarray,
                       threshold: np.ndarray, default_left: np.ndarray,
                       left: np.ndarray, right: np.ndarray,
                       value: np.ndarray, class_of_tree: np.ndarray,
                       num_class: int) -> Optional[np.ndarray]:
    """f64 dense forest traversal — bit-equal to the Python host path
    (predict.predict_single_tree) for numeric splits; ``value`` must be
    pre-scaled by shrinkage. Node arrays are [T, m] padded SoA."""
    lib = load()
    if lib is None:
        return None
    X = np.ascontiguousarray(X, dtype=np.float64)
    n, num_feat = X.shape
    t, m = feature.shape
    feature = np.ascontiguousarray(feature, dtype=np.int32)
    threshold = np.ascontiguousarray(threshold, dtype=np.float64)
    dl = np.ascontiguousarray(default_left, dtype=np.uint8)
    left = np.ascontiguousarray(left, dtype=np.int32)
    right = np.ascontiguousarray(right, dtype=np.int32)
    value = np.ascontiguousarray(value, dtype=np.float64)
    cot = np.ascontiguousarray(class_of_tree, dtype=np.int32)
    out = np.zeros((n, num_class), dtype=np.float64)
    lib.mml_forest_predict_f64(
        _ptr(X, ctypes.c_double), n, num_feat,
        _ptr(feature, ctypes.c_int32), _ptr(threshold, ctypes.c_double),
        _ptr(dl, ctypes.c_uint8), _ptr(left, ctypes.c_int32),
        _ptr(right, ctypes.c_int32), _ptr(value, ctypes.c_double),
        t, m, _ptr(cot, ctypes.c_int32), num_class,
        _ptr(out, ctypes.c_double))
    return out
