"""mmlspark_tpu — a TPU-native distributed ML pipeline framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of MMLSpark
(Microsoft ML for Apache Spark): declarative fit/transform pipeline stages over
partitioned columnar DataFrames, distributed DNN inference and image featurization,
gradient-boosted trees, online linear learning, HTTP integration and low-latency
serving, interpretability, recommendation, AutoML, and a featurization library —
running SPMD over TPU device meshes instead of Spark executors.
"""

__version__ = "0.1.0"

from .core.dataframe import DataFrame
from .core.params import (
    ComplexParam,
    Param,
    Params,
    ServiceParam,
)
from .core.pipeline import (
    Estimator,
    Evaluator,
    Model,
    Pipeline,
    PipelineModel,
    PipelineStage,
    Transformer,
    pipeline_model,
)
from .core.schema import ColType, ImageSchema, Schema

__all__ = [
    "ColType",
    "ComplexParam",
    "DataFrame",
    "Estimator",
    "Evaluator",
    "ImageSchema",
    "Model",
    "Param",
    "Params",
    "Pipeline",
    "PipelineModel",
    "PipelineStage",
    "Schema",
    "ServiceParam",
    "Transformer",
    "pipeline_model",
]
