"""`python -m mmlspark_tpu` — the reflected CLI binding surface
(codegen/cli.py; reference WrapperGenerator's second-language wrappers)."""

import sys

from .codegen.cli import main

# guard: reflection (pkgutil.walk_packages in the fuzzing tier) imports this
# module too, and must not trigger an argparse exit
if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. `... list | head`
        sys.exit(0)
