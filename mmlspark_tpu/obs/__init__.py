"""Unified observability: metrics registry, Prometheus exposition, tracing.

Three pillars (docs/observability.md):

  - ``obs.metrics``  — MetricsRegistry (thread-safe counters/gauges/
    histograms with label sets) + the Prometheus text-format writer served
    at ``/_mmlspark/metrics`` on every ServingServer and RoutingFront.
  - ``obs.bridge``   — scrape-time adapters folding the pre-existing stats
    surfaces (IngestStats, LatencyStats, CompileCache, executor timelines,
    circuit breakers) into the registry, so ``/_mmlspark/stats`` and
    Prometheus report from one source of truth.
  - ``obs.trace``    — span context propagated across HTTP hops via the
    ``X-MMLSpark-Trace`` header (deadline-header pattern), with JSONL and
    Perfetto exporters and head-based sampling.
"""

from .metrics import (Counter, Gauge, Histogram, MetricFamily,
                      MetricsRegistry, Sample, TrainRecorder,
                      default_registry, set_default_registry)
from .trace import (Span, SpanContext, TRACE_HEADER, Tracer, batch_context,
                    current_batch, parse_trace_header)
from . import bridge

__all__ = ["Counter", "Gauge", "Histogram", "MetricFamily",
           "MetricsRegistry", "Sample", "Span", "SpanContext",
           "TRACE_HEADER", "Tracer", "TrainRecorder", "batch_context",
           "bridge", "current_batch", "default_registry",
           "parse_trace_header", "set_default_registry"]
