"""Unified observability: metrics registry, Prometheus exposition, tracing.

Three pillars (docs/observability.md):

  - ``obs.metrics``  — MetricsRegistry (thread-safe counters/gauges/
    histograms with label sets) + the Prometheus text-format writer served
    at ``/_mmlspark/metrics`` on every ServingServer and RoutingFront.
  - ``obs.bridge``   — scrape-time adapters folding the pre-existing stats
    surfaces (IngestStats, LatencyStats, CompileCache, executor timelines,
    circuit breakers) into the registry, so ``/_mmlspark/stats`` and
    Prometheus report from one source of truth.
  - ``obs.trace``    — span context propagated across HTTP hops via the
    ``X-MMLSpark-Trace`` header (deadline-header pattern), with JSONL and
    Perfetto exporters and head-based sampling.
  - ``obs.perf``     — performance attribution: per-segment XLA cost
    analytics (``extract_cost`` at CompileCache miss time), roofline
    achieved-vs-bound ratios with dominant-bottleneck labels, device
    memory telemetry, and SLO burn-rate tracking
    (``SLOConfig``/``SLOTracker``).
"""

from .metrics import (COMPILE_BUCKETS, Counter, DEFAULT_BUCKETS, Gauge,
                      Histogram, MetricFamily, MetricsRegistry,
                      SERVING_LATENCY_BUCKETS, Sample, TrainRecorder,
                      default_registry, set_default_registry)
from .trace import (Span, SpanContext, TRACE_HEADER, Tracer, batch_context,
                    current_batch, parse_trace_header)
from .perf import SLOConfig, SLOTracker, attribute_segments, extract_cost
from . import bridge
from . import perf

__all__ = ["COMPILE_BUCKETS", "Counter", "DEFAULT_BUCKETS", "Gauge",
           "Histogram", "MetricFamily", "MetricsRegistry",
           "SERVING_LATENCY_BUCKETS", "SLOConfig", "SLOTracker", "Sample",
           "Span", "SpanContext", "TRACE_HEADER", "Tracer", "TrainRecorder",
           "attribute_segments", "batch_context", "bridge", "current_batch",
           "default_registry", "extract_cost", "parse_trace_header", "perf",
           "set_default_registry"]
