"""MetricsRegistry: thread-safe counters/gauges/histograms + Prometheus text.

The reference's visibility story is Spark's metrics sink plus MetricsLogger
(train/ComputeModelStatistics.scala:461-470); the TPU-native stack grew four
disjoint ad-hoc stats surfaces instead (IngestStats, LatencyStats, the
CompileCache counters, the executor timelines). This module is the single
registry they all fold into (obs/bridge.py holds the adapters), exposed in
the Prometheus text format at ``/_mmlspark/metrics`` on every ServingServer
and RoutingFront.

Design (a dependency-free subset of the prometheus_client data model):

  - ``Counter`` / ``Gauge`` / ``Histogram`` instruments with label sets;
    every mutation is lock-protected, so serving threads can record from the
    hot path without coordination.
  - ``MetricsRegistry.collect()`` also pulls from registered COLLECTOR
    callbacks at scrape time — the bridge pattern: existing stats objects
    stay the source of truth and are read lazily, so ``/_mmlspark/stats``
    and ``/_mmlspark/metrics`` can never disagree.
  - ``exposition()`` renders text format 0.0.4 (HELP/TYPE lines, label
    escaping, ``_bucket``/``_sum``/``_count`` histogram series).

A process-wide default registry (``default_registry()``) carries metrics
from surfaces without a natural owner object (training loops, eval stages,
the HTTP client); servers own per-instance registries so tests and
multi-server processes stay isolated.
"""

from __future__ import annotations

import math
import re
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["COMPILE_BUCKETS", "Counter", "DEFAULT_BUCKETS", "Gauge",
           "Histogram", "MetricFamily", "MetricsRegistry",
           "SERVING_LATENCY_BUCKETS", "Sample", "TrainRecorder",
           "default_registry", "set_default_registry"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: default histogram buckets (seconds-oriented, like prometheus_client)
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)

#: ms-scale buckets for serving latency histograms (sub-ms floor through
#: the slot timeout) — pass at registration; DEFAULT_BUCKETS is unchanged
SERVING_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                           0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

#: seconds-scale buckets for XLA compile / warmup timings
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0, 300.0)


class Sample:
    """One exposition line: ``name{labels} value``; ``exemplar`` (set only
    on histogram ``_bucket`` samples that captured one) is rendered in
    OpenMetrics syntax when the writer is asked for it."""

    __slots__ = ("name", "labels", "value", "exemplar")

    def __init__(self, name: str, labels: Dict[str, str], value: float,
                 exemplar: Optional[Dict[str, Any]] = None):
        self.name = name
        self.labels = labels
        self.value = value
        self.exemplar = exemplar


class MetricFamily:
    """HELP/TYPE header + its samples (collector callbacks return these)."""

    __slots__ = ("name", "mtype", "help", "samples")

    def __init__(self, name: str, mtype: str, help: str = "",
                 samples: Optional[List[Sample]] = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        if mtype not in ("counter", "gauge", "histogram", "untyped"):
            raise ValueError(f"invalid metric type {mtype!r}")
        self.name = name
        self.mtype = mtype
        self.help = help
        self.samples = samples if samples is not None else []

    def add(self, value: float, labels: Optional[Dict[str, str]] = None,
            suffix: str = "",
            exemplar: Optional[Dict[str, Any]] = None) -> "MetricFamily":
        self.samples.append(Sample(self.name + suffix, dict(labels or {}),
                                   float(value), exemplar))
        return self


class _Instrument:
    """Shared label-set bookkeeping for Counter/Gauge/Histogram."""

    mtype = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def _label_dict(self, key: Tuple[str, ...]) -> Dict[str, str]:
        return dict(zip(self.labelnames, key))

    def labels(self, **labels: str) -> "_Bound":
        return _Bound(self, self._key(labels))

    def clear(self) -> None:
        with self._lock:
            self._values.clear()


class _Bound:
    """Instrument bound to one label-value tuple (``c.labels(x="y").inc()``)."""

    __slots__ = ("_inst", "_key")

    def __init__(self, inst: _Instrument, key: Tuple[str, ...]):
        self._inst = inst
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._inst._inc(self._key, amount)

    def set(self, value: float) -> None:
        self._inst._set(self._key, value)

    def observe(self, value: float,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        self._inst._observe(self._key, value, exemplar)

    @property
    def value(self) -> float:
        return self._inst._get(self._key)


class Counter(_Instrument):
    """Monotonically-increasing count (requests, bytes, sheds)."""

    mtype = "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._inc((), amount)

    @property
    def value(self) -> float:
        return self._get(())

    def _inc(self, key: Tuple[str, ...], amount: float) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def _get(self, key: Tuple[str, ...]) -> float:
        with self._lock:
            return float(self._values.get(key, 0.0))

    def collect(self) -> MetricFamily:
        fam = MetricFamily(self.name, self.mtype, self.help)
        with self._lock:
            for key, v in sorted(self._values.items()):
                fam.add(v, self._label_dict(key))
        return fam


class Gauge(Counter):
    """Point-in-time value (queue depth, loss, utilization)."""

    mtype = "gauge"

    def set(self, value: float) -> None:
        self._set((), value)

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._values[()] = self._values.get((), 0.0) - amount

    def _inc(self, key: Tuple[str, ...], amount: float) -> None:
        with self._lock:  # gauges may go down: no monotonic check
            self._values[key] = self._values.get(key, 0.0) + amount

    def _set(self, key: Tuple[str, ...], value: float) -> None:
        with self._lock:
            self._values[key] = float(value)


class Histogram(_Instrument):
    """Bucketed distribution (step times, latencies): per label set keeps
    per-bucket counts + sum + count, rendered as the cumulative
    ``_bucket{le=...}`` / ``_sum`` / ``_count`` series.

    Bucket boundaries are per-metric at registration (serving latency wants
    ms-scale — SERVING_LATENCY_BUCKETS; compile times want seconds-scale —
    COMPILE_BUCKETS); re-registering the same name with different buckets
    raises (one name, one meaning — MetricsRegistry enforces it).

    ``observe(value, exemplar={"trace_id": ...})`` pins the exemplar to the
    bucket the observation lands in (last-write-wins per bucket, with the
    observed value and a unix timestamp) — the metrics->traces link: a p99
    bucket carries the trace_id of a request that landed there."""

    mtype = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"{self.name}: need at least one bucket")
        self.buckets = tuple(bs)

    def observe(self, value: float,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        self._observe((), value, exemplar)

    def _observe(self, key: Tuple[str, ...], value: float,
                 exemplar: Optional[Dict[str, str]] = None) -> None:
        v = float(value)
        with self._lock:
            state = self._values.get(key)
            if state is None:
                state = {"counts": [0] * len(self.buckets),
                         "sum": 0.0, "count": 0, "exemplars": {}}
                self._values[key] = state
            idx = len(self.buckets)  # +Inf overflow bucket
            for i, b in enumerate(self.buckets):
                if v <= b:
                    state["counts"][i] += 1
                    idx = i
                    break
            state["sum"] += v
            state["count"] += 1
            if exemplar:
                state["exemplars"][idx] = {
                    "labels": {str(k): str(lv) for k, lv in exemplar.items()},
                    "value": v, "ts": time.time()}

    def collect(self) -> MetricFamily:
        fam = MetricFamily(self.name, self.mtype, self.help)
        with self._lock:
            for key, state in sorted(self._values.items()):
                labels = self._label_dict(key)
                exemplars = state.get("exemplars", {})
                cum = 0
                for i, (b, c) in enumerate(zip(self.buckets,
                                               state["counts"])):
                    cum += c
                    fam.add(cum, {**labels, "le": _fmt_float(b)},
                            suffix="_bucket", exemplar=exemplars.get(i))
                fam.add(state["count"], {**labels, "le": "+Inf"},
                        suffix="_bucket",
                        exemplar=exemplars.get(len(self.buckets)))
                fam.add(state["sum"], labels, suffix="_sum")
                fam.add(state["count"], labels, suffix="_count")
        return fam

    def snapshot(self, **labels: str) -> Dict[str, Any]:
        """JSON-friendly view of one label set (default: the unlabeled
        series): cumulative buckets, sum/count, and the captured exemplars
        keyed by their bucket's ``le`` — the always-on exemplar surface in
        ``/_mmlspark/stats``."""
        key = self._key(labels) if labels or self.labelnames else ()
        with self._lock:
            state = self._values.get(key)
            if state is None:
                return {"count": 0, "sum": 0.0, "buckets": {},
                        "exemplars": {}}
            counts = list(state["counts"])
            out = {"count": state["count"], "sum": round(state["sum"], 6),
                   "exemplars": {}}
            for i, ex in state.get("exemplars", {}).items():
                le = _fmt_float(self.buckets[i]) \
                    if i < len(self.buckets) else "+Inf"
                out["exemplars"][le] = dict(ex["labels"],
                                            value=round(ex["value"], 6),
                                            ts=round(ex["ts"], 3))
        cum = 0
        buckets = {}
        for b, c in zip(self.buckets, counts):
            cum += c
            buckets[_fmt_float(b)] = cum
        buckets["+Inf"] = out["count"]
        out["buckets"] = buckets
        return out


def _fmt_float(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _escape_help(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _render_exemplar(ex: Dict[str, Any]) -> str:
    """OpenMetrics exemplar suffix: `` # {labels} value timestamp``."""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in (ex.get("labels") or {}).items())
    out = f" # {{{inner}}} {_fmt_float(ex['value'])}"
    if ex.get("ts") is not None:
        out += f" {_fmt_float(round(ex['ts'], 3))}"
    return out


def render_family(fam: MetricFamily, exemplars: bool = False) -> str:
    lines = []
    if fam.help:
        lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
    lines.append(f"# TYPE {fam.name} {fam.mtype}")
    for s in fam.samples:
        if s.labels:
            inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                             for k, v in s.labels.items())
            line = f"{s.name}{{{inner}}} {_fmt_float(s.value)}"
        else:
            line = f"{s.name} {_fmt_float(s.value)}"
        if exemplars and s.exemplar:
            line += _render_exemplar(s.exemplar)
        lines.append(line)
    return "\n".join(lines)


class MetricsRegistry:
    """Instrument factory + scrape-time collection.

    ``counter()``/``gauge()``/``histogram()`` are get-or-create: asking for
    an existing name returns the existing instrument (a type or label-set
    mismatch raises — one name, one meaning). ``register_collector`` adds a
    zero-arg callback returning MetricFamily objects, evaluated per scrape —
    the bridge adapters (obs/bridge.py) use this to read the live stats
    objects lazily instead of double-booking counts.
    """

    #: exposition Content-Type (Prometheus text format 0.0.4)
    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
    #: Content-Type of the exemplar-carrying exposition (OpenMetrics-
    #: flavored: 0.0.4 lines + exemplar suffixes + the ``# EOF`` trailer)
    OPENMETRICS_CONTENT_TYPE = \
        "application/openmetrics-text; version=1.0.0; charset=utf-8"

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}
        self._collectors: List[Callable[[], Iterable[MetricFamily]]] = []

    # -- instrument factories -------------------------------------------
    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw) -> Any:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if type(inst) is not cls or \
                        inst.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(inst).__name__}{inst.labelnames}")
                want = kw.get("buckets")
                if want is not None and \
                        tuple(sorted(float(b) for b in want)) != \
                        inst.buckets:
                    # bucket boundaries are part of the metric's meaning: a
                    # second registrant asking for different ones would
                    # silently get series it cannot interpret
                    raise ValueError(
                        f"metric {name!r} already registered with buckets "
                        f"{inst.buckets}")
                return inst
            inst = cls(name, help, labelnames, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    # -- collectors ------------------------------------------------------
    def register_collector(
            self, fn: Callable[[], Iterable[MetricFamily]]) -> None:
        with self._lock:
            self._collectors.append(fn)

    # -- scrape ----------------------------------------------------------
    def collect(self) -> List[MetricFamily]:
        with self._lock:
            instruments = list(self._instruments.values())
            collectors = list(self._collectors)
        fams = [inst.collect() for inst in instruments]
        for fn in collectors:
            try:
                fams.extend(fn())
            except Exception as e:  # noqa: BLE001 — one bad bridge
                # must not take the whole scrape down
                fams.append(MetricFamily(
                    "mmlspark_collector_errors", "untyped",
                    "a registered collector raised at scrape time").add(
                        1.0, {"error": type(e).__name__}))
        return sorted(fams, key=lambda f: f.name)

    def exposition(self, exemplars: bool = False) -> str:
        """The full scrape payload (text format 0.0.4, trailing newline).
        ``exemplars=True`` appends OpenMetrics exemplar suffixes to the
        histogram bucket samples that captured one, plus the ``# EOF``
        trailer (serve with OPENMETRICS_CONTENT_TYPE) — behind a flag
        because classic 0.0.4 parsers reject exemplar syntax."""
        body = "\n".join(render_family(f, exemplars=exemplars)
                         for f in self.collect()) + "\n"
        if exemplars:
            body += "# EOF\n"
        return body

    def sample_value(self, name: str,
                     labels: Optional[Dict[str, str]] = None
                     ) -> Optional[float]:
        """Scrape-equivalent point read (tests / bridge parity checks)."""
        labels = labels or {}
        for fam in self.collect():
            for s in fam.samples:
                if s.name == name and s.labels == labels:
                    return s.value
        return None


class TrainRecorder:
    """The standard training-instrument bundle (step time, examples/s,
    loss, checkpoint latency, eval metrics), shared by the GBDT boost
    loops and ``models.training.run_train_loop`` so every engine reports
    the same series with only the ``engine`` label differing."""

    #: buckets sized for training steps (ms to minutes)
    STEP_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                    30.0, 60.0, 300.0)

    def __init__(self, engine: str,
                 registry: Optional["MetricsRegistry"] = None):
        reg = registry if registry is not None else default_registry()
        self.engine = str(engine)
        self._steps = reg.counter(
            "mmlspark_train_steps_total",
            "training steps/iterations completed", ("engine",))
        self._step_time = reg.histogram(
            "mmlspark_train_step_seconds", "per-step wall time",
            ("engine",), buckets=self.STEP_BUCKETS)
        self._eps = reg.gauge(
            "mmlspark_train_examples_per_second",
            "training throughput of the last step", ("engine",))
        self._loss = reg.gauge(
            "mmlspark_train_loss", "last reported training loss",
            ("engine",))
        self._ckpt = reg.histogram(
            "mmlspark_train_checkpoint_seconds",
            "checkpoint save latency", ("engine",),
            buckets=self.STEP_BUCKETS)
        self._metric = reg.gauge(
            "mmlspark_train_metric", "last reported eval metric value",
            ("engine", "metric"))

    def step(self, dur_s: float, examples: Optional[int] = None,
             loss: Optional[float] = None) -> None:
        self._steps.labels(engine=self.engine).inc()
        self._step_time.labels(engine=self.engine).observe(dur_s)
        if examples is not None and dur_s > 0:
            self._eps.labels(engine=self.engine).set(examples / dur_s)
        if loss is not None:
            try:
                self._loss.labels(engine=self.engine).set(float(loss))
            except (TypeError, ValueError):
                pass

    def checkpoint(self, dur_s: float) -> None:
        self._ckpt.labels(engine=self.engine).observe(dur_s)

    def metric(self, name: str, value: Any) -> None:
        try:
            self._metric.labels(engine=self.engine,
                                metric=str(name)).set(float(value))
        except (TypeError, ValueError):
            pass


_default_lock = threading.Lock()
_default: Optional[MetricsRegistry] = None


def default_registry() -> MetricsRegistry:
    """Process-wide registry for surfaces without an owner object
    (training loops, eval stages, the HTTP client)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default


def set_default_registry(reg: Optional[MetricsRegistry]
                         ) -> Optional[MetricsRegistry]:
    """Swap the process default (tests isolate with a fresh registry);
    returns the previous one. ``None`` resets to a lazily-created fresh
    registry."""
    global _default
    with _default_lock:
        prev = _default
        _default = reg
        return prev
