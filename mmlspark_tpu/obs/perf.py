"""Performance attribution: XLA cost analytics, roofline, memory, SLOs.

BENCH_mfu_roofline.json bounds the image chain at ~16,000 images/s while
BENCH_image_e2e.json measures 64.7 end-to-end — a ~250x gap the obs layer
(PR 5) could time but never ATTRIBUTE: it said how long things took, not how
far from the hardware bound they ran. This module is the measurement
substrate the cost-model-driven auto-tuner (ROADMAP; "A Learned Performance
Model for TPUs", arXiv:2008.01040) will train on — the per-kernel
flops/bytes/latency tuples, collected where they are cheapest to observe:

  - ``extract_cost(compiled)`` harvests ``cost_analysis()`` +
    ``memory_analysis()`` from an AOT-compiled executable, getattr-gated per
    the jax 0.4.37 compat convention in ``core/`` (either may be absent,
    raise, or return None/list/dict depending on backend and version — every
    shape degrades to None, never to an error). CompileCache calls it once
    per miss, so steady-state serving pays nothing.
  - ``attribute_segments()`` joins those per-(segment, shape-bucket) costs
    with the IngestStats queue/h2d/compute/readback decomposition into a
    per-segment roofline report: the cost-model bound time per batch, the
    measured wall per batch, their ratio (1.0 = running at the hardware
    bound), and a dominant-bottleneck label (``queue``/``h2d``/``compute``/
    ``dispatch``/``host``) — the e2e-vs-roofline gap as a first-class
    per-segment number.
  - ``device_peaks()`` supplies the roofline ceilings: the public TPU chip
    specs (tools/mfu_roofline.py table), overridable via
    ``MMLSPARK_PEAK_FLOPS``/``MMLSPARK_PEAK_GBPS``; unknown devices (CPU
    containers) get a clearly-labeled nominal ceiling so the ratio stays
    comparable run-to-run (``peak_source`` says which you got).
  - ``fold_device_memory()`` registers a scrape-time collector over
    ``device.memory_stats()`` (gated: absent or None on CPU backends) as
    ``mmlspark_device_memory_bytes{device, stat}``.
  - ``SLOConfig``/``SLOTracker``: a declarative latency objective (target
    percentile over multi-window burn rates) evaluated at scrape time —
    ``mmlspark_slo_burn_rate{window=}`` is the error-budget signal the helm
    HPA can key on instead of raw queue depth.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .metrics import MetricFamily, MetricsRegistry

__all__ = ["SLOConfig", "SLOTracker", "attribute_segments", "device_peaks",
           "extract_cost", "fold_device_memory"]


# ---------------------------------------------------------------------------
# XLA cost harvesting (getattr-gated: jax 0.4.37 compat convention)
# ---------------------------------------------------------------------------


def _num_or_none(v: Any) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if f == f else None  # NaN -> None


def extract_cost(compiled: Any) -> Optional[Dict[str, float]]:
    """Harvest XLA's own cost numbers from an AOT-compiled executable.

    Returns ``{flops, bytes_accessed, peak_memory_bytes, output_bytes,
    argument_bytes}`` (whatever subset the backend reports), or None when
    nothing is available. Every access is gated: ``cost_analysis`` /
    ``memory_analysis`` may be absent (the eval_shape fallback path in
    core/fusion.py returns a plain jitted callable), may raise, or may
    return None / a dict / a list of per-computation dicts — all of which
    must degrade to "no data", never to an exception (the caller sits on
    the CompileCache miss path of a live server).
    """
    out: Dict[str, float] = {}
    ca = getattr(compiled, "cost_analysis", None)
    if callable(ca):
        try:
            rep = ca()
        except Exception:  # noqa: BLE001 — backend without the hook
            rep = None
        if isinstance(rep, (list, tuple)):
            rep = rep[0] if rep else None
        if isinstance(rep, dict):
            flops = _num_or_none(rep.get("flops"))
            if flops is not None:
                out["flops"] = flops
            nbytes = _num_or_none(rep.get("bytes accessed"))
            if nbytes is not None:
                out["bytes_accessed"] = nbytes
    ma = getattr(compiled, "memory_analysis", None)
    if callable(ma):
        try:
            mem = ma()
        except Exception:  # noqa: BLE001
            mem = None
        if mem is not None:
            parts = {}
            for attr in ("temp_size_in_bytes", "argument_size_in_bytes",
                         "output_size_in_bytes"):
                v = _num_or_none(getattr(mem, attr, None))
                if v is not None:
                    parts[attr] = v
            if parts:
                out["peak_memory_bytes"] = sum(parts.values())
                if "output_size_in_bytes" in parts:
                    out["output_bytes"] = parts["output_size_in_bytes"]
                if "argument_size_in_bytes" in parts:
                    out["argument_bytes"] = parts["argument_size_in_bytes"]
    return out or None


# ---------------------------------------------------------------------------
# Roofline ceilings
# ---------------------------------------------------------------------------

#: public chip specs (tools/mfu_roofline.py) keyed by device_kind prefix
PEAKS = {
    "TPU v5 lite": {"flops": 197e12, "bytes_per_s": 819e9},
    "TPU v4": {"flops": 275e12, "bytes_per_s": 1228e9},
    "TPU v6 lite": {"flops": 918e12, "bytes_per_s": 1640e9},
}

#: clearly-labeled stand-in for devices without a table entry (CPU
#: containers): ~one modern server core. The roofline RATIO on such hosts is
#: indicative, not absolute — the bottleneck label never depends on it.
NOMINAL_PEAKS = {"flops": 1e11, "bytes_per_s": 2e10}


def device_peaks(data_shards: int = 1) -> Dict[str, Any]:
    """Roofline ceilings for the current device: env override >
    chip-spec table > nominal stand-in. ``peak_source`` records which.

    ``data_shards`` > 1 aggregates over a mesh: a segment sharded N ways
    has N chips' worth of flops and bandwidth as its bound (the
    ``peak_source`` gains an ``xN`` suffix so a mesh-scaled bound is never
    mistaken for a single-chip one)."""
    env_f = _num_or_none(os.environ.get("MMLSPARK_PEAK_FLOPS"))
    env_b = _num_or_none(os.environ.get("MMLSPARK_PEAK_GBPS"))
    if env_f and env_b:
        out = {"flops": env_f, "bytes_per_s": env_b * 1e9,
               "peak_source": "env"}
        return _scale_peaks(out, data_shards)
    kind = None
    jax = sys.modules.get("jax")  # never import (and init a backend) here
    if jax is not None:
        try:
            dev = jax.devices()[0]
            kind = getattr(dev, "device_kind", None) or dev.platform
        except Exception:  # noqa: BLE001 — backend init failure
            kind = None
    if kind is not None:
        for prefix, peak in PEAKS.items():
            if str(kind).startswith(prefix):
                return _scale_peaks({**peak, "peak_source": "table",
                                     "device_kind": kind}, data_shards)
    return _scale_peaks({**NOMINAL_PEAKS, "peak_source": "nominal",
                         "device_kind": kind}, data_shards)


def _scale_peaks(peaks: Dict[str, Any], data_shards: int) -> Dict[str, Any]:
    n = max(1, int(data_shards or 1))
    if n == 1:
        return peaks
    return {**peaks, "flops": peaks["flops"] * n,
            "bytes_per_s": peaks["bytes_per_s"] * n,
            "peak_source": f"{peaks['peak_source']}x{n}",
            "data_shards": n}


# ---------------------------------------------------------------------------
# Per-segment roofline attribution
# ---------------------------------------------------------------------------

#: IngestStats summary key -> bottleneck label. dispatch gets its own label
#: (the fixed Python submit cost K-step mega-dispatch amortizes — folding it
#: into "host" would hide that win); readback stays the host's share of the
#: batch loop (D2H fetch + finalize wait).
_BOTTLENECK_OF = (("queue_s", "queue"), ("h2d_s", "h2d"),
                  ("compute_s", "compute"), ("dispatch_s", "dispatch"),
                  ("readback_s", "host"))


def _mean_cost(shapes: Dict[str, Dict[str, Any]], key: str
               ) -> Optional[float]:
    vals = [v[key] for v in shapes.values() if _num_or_none(v.get(key))]
    return sum(vals) / len(vals) if vals else None


def attribute_segments(per_segment: Dict[str, Dict[str, Any]],
                       costs: Dict[str, Dict[str, Dict[str, Any]]],
                       peaks: Optional[Dict[str, Any]] = None,
                       sharding: Optional[Dict[str, Dict[str, Any]]] = None,
                       cost_model=None,
                       layout: Optional[Dict[str, str]] = None
                       ) -> Dict[str, Dict[str, Any]]:
    """Join per-segment ingest decompositions with per-(segment, shape)
    XLA costs into the roofline report.

    ``per_segment``: {label: IngestStats.summary()} from the last transform.
    ``costs``: {label: {shape_key: cost record}} from CompileCache.costs().
    Returns {label: {flops_per_batch, bytes_per_batch, peak_memory_bytes,
    bound_ms_per_batch, measured_ms_per_batch, roofline_ratio, bottleneck,
    stage_share, peak_source}} — cost fields absent when the backend
    reported none (the report never fails for lack of them).

    ``sharding`` ({label: SegmentSharding.describe()}, core/fusion.py) marks
    segments executing sharded: their bound aggregates over the mesh
    (per-chip peak × shards), the record carries ``spec``/``shards``, and —
    when ``cost_model`` has calibrated collective probes — the measured
    per-batch collective time is attributed (``collective_ms_per_batch``).
    With ``sharding=None`` the report is byte-identical to the unsharded
    one.

    ``layout`` ({label: "csr"}, the tuned staging-layout knob) marks
    segments running CSR staging: the record carries ``layout``, and —
    when ``cost_model`` has a calibrated nnz term — the bandwidth side of
    the roofline bound uses the fitted nnz bytes (bytes ≈ f(nnz), not
    N·F: the whole point of staging sparse) as
    ``nnz_bytes_per_batch``. With ``layout=None`` the report is
    byte-identical to the dense one."""
    peaks = peaks if peaks is not None else device_peaks()
    sharding = sharding or {}
    layout = layout or {}
    out: Dict[str, Dict[str, Any]] = {}
    for label, s in per_segment.items():
        n = int(s.get("n_batches") or 0)
        if n <= 0:
            continue
        shard = sharding.get(label)
        seg_peaks = peaks
        if shard and int(shard.get("shards", 1) or 1) > 1:
            seg_peaks = _scale_peaks(peaks, int(shard["shards"]))
        rec: Dict[str, Any] = {"n_batches": n, "rows": s.get("rows"),
                               "peak_source": seg_peaks.get("peak_source")}
        if shard:
            rec["spec"] = shard.get("spec")
            rec["shards"] = int(shard.get("shards", 1) or 1)
        lay = layout.get(label)
        if lay:
            rec["layout"] = str(lay)
        # dominant bottleneck from the measured stage decomposition alone
        shares: Dict[str, float] = {}
        for key, bn in _BOTTLENECK_OF:
            v = _num_or_none(s.get(key))
            if v is not None:
                shares[bn] = shares.get(bn, 0.0) + v
        total_stage = sum(shares.values())
        if total_stage > 0:
            rec["bottleneck"] = max(shares, key=shares.get)
            rec["stage_share"] = {k: round(v / total_stage, 4)
                                  for k, v in shares.items()}
        wall = _num_or_none(s.get("wall_s"))
        if wall and wall > 0:
            rec["measured_ms_per_batch"] = round(wall / n * 1e3, 4)
        shapes = costs.get(label) or {}
        flops = _mean_cost(shapes, "flops")
        nbytes = _mean_cost(shapes, "bytes_accessed")
        peak_mem = max((v["peak_memory_bytes"] for v in shapes.values()
                        if _num_or_none(v.get("peak_memory_bytes"))),
                       default=None)
        if flops is not None:
            rec["flops_per_batch"] = round(flops, 1)
        if nbytes is not None:
            rec["bytes_per_batch"] = round(nbytes, 1)
        if peak_mem is not None:
            rec["peak_memory_bytes"] = round(peak_mem, 1)
        # a CSR-staged segment's bandwidth bound comes from the fitted
        # nnz bytes, not the XLA dense-buffer report: the staged payload
        # IS f(nnz), so pricing it as N·F would overstate the bound
        nnz_bytes = None
        if lay == "csr" and cost_model is not None:
            nnz_fn = getattr(cost_model, "nnz_bytes", None)
            rows = _num_or_none(s.get("rows"))
            if callable(nnz_fn) and rows:
                try:
                    nnz_bytes = _num_or_none(nnz_fn(label, rows / n))
                except Exception:  # noqa: BLE001 — estimate only
                    nnz_bytes = None
            if nnz_bytes is not None:
                rec["nnz_bytes_per_batch"] = round(nnz_bytes, 1)
        # roofline: bound time = max(compute-bound, bandwidth-bound) per
        # batch; ratio = bound / measured (1.0 = running at the bound, the
        # ~250x image-chain gap shows up as ~0.004 here)
        if (flops or nbytes or nnz_bytes) and wall and wall > 0:
            t_flops = (flops or 0.0) / seg_peaks["flops"]
            band_bytes = nnz_bytes if nnz_bytes is not None else nbytes
            t_mem = (band_bytes or 0.0) / seg_peaks["bytes_per_s"]
            bound_s = max(t_flops, t_mem)
            if bound_s > 0:
                rec["bound_ms_per_batch"] = round(bound_s * 1e3, 6)
                rec["roofline_ratio"] = round(bound_s / (wall / n), 6)
        # measured collective time one sharded batch pays (the fitted
        # α·bytes term over the harvested output payload)
        if shard and cost_model is not None:
            coll_fn = getattr(cost_model, "collective_ms", None)
            out_bytes = _mean_cost(shapes, "output_bytes")
            if callable(coll_fn) and out_bytes:
                ms = coll_fn(str(shard.get("collective", "all_gather")),
                             out_bytes)
                if ms is not None:
                    rec["collective_ms_per_batch"] = round(ms, 6)
        out[label] = rec
    return out


def segment_families(fusion: Dict[str, Any]) -> List[MetricFamily]:
    """Render a fusion_stats() payload (with ``segment_costs`` and
    ``roofline`` sections — core/fusion.py) as the
    ``mmlspark_segment_*`` gauge families."""
    fams: List[MetricFamily] = []
    costs = fusion.get("segment_costs") or {}
    per_metric = (("flops", "mmlspark_segment_cost_flops",
                   "XLA-reported flops of one fused batch"),
                  ("bytes_accessed", "mmlspark_segment_cost_bytes",
                   "XLA-reported bytes accessed by one fused batch"),
                  ("peak_memory_bytes",
                   "mmlspark_segment_cost_peak_memory_bytes",
                   "argument+output+temp bytes of the compiled executable"),
                  ("compile_s", "mmlspark_segment_compile_seconds",
                   "XLA compile seconds for this (segment, shape bucket)"))
    for key, name, help in per_metric:
        fam = MetricFamily(name, "gauge", help)
        for label, shapes in sorted(costs.items()):
            for shape, rec in sorted(shapes.items()):
                v = _num_or_none(rec.get(key))
                if v is not None:
                    fam.add(v, {"segment": label, "shape": shape})
        if fam.samples:
            fams.append(fam)
    roofline = fusion.get("roofline") or {}
    ratio = MetricFamily(
        "mmlspark_segment_roofline_ratio", "gauge",
        "cost-model bound time / measured wall per batch (1.0 = at the "
        "hardware bound)")
    bound = MetricFamily(
        "mmlspark_segment_bound_ms_per_batch", "gauge",
        "roofline bound time for one fused batch")
    measured = MetricFamily(
        "mmlspark_segment_measured_ms_per_batch", "gauge",
        "measured wall per fused batch (TransferRing)")
    bneck = MetricFamily(
        "mmlspark_segment_bottleneck", "gauge",
        "one-hot dominant bottleneck per segment "
        "(queue/h2d/compute/dispatch/host)")
    collective = MetricFamily(
        "mmlspark_segment_collective_ms_per_batch", "gauge",
        "fitted collective (all-reduce/all-gather) time one sharded batch "
        "pays, from measured mesh probes")
    for label, rec in sorted(roofline.items()):
        # sharded segments carry spec labels so a mesh-scaled bound/ratio
        # series never aliases the single-device one; unsharded samples
        # keep exactly the historical label set
        extra = {}
        if rec.get("spec"):
            extra = {"sharded": "1", "spec": str(rec["spec"])}
        if rec.get("layout"):
            # CSR-staged segments carry layout= so an nnz-bound series
            # never aliases the dense-bound one (same no-alias contract
            # as spec=); dense samples keep the historical label set
            extra = {**extra, "layout": str(rec["layout"])}
        for fam, key in ((ratio, "roofline_ratio"),
                         (bound, "bound_ms_per_batch"),
                         (measured, "measured_ms_per_batch")):
            v = _num_or_none(rec.get(key))
            if v is not None:
                fam.add(v, {"segment": label, **extra})
        v = _num_or_none(rec.get("collective_ms_per_batch"))
        if v is not None:
            fam_labels = {"segment": label, **extra}
            collective.add(v, fam_labels)
        dom = rec.get("bottleneck")
        if dom:
            for name in ("queue", "h2d", "compute", "dispatch", "host"):
                bneck.add(1.0 if name == dom else 0.0,
                          {"segment": label, "bottleneck": name, **extra})
    return fams + [f for f in (ratio, bound, measured, bneck, collective)
                   if f.samples]


# ---------------------------------------------------------------------------
# Device memory telemetry
# ---------------------------------------------------------------------------


def device_memory_families() -> List[MetricFamily]:
    """``device.memory_stats()`` per local device as one gauge family.
    Gated three ways: jax not yet imported in this process -> no families
    (never initialize a backend from a scrape); ``memory_stats`` absent ->
    skip the device; returning None (CPU backends) -> skip the device."""
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    try:
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — backend init failure
        return []
    fam = MetricFamily(
        "mmlspark_device_memory_bytes", "gauge",
        "device.memory_stats() snapshot per local device (absent on "
        "backends that do not report it)")
    for dev in devices:
        ms = getattr(dev, "memory_stats", None)
        if not callable(ms):
            continue
        try:
            stats = ms()
        except Exception:  # noqa: BLE001
            continue
        if not isinstance(stats, dict):
            continue
        for key, v in sorted(stats.items()):
            f = _num_or_none(v)
            if f is not None:
                fam.add(f, {"device": str(dev), "stat": str(key)})
    return [fam] if fam.samples else []


def fold_device_memory(registry: MetricsRegistry) -> None:
    """Register the scrape-time device-memory collector."""
    registry.register_collector(device_memory_families)


# ---------------------------------------------------------------------------
# SLO burn-rate tracking
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Declarative latency objective: ``target`` fraction of requests must
    complete within ``objective_ms``, evaluated over each window in
    ``windows_s``. Burn rate = (violating fraction) / (error budget): 1.0
    means the budget burns exactly as fast as it accrues; the standard
    multi-window alert pairs a short window (fast detection) with a long
    one (noise rejection)."""

    name: str = "latency"
    objective_ms: float = 250.0
    target: float = 0.99
    windows_s: Tuple[int, ...] = (60, 300, 3600)

    def __post_init__(self):
        if not 0.0 < self.target <= 1.0:
            raise ValueError(f"target must be in (0, 1], got {self.target}")
        if self.objective_ms <= 0:
            raise ValueError("objective_ms must be positive")
        ws = tuple(int(w) for w in self.windows_s)
        if not ws or any(w <= 0 for w in ws):
            raise ValueError(f"bad windows_s {self.windows_s!r}")
        object.__setattr__(self, "windows_s", ws)


class SLOTracker:
    """Per-second (total, breaches) buckets over the largest window,
    evaluated into burn rates at scrape time.

    ``record(latency_s)`` is the hot-path cost: one lock, one comparison,
    two integer increments. ``families()`` is a registry collector —
    register it with ``registry.register_collector(tracker.families)``.
    """

    def __init__(self, config: Optional[SLOConfig] = None,
                 clock=time.monotonic):
        self.config = config if config is not None else SLOConfig()
        self._clock = clock
        self._lock = threading.Lock()
        # (second, total, breaches) triples, oldest first; bounded by the
        # largest window (+ slack for the partially-filled current second)
        self._buckets: "deque[List[float]]" = deque(
            maxlen=max(self.config.windows_s) + 2)
        self.requests_total = 0
        self.breaches_total = 0

    def record(self, latency_s: float, breach: Optional[bool] = None) -> None:
        """Count one request; ``breach`` overrides the latency comparison
        (shed/timeout responses count against the budget regardless of how
        fast the rejection was)."""
        if breach is None:
            breach = latency_s * 1e3 > self.config.objective_ms
        sec = int(self._clock())
        with self._lock:
            if self._buckets and self._buckets[-1][0] == sec:
                b = self._buckets[-1]
            else:
                b = [sec, 0, 0]
                self._buckets.append(b)
            b[1] += 1
            b[2] += 1 if breach else 0
            self.requests_total += 1
            self.breaches_total += 1 if breach else 0

    def _window_counts(self, now: int) -> Dict[int, Tuple[int, int]]:
        out = {w: (0, 0) for w in self.config.windows_s}
        with self._lock:
            buckets = list(self._buckets)
        for sec, total, bad in buckets:
            age = now - sec
            for w in self.config.windows_s:
                if 0 <= age < w:
                    t, b = out[w]
                    out[w] = (t + total, b + bad)
        return out

    def arrival_buckets(self) -> Dict[str, Any]:
        """Snapshot of the per-second ``(second, total, breaches)`` triples
        plus this tracker's own clock reading — the fleet planner's
        arrival-rate source (serving/fleet/planner.py forecast_rps). The
        clock rides along because the buckets are stamped with THIS clock
        (monotonic by default), which need not agree with wall time."""
        with self._lock:
            buckets = [tuple(b) for b in self._buckets]
        return {"now": self._clock(), "buckets": buckets}

    def burn_rates(self) -> Dict[int, float]:
        """{window_s: burn rate}: violating fraction / error budget; 0.0
        with no traffic in the window (nothing burning)."""
        budget = max(1.0 - self.config.target, 1e-9)
        now = int(self._clock())
        return {w: (round(bad / total / budget, 6) if total else 0.0)
                for w, (total, bad) in self._window_counts(now).items()}

    def summary(self) -> Dict[str, Any]:
        now = int(self._clock())
        counts = self._window_counts(now)
        budget = max(1.0 - self.config.target, 1e-9)
        with self._lock:
            total, breaches = self.requests_total, self.breaches_total
        return {
            "name": self.config.name,
            "objective_ms": self.config.objective_ms,
            "target": self.config.target,
            "requests_total": total,
            "breaches_total": breaches,
            "windows": {str(w): {
                "requests": t, "breaches": b,
                "burn_rate": round(b / t / budget, 4) if t else 0.0}
                for w, (t, b) in counts.items()},
        }

    def families(self) -> List[MetricFamily]:
        s = self.summary()
        labels = {"slo": s["name"]}
        fams = [
            MetricFamily("mmlspark_slo_objective_ms", "gauge",
                         "latency objective").add(s["objective_ms"], labels),
            MetricFamily("mmlspark_slo_target", "gauge",
                         "target within-objective fraction").add(
                             s["target"], labels),
            MetricFamily("mmlspark_slo_requests_total", "counter",
                         "requests evaluated against the SLO").add(
                             s["requests_total"], labels),
            MetricFamily("mmlspark_slo_breaches_total", "counter",
                         "requests over the latency objective").add(
                             s["breaches_total"], labels),
        ]
        burn = MetricFamily(
            "mmlspark_slo_burn_rate", "gauge",
            "error-budget burn rate per window (1.0 = burning exactly at "
            "budget; the HPA signal)")
        win_req = MetricFamily("mmlspark_slo_window_requests", "gauge",
                               "requests inside each burn-rate window")
        for w, rec in s["windows"].items():
            burn.add(rec["burn_rate"], {**labels, "window": f"{w}s"})
            win_req.add(rec["requests"], {**labels, "window": f"{w}s"})
        fams.extend([burn, win_req])
        return fams


def make_slo(slo: Any) -> Optional[SLOTracker]:
    """Coerce a server's ``slo`` knob: None -> default SLOConfig, False ->
    disabled, an SLOConfig/dict -> configured tracker."""
    if slo is False:
        return None
    if slo is None or slo is True:
        return SLOTracker(SLOConfig())
    if isinstance(slo, SLOTracker):
        return slo
    if isinstance(slo, SLOConfig):
        return SLOTracker(slo)
    if isinstance(slo, dict):
        return SLOTracker(SLOConfig(**slo))
    raise ValueError(f"slo must be None/bool/SLOConfig/dict, got {slo!r}")
