"""Cross-hop request tracing: span context, X-MMLSpark-Trace, exporters.

One serving request crosses three thread/process boundaries (client ->
RoutingFront -> worker ingress -> batch pipeline -> reply), and before this
module nothing tied those hops together. The design mirrors the deadline
layer (core/faults.py ``X-MMLSpark-Deadline``): a tiny header carries the
context across existing HTTP hops, and every stage records spans against it.

  - ``SpanContext``: (trace_id, span_id, parent_id, sampled). The header
    format is ``<trace16hex>-<span16hex>-<01|00>`` (flags = sampled), parsed
    case-insensitively from any mapping like the deadline header.
  - ``Tracer``: owns the HEAD-BASED sampling decision (made once at ingress,
    carried in the header flag so downstream hops never re-roll), a bounded
    ring of finished spans, and the exporters — ``export_jsonl`` (one span
    per line) and ``export_perfetto`` (Chrome trace-event JSON, loadable in
    Perfetto/chrome://tracing). With a ``seed`` the sampling stream is
    deterministic, so chaos runs replay with identical trace sets.
  - ``span()`` wraps ``core.profiling.annotate`` when jax is importable, so
    the same stage boundaries land inside ``jax.profiler`` device traces.
  - Batch stages serve MANY requests at once: ``record_batch`` writes one
    span per SAMPLED context in the batch, so every traced request sees the
    drain/dispatch/readback stages it rode through. Head sampling keeps
    this multiplicative cost bounded.
  - ``batch_context``/``current_batch``: a contextvar carrying the current
    batch's (tracer, sampled contexts) into layers that can't thread them
    explicitly (parallel/ingest.timed_stage records H2D spans through it).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import random
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

__all__ = ["Span", "SpanContext", "TRACE_HEADER", "Tracer", "batch_context",
           "current_batch", "parse_trace_header"]

#: header carrying the trace context across hops (deadline-header pattern)
TRACE_HEADER = "X-MMLSpark-Trace"

_FLAG_SAMPLED = "01"
_FLAG_DROPPED = "00"


class SpanContext:
    """Identity of one span within one trace (immutable value object)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled

    def to_header(self) -> str:
        return f"{self.trace_id}-{self.span_id}-" \
               f"{_FLAG_SAMPLED if self.sampled else _FLAG_DROPPED}"

    def __repr__(self) -> str:  # pragma: no cover - debugging sugar
        return f"SpanContext({self.to_header()!r})"


def parse_trace_header(value: Optional[str]) -> Optional[SpanContext]:
    """``trace-span-flags`` -> SpanContext (None on malformed input: a bad
    header must never fail a request, it just starts a fresh trace)."""
    if not value:
        return None
    parts = str(value).strip().split("-")
    if len(parts) != 3:
        return None
    trace_id, span_id, flags = parts
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if not trace_id or not span_id:
        return None
    return SpanContext(trace_id.lower(), span_id.lower(),
                       sampled=flags == _FLAG_SAMPLED)


def context_from_headers(headers: Optional[Mapping[str, str]]
                         ) -> Optional[SpanContext]:
    """Case-insensitive ``X-MMLSpark-Trace`` lookup on any mapping
    (mirrors core.faults.deadline_from_headers)."""
    if not headers:
        return None
    get = getattr(headers, "get", None)
    if get is not None:
        v = get(TRACE_HEADER) or get(TRACE_HEADER.lower())
        if v is not None:
            return parse_trace_header(v)
    low = TRACE_HEADER.lower()
    for k in headers:
        if str(k).lower() == low:
            return parse_trace_header(headers[k])
    return None


class Span:
    """One finished span (epoch-second timestamps, duration in seconds)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0", "dur_s",
                 "attrs", "service")

    def __init__(self, name: str, ctx: SpanContext, t0: float, dur_s: float,
                 attrs: Optional[Dict[str, Any]] = None, service: str = ""):
        self.name = name
        self.trace_id = ctx.trace_id
        self.span_id = ctx.span_id
        self.parent_id = ctx.parent_id
        self.t0 = t0
        self.dur_s = dur_s
        self.attrs = attrs or {}
        self.service = service

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "t0": self.t0, "dur_s": self.dur_s, "service": self.service,
                "attrs": self.attrs}


class Tracer:
    """Span factory + bounded buffer + exporters for one service.

    ``sample_rate``: head-based sampling probability for traces ORIGINATING
    here (an incoming header's flag always wins — the ingress hop decided).
    ``seed``: deterministic sampling/id stream (chaos replay); None draws
    from the system RNG. ``cap`` bounds the in-memory span ring.
    ``annotate=True`` additionally wraps live ``span()`` blocks in
    ``jax.profiler.TraceAnnotation`` (via core.profiling) when jax imports.
    """

    def __init__(self, sample_rate: float = 1.0, cap: int = 8192,
                 seed: Optional[int] = None, service: str = "mmlspark",
                 annotate: bool = False):
        self.sample_rate = float(sample_rate)
        self.service = service
        self.annotate = bool(annotate)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._spans: "deque[Span]" = deque(maxlen=cap)
        # lock-free child-id stream for batch spans: itertools.count is
        # atomic under the GIL, and the fixed-point multiply is a bijection
        # on 64 bits, so every draw is unique within this tracer without
        # touching the seeded RNG (whose lock the ingress path contends
        # on). The random base drawn ONCE at construction keeps ids from
        # different tracers writing into the same trace (front + worker
        # across the hop) from colliding at equal sequence numbers.
        self._seq = itertools.count(1)
        self._seq_base = self._rng.getrandbits(64)
        self.started = 0   # traces originated here
        self.joined = 0    # traces continued from an incoming header
        self.dropped = 0   # unsampled ingress decisions

    # -- context construction -------------------------------------------
    # (id generation inlines under an already-held lock where possible:
    # the serving hot path at sample_rate=1.0 crosses this lock ~10x per
    # request if every draw/push re-acquires, and on a contended host each
    # handoff can cost a scheduler trip — so ingress/record_batch do ONE
    # acquisition each)
    def _id_locked(self, bits: int = 64) -> str:
        return f"{self._rng.getrandbits(bits):0{bits // 4}x}"

    def _sample(self) -> bool:
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        with self._lock:
            return self._rng.random() < self.sample_rate

    def ingress(self, headers: Optional[Mapping[str, str]] = None
                ) -> SpanContext:
        """Context for a request entering this service: continue the trace
        in the incoming header (its sampled flag is authoritative — the
        head decision), or originate a new one."""
        parent = context_from_headers(headers)
        if parent is not None:
            with self._lock:
                self.joined += 1
                span_id = self._id_locked()
            return SpanContext(parent.trace_id, span_id,
                               parent_id=parent.span_id,
                               sampled=parent.sampled)
        sampled = self._sample()
        with self._lock:
            if sampled:
                self.started += 1
            else:
                self.dropped += 1
            trace_id = self._id_locked(128)
            span_id = self._id_locked()
        return SpanContext(trace_id, span_id, sampled=sampled)

    def child(self, ctx: SpanContext) -> SpanContext:
        """New span context under ``ctx`` (same trace, parent = ctx)."""
        return SpanContext(ctx.trace_id, self._seq_id(),
                           parent_id=ctx.span_id, sampled=ctx.sampled)

    def _seq_id(self) -> str:
        """Unique 64-bit span id without taking the RNG lock: a
        Fibonacci-hashed counter (bijective on 64 bits — no collisions
        within a tracer) XOR a per-tracer random base (collision odds
        across tracers match the old fully-random ids)."""
        n = next(self._seq) * 0x9e3779b97f4a7c15 & (1 << 64) - 1
        return f"{n ^ self._seq_base:016x}"

    # -- recording -------------------------------------------------------
    # deque appends are atomic under the GIL, so the serving hot path
    # records spans LOCK-FREE — the batcher thread's 3 batch-stage records
    # per request no longer trade the tracer lock with the handler
    # thread's ingress/finish (each contended handoff is a potential
    # scheduler trip on a loaded host). Snapshot reads retry around a
    # concurrent append instead (spans()).
    def _push(self, span: Span) -> None:
        self._spans.append(span)

    def record(self, name: str, ctx: Optional[SpanContext], t0: float,
               dur_s: float, **attrs: Any) -> None:
        """Record a finished span with explicit epoch-second timestamps
        (batch stages measure once, then record per context)."""
        if ctx is None or not ctx.sampled:
            return
        self._push(Span(name, ctx, t0, dur_s, attrs or None, self.service))

    def record_batch(self, name: str, ctxs: Sequence[Optional[SpanContext]],
                     t0: float, dur_s: float, **attrs: Any) -> None:
        """One span per SAMPLED context — a batch-level stage (drain, H2D,
        dispatch, readback) seen from every traced request it carried. Each
        span gets its own span_id, parented to the request's ingress span."""
        a = attrs or None
        push = self._spans.append
        for ctx in ctxs:
            if ctx is None or not ctx.sampled:
                continue
            child = SpanContext(ctx.trace_id, self._seq_id(),
                                parent_id=ctx.span_id, sampled=True)
            push(Span(name, child, t0, dur_s, a, self.service))

    @contextlib.contextmanager
    def span(self, name: str, ctx: Optional[SpanContext],
             **attrs: Any) -> Iterator[Optional[SpanContext]]:
        """Live span: measures the enclosed block and records it as a CHILD
        of ``ctx`` (yields the child context, so nested hops can parent to
        it / put it on the wire). Unsampled contexts cost two branch
        checks and no clock reads."""
        if ctx is None or not ctx.sampled:
            yield ctx
            return
        child = self.child(ctx)
        cm = contextlib.nullcontext()
        if self.annotate:
            try:
                from ..core.profiling import annotate as _annotate

                cm = _annotate(name)
            except Exception:  # noqa: BLE001 — jax-less host
                cm = contextlib.nullcontext()
        t0 = time.time()
        p0 = time.perf_counter()
        try:
            with cm:
                yield child
        finally:
            self._push(Span(name, child, t0, time.perf_counter() - p0,
                            attrs or None, self.service))

    # -- introspection / export -----------------------------------------
    def spans(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        # recorders append lock-free; a snapshot that races one retries
        # (appends are sub-microsecond, so a second attempt always lands)
        for _ in range(64):
            try:
                snap = list(self._spans)
                break
            except RuntimeError:  # deque mutated during iteration
                continue
        else:  # pragma: no cover - 64 consecutive races
            snap = []
        out = [s.to_dict() for s in snap]
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        return out

    def clear(self) -> None:
        self._spans.clear()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"sample_rate": self.sample_rate, "service": self.service,
                    "buffered": len(self._spans), "started": self.started,
                    "joined": self.joined, "dropped": self.dropped}

    def export_jsonl(self, path: str) -> int:
        """One JSON span per line; returns the number written."""
        spans = self.spans()
        with open(path, "w", encoding="utf-8") as fh:
            for s in spans:
                fh.write(json.dumps(s) + "\n")
        return len(spans)

    def export_perfetto(self, path: str) -> int:
        """Chrome trace-event JSON (complete 'X' events, microsecond
        timestamps) — drag into https://ui.perfetto.dev or
        chrome://tracing. Spans group by service (pid) and trace (tid)."""
        spans = self.spans()
        tids: Dict[str, int] = {}
        events = []
        for s in spans:
            tid = tids.setdefault(s["trace_id"], len(tids) + 1)
            events.append({
                "ph": "X", "name": s["name"], "cat": s["service"] or "span",
                "ts": s["t0"] * 1e6, "dur": max(s["dur_s"], 0.0) * 1e6,
                "pid": 1, "tid": tid,
                "args": {**(s["attrs"] or {}), "trace_id": s["trace_id"],
                         "span_id": s["span_id"],
                         "parent_id": s["parent_id"]}})
        doc = {"traceEvents": events,
               "metadata": {"service": self.service}}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        return len(events)


# ---------------------------------------------------------------------------
# Current-batch propagation (implicit context for deep layers)
# ---------------------------------------------------------------------------

_BATCH: "contextvars.ContextVar[Optional[Tuple[Tracer, tuple]]]" = \
    contextvars.ContextVar("mmlspark_obs_batch", default=None)


@contextlib.contextmanager
def batch_context(tracer: Optional[Tracer],
                  ctxs: Sequence[Optional[SpanContext]]) -> Iterator[None]:
    """Bind (tracer, sampled contexts of the current batch) for the
    duration of a transform, so layers without an explicit tracer handle
    (TransferRing H2D staging, fused segment execution) can record spans.
    A no-op when the tracer is None or nothing in the batch is sampled."""
    live = tuple(c for c in ctxs if c is not None and c.sampled)
    if tracer is None or not live:
        yield
        return
    tok = _BATCH.set((tracer, live))
    try:
        yield
    finally:
        _BATCH.reset(tok)


def current_batch() -> Optional[Tuple[Tracer, tuple]]:
    """The innermost ``batch_context`` binding, or None."""
    return _BATCH.get()
