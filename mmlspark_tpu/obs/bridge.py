"""Bridge adapters: fold the existing stats surfaces into a MetricsRegistry.

PRs 1-4 each grew an ad-hoc stats object — IngestStats (parallel/ingest.py),
LatencyStats + shed counters (serving/server.py), CompileCache hit/miss
(core/device_stage.py via core/fusion.py), PipelinedExecutor busy/overlap
(serving/executor.py), and the RoutingFront circuit breakers. These adapters
register scrape-time COLLECTORS that read those live objects and render them
as Prometheus families, so the JSON ``/_mmlspark/stats`` payload and the
``/_mmlspark/metrics`` exposition report from one source of truth — there is
no second set of counters to drift.

Naming conventions (docs/observability.md): every series is prefixed
``mmlspark_``, seconds are ``_seconds``/``_seconds_total``, monotonic counts
are ``_total``, and enum-ish states are one-hot gauges (``state`` label).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

from .metrics import MetricFamily, MetricsRegistry

__all__ = ["fold_front", "fold_server", "fold_tracer"]


def _num(v: Any) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f


# ---------------------------------------------------------------------------
# ServingServer
# ---------------------------------------------------------------------------


def _latency_families(summary: Dict[str, Any]) -> Iterable[MetricFamily]:
    served = MetricFamily(
        "mmlspark_latency_window_requests", "gauge",
        "requests in the rolling latency window")
    served.add(summary.get("n", 0))
    yield served
    lat = MetricFamily(
        "mmlspark_request_latency_ms", "gauge",
        "rolling-window request latency decomposition "
        "(component x p50/p95/mean)")
    for component in ("queue", "compute", "overhead", "total"):
        block = summary.get(f"{component}_ms") or {}
        for stat, v in block.items():
            f = _num(v)
            if f is not None:
                lat.add(f, {"component": component, "stat": stat})
    yield lat
    mb = _num(summary.get("mean_batch"))
    if mb is not None:
        fam = MetricFamily("mmlspark_mean_batch_rows", "gauge",
                           "mean drained batch size (rolling window)")
        fam.add(mb)
        yield fam
    shed = (summary.get("shed") or {})
    sheds = MetricFamily(
        "mmlspark_sheds_total", "counter",
        "load-shed responses by HTTP status and reason")
    for status, n in (shed.get("by_status") or {}).items():
        sheds.add(n, {"kind": "status", "value": str(status)})
    for reason, n in (shed.get("by_reason") or {}).items():
        sheds.add(n, {"kind": "reason", "value": str(reason)})
    for tenant, n in (shed.get("by_tenant") or {}).items():
        sheds.add(n, {"kind": "tenant", "value": str(tenant)})
    yield sheds


def _ingest_families(summary: Dict[str, Any]) -> Iterable[MetricFamily]:
    stage = MetricFamily(
        "mmlspark_ingest_stage_seconds_total", "counter",
        "cumulative device-ingest decomposition (TransferRing stages)")
    for name in ("queue_s", "h2d_s", "dispatch_s", "compute_s",
                 "readback_s"):
        f = _num(summary.get(name))
        if f is not None:
            stage.add(f, {"stage": name[:-2]})
    yield stage
    scalars = (("mmlspark_ingest_batches_total", "counter", "n_batches",
                "batches through the transfer ring"),
               ("mmlspark_ingest_rows_total", "counter", "rows",
                "rows through the transfer ring"),
               ("mmlspark_ingest_bytes_total", "counter", "bytes",
                "wire bytes shipped host->device"),
               ("mmlspark_ingest_overlap_ratio", "gauge", "overlap_ratio",
                "ring wall / serial stage time (<1 = overlapped)"),
               ("mmlspark_ingest_h2d_gbps", "gauge", "h2d_gbps",
                "host->device transfer bandwidth"),
               ("mmlspark_transfer_ring_depth", "gauge", "ring_depth",
                "configured in-flight slot depth of the transfer ring"),
               ("mmlspark_ingest_deposits_total", "counter",
                "slot_deposits",
                "batches deposited in place into pre-staged H2D slots"),
               ("mmlspark_ingest_copies_total", "counter",
                "fallback_copies",
                "batches that fell back to the allocating copy path"),
               ("mmlspark_ingest_zero_copy_batches_total", "counter",
                "zero_copy_batches",
                "batches assembled as strided views (no host copy)"),
               ("mmlspark_ingest_copied_batches_total", "counter",
                "copied_batches",
                "batches assembled via a host copy (stack or slot fill)"),
               ("mmlspark_ingest_slot_fill_seconds_total", "counter",
                "slot_fill_s", "time spent filling staging slots"),
               ("mmlspark_ingest_slot_transfer_seconds_total", "counter",
                "slot_transfer_s",
                "H2D transfer time out of staging slots"),
               ("mmlspark_ingest_slot_overlap_ratio", "gauge",
                "slot_overlap_ratio",
                "fraction of slot H2D time overlapped with the next "
                "slot's fill (double-buffered staging)"),
               ("mmlspark_ingest_densified_bytes_total", "counter",
                "densified_bytes",
                "dense bytes materialized densifying sparse columns"),
               ("mmlspark_ingest_densify_ratio", "gauge",
                "densify_ratio",
                "densified bytes per CSR byte the same rows hold "
                "(layout-knob headroom; absent with no sparse data)"),
               ("mmlspark_ingest_csr_batches_total", "counter",
                "csr_batches",
                "batches staged as CSR triples without densifying"),
               ("mmlspark_ingest_csr_bytes_total", "counter",
                "csr_nnz_bytes",
                "CSR triple bytes staged host->device"))
    for mname, mtype, key, help in scalars:
        f = _num(summary.get(key))
        if f is not None:
            yield MetricFamily(mname, mtype, help).add(f)
    occ = MetricFamily(
        "mmlspark_transfer_ring_occupancy", "gauge",
        "observed dispatched-but-undrained steps in the ring "
        "(mean/max per dispatch; max == depth means the ring saturated)")
    for stat, key in (("mean", "ring_occupancy_mean"),
                      ("max", "ring_occupancy_max")):
        f = _num(summary.get(key))
        if f is not None:
            occ.add(f, {"stat": stat})
    if occ.samples:
        yield occ
    # per-bucket pad-waste (the measured term of the auto-tuner's bucket
    # chooser): fraction of each static bucket's rows that were padding
    padding = summary.get("padding") or {}
    ratio = MetricFamily(
        "mmlspark_batch_pad_ratio", "gauge",
        "pad rows / bucket rows per static shape bucket (0 = no waste)")
    padded = MetricFamily(
        "mmlspark_batch_pad_rows_total", "counter",
        "padded (static) rows shipped per shape bucket")
    for bucket, rec in padding.items():
        f = _num(rec.get("pad_ratio"))
        if f is not None:
            ratio.add(f, {"bucket": str(bucket)})
        f = _num(rec.get("padded"))
        if f is not None:
            padded.add(f, {"bucket": str(bucket)})
    if ratio.samples:
        yield ratio
    if padded.samples:
        yield padded


def _fusion_families(stats: Dict[str, Any]) -> Iterable[MetricFamily]:
    cache = stats.get("compile_cache") or {}
    for key, mtype, help in (
            ("hits", "counter", "fused-executable cache hits"),
            ("misses", "counter", "fused-executable cache misses"),
            ("entries", "gauge", "live fused executables"),
            ("compile_s", "counter", "seconds spent compiling fused "
                                     "executables")):
        f = _num(cache.get(key))
        if f is not None:
            yield MetricFamily(f"mmlspark_compile_cache_{key}"
                               + ("_total" if mtype == "counter" else ""),
                               mtype, help).add(f)
    rate = _num(cache.get("hit_rate"))
    if rate is not None:
        yield MetricFamily("mmlspark_compile_cache_hit_rate", "gauge",
                           "hits / (hits + misses)").add(rate)
    ev = _num(cache.get("evictions"))
    if ev is not None:
        yield MetricFamily(
            "mmlspark_segment_cache_evictions_total", "counter",
            "fused executables dropped by the CompileCache's LRU bound"
        ).add(ev)
    cap = _num(cache.get("capacity"))
    if cap is not None:
        yield MetricFamily(
            "mmlspark_segment_cache_capacity", "gauge",
            "configured CompileCache entry cap").add(cap)
    tier = cache.get("persistent")
    if tier:
        # two-tier view (serving/fleet/cache.py): the untierred families
        # above keep their pre-fleet meaning (in-process builds); these
        # label the same memory numbers tier="memory" next to the
        # persistent tier's own counters. Absent when fleet is off, so
        # the disabled exposition stays byte-identical.
        hits = MetricFamily("mmlspark_compile_cache_tier_hits_total",
                            "counter", "compile-cache hits per tier")
        misses = MetricFamily("mmlspark_compile_cache_tier_misses_total",
                              "counter", "compile-cache misses per tier")
        for fam, key in ((hits, "hits"), (misses, "misses")):
            f = _num(cache.get(key))
            if f is not None:
                fam.add(f, {"tier": "memory"})
            f = _num(tier.get(key))
            if f is not None:
                fam.add(f, {"tier": "persistent"})
            if fam.samples:
                yield fam
        for key, name, help in (
                ("entries", "mmlspark_compile_cache_tier_entries",
                 "entries resident per tier"),
                ("load_s", "mmlspark_compile_cache_load_seconds_total",
                 "seconds spent loading persisted executables"),
                ("store_s", "mmlspark_compile_cache_store_seconds_total",
                 "seconds spent serializing + writing executables")):
            f = _num(tier.get(key))
            if f is not None:
                yield MetricFamily(
                    name, "gauge" if key == "entries" else "counter",
                    help).add(f, {"tier": "persistent"})
        errs = MetricFamily("mmlspark_compile_cache_tier_errors_total",
                            "counter", "persistent-tier entries that "
                            "failed to load/store (degraded to recompile)")
        for op, key in (("load", "load_errors"), ("store", "store_errors")):
            f = _num(tier.get(key))
            if f is not None:
                errs.add(f, {"tier": "persistent", "op": op})
        if errs.samples:
            yield errs
        f = _num(tier.get("write_degrades"))
        if f is not None and f > 0:
            yield MetricFamily(
                "mmlspark_compile_cache_write_degraded", "gauge",
                "1 after the persistent tier dropped to read-only "
                "(ENOSPC) — reads and recompiles continue").add(1.0)
        store = tier.get("store")
        if store:
            # object-store backend (fleet/objstore.py) under the
            # persistent tier; absent when the tier is local-disk only,
            # so the storeless exposition stays byte-identical
            backend = str(store.get("store", "objstore"))
            ops = MetricFamily(
                "mmlspark_store_ops_total", "counter",
                "object-store operations by op (put / get)")
            errf = MetricFamily(
                "mmlspark_store_errors_total", "counter",
                "failed object-store operations by op (the tier "
                "degrades to recompile / read-only, never crashes)")
            byt = MetricFamily(
                "mmlspark_store_bytes_total", "counter",
                "object-store payload bytes by direction (put / get)")
            for op, okey, ekey, bkey in (
                    ("put", "puts", "put_errors", "bytes_put"),
                    ("get", "gets", "get_errors", "bytes_got")):
                f = _num(store.get(okey))
                if f is not None:
                    ops.add(f, {"backend": backend, "op": op})
                f = _num(store.get(ekey))
                if f is not None:
                    errf.add(f, {"backend": backend, "op": op})
                f = _num(store.get(bkey))
                if f is not None:
                    byt.add(f, {"backend": backend, "direction": op})
            for fam in (ops, errf, byt):
                if fam.samples:
                    yield fam
        f = _num(tier.get("snapshots"))
        if f is not None and f > 0:
            yield MetricFamily(
                "mmlspark_store_snapshots_total", "counter",
                "knob-shipping snapshots published (KnobSet + capacity "
                "plan, deduplicated byte-identically)").add(f)
    nseg = _num(stats.get("n_fused_segments"))
    if nseg is not None:
        yield MetricFamily("mmlspark_fused_segments", "gauge",
                           "device-fused segments in the active plan"
                           ).add(nseg)
    fallbacks = stats.get("fallbacks")
    if fallbacks is not None:
        yield MetricFamily("mmlspark_fusion_fallbacks", "gauge",
                           "partitions that fell back to the host path "
                           "on the last transform").add(len(fallbacks))
    # cross-segment stitches in force (core/fusion.py plan()): one sample
    # per merged segment, valued at the number of transpiled shims it
    # carries. The stats key — and hence this family — is absent while no
    # stitch is active, keeping the default exposition byte-identical.
    stitched = stats.get("stitched")
    if stitched:
        fam = MetricFamily(
            "mmlspark_segment_stitched", "gauge",
            "transpiled host shims stitched through per fused segment")
        for seg, names in stitched.items():
            fam.add(float(len(names or ())), {"segment": str(seg)})
        yield fam
    # pipeline-parallel stream (core/fusion.py fusion_stats()["pipeline"],
    # fed by parallel/pipeplan.py PipeRunner). The stats key — and so
    # every family below — exists ONLY while a pipe plan is active: the
    # serial exposition stays byte-identical.
    pipe = stats.get("pipeline")
    if pipe:
        f = _num(pipe.get("depth"))
        if f is not None:
            yield MetricFamily(
                "mmlspark_pipe_depth", "gauge",
                "active pipeline-parallel stage count").add(f)
        f = _num(pipe.get("bubble_ratio"))
        if f is not None:
            yield MetricFamily(
                "mmlspark_pipe_bubble_ratio", "gauge",
                "pipeline fill/drain idle fraction, (S-1)/(M+S-1) over "
                "the last stream").add(f)
        busy = MetricFamily(
            "mmlspark_pipe_stage_busy_ratio", "gauge",
            "per-stage busy seconds / stream wall")
        hand = MetricFamily(
            "mmlspark_pipe_handoff_bytes_total", "counter",
            "inter-stage device-to-device bytes moved, by receiving "
            "stage")
        reqs = MetricFamily(
            "mmlspark_pipe_stage_requeues_total", "counter",
            "micro-batch streams requeued after this stage wedged "
            "(each one re-planned at depth N-1)")
        for st in (pipe.get("stages") or []):
            labels = {"stage": str(st.get("index"))}
            for fam, key in ((busy, "busy_ratio"),
                             (hand, "handoff_bytes"),
                             (reqs, "requeues")):
                f = _num(st.get(key))
                if f is not None:
                    fam.add(f, labels)
        for fam in (busy, hand, reqs):
            if fam.samples:
                yield fam
    # per-(segment, shape-bucket) XLA costs + roofline attribution
    # (obs/perf.py; families absent when the backend reports no cost data)
    from .perf import segment_families

    for fam in segment_families(stats):
        yield fam


def _executor_families(stats: Dict[str, Any]) -> Iterable[MetricFamily]:
    busy = MetricFamily("mmlspark_executor_busy_seconds_total", "counter",
                        "pipelined-executor stage busy time")
    for stage, v in (stats.get("busy_s") or {}).items():
        f = _num(v)
        if f is not None:
            busy.add(f, {"stage": stage})
    yield busy
    for key, mtype, help in (
            ("epochs", "counter", "batches through the pipelined executor"),
            ("inflight", "gauge", "configured in-flight slot depth"),
            ("inflight_active", "gauge",
             "batches currently in flight (== inflight means saturated)"),
            ("overlap_ratio", "gauge",
             "stage-busy seconds / pipeline-active wall (>1 = overlapped)"),
            ("active_wall_s", "counter",
             "wall seconds with >=1 batch in flight")):
        f = _num(stats.get(key))
        if f is not None:
            name = f"mmlspark_executor_{key}"
            if mtype == "counter" and not name.endswith("_total") \
                    and not name.endswith("_s"):
                name += "_total"
            yield MetricFamily(name, mtype, help).add(f)
    reps = MetricFamily("mmlspark_replica_busy_seconds_total", "counter",
                        "per-replica transform busy time")
    util = MetricFamily("mmlspark_replica_utilization", "gauge",
                        "per-replica busy / pipeline-active wall")
    rows = MetricFamily("mmlspark_replica_rows_total", "counter",
                        "rows computed per replica")
    for r in (stats.get("replicas") or []):
        labels = {"replica": str(r.get("replica"))}
        for fam, key in ((reps, "busy_s"), (util, "utilization"),
                         (rows, "rows")):
            f = _num(r.get(key))
            if f is not None:
                fam.add(f, labels)
    yield reps
    yield util
    yield rows
    sup = stats.get("supervisor")
    if sup:
        for fam in _supervisor_families(sup):
            yield fam
    wd = stats.get("watchdog")
    if wd:
        for fam in _watchdog_families(wd):
            yield fam


def _supervisor_families(sup: Dict[str, Any]) -> Iterable[MetricFamily]:
    """Replica supervision (serving/supervisor.py): one-hot health states,
    decayed health scores, and the eject/readmit lifecycle counters —
    naming per docs/observability.md (mmlspark_replica_* families)."""
    state = MetricFamily(
        "mmlspark_replica_state", "gauge",
        "one-hot replica health state (healthy/quarantined/probing)")
    score = MetricFamily("mmlspark_replica_health_score", "gauge",
                         "decayed per-replica health score in [0, 1]")
    timeouts = MetricFamily("mmlspark_replica_timeouts_total", "counter",
                            "wedged dispatches (watchdog expiries) "
                            "per replica")
    errors = MetricFamily("mmlspark_replica_errors_total", "counter",
                          "failed dispatches per replica")
    outliers = MetricFamily("mmlspark_replica_outliers_total", "counter",
                            "latency-outlier completions per replica")
    ejections = MetricFamily("mmlspark_replica_ejections_total", "counter",
                             "quarantine transitions per replica")
    readmits = MetricFamily("mmlspark_replica_readmissions_total", "counter",
                            "probe-success re-admissions per replica")
    for r in (sup.get("replicas") or []):
        labels = {"replica": str(r.get("replica"))}
        for name in ("healthy", "quarantined", "probing"):
            state.add(1.0 if r.get("state") == name else 0.0,
                      {**labels, "state": name})
        for fam, key in ((score, "score"), (timeouts, "timeouts"),
                         (errors, "errors"), (outliers, "outliers"),
                         (ejections, "ejections"),
                         (readmits, "readmissions")):
            f = _num(r.get(key))
            if f is not None:
                fam.add(f, labels)
    yield state
    yield score
    yield timeouts
    yield errors
    yield outliers
    yield ejections
    yield readmits


def _watchdog_families(wd: Dict[str, Any]) -> Iterable[MetricFamily]:
    trips = MetricFamily(
        "mmlspark_watchdog_trips_total", "counter",
        "hung-dispatch watchdog expiries by action "
        "(requeue = re-dispatched, extend = budget doubled in place, "
        "abandon = accounted 504)")
    for key in ("requeues", "abandons"):
        f = _num(wd.get(key))
        if f is not None:
            trips.add(f, {"action": key[:-1]})
    total = _num(wd.get("trips"))
    if total is not None:
        rq = _num(wd.get("requeues")) or 0.0
        ab = _num(wd.get("abandons")) or 0.0
        trips.add(max(0.0, total - rq - ab), {"action": "extend"})
    yield trips
    yield MetricFamily(
        "mmlspark_watchdog_armed", "gauge",
        "1 while the watchdog has a budget source (fixed / cost model / "
        "measured EWMA)").add(1.0 if wd.get("armed") else 0.0)
    ew = _num(wd.get("compute_ewma_ms"))
    if ew is not None:
        yield MetricFamily(
            "mmlspark_watchdog_compute_ewma_ms", "gauge",
            "measured dispatch EWMA feeding the wall budget").add(ew)


def _wire_families(server: Any) -> Iterable[MetricFamily]:
    """Per-wire-format ingress counters (the binary frame wire A/B signal:
    requests and body bytes by ``format`` = json | binary)."""
    with server._wire_lock:
        counts = dict(server.wire_counts)
        nbytes = dict(server.wire_bytes)
    reqs = MetricFamily("mmlspark_wire_requests_total", "counter",
                        "public requests by wire format")
    byts = MetricFamily("mmlspark_wire_bytes_total", "counter",
                        "request body bytes by wire format")
    for fmt, n in counts.items():
        reqs.add(n, {"format": fmt})
    for fmt, n in nbytes.items():
        byts.add(n, {"format": fmt})
    yield reqs
    yield byts


def _tenant_families(summary: Dict[str, Any]) -> Iterable[MetricFamily]:
    """Per-tenant admission-class gauges/counters (weighted-fair shedding:
    a light tenant's shed rate staying below a heavy tenant's is readable
    straight off mmlspark_tenant_sheds_total)."""
    weight = MetricFamily("mmlspark_tenant_weight", "gauge",
                          "configured admission weight per tenant")
    inflight = MetricFamily("mmlspark_tenant_inflight", "gauge",
                            "admitted-unanswered requests per tenant")
    admitted = MetricFamily("mmlspark_tenant_admitted_total", "counter",
                            "admissions per tenant")
    shed = MetricFamily("mmlspark_tenant_sheds_total", "counter",
                        "weighted-fair sheds per tenant")
    for tenant, s in summary.items():
        labels = {"tenant": tenant}
        for fam, key in ((weight, "weight"), (inflight, "inflight"),
                         (admitted, "admitted"), (shed, "shed")):
            f = _num(s.get(key))
            if f is not None:
                fam.add(f, labels)
    yield weight
    yield inflight
    yield admitted
    yield shed


def _tuner_families(stats: Dict[str, Any]) -> Iterable[MetricFamily]:
    """Auto-tuner telemetry (core/tune.py Tuner.stats()): lifecycle
    counters, calibration state, the numeric knobs in force, and the
    per-(segment, bucket) predicted-vs-measured error the perf_report
    renders (naming per docs/autotune.md / the H002 conventions)."""
    for key, mtype, help in (
            ("epochs", "counter", "batches the tuner has observed"),
            ("applies", "counter", "knob sets applied"),
            ("rollbacks", "counter",
             "one-step rollbacks after a measured regression")):
        f = _num(stats.get(key))
        if f is not None:
            yield MetricFamily(f"mmlspark_tuner_{key}_total", mtype,
                               help).add(f)
    yield MetricFamily(
        "mmlspark_tuner_calibrated", "gauge",
        "1 once measured data backs the cost model (knobs may move)").add(
            1.0 if stats.get("calibrated") else 0.0)
    yield MetricFamily(
        "mmlspark_tuner_knobs_active", "gauge",
        "1 while a non-default knob set is applied").add(
            1.0 if stats.get("knobs_active") else 0.0)
    knobs = stats.get("knobs") or {}
    knob = MetricFamily("mmlspark_tuner_knob", "gauge",
                        "numeric knob values currently applied")
    for name in ("window_seed_ms", "inflight", "replicas"):
        f = _num(knobs.get(name))
        if f is not None:
            knob.add(f, {"knob": name})
    if knob.samples:
        yield knob
    # compiler-search knobs: the per-(segment, bucket) kernel variant in
    # force (info-style gauge, value 1) and the switch counter. Both are
    # absent until the knob first moves, so the exposition of a server
    # that never tuned variants stays byte-identical to pre-search builds.
    variant = MetricFamily(
        "mmlspark_kernel_variant", "gauge",
        "applied Pallas kernel variant per (segment, bucket) — info "
        "gauge, value is always 1")
    for seg, buckets in (knobs.get("kernel_variants") or {}).items():
        for bucket, vid in (buckets or {}).items():
            variant.add(1.0, {"segment": seg, "bucket": str(bucket),
                              "variant": str(vid)})
    if variant.samples:
        yield variant
    f = _num(stats.get("variant_switches"))
    if f is not None and f > 0:
        yield MetricFamily(
            "mmlspark_kernel_variant_switches_total", "counter",
            "tuner applies that changed the kernel-variant knob").add(f)
    conf = MetricFamily("mmlspark_tuner_confidence", "gauge",
                        "cost-model calibration confidence per segment")
    for seg, v in ((stats.get("model") or {}).get("confidence")
                   or {}).items():
        f = _num(v)
        if f is not None:
            conf.add(f, {"segment": seg})
    if conf.samples:
        yield conf
    pred = MetricFamily(
        "mmlspark_tuner_predicted_ms", "gauge",
        "analytical cost-model batch prediction per (segment, bucket)")
    meas = MetricFamily(
        "mmlspark_tuner_measured_ms", "gauge",
        "measured batch EWMA per (segment, bucket)")
    err = MetricFamily(
        "mmlspark_tuner_prediction_error_ratio", "gauge",
        "measured / analytical-predicted batch time (1.0 = exact)")
    for seg, buckets in (stats.get("predicted_vs_measured") or {}).items():
        for bucket, rec in buckets.items():
            labels = {"segment": seg, "bucket": str(bucket)}
            for fam, key in ((pred, "analytic_ms"), (meas, "measured_ms"),
                             (err, "error_ratio")):
                f = _num(rec.get(key))
                if f is not None:
                    fam.add(f, labels)
    for fam in (pred, meas, err):
        if fam.samples:
            yield fam


def _brownout_families(summary: Dict[str, Any]) -> Iterable[MetricFamily]:
    """Brownout controller state (serving/supervisor.py): the applied
    degradation level, whether any step is active, and the transition
    counters — mmlspark_brownout_* per docs/observability.md."""
    yield MetricFamily(
        "mmlspark_brownout_step", "gauge",
        "applied degradation steps (0 = full service)").add(
            summary.get("step", 0))
    yield MetricFamily(
        "mmlspark_brownout_max_steps", "gauge",
        "declared degradation ladder depth").add(
            summary.get("max_steps", 0))
    yield MetricFamily(
        "mmlspark_brownout_active", "gauge",
        "1 while at least one degradation step is applied").add(
            1.0 if summary.get("active") else 0.0)
    trans = MetricFamily(
        "mmlspark_brownout_transitions_total", "counter",
        "brownout transitions by direction (degrade/restore/rollback)")
    for direction, n in (summary.get("transitions") or {}).items():
        f = _num(n)
        if f is not None:
            trans.add(f, {"direction": str(direction)})
    yield trans


def _fleet_families(summary: Dict[str, Any]) -> Iterable[MetricFamily]:
    """Fleet controller state (serving/fleet): the capacity
    recommendation an external scaler keys on, the demand forecast
    behind it, and the decision counters — mmlspark_capacity_* per
    docs/observability.md."""
    rec = summary.get("recommended_replicas")
    f = _num(rec)
    if f is not None:
        yield MetricFamily(
            "mmlspark_capacity_recommended_replicas", "gauge",
            "planner-recommended replica count (the HPA signal)").add(f)
    f = _num((summary.get("forecast") or {}).get("forecast_rps"))
    if f is not None:
        yield MetricFamily(
            "mmlspark_capacity_forecast_rps", "gauge",
            "forecast arrival rate (rows/s) at the planning horizon"
        ).add(f)
    dec = MetricFamily(
        "mmlspark_capacity_decisions_total", "counter",
        "fleet controller decisions by kind "
        "(scale_out / scale_in / rollback / held_degraded)")
    for kind, n in (summary.get("decisions") or {}).items():
        f = _num(n)
        if f is not None:
            dec.add(f, {"decision": str(kind)})
    yield dec


def _hedge_families(summary: Dict[str, Any]) -> Iterable[MetricFamily]:
    """Hedged-request accounting (serving/supervisor.py HedgeTracker):
    volume by outcome, win attribution, and the live quantile delay —
    mmlspark_hedge_* per docs/observability.md."""
    reqs = MetricFamily(
        "mmlspark_hedge_requests_total", "counter",
        "hedge-eligible public requests by outcome "
        "(hedged / suppressed / both_failed)")
    for key in ("hedged", "suppressed", "both_failed"):
        f = _num(summary.get(key))
        if f is not None:
            reqs.add(f, {"outcome": key})
    yield reqs
    wins = MetricFamily(
        "mmlspark_hedge_wins_total", "counter",
        "first-response winners by role (primary / hedge)")
    for role, key in (("primary", "wins_primary"), ("hedge", "wins_hedge")):
        f = _num(summary.get(key))
        if f is not None:
            wins.add(f, {"role": role})
    yield wins
    f = _num(summary.get("delay_ms"))
    if f is not None:
        yield MetricFamily(
            "mmlspark_hedge_delay_ms", "gauge",
            "current hedge trigger delay (the configured quantile of "
            "observed forward latency)").add(f)
    f = _num(summary.get("hedge_fraction"))
    if f is not None:
        yield MetricFamily(
            "mmlspark_hedge_fraction", "gauge",
            "hedged / eligible requests (the duplicate-work bound)").add(f)


def _lifecycle_families(summary: Dict[str, Any]) -> Iterable[MetricFamily]:
    """Model lifecycle state (serving/lifecycle): one series per
    registered version — state, traffic share, served batches, shadow
    scoring, burn — mmlspark_model_* per docs/lifecycle.md."""
    reg = summary.get("registry") or {}
    versions = reg.get("versions") or []
    info = MetricFamily(
        "mmlspark_model_info", "gauge",
        "registered model versions (1 per version; state as a label)")
    share = MetricFamily(
        "mmlspark_model_traffic_share", "gauge",
        "fraction of real traffic routed to the version")
    reqs = MetricFamily(
        "mmlspark_model_requests_total", "counter",
        "batches served per version by role (live / canary)")
    scored = MetricFamily(
        "mmlspark_model_shadow_scored_total", "counter",
        "shadow rows compared against the incumbent")
    diverged = MetricFamily(
        "mmlspark_model_divergence_total", "counter",
        "shadow rows outside the per-dtype tolerance")
    burn = MetricFamily(
        "mmlspark_model_burn_rate", "gauge",
        "per-version SLO burn rate by window")
    for v in versions:
        vid = str(v.get("version"))
        info.add(1.0, {"version": vid, "state": str(v.get("state")),
                       "digest": str(v.get("digest"))})
        f = _num(v.get("traffic_share"))
        if f is not None:
            share.add(f, {"version": vid})
        for role, n in (v.get("requests") or {}).items():
            f = _num(n)
            if f is not None:
                reqs.add(f, {"version": vid, "role": str(role)})
        shadow = v.get("shadow") or {}
        f = _num(shadow.get("scored"))
        if f is not None:
            scored.add(f, {"version": vid})
        f = _num(shadow.get("divergent"))
        if f is not None:
            diverged.add(f, {"version": vid})
        for window, rate in (v.get("burn") or {}).items():
            f = _num(rate)
            if f is not None:
                burn.add(f, {"version": vid, "window": str(window)})
    yield info
    yield share
    yield reqs
    yield scored
    yield diverged
    yield burn
    trans = MetricFamily(
        "mmlspark_model_transitions_total", "counter",
        "registry lifecycle actions (register / transition / promote)")
    for action, n in (reg.get("transitions") or {}).items():
        f = _num(n)
        if f is not None:
            trans.add(f, {"action": str(action)})
    yield trans
    canary = summary.get("canary") or {}
    rolls = MetricFamily(
        "mmlspark_model_rollouts_total", "counter",
        "rollout outcomes (started / promoted / rolled_back)")
    for key, outcome in (("rollouts", "started"), ("promotions", "promoted"),
                         ("rollbacks", "rolled_back")):
        f = _num(canary.get(key))
        if f is not None:
            rolls.add(f, {"outcome": outcome})
    yield rolls


def _mall_families(summary: Dict[str, Any]) -> Iterable[MetricFamily]:
    """Model-mall state (serving/multimodel): per-model residency and
    traffic, eviction / re-warm accounting, packing idle share, and the
    idle-capacity AutoML trial counters — mmlspark_mall_* per
    docs/multimodel.md. Absent entirely while multimodel=None (the
    bitwise-parity contract)."""
    models = summary.get("models") or {}
    info = MetricFamily(
        "mmlspark_mall_model_info", "gauge",
        "admitted models (1 per model; residency state as a label)")
    reqs = MetricFamily(
        "mmlspark_mall_requests_total", "counter",
        "rows routed per model")
    svc = MetricFamily(
        "mmlspark_mall_service_ms", "gauge",
        "measured per-row service EWMA (ms) per model — the packing "
        "planner's probe-graduated cost input")
    rewarms = MetricFamily(
        "mmlspark_mall_rewarms_total", "counter",
        "tier restores per model (evicted model taking traffic again)")
    rewarm_s = MetricFamily(
        "mmlspark_mall_rewarm_seconds_total", "counter",
        "accounted wall seconds spent re-warming per model")
    for name, m in models.items():
        lbl = {"model": str(name)}
        info.add(1.0, {**lbl, "state": str(m.get("state")),
                       "default": "true" if m.get("default") else "false"})
        for fam, key in ((reqs, "requests"), (svc, "service_ms"),
                         (rewarms, "rewarms"),
                         (rewarm_s, "rewarm_seconds")):
            f = _num(m.get(key))
            if f is not None:
                fam.add(f, lbl)
    yield info
    yield reqs
    yield svc
    yield rewarms
    yield rewarm_s
    counters = summary.get("counters") or {}
    ev = MetricFamily(
        "mmlspark_mall_evictions_total", "counter",
        "models parked to the persistent/object-store tier by outcome "
        "(clean / crashed — crashed means the mall.evict seam fired "
        "mid-evict and the tier copy now serves)")
    f = _num(counters.get("evictions"))
    crashed = _num(counters.get("evict_crashes")) or 0.0
    if f is not None:
        ev.add(max(0.0, f - crashed), {"outcome": "clean"})
        ev.add(crashed, {"outcome": "crashed"})
    yield ev
    for key, mname, doc in (
            ("swaps", "mmlspark_mall_swaps_total",
             "per-model live-pointer promotions applied by the mall"),
            ("unknown_requests", "mmlspark_mall_unknown_requests_total",
             "rows naming a model the mall never admitted (shed 404)")):
        f = _num(counters.get(key))
        if f is not None:
            yield MetricFamily(mname, "counter", doc).add(f)
    packing = summary.get("packing") or {}
    current = packing.get("current") or {}
    f = _num(current.get("idle_share"))
    if f is not None:
        yield MetricFamily(
            "mmlspark_mall_packing_idle_share", "gauge",
            "fraction of fleet service capacity the current packing plan "
            "leaves idle (the AutoML trial budget)").add(f)
    f = _num(packing.get("plans_total"))
    if f is not None:
        yield MetricFamily(
            "mmlspark_mall_packing_plans_total", "counter",
            "packing plans journaled (each with one-step rollback)").add(f)
    automl = summary.get("automl")
    if automl:
        trials = MetricFamily(
            "mmlspark_mall_trials_total", "counter",
            "idle-capacity AutoML trials by outcome (started / promoted "
            "/ shed / rolled_back)")
        for key, outcome in (("trials_started", "started"),
                             ("trials_promoted", "promoted"),
                             ("trials_shed", "shed"),
                             ("trials_rolled_back", "rolled_back")):
            f = _num(automl.get(key))
            if f is not None:
                trials.add(f, {"outcome": outcome})
        yield trials


def fold_server(registry: MetricsRegistry, server: Any) -> None:
    """Register collectors reading a ServingServer's live stats surfaces:
    LatencyStats window + shed counters, the admission queue, wire-format
    and tenant admission counters, the async executor, and the
    ingest/fusion providers when wired (serve_pipeline).
    Safe to call before start() — everything is read at scrape time."""

    def collect() -> List[MetricFamily]:
        fams: List[MetricFamily] = []
        fams.append(MetricFamily(
            "mmlspark_requests_served_total", "counter",
            "requests answered (all statuses) since process start").add(
                server.requests_served))
        fams.append(MetricFamily(
            "mmlspark_queue_depth", "gauge",
            "requests waiting for a batch slot").add(server._queue.qsize()))
        fams.append(MetricFamily(
            "mmlspark_draining", "gauge",
            "1 while the server refuses new work (graceful stop)").add(
                1.0 if server._draining.is_set() else 0.0))
        fams.extend(_latency_families(server.stats.summary()))
        if getattr(server, "_wire_lock", None) is not None:
            fams.extend(_wire_families(server))
        if getattr(server, "_tenants", None) is not None:
            fams.extend(_tenant_families(server._tenants.summary()))
        if server._executor is not None:
            try:
                fams.extend(_executor_families(server._executor.stats()))
            except Exception:  # noqa: BLE001 — executor mid-shutdown
                pass
        if getattr(server, "_tuner", None) is not None:
            try:
                fams.extend(_tuner_families(server._tuner.stats()))
            except Exception:  # noqa: BLE001 — tuner mid-refit
                pass
        if getattr(server, "_brownout", None) is not None:
            try:
                fams.extend(_brownout_families(server._brownout.summary()))
            except Exception:  # noqa: BLE001 — brownout mid-transition
                pass
        if getattr(server, "_fleet", None) is not None:
            try:
                fams.extend(_fleet_families(server._fleet.summary()))
            except Exception:  # noqa: BLE001 — fleet mid-plan
                pass
        if getattr(server, "_lifecycle", None) is not None:
            try:
                fams.extend(_lifecycle_families(server._lifecycle.summary()))
            except Exception:  # noqa: BLE001 — rollout mid-transition
                pass
        if getattr(server, "_multimodel", None) is not None:
            try:
                fams.extend(_mall_families(server._multimodel.summary()))
            except Exception:  # noqa: BLE001 — mall mid-evict
                pass
        if server.ingest_stats is not None:
            try:
                s = server.ingest_stats()
                if s:
                    fams.extend(_ingest_families(s))
            except Exception:  # noqa: BLE001
                pass
        if server.fusion_stats is not None:
            try:
                s = server.fusion_stats()
                if s:
                    fams.extend(_fusion_families(s))
            except Exception:  # noqa: BLE001
                pass
        return fams

    registry.register_collector(collect)


# ---------------------------------------------------------------------------
# RoutingFront
# ---------------------------------------------------------------------------


def _fabric_families(summary: Dict[str, Any]) -> Iterable[MetricFamily]:
    """Front-fabric state (serving/fabric): the consistent-hash ring's
    epoch and membership, per-cell affinity accounting, and the drain /
    re-hash counters — mmlspark_ring_* / mmlspark_cell_* per
    docs/observability.md. Absent entirely when the fabric is off, so
    the single-front exposition stays byte-identical."""
    ring = summary.get("ring") or {}
    f = _num(ring.get("epoch"))
    if f is not None:
        yield MetricFamily(
            "mmlspark_ring_epoch", "gauge",
            "consistent-hash ring epoch (bumps once per journaled "
            "membership transition)").add(f)
    cells = ring.get("cells") or {}
    byst = MetricFamily(
        "mmlspark_ring_cells", "gauge",
        "ring members by state (up / draining)")
    for state in ("up", "draining"):
        byst.add(float(sum(1 for s in cells.values() if s == state)),
                 {"state": state})
    yield byst
    trans = MetricFamily(
        "mmlspark_ring_transitions_total", "counter",
        "ring membership transitions by kind (rebalance / rollback / "
        "failed / journal_error)")
    for kind, key in (("rebalance", "rebalances"), ("rollback", "rollbacks"),
                      ("failed", "rebalance_failures"),
                      ("journal_error", "journal_errors")):
        f = _num(ring.get(key))
        if f is not None:
            trans.add(f, {"kind": kind})
    yield trans
    st = MetricFamily(
        "mmlspark_cell_state", "gauge",
        "one-hot ring state per L2 cell (up / draining)")
    for cell, s in cells.items():
        for name in ("up", "draining"):
            st.add(1.0 if s == name else 0.0,
                   {"cell": str(cell), "state": name})
    yield st
    infl = MetricFamily(
        "mmlspark_cell_inflight", "gauge",
        "requests in flight to each L2 cell (the drain flush gate)")
    for cell, n in (summary.get("inflight") or {}).items():
        f = _num(n)
        if f is not None:
            infl.add(f, {"cell": str(cell)})
    yield infl
    f = _num(summary.get("assignments"))
    if f is not None:
        yield MetricFamily(
            "mmlspark_cell_assignments_total", "counter",
            "affinity-key routing decisions made by the ring").add(f)
    f = _num(summary.get("rehashes"))
    if f is not None:
        yield MetricFamily(
            "mmlspark_cell_rehashes_total", "counter",
            "assignments whose ring-preferred cell was unroutable and "
            "re-hashed to a survivor").add(f)
    f = _num(summary.get("drains"))
    if f is not None:
        yield MetricFamily(
            "mmlspark_cell_drains_total", "counter",
            "planned drain-and-shift cycles completed").add(f)


def fold_front(registry: MetricsRegistry, front: Any) -> None:
    """Register collectors for a RoutingFront: registered-worker count,
    one-hot circuit-breaker states, and capacity weights."""

    def collect() -> List[MetricFamily]:
        states = front.worker_states
        caps = front.worker_capacities
        fams = [MetricFamily(
            "mmlspark_workers", "gauge",
            "registered workers by routability").add(
                sum(1 for s in states.values() if s != "open"),
                {"routable": "true"}).add(
                sum(1 for s in states.values() if s == "open"),
                {"routable": "false"})]
        st = MetricFamily(
            "mmlspark_worker_circuit_state", "gauge",
            "one-hot circuit-breaker state per worker "
            "(closed/half_open/open)")
        for w, s in states.items():
            for name in ("closed", "half_open", "open"):
                st.add(1.0 if s == name else 0.0,
                       {"worker": w, "state": name})
        fams.append(st)
        cap = MetricFamily("mmlspark_worker_capacity", "gauge",
                           "concurrent-batch capacity weight per worker")
        for w, c in caps.items():
            cap.add(c, {"worker": w})
        fams.append(cap)
        if getattr(front, "_hedge", None) is not None:
            try:
                fams.extend(_hedge_families(front._hedge.summary()))
            except Exception:  # noqa: BLE001 — tracker mid-update
                pass
        if getattr(front, "_fabric", None) is not None:
            try:
                fams.extend(_fabric_families(front._fabric.summary()))
            except Exception:  # noqa: BLE001 — ring mid-transition
                pass
        return fams

    registry.register_collector(collect)


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------


def fold_tracer(registry: MetricsRegistry, tracer: Any) -> None:
    """Trace-pipeline health: originated/joined/head-dropped traces and the
    buffered span count, so sampling behavior is itself observable."""

    def collect() -> List[MetricFamily]:
        s = tracer.stats()
        fams = [MetricFamily(
            "mmlspark_trace_sample_rate", "gauge",
            "head-based sampling probability at this ingress").add(
                s["sample_rate"])]
        tr = MetricFamily("mmlspark_traces_total", "counter",
                          "ingress trace decisions by kind")
        tr.add(s["started"], {"kind": "started"})
        tr.add(s["joined"], {"kind": "joined"})
        tr.add(s["dropped"], {"kind": "dropped"})
        fams.append(tr)
        fams.append(MetricFamily(
            "mmlspark_trace_buffered_spans", "gauge",
            "finished spans held in the tracer ring").add(s["buffered"]))
        return fams

    registry.register_collector(collect)
