"""Pallas TPU kernel for binned histogram accumulation.

The GBDT hot loop (reference: LGBM_BoosterUpdateOneIter's histogram build,
lightgbm/TrainUtils.scala:170-233) is a scatter-add of per-row (grad, hess,
count) triples into [F, B] bins. XLA lowers ``hist.at[idx].add(vals)`` to a
serialized sort-major scatter on TPU — correct but far off the roofline.

This kernel reformulates the scatter as a **one-hot contraction on the MXU**
over FEATURE-MAJOR inputs (bins [F, N], vals [3, N] — minor dim rows, so the
HBM arrays carry no lane padding; an [N, 28] int32 layout tiles 28 -> 128
lanes, a 4.6x HBM blowup that OOMed the 10M-row bench):

    hist[f, b, c] = sum_n (bins[f, n] == b) * vals[c, n]
                  = vals @ onehot_t(bins[f, :]).T          # [3, B] per feature

The transposed one-hot ([B_pad, CHUNK]: the feature row broadcast over
sublanes against a dim-0 iota) is materialized only inside VMEM, one chunk
at a time, and immediately contracted — it never exists in HBM, so HBM
traffic is exactly the input reads (bins, vals) plus one [3, F*B_pad]
accumulator. The grid is 1-D over row chunks with the accumulator block
resident in VMEM across the whole grid (standard Pallas reduction pattern);
the feature dim is never block-sliced — inputs wider than FMAX features are
split into separate pallas_call slabs on the host, bounding the accumulator
at [3, FMAX*B_pad].

Bin counts are padded to a multiple of 128 (the TPU lane width) so every
slice write is tile-aligned; features are padded to the feature-tile size.
Padded rows/features contribute zero because ``vals`` is pre-masked.

Dispatch: ``histogram.compute_histogram`` routes here when the default backend
is TPU (env ``MMLSPARK_TPU_NO_PALLAS=1`` forces the XLA path). On CPU the
kernel runs in interpreter mode for tests only.

Measured on TPU v5e (1 chip, tunneled), N=100k rows, F=32, B=256, f32, via
tools/bench_hist.py: XLA scatter 125-138 ms/hist vs Pallas MXU 8.1-9.9
ms/hist — 12.9-17.1x across 4 runs (the recorded run in BENCH_hist.json:
125.0 ms vs 9.7 ms, 12.9x; the tunnel adds run-to-run variance). At N=1M
the XLA scatter path fails to compile (temp-buffer OOM: its sort-based
lowering materializes s32[N*F] keys); the Pallas path runs fine.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Row-chunk size: bounds the one-hot VMEM tile ([CHUNK, B_pad] f32 = 256 KB at
# B_pad=128). FMAX bounds features handled per pallas_call — wider inputs are
# processed in host-side slabs so the [3, F*B_pad] accumulator stays in VMEM.
# CHUNK is env-tunable for kernel A/B runs (tools/bench_hist.py).
CHUNK = int(os.environ.get("MMLSPARK_TPU_HIST_CHUNK", "512"))
FMAX = 64


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _hist_kernel(bins_ref, vals_ref, out_ref, *, nf: int, b_pad: int,
                 hilo: bool):
    """One row-chunk grid cell, feature-major layout.

    bins_ref: [nf, CHUNK] int (feature-major: minor dim = rows, so the HBM
    array carries no lane padding — an [N, F] layout tiles F up to 128 lanes,
    a 4.6x HBM blowup at F=28 that OOMed the 10M-row bench),
    vals_ref: [3, CHUNK] f32 (pre-masked channels x rows) — or, in hi/lo
    mode, [5, CHUNK] bf16 (g_hi, g_lo, h_hi, h_lo, mask),
    out_ref:  [3, nf*B_pad] f32 accumulator, VMEM-resident across the grid.

    The one-hot is built TRANSPOSED ([B_pad, CHUNK]: sublane broadcast of the
    feature row against a dim-0 iota) and contracted over rows on the MXU —
    no in-kernel transposes or minor-dim reshapes (Mosaic rejects those).

    ``hilo`` (default on — see hist_hilo() for the N-dependent
    measurements): the one-hot is EXACT in bf16 (0/1), so splitting
    grad/hess into bf16 (hi, lo) pairs turns the 3-pass f32-HIGHEST
    contraction into ONE bf16 MXU pass over 5 channels. Below ~2M rows the
    kernel is VPU/DMA-bound and the modes tie; above, hi/lo wins 1.6x.
    """
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    vals = vals_ref[...]                          # [3, CHUNK] f32 | [5] bf16
    chunk = vals.shape[1]
    iota0 = jax.lax.broadcasted_iota(jnp.int32, (b_pad, chunk), 0)
    for f in range(nf):                                      # static unroll
        col = bins_ref[f : f + 1, :].astype(jnp.int32)       # [1, CHUNK]
        onehot = jnp.broadcast_to(col, (b_pad, chunk)) == iota0
        if hilo:
            acc5 = jax.lax.dot_general(                      # [5, B_pad], 1 pass
                vals, onehot.astype(jnp.bfloat16),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc = jnp.concatenate(
                [acc5[0:1] + acc5[1:2],                      # grad = hi + lo
                 acc5[2:3] + acc5[3:4],                      # hess = hi + lo
                 acc5[4:5]], axis=0)                         # count
        else:
            acc = jax.lax.dot_general(                       # [3, B_pad] on MXU
                vals, onehot.astype(jnp.float32),
                dimension_numbers=(((1,), (1,)), ((), ())),
                # HIGHEST = full-f32 MXU passes: gradient sums feed split
                # gains, and plain bf16 rounding of vals costs ~1e-3 relative
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)
        out_ref[:, f * b_pad : (f + 1) * b_pad] += acc


def _hist_slab(bins_slab, vals, b_pad: int, interpret: bool, hilo: bool,
               chunk: int):
    """[Fs, N_pad] bins + [3|5, N_pad] masked vals -> [3, Fs*b_pad] sums."""
    fs, n_pad = bins_slab.shape
    n_chunks = n_pad // chunk
    nch = vals.shape[0]
    return pl.pallas_call(
        functools.partial(_hist_kernel, nf=fs, b_pad=b_pad, hilo=hilo),
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((fs, chunk), lambda j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nch, chunk), lambda j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((3, fs * b_pad), lambda j: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((3, fs * b_pad), jnp.float32),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * nch * n_pad * fs * b_pad,
            bytes_accessed=bins_slab.size * 4
            + vals.size * vals.dtype.itemsize
            + 3 * fs * b_pad * 4,
            transcendentals=0,
        ),
    )(bins_slab, vals)


def hist_hilo() -> bool:
    """bf16 hi/lo histogram contraction: default ON
    (MMLSPARK_TPU_HIST_EXACT=1 restores the full-f32 3-pass path).

    Measured on the chip (tools/bench_hist.py, F=28, B=256) — the verdict
    FLIPS with N, so both points are recorded:
      - 1M rows: 29.3 ms BOTH modes (kernel bound by VPU one-hot build +
        grid overhead; MXU passes hide) — an isolated small-N A/B wrongly
        suggests hi/lo is free of benefit;
      - 5M rows: exact 280.7 ms vs hi/lo 175.4 ms (1.6x) — past ~2M rows
        the f32-HIGHEST passes dominate and scale superlinearly; in the
        full 10M training scan the difference is ~150 s vs ~109 s.
    Precision: grad bin-sums differ from the f32 scatter by up to ~0.4
    absolute on |sum|~70 cells at 1M rows (sign-biased rounding of the
    bf16 lo term). Model-level effect is measured and recorded in
    BENCH_gbdt_train.json (train_accuracy vs the exact path); the
    histogram noise is far below LightGBM's own quantized-training regime
    (8-bit gradients)."""
    return os.environ.get("MMLSPARK_TPU_HIST_EXACT", "") in ("", "0")


def compute_histogram_mxu(bins_fm, grad, hess, row_mask, num_bins: int,
                          interpret: bool = False,
                          hilo: Optional[bool] = None,
                          chunk: Optional[int] = None):
    """[F,N] feature-major int bins + per-row grad/hess + row mask ->
    [F, num_bins, 3] sums.

    Drop-in replacement for histogram.compute_histogram's XLA scatter path.
    Rows are padded to a CHUNK multiple here; callers that keep N a CHUNK
    multiple (booster.train pads once on host) make the pad a no-op.

    ``hilo`` resolves from the env OUTSIDE the jit boundary so flipping
    MMLSPARK_TPU_HIST_EXACT between calls takes effect (it is a static jit
    arg below — resolving it inside would freeze the first call's value
    into the cache). Jitted callers (the fused tree/scan bodies) resolve it
    at their own trace time. ``chunk`` (row-chunk size — the Tuner's
    ``hist.c*`` kernel variants) resolves from the variant registry the
    same way, falling back to the env-tuned module default.
    """
    if hilo is None:
        hilo = hist_hilo()
    if chunk is None:
        from ..core import kernels as _kernels

        chunk = int(_kernels.active_param("hist", "chunk", CHUNK))
    return _compute_histogram_mxu(bins_fm, grad, hess, row_mask, num_bins,
                                  interpret, hilo, chunk)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "interpret", "hilo", "chunk"))
def _compute_histogram_mxu(bins_fm, grad, hess, row_mask, num_bins: int,
                           interpret: bool, hilo: bool, chunk: int = CHUNK):
    f, n = bins_fm.shape
    b_pad = max(128, _round_up(num_bins, 128))
    n_pad = _round_up(max(n, 1), chunk)

    m = row_mask.astype(jnp.float32)
    g = (grad * m).astype(jnp.float32)
    h = (hess * m).astype(jnp.float32)
    if hilo:
        # channel-major [5, N] bf16: exact one-hot x (hi, lo) value split —
        # one bf16 MXU pass reconstructs ~17 value mantissa bits
        g_hi = g.astype(jnp.bfloat16)
        h_hi = h.astype(jnp.bfloat16)
        vals = jnp.stack([
            g_hi, (g - g_hi.astype(jnp.float32)).astype(jnp.bfloat16),
            h_hi, (h - h_hi.astype(jnp.float32)).astype(jnp.bfloat16),
            m.astype(jnp.bfloat16)], axis=0)
    else:
        # channel-major [3, N]: minor dim rows -> no lane padding (an [N, 3]
        # layout pads 3 -> 128 lanes, a 42x HBM blowup at large N)
        vals = jnp.stack([g, h, m], axis=0)
    vals = jnp.pad(vals, ((0, 0), (0, n_pad - n)))
    bins_p = jnp.pad(bins_fm, ((0, 0), (0, n_pad - n)))

    slabs = []
    for f0 in range(0, f, FMAX):
        fs = min(FMAX, f - f0)
        out = _hist_slab(bins_p[f0 : f0 + fs, :], vals, b_pad, interpret,
                         hilo, chunk)
        slabs.append(out.reshape(3, fs, b_pad))
    hist = jnp.concatenate(slabs, axis=1)        # [3, F, b_pad]
    return hist.transpose(1, 2, 0)[:, :num_bins, :]


def compute_histogram_sharded(bins_fm, grad, hess, row_mask, num_bins: int,
                              interpret: bool = False):
    """Row-sharded variant: per-shard Pallas histogram + psum over the row
    axes — the multi-chip data-parallel path (LightGBM's socket-ring
    allreduce as one XLA collective). ``bins_fm`` is feature-major [F, N]
    and must be a concrete jax.Array with a NamedSharding whose spec shards
    dim 1 (the row dim)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import shard_map_compat as shard_map

    sh = bins_fm.sharding
    mesh = sh.mesh
    row_axes = sh.spec[1]
    specs = (sh.spec, P(row_axes), P(row_axes), P(row_axes))

    # check_vma=False: pallas_call can't declare varying-mesh-axes metadata
    @functools.partial(shard_map, mesh=mesh, in_specs=specs, out_specs=P(),
                       check_vma=False)
    def _go(b, g, h, m):
        local = compute_histogram_mxu(b, g, h, m, num_bins,
                                      interpret=interpret)
        return jax.lax.psum(local, row_axes)

    return _go(bins_fm, grad, hess, row_mask)


def _row_sharded_spec(x):
    """Return True if x is a concrete feature-major [F, N] array with a
    NamedSharding that splits dim 1 (rows) over >1 device (the GBDT
    data-parallel layout)."""
    from jax.sharding import NamedSharding

    if not isinstance(x, jax.Array) or isinstance(x, jax.core.Tracer):
        return False
    sh = getattr(x, "sharding", None)
    if not isinstance(sh, NamedSharding) or len(sh.device_set) <= 1:
        return False
    spec = sh.spec
    return len(spec) > 1 and spec[1] is not None


def dispatch(bins, grad, hess, row_mask, num_bins: int):
    """Backend/sharding-aware histogram dispatch used by
    histogram.compute_histogram. Returns None when the caller should use the
    XLA scatter path (non-TPU backend, traced values, or exotic shardings
    GSPMD already partitions correctly)."""
    if not use_pallas():
        return None
    if isinstance(bins, jax.core.Tracer):
        return None  # inside someone else's jit: let GSPMD lower the scatter
    if _row_sharded_spec(bins):
        return compute_histogram_sharded(bins, grad, hess, row_mask, num_bins)
    if isinstance(bins, jax.Array) and len(bins.sharding.device_set) > 1:
        return None  # replicated/oddly-sharded multi-device input: XLA path
    return compute_histogram_mxu(bins, grad, hess, row_mask, num_bins)


def use_mxu_single_device(bins) -> bool:
    """Should a jitted caller lower its histogram through the single-device
    MXU kernel? (The fused split step's routing — kept here, next to
    dispatch(), so the backend predicates cannot drift apart.) Row-sharded
    inputs must NOT take this path OR the in-jit XLA scatter: they need
    dispatch()'s per-shard kernel + psum."""
    if not use_pallas():
        return False
    if isinstance(bins, jax.core.Tracer):
        return False
    if isinstance(bins, jax.Array) and len(bins.sharding.device_set) > 1:
        return False
    return True


def interpret_mode() -> bool:
    """MMLSPARK_TPU_PALLAS_INTERPRET=1: run the Pallas kernels (histogram,
    tier select) in interpreter mode — CPU test coverage of the MXU paths.
    Single parser so the scan path and the per-tree path cannot diverge."""
    return os.environ.get("MMLSPARK_TPU_PALLAS_INTERPRET",
                          "") not in ("", "0")


def use_pallas() -> bool:
    """True when the Pallas path should be dispatched (TPU backend, not
    disabled via MMLSPARK_TPU_NO_PALLAS)."""
    if os.environ.get("MMLSPARK_TPU_NO_PALLAS", "") not in ("", "0"):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False
