"""Mid-train GBDT checkpointing: interrupt at iteration k, resume bitwise.

The reference's continued-training hooks are model-level (LightGBM
BoosterMerge / init model strings); a preemptible-TPU training loop needs
ITERATION-level resume: the model so far PLUS the loop state that the next
iteration consumes — running scores (f64), the bagging/feature RNG stream,
the persistent bagging mask, and the early-stopping bookkeeping. With all of
that restored, iterations k..N of a resumed run replay the exact computation
of an uninterrupted run, so the final models are identical (bitwise on the
host/CPU loop; the device fast-score path restores f64 scores but its Kahan
residuals restart at zero, so agreement there is ~f32-rounding instead).

Checkpoints are single JSON files written atomically + durably (tmp + fsync
+ rename + dir fsync, core.faults.atomic_write_text): a preemption mid-write
leaves the previous complete checkpoint.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import os
from typing import Any, Dict, Optional

import numpy as np

from ..core.faults import atomic_write_text

CKPT_FORMAT = "mmlspark_tpu.gbdt.ckpt.v1"


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """``train(..., checkpoint=CheckpointConfig(path))``.

    ``every_k``: checkpoint every k completed iterations (and at the end).
    ``resume``: load ``path`` if it exists and continue from its iteration
    (params must match the checkpoint's; mismatch raises).

    Checkpointing pins the fit to the per-iteration host-orchestrated loop —
    the whole-run lax.scan path has no per-iteration host boundary to
    checkpoint at, and the small-fit native engine keeps its loop state in
    C++ — so expect per-iteration dispatch cadence while a checkpoint is
    configured.
    """

    path: str
    every_k: int = 10
    resume: bool = True


def _arr_to_json(a: np.ndarray) -> Dict[str, Any]:
    a = np.ascontiguousarray(a)
    return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
            "dtype": str(a.dtype), "shape": list(a.shape)}


def _arr_from_json(d: Dict[str, Any]) -> np.ndarray:
    return np.frombuffer(base64.b64decode(d["b64"]),
                         dtype=d["dtype"]).reshape(d["shape"]).copy()


def save_checkpoint(path: str, *, params_dict: Dict[str, Any],
                    model_string: str, iteration: int,
                    scores: np.ndarray, rng_state: Dict[str, Any],
                    bag_mask: np.ndarray, best_val: float, best_iter: int,
                    rounds_no_improve: int) -> None:
    payload = json.dumps({
        "format": CKPT_FORMAT,
        "params": _jsonable_params(params_dict),
        "iteration": int(iteration),
        "model": model_string,
        "scores": _arr_to_json(np.asarray(scores, dtype=np.float64)),
        "rng_state": rng_state,
        "bag_mask": _arr_to_json(np.asarray(bag_mask, dtype=bool)),
        "best_val": float(best_val),
        "best_iter": int(best_iter),
        "rounds_no_improve": int(rounds_no_improve),
    })
    atomic_write_text(path, payload)


def load_checkpoint(path: str) -> Optional[Dict[str, Any]]:
    """Parsed checkpoint dict (arrays decoded), or None when absent."""
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        d = json.load(fh)
    if d.get("format") != CKPT_FORMAT:
        raise ValueError(f"bad checkpoint format {d.get('format')!r} "
                         f"in {path!r}")
    d["scores"] = _arr_from_json(d["scores"])
    d["bag_mask"] = _arr_from_json(d["bag_mask"])
    return d


def _jsonable_params(params_dict: Dict[str, Any]) -> Dict[str, Any]:
    """TrainParams asdict with tuples as lists (JSON round-trip stable)."""
    return {k: (list(v) if isinstance(v, tuple) else v)
            for k, v in params_dict.items()}


def check_params_match(saved: Dict[str, Any],
                       current: Dict[str, Any], path: str) -> None:
    cur = _jsonable_params(current)
    if saved != cur:
        diff = sorted(k for k in set(saved) | set(cur)
                      if saved.get(k) != cur.get(k))
        raise ValueError(
            f"checkpoint {path!r} was written with different train params "
            f"(mismatched: {diff}); refusing to resume — delete the "
            f"checkpoint or restore the original params")
