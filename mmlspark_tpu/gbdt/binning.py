"""Quantile feature binning: float matrix -> uint8/int16 bin indices.

Equivalent of LightGBM's Dataset construction (driven by the reference at
lightgbm/LightGBMUtils.scala:199-252 via LGBM_DatasetCreateFromMat): per-feature
quantile-spaced bin edges, reserved bin for missing values, categorical features
binned by value identity.

Binning is a one-time host/device preprocessing step; the binned matrix is what
lives in device HBM during training (4-8x smaller than float32 features).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class BinMapper:
    """Per-feature bin edges; maps float features -> integer bins.

    Bin layout per feature (LightGBM convention):
      - bin 0 reserved for missing (NaN)
      - bins 1..num_bins(f)-1 are value bins, upper-edge inclusive
    """

    edges: List[np.ndarray]              # per feature: ascending inner edges
    categorical: List[bool]
    categories: Dict[int, np.ndarray]    # feature -> sorted category values
    max_bin: int = 255

    @property
    def num_features(self) -> int:
        return len(self.edges)

    def num_bins(self, f: int) -> int:
        if self.categorical[f]:
            return len(self.categories[f]) + 1
        return len(self.edges[f]) + 2  # missing + (len+1) value bins

    @property
    def max_num_bins(self) -> int:
        return max((self.num_bins(f) for f in range(self.num_features)), default=1)

    @staticmethod
    def fit(X: np.ndarray, max_bin: int = 255,
            categorical_indexes: Sequence[int] = (),
            sample_cnt: int = 200_000, seed: int = 0,
            max_bin_by_feature: Sequence[int] = ()) -> "BinMapper":
        """Compute quantile edges from (a sample of) the data
        (LightGBM bin_construct_sample_cnt semantics).

        ``max_bin_by_feature``: per-feature bin counts overriding ``max_bin``
        outright — in either direction, like LightGBM's max_bin_by_feature
        (empty = uniform ``max_bin``)."""
        n, num_f = X.shape
        rng = np.random.default_rng(seed)
        if n > sample_cnt:
            idx = rng.choice(n, sample_cnt, replace=False)
            sample = X[idx]
        else:
            sample = X
        cat = set(categorical_indexes)
        caps = list(max_bin_by_feature) if max_bin_by_feature else []
        if caps and len(caps) != num_f:
            raise ValueError(
                f"max_bin_by_feature has {len(caps)} entries for {num_f} "
                f"features")
        edges: List[np.ndarray] = []
        categorical: List[bool] = []
        categories: Dict[int, np.ndarray] = {}
        for f in range(num_f):
            fmax = int(caps[f]) if caps else max_bin
            if not 2 <= fmax <= 65535:
                what = f"max_bin_by_feature[{f}]" if caps else "max_bin"
                raise ValueError(f"{what}={fmax} must be in [2, 65535]")
            col = sample[:, f]
            col = col[~np.isnan(col)]
            if f in cat:
                # inf is not a representable category either: int64 cast of
                # non-finite values is platform-defined (and warns)
                col = col[np.isfinite(col)]
                vals = np.unique(col.astype(np.int64)) if col.size else np.array([0])
                categories[f] = vals[: fmax - 1]
                edges.append(np.empty(0))
                categorical.append(True)
                continue
            categorical.append(False)
            uniq = np.unique(col)
            if len(uniq) <= 1:
                edges.append(np.empty(0))
                continue
            if len(uniq) <= fmax - 1:
                # one bin per distinct value: edges at midpoints
                e = (uniq[:-1] + uniq[1:]) / 2.0
            else:
                qs = np.linspace(0, 1, fmax)[1:-1]
                e = np.unique(np.quantile(col, qs))
            edges.append(e.astype(np.float64))
        return BinMapper(edges, categorical, categories, max_bin)

    def transform_col(self, f: int, col: np.ndarray) -> np.ndarray:
        """One feature column -> int32 bins (0 = missing)."""
        if self.categorical[f]:
            # cast only the FINITE entries: NaN/inf->int64 is a
            # platform-defined cast (and warns); missing stays bin 0, as
            # does any category outside the learned set (LightGBM missing
            # semantics, ref lightgbm/TrainParams.scala)
            cats = self.categories[f]
            out = np.zeros(len(col), dtype=np.int32)
            valid = np.isfinite(col)
            iv = col[valid].astype(np.int64)
            pos = np.clip(np.searchsorted(cats, iv), 0, len(cats) - 1)
            out[valid] = np.where(cats[pos] == iv, pos + 1, 0)
            return out
        edges = self.edges[f]
        if len(edges) >= 8 and len(col) >= 4096 and col.dtype == np.float64:
            # native single-sweep binning (NaN handled in the kernel); the
            # numpy path below is the parity reference and fallback
            from .. import native_loader

            out = native_loader.bin_column(col, edges)
            if out is not None:
                return out
        miss = np.isnan(col)
        bins = np.searchsorted(edges, col, side="left") + 1
        return np.where(miss, 0, bins).astype(np.int32)

    def transform(self, X: np.ndarray) -> np.ndarray:
        """Float [N,F] -> int32 bins [N,F] (0 = missing)."""
        n, num_f = X.shape
        if num_f != self.num_features:
            # explicit check: under `python -O` a bare assert disappears and
            # mismatched widths would bin silently against wrong edges
            raise ValueError(f"feature count {num_f} != fitted "
                             f"{self.num_features}")
        out = np.zeros((n, num_f), dtype=np.int32)
        for f in range(num_f):
            out[:, f] = self.transform_col(f, X[:, f])
        return out

    def transform_fm(self, X: np.ndarray, dtype=np.int32,
                     n_threads: int = 0) -> np.ndarray:
        """Float [N,F] -> FEATURE-MAJOR bins [F,N] (the device column-store
        layout), binning columns in parallel — np.searchsorted releases the
        GIL, so the 10M-row transform drops from ~30 s single-threaded to
        the per-core share (tools/profile_gbdt_10m.py)."""
        import concurrent.futures
        import os

        n, num_f = X.shape
        if num_f != self.num_features:
            raise ValueError(f"feature count {num_f} != fitted "
                             f"{self.num_features}")
        if (not any(self.categorical) and dtype in (np.uint8, np.int32)
                and X.dtype == np.float64 and n * num_f >= 1 << 18):
            # native whole-matrix pass: streams row-major X ONCE instead of
            # re-reading the strided matrix per column (the measured
            # bottleneck of the per-column path at 200k x 28)
            from .. import native_loader

            out = native_loader.bin_matrix(X, self.edges, dtype)
            if out is not None:
                return out
        out = np.empty((num_f, n), dtype=dtype)
        n_threads = n_threads or min(num_f, os.cpu_count() or 1)
        if n_threads <= 1 or n * num_f < 1 << 22:
            for f in range(num_f):
                out[f] = self.transform_col(f, np.ascontiguousarray(X[:, f]))
            return out

        def _one(f):
            out[f] = self.transform_col(f, np.ascontiguousarray(X[:, f]))

        with concurrent.futures.ThreadPoolExecutor(n_threads) as pool:
            list(pool.map(_one, range(num_f)))
        return out

    def bin_upper_value(self, f: int, b: int) -> float:
        """Real-valued threshold for 'bin <= b' splits (used at predict time so the
        model evaluates raw floats, like LightGBM's stored tree thresholds).

        Categorical features: categories are stored sorted ascending, so bin order
        equals value order and 'bin <= b' is exactly 'value <= categories[b-1]'
        (an ordered-split approximation of LightGBM's category subsets; unseen
        categories follow the threshold rather than the missing direction)."""
        if b <= 0:
            return -np.inf
        if self.categorical[f]:
            cats = self.categories[f]
            return float(cats[b - 1]) if b - 1 < len(cats) else np.inf
        e = self.edges[f]
        if b - 1 < len(e):
            return float(e[b - 1])
        return np.inf

    def to_json(self) -> dict:
        return {
            "max_bin": self.max_bin,
            "edges": [e.tolist() for e in self.edges],
            "categorical": list(self.categorical),
            "categories": {str(k): v.tolist() for k, v in self.categories.items()},
        }

    @staticmethod
    def from_json(d: dict) -> "BinMapper":
        return BinMapper(
            edges=[np.asarray(e, dtype=np.float64) for e in d["edges"]],
            categorical=list(d["categorical"]),
            categories={int(k): np.asarray(v, dtype=np.int64)
                        for k, v in d["categories"].items()},
            max_bin=d["max_bin"],
        )
