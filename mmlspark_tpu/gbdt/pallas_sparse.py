"""Pallas TPU kernels for the CSR sparse path (docs/sparse.md).

Two device kernels ride the CSR wire triple ``(indptr, indices, values)``
that core/fusion.py stages for a sparse-capable segment:

  - **CSR gather** (``csr_gather``): wire triple -> the dense ``[N, U]``
    matrix of the forest's *used* feature columns — the only columns the
    traversal ever reads. ``U = |used features|`` is forest-sized (tens to
    hundreds), not data-sized (VW widths, 2^18+), so the gather replaces an
    ``N x width`` densify with an ``N x U`` one: bytes scale with nnz + the
    forest, not the feature space. The XLA formulation is one global
    ``searchsorted`` over composite ``row * width + index`` keys (CSR rows
    are sorted, so the flat key array is globally ascending — the same
    trick as sparse.predict_csr's lookup); the Pallas formulation contracts
    transposed one-hots on the MXU, chunk by chunk, like pallas_hist.py.
    Both are EXACT: every output cell receives at most one nonzero (CSR
    rows carry distinct indices), and f32 adds of zeros are exact, so the
    two formulations — and the densify path they replace — are bitwise
    equal.

  - **Sparse histogram** (``sparse_histogram_mxu``): the GBDT sparse
    engine's nonzero-entry histogram ([3, total_bins] grad/hess/count sums
    over the flat ragged bin space) as a one-hot MXU contraction over nnz
    chunks — the sparse sibling of pallas_hist's dense kernel, hooked into
    sparse._flat_histogram behind the ``hist.csr`` kernel variant. Unlike
    the gather, bins accumulate MANY entries, so chunk order changes the
    f32 summation order versus the prefix-sum path: the variant declares a
    tolerance (core/kernels.py) instead of bitwise equality.

Parity contract for the gather (enforced in tests/test_sparse_e2e.py):
``csr_gather(triple, width, used)[:, u]`` is bitwise-equal to
``densify(triple, width)[:, min(used[u], width - 1)]`` — including the
upper clamp, because the dense traversal reads features through
``take_along_axis``/advanced indexing, which XLA clamps out-of-range.
Padded CSR tail entries (fusion pads nnz to a power-of-two bucket)
resolve to row ``N`` in composite-key space — past every real query, so
they can never alias a live cell.

``remap_ensemble`` rewrites a DeviceEnsemble's feature ids into positions
in the used-feature set so the unmodified traversal kernels (gather loop
and path-matrix GEMM, gbdt/predict.py) run on the compacted ``[N, U]``
matrix: internal-node features remap by position, leaf markers (-1) and
GEMM pad slots (ivalid == 0) stay inert exactly as on the dense path.

Dispatch mirrors pallas_hist.py: the Pallas kernels run on TPU (or in
interpreter mode for CPU tests, MMLSPARK_TPU_PALLAS_INTERPRET=1); every
other configuration takes the XLA formulation, which is what the CPU test
suite and the serving bench exercise.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import numpy as np

# Row-chunk size for the one-hot contractions (bounds the [*, CHUNK] VMEM
# tiles); env-tunable for kernel A/B runs like pallas_hist.CHUNK.
CHUNK = int(os.environ.get("MMLSPARK_TPU_SPARSE_CHUNK", "512"))
#: VMEM guard for the gather accumulator [N, U_pad] f32 (~8 MB).
_GATHER_MAX_CELLS = 1 << 21
#: VMEM guard for the sparse-hist accumulator [3, TB_pad] f32 (~1.5 MB).
_SPARSE_HIST_MAX_TB = 128 * 1024


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# used-feature set + ensemble remap (host, once per forest)
# ---------------------------------------------------------------------------


def used_features(ens) -> np.ndarray:
    """Sorted unique feature ids the forest's internal nodes read (i64).
    Never empty: an all-leaf forest reads no features, but the traversal
    kernels still gather column 0 through the leaf markers — keep one
    column so the compacted matrix has a valid shape."""
    feats = np.asarray(ens.feature)
    pos = np.unique(feats[feats >= 0]).astype(np.int64)
    if len(pos) == 0:
        pos = np.zeros(1, dtype=np.int64)
    return pos


def remap_ensemble(ens, used: np.ndarray):
    """A shallow-copied DeviceEnsemble whose feature ids are POSITIONS in
    ``used`` — ready to traverse the compacted [N, U] matrix csr_gather
    produces. Leaf markers (-1) are kept; GEMM pad slots (ivalid == 0,
    feature 0) map to a clipped in-range position, where their sign
    products are zeroed exactly as on the dense path. Compiled-forward
    caches are reset so the remapped copy traces its own programs."""
    import copy

    used = np.asarray(used, dtype=np.int64)
    remapped = copy.copy(ens)
    feats = np.asarray(ens.feature)
    pos = np.searchsorted(used, np.maximum(feats.astype(np.int64), 0))
    pos = np.minimum(pos, len(used) - 1)
    remapped.feature = np.where(feats >= 0, pos, feats).astype(feats.dtype)
    if getattr(ens, "_gemm", None) is not None:
        feat_g, thr, dl, ivalid, C, plen, lval = ens._gemm
        gpos = np.searchsorted(used, np.asarray(feat_g, dtype=np.int64))
        gpos = np.minimum(gpos, len(used) - 1)
        remapped._gemm = (gpos.astype(np.asarray(feat_g).dtype), thr, dl,
                          ivalid, C, plen, lval)
    remapped._jitted = None
    remapped._jitted_gather = None
    return remapped


# ---------------------------------------------------------------------------
# CSR gather: wire triple -> [N, U] used-feature matrix
# ---------------------------------------------------------------------------


def _csr_row_of(indptr, nnz: int):
    """Row id per CSR entry position (traced). Padded tail positions
    (>= indptr[-1]) land on row N — past every composite-key query."""
    import jax.numpy as jnp

    j = jnp.arange(nnz, dtype=jnp.int32)
    return (jnp.searchsorted(indptr.astype(jnp.int32), j, side="right")
            .astype(jnp.int32) - 1)


def csr_gather_xla(indptr, indices, values, width, used):
    """XLA formulation: one searchsorted over globally ascending composite
    ``row * width + index`` keys answers all N x U "value of feature u in
    row n" lookups at once (absent -> 0.0, exactly the densify fill)."""
    import jax.numpy as jnp

    n = indptr.shape[0] - 1
    nnz = indices.shape[0]
    w = jnp.asarray(width, dtype=jnp.int32)
    used_q = jnp.minimum(jnp.asarray(used, dtype=jnp.int32), w - 1)
    row_of = _csr_row_of(indptr, nnz)
    key = row_of * w + indices.astype(jnp.int32)
    q = (jnp.arange(n, dtype=jnp.int32)[:, None] * w
         + used_q[None, :]).reshape(-1)
    pos = jnp.searchsorted(key, q)
    pos_c = jnp.minimum(pos, nnz - 1)
    ok = (pos < nnz) & (jnp.take(key, pos_c) == q)
    x = jnp.where(ok, jnp.take(values, pos_c), jnp.float32(0.0))
    return x.reshape(n, used_q.shape[0]).astype(jnp.float32)


def _gather_kernel(row_ref, idx_ref, val_ref, uq_ref, out_ref):
    """One nnz-chunk grid cell of the Pallas gather.

    row_ref/idx_ref: [1, CHUNK] i32 (entry row / feature id; padded rows
    are out of range -> all-zero row one-hot), val_ref: [1, CHUNK] f32,
    uq_ref: [U_pad, 1] i32 (clamped used-feature column, full block),
    out_ref: [N_pad, U_pad] f32 accumulator, VMEM-resident across the grid.

    out[n, u] += sum_k (row[k] == n) * (uq[u] == idx[k]) * val[k] — both
    one-hots built transposed against dim-0 iotas (the pallas_hist idiom;
    no in-kernel transposes), contracted over the chunk on the MXU. At
    most one k matches any (n, u), so the f32 accumulation is exact.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    n_pad, u_pad = out_ref.shape
    chunk = row_ref.shape[1]
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (n_pad, chunk), 0)
    row_onehot = (jnp.broadcast_to(row_ref[...], (n_pad, chunk))
                  == iota_n).astype(jnp.float32)              # [N_pad, CHUNK]
    feat_onehot = (jnp.broadcast_to(uq_ref[...], (u_pad, chunk))
                   == jnp.broadcast_to(idx_ref[...], (u_pad, chunk)))
    contrib = feat_onehot.astype(jnp.float32) \
        * jnp.broadcast_to(val_ref[...], (u_pad, chunk))      # [U_pad, CHUNK]
    out_ref[...] += jax.lax.dot_general(
        row_onehot, contrib,
        dimension_numbers=(((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)


def csr_gather_pallas(indptr, indices, values, width, used,
                      interpret: bool = False):
    """MXU formulation of csr_gather: one-hot contraction per nnz chunk.
    Bitwise-equal to csr_gather_xla (at most one hit per output cell)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = indptr.shape[0] - 1
    nnz = indices.shape[0]
    u = int(np.shape(used)[0])
    w = jnp.asarray(width, dtype=jnp.int32)
    used_q = jnp.minimum(jnp.asarray(used, dtype=jnp.int32), w - 1)

    n_pad = _round_up(max(n, 8), 8)
    u_pad = _round_up(max(u, 128), 128)
    nnz_pad = _round_up(max(nnz, 1), CHUNK)
    row_of = _csr_row_of(indptr, nnz)
    # kernel pad entries: out-of-range row (-1) zeroes the row one-hot
    row2 = jnp.full((1, nnz_pad), -1, dtype=jnp.int32)
    row2 = row2.at[0, :nnz].set(row_of)
    idx2 = jnp.zeros((1, nnz_pad), dtype=jnp.int32)
    idx2 = idx2.at[0, :nnz].set(indices.astype(jnp.int32))
    val2 = jnp.zeros((1, nnz_pad), dtype=jnp.float32)
    val2 = val2.at[0, :nnz].set(values.astype(jnp.float32))
    uq2 = jnp.full((u_pad, 1), -1, dtype=jnp.int32)
    uq2 = uq2.at[:u, 0].set(used_q)

    out = pl.pallas_call(
        _gather_kernel,
        grid=(nnz_pad // CHUNK,),
        in_specs=[
            pl.BlockSpec((1, CHUNK), lambda j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, CHUNK), lambda j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, CHUNK), lambda j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((u_pad, 1), lambda j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((n_pad, u_pad), lambda j: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, u_pad), jnp.float32),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * nnz_pad * n_pad * u_pad,
            bytes_accessed=3 * nnz_pad * 4 + u_pad * 4 + n_pad * u_pad * 4,
            transcendentals=0,
        ),
    )(row2, idx2, val2, uq2)
    return out[:n, :u]


def csr_gather(indptr, indices, values, width, used,
               pallas: bool = False):
    """Dispatching CSR gather (traced; called inside the fused program).
    ``pallas=True`` (the ``forest.csr`` variant) routes to the MXU kernel
    when the backend supports it — bitwise-equal either way, so the
    routing can never change results."""
    from .pallas_hist import interpret_mode, use_pallas

    n = indptr.shape[0] - 1
    u = int(np.shape(used)[0])
    if pallas and n * _round_up(max(u, 128), 128) <= _GATHER_MAX_CELLS:
        if use_pallas():
            return csr_gather_pallas(indptr, indices, values, width, used)
        if interpret_mode():
            return csr_gather_pallas(indptr, indices, values, width, used,
                                     interpret=True)
    return csr_gather_xla(indptr, indices, values, width, used)


# ---------------------------------------------------------------------------
# Sparse histogram: flat ragged bin sums as a one-hot MXU contraction
# ---------------------------------------------------------------------------


def _sparse_hist_kernel(bins_ref, stats_ref, out_ref):
    """One nnz-chunk grid cell: bins_ref [1, CHUNK] i32 flat bin ids,
    stats_ref [3, CHUNK] f32 pre-masked (g, h, count) channels, out_ref
    [3, TB_pad] f32 accumulator resident across the grid. The transposed
    one-hot ([TB_pad, CHUNK], dim-0 iota) is contracted over the chunk on
    the MXU — pallas_hist's reduction pattern over the flat ragged bin
    space instead of the [F, B] grid."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    tb_pad = out_ref.shape[1]
    chunk = bins_ref.shape[1]
    iota0 = jax.lax.broadcasted_iota(jnp.int32, (tb_pad, chunk), 0)
    onehot = jnp.broadcast_to(bins_ref[...], (tb_pad, chunk)) == iota0
    out_ref[...] += jax.lax.dot_general(
        stats_ref[...], onehot.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)


def sparse_histogram_mxu(flat_bins, stats, total_bins: int,
                         interpret: bool = False):
    """[nnz] i32 flat bin ids + [3, nnz] pre-masked channel stats ->
    [3, total_bins] f32 sums. Masked/padded entries carry zero stats, so
    their one-hot column contributes nothing wherever it lands. Chunk
    order changes the f32 accumulation order versus the prefix-sum path
    (sparse._flat_histogram): callers gate on the ``hist.csr`` variant's
    declared tolerance, and the count channel is exact below 2^24."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nnz = flat_bins.shape[0]
    tb_pad = _round_up(max(total_bins, 128), 128)
    nnz_pad = _round_up(max(nnz, 1), CHUNK)
    bins2 = jnp.zeros((1, nnz_pad), dtype=jnp.int32)
    bins2 = bins2.at[0, :nnz].set(flat_bins.astype(jnp.int32))
    stats2 = jnp.zeros((3, nnz_pad), dtype=jnp.float32)
    stats2 = stats2.at[:, :nnz].set(stats.astype(jnp.float32))

    out = pl.pallas_call(
        _sparse_hist_kernel,
        grid=(nnz_pad // CHUNK,),
        in_specs=[
            pl.BlockSpec((1, CHUNK), lambda j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, CHUNK), lambda j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((3, tb_pad), lambda j: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((3, tb_pad), jnp.float32),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * 3 * nnz_pad * tb_pad,
            bytes_accessed=nnz_pad * 4 + 3 * nnz_pad * 4 + 3 * tb_pad * 4,
            transcendentals=0,
        ),
    )(bins2, stats2)
    return out[:, :total_bins]


def flat_hist_dispatch(dev, data) -> Optional[object]:
    """sparse._flat_histogram's Pallas route: [3, TB] sums when the
    ``hist.csr`` kernel variant is active AND the backend runs Pallas
    (TPU, or interpreter mode for CPU tests) AND the flat bin space fits
    the VMEM accumulator guard; None keeps the prefix-sum path. Resolved
    at trace time — the executor/trainer activates the variant around its
    jit trace, so the choice is a static program property.

    ``data`` is the channel-major [3, nnz] masked (g, h, count) stack in
    BIN-SORTED entry order; the per-entry flat bin id is recovered from
    the bin boundary offsets (entry j belongs to the first bin whose end
    offset exceeds j — empty bins skip naturally)."""
    from ..core import kernels as _kernels

    from .pallas_hist import interpret_mode, use_pallas

    var = _kernels.active("hist")
    if var is None or var.params.get("layout") != "csr":
        return None
    if use_pallas():
        interpret = False
    elif interpret_mode():
        interpret = True
    else:
        return None
    total_bins = int(dev["bin_end"].shape[0])
    if total_bins > _SPARSE_HIST_MAX_TB:
        return None
    import jax.numpy as jnp

    nnz = data.shape[1]
    j = jnp.arange(nnz, dtype=jnp.int32)
    bin_of = jnp.searchsorted(dev["bin_end"].astype(jnp.int32), j,
                              side="right").astype(jnp.int32)
    return sparse_histogram_mxu(bin_of, data, total_bins,
                                interpret=interpret)
