"""Boosting engine: objectives, gbdt/rf/dart/goss variants, metrics, persistence.

LGBM_Booster* parity (the surface the reference drives, lightgbm/
LightGBMBooster.scala:21-148, TrainUtils.scala:134-233): iterate trees over
grad/hess of a pluggable objective, evaluate metrics per iteration, early-stop,
serialize to a model string, merge boosters (continued / multi-batch training),
single-row and batched prediction, feature importances.

Grad/hess computation and score updates are jitted; tree growth is tree.py.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import faults as _faults
from ..parallel.mesh import fetch_global

from .binning import BinMapper
from .tree import GrowerConfig, Tree, build_thresholds, grow_tree

MODEL_FORMAT = "mmlspark_tpu.gbdt.v1"


@dataclasses.dataclass
class TrainParams:
    """Native-param-string equivalent (reference lightgbm/TrainParams.scala:1-117)."""

    # regression|regression_l1|quantile|binary|multiclass|lambdarank
    objective: str = "regression"
    boosting_type: str = "gbdt"            # gbdt|rf|dart|goss
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    max_bin: int = 255
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    feature_fraction: float = 1.0
    feature_fraction_seed: int = 2
    early_stopping_round: int = 0
    num_class: int = 1
    alpha: float = 0.9                     # quantile / huber parameter
    drop_rate: float = 0.1                 # dart
    max_drop: int = 50                     # dart
    uniform_drop: bool = False             # dart
    top_rate: float = 0.2                  # goss
    other_rate: float = 0.1                # goss
    categorical_feature: Tuple[int, ...] = ()
    # categorical SET-split controls (LightGBM cat_smooth / cat_l2 /
    # max_cat_threshold defaults): sorted-by-gradient-statistic category
    # subsets, not ordered-int thresholds
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_threshold: int = 32
    # tree_learner parity (LightGBMParams.scala:13-18). Both values run the
    # exact psum'd-histogram algorithm: voting_parallel is LightGBM's lossy
    # bandwidth optimization for slow networks; exact histograms over ICI
    # strictly dominate (same or better splits at no extra cost here).
    parallelism: str = "data_parallel"
    max_delta_step: float = 0.0            # clamp |leaf value| (0 = off)
    pos_bagging_fraction: float = 1.0      # binary class-aware bagging
    neg_bagging_fraction: float = 1.0
    max_bin_by_feature: Tuple[int, ...] = ()
    # log the TRAIN metric every iteration (isProvideTrainingMetric,
    # TrainUtils.scala:194-230) — also when a validation set is present
    train_metric: bool = False
    metric: str = ""                       # default chosen by objective
    verbosity: int = -1
    seed: int = 0

    def to_string(self) -> str:
        """LightGBM-style 'key=value key=value' param string."""
        return " ".join(f"{k}={v}" for k, v in dataclasses.asdict(self).items())


# ---------------------------------------------------------------------------
# Objectives: per-row grad/hess of the loss wrt raw scores (jitted)
# ---------------------------------------------------------------------------


def _sigmoid(x):
    import jax.numpy as jnp

    return 1.0 / (1.0 + jnp.exp(-x))


def grad_hess(objective: str, scores, labels, weights=None, alpha: float = 0.9,
              groups=None, group_segments=None):
    """Returns (grad, hess) arrays, shape [N] (or [N,K] multiclass)."""
    import jax
    import jax.numpy as jnp

    if objective == "binary":
        p = _sigmoid(scores)
        g = p - labels
        h = jnp.maximum(p * (1.0 - p), 1e-16)
    elif objective == "multiclass":
        p = jax.nn.softmax(scores, axis=-1)
        y = jax.nn.one_hot(labels.astype(jnp.int32), scores.shape[-1])
        g = p - y
        h = jnp.maximum(2.0 * p * (1.0 - p), 1e-16)
    elif objective in ("regression", "regression_l2", "l2", "mean_squared_error"):
        g = scores - labels
        h = jnp.ones_like(scores)
    elif objective in ("regression_l1", "l1", "mae"):
        g = jnp.sign(scores - labels)
        h = jnp.ones_like(scores)
    elif objective == "quantile":
        diff = scores - labels
        g = jnp.where(diff >= 0, 1.0 - alpha, -alpha)
        h = jnp.ones_like(scores)
    elif objective == "huber":
        diff = scores - labels
        g = jnp.clip(diff, -alpha, alpha)
        h = jnp.ones_like(scores)
    elif objective == "poisson":
        g = jnp.exp(scores) - labels
        h = jnp.exp(scores)
    elif objective == "lambdarank":
        return _lambdarank_grad_hess(scores, labels, groups,
                                     segments=group_segments)
    else:
        raise ValueError(f"Unknown objective {objective!r}")
    if weights is not None:
        w = weights if g.ndim == 1 else weights[:, None]
        g, h = g * w, h * w
    return g, h


class GroupSegments:
    """Host-side segmentation of contiguous ``group_ids`` runs, bucketed by
    padded (power-of-two) group size. Computed once per dataset and reused
    every boosting iteration (the group layout never changes)."""

    __slots__ = ("n", "buckets")

    def __init__(self, n, buckets):
        self.n = n
        # buckets: list of (Gb, rows, loc_g, loc_slot, m) — see segment_groups
        self.buckets = buckets


def segment_groups(group_ids) -> GroupSegments:
    """Segment rows into contiguous groups and bucket groups by size class.

    Raises if a group id appears in two non-adjacent runs — that silently
    breaks pairwise ranking, so it must be an error (sort by group first).
    """
    gi = np.asarray(group_ids)
    n = len(gi)
    change = np.nonzero(gi[1:] != gi[:-1])[0] + 1
    starts = np.concatenate([[0], change]).astype(np.int64)
    counts = np.diff(np.concatenate([starts, [n]])).astype(np.int64)
    run_ids = gi[starts]
    if len(np.unique(run_ids)) != len(run_ids):
        raise ValueError(
            "lambdarank requires rows grouped contiguously by group id; a "
            "group id reappears after a different group — sort the dataset "
            "by the group column first")

    by_size: Dict[int, list] = {}
    for g in range(len(starts)):
        c = int(counts[g])
        gb = 1 if c <= 1 else 1 << int(np.ceil(np.log2(c)))
        by_size.setdefault(gb, []).append(g)

    import jax.numpy as jnp

    buckets = []
    for gb, glist in sorted(by_size.items()):
        bcounts = counts[glist]
        rows = np.concatenate(
            [np.arange(starts[g], starts[g] + counts[g]) for g in glist])
        loc_g = np.repeat(np.arange(len(glist), dtype=np.int64), bcounts)
        loc_slot = np.concatenate(
            [np.arange(c, dtype=np.int64) for c in bcounts])
        # store as device arrays: the layout is static across boosting, so the
        # H2D upload of the index arrays happens once, not per iteration
        buckets.append((gb, jnp.asarray(rows, dtype=jnp.int32),
                        jnp.asarray(loc_g, dtype=jnp.int32),
                        jnp.asarray(loc_slot, dtype=jnp.int32), len(glist)))
    return GroupSegments(n, buckets)


# Bound on pairwise-tensor elements materialized at once (f32 [chunk, Gb, Gb];
# 2**24 elements = 64 MB per tensor, ~6 live tensors => a few hundred MB peak).
_LAMBDARANK_PAIR_BUDGET = 1 << 24


@functools.partial(
    __import__("jax").jit, static_argnames=("sigma",))
def _lambdarank_bucket(s_pad, l_pad, valid, sigma: float = 1.0):
    """Pairwise LambdaRank lambdas for one [m, G] padded bucket of groups."""
    import jax.numpy as jnp

    m, G = s_pad.shape
    gains = jnp.where(valid, 2.0 ** l_pad - 1.0, 0.0)
    # within-group rank by current score (invalid slots sort last: score -inf)
    order = jnp.argsort(-s_pad, axis=1)
    rank_of = jnp.zeros((m, G), dtype=jnp.int32)
    rank_of = rank_of.at[jnp.arange(m)[:, None], order].set(
        jnp.broadcast_to(jnp.arange(G, dtype=jnp.int32), (m, G)))
    disc = 1.0 / jnp.log2(rank_of.astype(jnp.float32) + 2.0)
    # ideal DCG per group (labels sorted descending)
    ideal_gains = jnp.sort(gains, axis=1)[:, ::-1]
    idcg = jnp.sum(ideal_gains / jnp.log2(jnp.arange(G, dtype=jnp.float32) + 2.0),
                   axis=1, keepdims=True)
    inv_idcg = jnp.where(idcg > 0, 1.0 / idcg, 0.0)[..., None]

    pair_ok = valid[:, :, None] & valid[:, None, :]
    better = (l_pad[:, :, None] > l_pad[:, None, :]) & pair_ok
    s_diff = jnp.where(pair_ok, s_pad[:, :, None] - s_pad[:, None, :], 0.0)
    rho = 1.0 / (1.0 + jnp.exp(sigma * s_diff))      # P(i beats j but doesn't)
    delta = jnp.abs((gains[:, :, None] - gains[:, None, :])
                    * (disc[:, :, None] - disc[:, None, :])) * inv_idcg
    lam = jnp.where(better, -sigma * rho * delta, 0.0)
    h_pair = jnp.where(better, sigma * sigma * rho * (1 - rho) * delta, 0.0)
    g_pad = jnp.sum(lam, axis=2) - jnp.sum(lam, axis=1)
    h_pad = jnp.sum(h_pair, axis=2) + jnp.sum(h_pair, axis=1)
    return g_pad, h_pad


def _lambdarank_grad_hess(scores, labels, group_ids, sigma: float = 1.0,
                          segments: Optional[GroupSegments] = None):
    """Pairwise LambdaRank with |ΔNDCG| weighting (LightGBM semantics).

    Groups (contiguous ``group_ids`` runs) are bucketed by power-of-two padded
    size, so a few singleton-heavy queries never inflate the padding of the
    rest; within a bucket the [m, G, G] pairwise tensors are materialized at
    most ``_LAMBDARANK_PAIR_BUDGET`` elements at a time (lax.map over group
    chunks), bounding memory at O(chunk * G^2) regardless of dataset size.
    """
    import jax
    import jax.numpy as jnp

    seg = segments if segments is not None else segment_groups(group_ids)
    n = seg.n
    g_out = jnp.zeros(n, dtype=jnp.float32)
    h_out = jnp.full(n, 1e-16, dtype=jnp.float32)

    for gb, rows, loc_g, loc_slot, m in seg.buckets:
        if gb <= 1:
            continue  # singleton groups: no pairs, keep (0, 1e-16)
        chunk = max(1, min(m, _LAMBDARANK_PAIR_BUDGET // (gb * gb)))
        m_pad = (m + chunk - 1) // chunk * chunk
        s_pad = jnp.full((m_pad, gb), -jnp.inf, dtype=jnp.float32)
        l_pad = jnp.zeros((m_pad, gb), dtype=jnp.float32)
        valid = jnp.zeros((m_pad, gb), dtype=bool)
        s_pad = s_pad.at[loc_g, loc_slot].set(scores[rows])
        l_pad = l_pad.at[loc_g, loc_slot].set(labels[rows])
        valid = valid.at[loc_g, loc_slot].set(True)

        nchunks = m_pad // chunk
        if nchunks == 1:
            g_pad, h_pad = _lambdarank_bucket(s_pad, l_pad, valid, sigma)
        else:
            g_pad, h_pad = jax.lax.map(
                lambda t: _lambdarank_bucket(t[0], t[1], t[2] > 0, sigma),
                (s_pad.reshape(nchunks, chunk, gb),
                 l_pad.reshape(nchunks, chunk, gb),
                 valid.reshape(nchunks, chunk, gb).astype(jnp.int8)))
            g_pad = g_pad.reshape(m_pad, gb)
            h_pad = h_pad.reshape(m_pad, gb)
        g_out = g_out.at[rows].set(g_pad[loc_g, loc_slot])
        h_out = h_out.at[rows].set(
            jnp.maximum(h_pad[loc_g, loc_slot], 1e-16))
    return g_out, h_out


def init_score(objective: str, labels: np.ndarray, num_class: int = 1,
               alpha: float = 0.9) -> np.ndarray:
    """Base score before the first tree (BoostFromAverage parity).
    ``alpha`` is the quantile level for the quantile objective."""
    if objective == "binary":
        p = np.clip(labels.mean(), 1e-12, 1 - 1e-12)
        return np.full(1, np.log(p / (1 - p)), dtype=np.float64)
    if objective == "multiclass":
        out = np.zeros(num_class, dtype=np.float64)
        for k in range(num_class):
            p = np.clip((labels == k).mean(), 1e-12, 1 - 1e-12)
            out[k] = np.log(p)
        return out
    if objective in ("regression", "regression_l2", "l2", "huber",
                     "mean_squared_error"):
        return np.full(1, labels.mean(), dtype=np.float64)
    if objective in ("regression_l1", "l1", "mae"):
        return np.full(1, np.median(labels), dtype=np.float64)
    if objective == "quantile":
        return np.full(1, np.quantile(labels, alpha), dtype=np.float64)
    if objective == "poisson":
        return np.full(1, np.log(max(labels.mean(), 1e-12)), dtype=np.float64)
    return np.zeros(1, dtype=np.float64)


# ---------------------------------------------------------------------------
# Metrics (per-iteration eval + early stopping; TrainUtils.scala:194-230)
# ---------------------------------------------------------------------------


def eval_metric(metric: str, scores: np.ndarray, labels: np.ndarray,
                groups: Optional[np.ndarray] = None) -> float:
    eps = 1e-15
    if metric == "binary_logloss":
        p = np.clip(1 / (1 + np.exp(-scores)), eps, 1 - eps)
        return float(-np.mean(labels * np.log(p) + (1 - labels) * np.log(1 - p)))
    if metric == "binary_error":
        return float(np.mean((scores > 0) != (labels > 0.5)))
    if metric == "auc":
        order = np.argsort(scores)
        ranks = np.empty(len(scores))
        ranks[order] = np.arange(1, len(scores) + 1)
        # average ranks for ties
        for v in np.unique(scores):
            m = scores == v
            if m.sum() > 1:
                ranks[m] = ranks[m].mean()
        pos = labels > 0.5
        n_pos, n_neg = pos.sum(), (~pos).sum()
        if n_pos == 0 or n_neg == 0:
            return 0.5
        return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))
    if metric == "multi_logloss":
        e = np.exp(scores - scores.max(axis=1, keepdims=True))
        p = np.clip(e / e.sum(axis=1, keepdims=True), eps, None)
        return float(-np.mean(np.log(p[np.arange(len(labels)),
                                       labels.astype(np.int64)])))
    if metric == "multi_error":
        return float(np.mean(np.argmax(scores, axis=1) != labels))
    if metric in ("l2", "mse"):
        return float(np.mean((scores - labels) ** 2))
    if metric == "rmse":
        return float(np.sqrt(np.mean((scores - labels) ** 2)))
    if metric in ("l1", "mae"):
        return float(np.mean(np.abs(scores - labels)))
    if metric == "ndcg":
        return _ndcg(scores, labels, groups)
    raise ValueError(f"Unknown metric {metric!r}")


def _ndcg(scores, labels, groups, k: int = 10) -> float:
    if groups is None:
        groups = np.zeros(len(scores), dtype=np.int64)
    vals = []
    for gid in np.unique(groups):
        m = groups == gid
        s, l = scores[m], labels[m]
        order = np.argsort(-s)[:k]
        dcg = np.sum((2 ** l[order] - 1) / np.log2(np.arange(len(order)) + 2))
        ideal = np.argsort(-l)[:k]
        idcg = np.sum((2 ** l[ideal] - 1) / np.log2(np.arange(len(ideal)) + 2))
        vals.append(dcg / idcg if idcg > 0 else 1.0)
    return float(np.mean(vals)) if vals else 1.0


_HIGHER_BETTER = {"auc", "ndcg"}


def default_metric(objective: str) -> str:
    return {
        "binary": "binary_logloss",
        "multiclass": "multi_logloss",
        "lambdarank": "ndcg",
        "regression_l1": "l1",
        "l1": "l1",
        "mae": "l1",
        "quantile": "l1",
    }.get(objective, "l2")


# ---------------------------------------------------------------------------
# Booster
# ---------------------------------------------------------------------------


class Booster:
    """Trained model: bin mapper + tree ensemble + objective metadata."""

    def __init__(self, params: TrainParams, bin_mapper: Optional[BinMapper],
                 trees: Optional[List[List[Tree]]] = None,
                 base_score: Optional[np.ndarray] = None,
                 best_iteration: int = -1):
        self.params = params
        self.bin_mapper = bin_mapper
        # trees[i][k]: iteration i, class k (num_class=1 => k=0)
        self.trees: List[List[Tree]] = trees or []
        self.base_score = (base_score if base_score is not None
                           else np.zeros(max(params.num_class, 1)))
        self.best_iteration = best_iteration

    # -- prediction ------------------------------------------------------
    def raw_predict(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        """[N,F] raw features -> [N] or [N,K] raw scores."""
        from .predict import predict_ensemble

        n_iter = num_iteration if num_iteration > 0 else (
            self.best_iteration if self.best_iteration > 0 else len(self.trees))
        n_iter = min(n_iter, len(self.trees))
        k = max(self.params.num_class, 1)
        scores = np.tile(self.base_score, (X.shape[0], 1)).astype(np.float64)
        if n_iter > 0:
            scores += predict_ensemble(
                [self.trees[i] for i in range(n_iter)], X, k)
        return scores[:, 0] if k == 1 else scores

    def predict_proba(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        raw = self.raw_predict(X, num_iteration)
        if self.params.objective == "binary":
            p = 1 / (1 + np.exp(-raw))
            return np.stack([1 - p, p], axis=1)
        if self.params.objective == "multiclass":
            e = np.exp(raw - raw.max(axis=1, keepdims=True))
            return e / e.sum(axis=1, keepdims=True)
        return raw

    # -- introspection (LightGBMBooster.scala feature importance parity) --
    def feature_importances(self, importance_type: str = "split") -> np.ndarray:
        num_f = self.bin_mapper.num_features if self.bin_mapper else 0
        imp = np.zeros(num_f, dtype=np.float64)
        for group in self.trees:
            for tree in group:
                internal = tree.feature >= 0
                if importance_type == "gain":
                    np.add.at(imp, tree.feature[internal], tree.gain[internal])
                else:
                    np.add.at(imp, tree.feature[internal], 1.0)
        return imp

    @property
    def num_total_model(self) -> int:
        return sum(len(g) for g in self.trees)

    # -- persistence (saveNativeModel / LGBM_BoosterMerge parity) ---------
    def to_string(self) -> str:
        return json.dumps({
            "format": MODEL_FORMAT,
            "params": dataclasses.asdict(self.params),
            "base_score": self.base_score.tolist(),
            "best_iteration": self.best_iteration,
            "bin_mapper": self.bin_mapper.to_json() if self.bin_mapper else None,
            "trees": [[t.to_dict() for t in group] for group in self.trees],
        })

    @staticmethod
    def from_string(s: str) -> "Booster":
        d = json.loads(s)
        if d.get("format") != MODEL_FORMAT:
            # explicit check (a bare assert vanishes under `python -O` and a
            # foreign payload would then explode deep inside TrainParams)
            raise ValueError(f"bad model format {d.get('format')!r}; "
                             f"expected {MODEL_FORMAT!r}")
        p = d["params"]
        p["categorical_feature"] = tuple(p.get("categorical_feature", ()))
        p["max_bin_by_feature"] = tuple(p.get("max_bin_by_feature", ()))
        params = TrainParams(**p)
        return Booster(
            params,
            BinMapper.from_json(d["bin_mapper"]) if d["bin_mapper"] else None,
            trees=[[Tree.from_dict(t) for t in group] for group in d["trees"]],
            base_score=np.asarray(d["base_score"], dtype=np.float64),
            best_iteration=d.get("best_iteration", -1),
        )

    def merge(self, other: "Booster") -> "Booster":
        """Append another booster's trees (LGBM_BoosterMerge — multi-batch/continued
        training, LightGBMBase.scala:26-39)."""
        return Booster(self.params, self.bin_mapper or other.bin_mapper,
                       trees=self.trees + other.trees,
                       base_score=self.base_score,
                       best_iteration=-1)


# ---------------------------------------------------------------------------
# Whole-run fused training (one dispatch for ALL boosting iterations)
# ---------------------------------------------------------------------------

# Precomputed bagging-mask budget for the scan path: [iters, N] bool uploaded
# once. Above this, fall back to the per-tree loop.
_SCAN_MASK_BUDGET = 1 << 28


def _now() -> float:
    import time

    return time.perf_counter()


@functools.partial(__import__("jax").jit, donate_argnums=(0,))
def _widen_bins(b):
    """uint8 bins -> device-resident int32 (donated: the u8 copy is freed).
    Keeps every downstream kernel on the int32 layout it was built for while
    the host->device transfer ships 1/4 the bytes."""
    import jax.numpy as jnp

    return b.astype(jnp.int32)


def _scan_train_ok(params: TrainParams, objective: str, valid, log,
                   shard_put, checkpoint=None) -> bool:
    """Can this run take the whole-training-in-one-dispatch lax.scan path?

    The scan path removes EVERY per-iteration host round trip (the per-tree
    fused grower still paid one dispatch + one fetch per tree — ~4 tunnel
    RTTs/iteration end to end). Exclusions: dart (host-side tree
    drop/re-add), lambdarank (grouped grad), validation/early-stopping +
    per-iteration logging (host eval), and sharded inputs (the per-tree
    shard_map grower handles those). GOSS runs in-scan with on-device
    gradient-threshold selection + row compaction (see _train_scan) — the
    sampling is the point of GOSS (LightGBM's speed feature,
    GossStrategy in the reference's underlying engine), so the compacted
    histogram stream is where the row-reduction actually buys time.
    """
    import jax

    if os.environ.get("MMLSPARK_TPU_NO_SCAN_TRAIN", "") not in ("", "0"):
        return False
    if params.boosting_type == "dart":
        return False
    if objective == "lambdarank":
        return False
    if valid is not None or log is not None or params.train_metric:
        return False
    if shard_put is not None:
        return False
    if checkpoint is not None:
        # iteration-level checkpointing needs a per-iteration host boundary;
        # the whole-run scan has none
        return False
    max_nodes = 2 * params.num_leaves - 1
    if max_nodes < 3:
        return False  # num_leaves=1: nothing to grow
    forced = os.environ.get("MMLSPARK_TPU_SCAN_TRAIN", "") not in ("", "0")
    if not forced and jax.default_backend() == "cpu":
        # CPU in-process dispatch is cheap; the host loop keeps exact-f64
        # score accumulation there
        return False
    return True


def _scan_precompute_masks(params: TrainParams, rng, n: int, num_f: int,
                           y: np.ndarray, is_rf: bool):
    """Replicate the host loop's per-iteration RNG draws (bagging mask, then
    feature mask — same order, same generator) for all iterations up front.
    Returns (row_masks [iters,N]|None, feat_masks [iters,F]|None, ok)."""
    iters = params.num_iterations
    bag_cond = ((params.bagging_fraction < 1.0
                 or params.pos_bagging_fraction < 1.0
                 or params.neg_bagging_fraction < 1.0)
                and (is_rf or params.bagging_freq > 0)
                # goss overrides bagging (host-path / LightGBM semantics:
                # the goss selection IS the row mask)
                and params.boosting_type != "goss")
    use_feat = params.feature_fraction < 1.0
    if bag_cond and iters * n > _SCAN_MASK_BUDGET:
        return None, None, False
    row_masks = np.empty((iters, n), dtype=bool) if bag_cond else None
    feat_masks = np.empty((iters, num_f), dtype=bool) if use_feat else None
    bag = np.ones(n, dtype=bool)
    for it in range(iters):
        if bag_cond and it % max(params.bagging_freq, 1) == 0:
            if (params.pos_bagging_fraction < 1.0
                    or params.neg_bagging_fraction < 1.0):
                pos = y > 0.5
                frac = np.where(pos, params.pos_bagging_fraction,
                                params.neg_bagging_fraction)
                bag = rng.random(n) < frac
            else:
                bag = rng.random(n) < params.bagging_fraction
        if bag_cond:
            row_masks[it] = bag
        if use_feat:
            m = np.zeros(num_f, dtype=bool)
            n_feat = max(1, int(num_f * params.feature_fraction))
            m[rng.choice(num_f, size=n_feat, replace=False)] = True
            feat_masks[it] = m
    return row_masks, feat_masks, True


def _train_scan(params: TrainParams, config: GrowerConfig, booster: "Booster",
                mapper: BinMapper, bins_dev, labels, w_dev,
                scores: np.ndarray, n: int, num_f: int, num_bins: int,
                k: int, lr: float, row_masks, feat_masks,
                pad_mask: Optional[np.ndarray] = None,
                cat_args=None) -> None:
    """Run ALL boosting iterations in ONE jitted lax.scan dispatch.

    Each scan step: grad/hess from the running scores, whole-tree growth via
    the fused while_loop grower (Pallas MXU histograms on TPU), on-device f32
    leaf values feeding a Kahan-compensated score update. The stacked tree
    arrays come back in a single fetch; leaf values of the SAVED trees are
    recomputed on host in f64 from the fetched (grad, hess, count) sums —
    the same precision lineage as the per-tree path. The running f32 score
    update uses device-f32 leaf values, so late-tree splits can differ from
    the per-tree path by float rounding (predictions agree to ~1e-5; the
    per-tree path remains available via MMLSPARK_TPU_NO_SCAN_TRAIN=1).

    Replaces ~4 tunnel round trips per boosting iteration with one dispatch
    + one fetch for the whole run (the reference's LGBM_BoosterUpdateOneIter
    loop is likewise in-process once entered, TrainUtils.scala:170-233).
    """
    import jax
    import jax.numpy as jnp

    from . import pallas_hist
    from .tree import _grow_tree_device_body

    iters = params.num_iterations
    M = 2 * params.num_leaves - 1
    # same interpret plumbing as tree._grow_tree_device: CPU tests exercise
    # the Pallas kernels (histogram + tier select) in interpreter mode
    interpret = pallas_hist.interpret_mode()
    use_mxu = pallas_hist.use_pallas() or interpret
    objective = params.objective
    alpha = params.alpha

    l1 = np.float32(config.lambda_l1)
    l2 = np.float32(config.lambda_l2)
    msh = np.float32(config.min_sum_hessian_in_leaf)
    mgs = np.float32(config.min_gain_to_split)
    has_fm = feat_masks is not None
    fm_dummy = jnp.zeros(0, dtype=bool)
    if pad_mask is not None and not pad_mask.all():
        if row_masks is not None:
            row_masks = row_masks & pad_mask[None, :]
        ones_mask = jnp.asarray(pad_mask)
    else:
        ones_mask = jnp.ones(n, dtype=bool)
    shrink = np.float32(lr)

    # ----- in-scan GOSS: the histogram kernel streams ~2 MXU cycles per
    # row*feature regardless of masking, so a masked goss subset saves
    # nothing — the win comes from COMPACTING the tree's rows to the
    # selected ~(top_rate+other_rate) fraction at the root, shrinking every
    # histogram/partition pass of the whole tree. Selection is on device
    # and EXACT-COUNT (_exact_topk_mask: bitwise bisection with index
    # tie-break — LightGBM's sorted-GOSS count semantics): exactly top_n
    # |grad| rows plus exactly other_n uniform draws among the rest,
    # amplified by (1-a)/b like the host path, gathered into a
    # static-capacity buffer that by construction can never overflow (the
    # pre-r4 >=-threshold mask truncated in row order on gradient ties).
    # Full-row score routing is recovered by replaying the grown tree's
    # splits over all N rows.
    is_goss = params.boosting_type == "goss"
    if is_goss:
        n_real = int(pad_mask.sum()) if pad_mask is not None else n
        top_n = int(n_real * params.top_rate)
        other_n = int(n_real * params.other_rate)
        sel_budget = max(top_n + other_n, 1)
        goss_cap = min(n, -(-(sel_budget + max(256, sel_budget // 16)) // 512)
                       * 512)
        goss_amp = np.float32((1.0 - params.top_rate)
                              / max(params.other_rate, 1e-12))
        goss_keys = jax.random.split(
            jax.random.PRNGKey(params.seed or params.bagging_seed), iters)

    # ----- bagging/rf row compaction: the same economics as GOSS — the
    # histogram kernel streams ~2 MXU cycles per row*feature regardless of
    # masking, so when host-precomputed bagging masks select a fraction of
    # rows, gathering them to the buffer front shrinks every histogram and
    # partition pass of the whole tree. The capacity is exact on the host
    # (masks are precomputed); full-row score routing is recovered by the
    # same split replay GOSS uses. Gated to a selected fraction <= 0.625
    # (above that, the per-iteration gather + replay eats the stream
    # savings) at real scale; MMLSPARK_TPU_DENSE_BAG_COMPACT=1 forces
    # (tests), MMLSPARK_TPU_NO_DENSE_BAG_COMPACT=1 kills.
    bag_cap = 0
    if (row_masks is not None and not is_goss
            and os.environ.get("MMLSPARK_TPU_NO_DENSE_BAG_COMPACT",
                               "") in ("", "0")):
        forced = os.environ.get("MMLSPARK_TPU_DENSE_BAG_COMPACT",
                                "") not in ("", "0")
        nr = int(pad_mask.sum()) if pad_mask is not None else n
        # cheap gates first: the mask reduction scans up to iters x n bools
        if forced or (jax.default_backend() == "tpu" and nr >= 100_000):
            max_cnt = int(row_masks.sum(axis=1).max())
            if forced or max_cnt / max(nr, 1) <= 0.625:
                bag_cap = min(n, -(-max(max_cnt, 1) // 512) * 512)

    from . import histogram as H

    def _route_full(tree_out):
        """Route ALL n rows through the grown tree (children have larger ids
        than parents, so one in-order replay of the split records is a full
        traversal)."""
        feat = tree_out["feature"]
        tb = tree_out["threshold_bin"]
        dl_ = tree_out["default_left"]
        li = tree_out["left"]
        ri = tree_out["right"]
        cwords = tree_out.get("cat_words")

        def rb(j, nor):
            f = feat[j]
            binrow = jax.lax.dynamic_index_in_dim(
                bins_dev, jnp.maximum(f, 0), axis=0, keepdims=False)
            if cwords is not None:
                new = H.partition_rows_cat(binrow, nor, j, tb[j], dl_[j],
                                           li[j], ri[j], cwords[j])
            else:
                new = H.partition_rows(binrow, nor, j, tb[j], dl_[j], li[j],
                                       ri[j])
            return jnp.where(f >= 0, new, nor)

        return jax.lax.fori_loop(0, tree_out["n_nodes"], rb,
                                 jnp.zeros(n, jnp.int32))

    def body(carry, xs):
        score, comp = carry
        row_mask = xs["rm"] if row_masks is not None else ones_mask
        fmask = xs["fm"] if has_fm else fm_dummy
        g, h = grad_hess(objective, score, labels, w_dev, alpha)
        if is_goss:
            from .sparse import _exact_topk_mask

            g_sel = jnp.abs(g) if g.ndim == 1 else jnp.sum(jnp.abs(g), axis=1)
            not_real = ~ones_mask if pad_mask is not None else None
            is_top = _exact_topk_mask(g_sel, top_n, n, exclude=not_real)
            u = jax.random.uniform(xs["gk"], (n,))
            excl_other = (is_top if not_real is None
                          else (is_top | not_real))
            sel = is_top | _exact_topk_mask(u, other_n, n,
                                            exclude=excl_other)
            amp = jnp.where(is_top, jnp.float32(1.0), goss_amp)
            idx = jnp.nonzero(sel, size=goss_cap, fill_value=0)[0]
            sel_cnt = jnp.sum(sel, dtype=jnp.int32)  # <= goss_cap always
            mask_it = jnp.arange(goss_cap, dtype=jnp.int32) < sel_cnt
            bins_it = jnp.take(bins_dev, idx, axis=1)
            amp_c = jnp.take(amp, idx)
            nor0 = jnp.zeros(goss_cap, jnp.int32)
        elif bag_cap:
            idx = jnp.nonzero(row_mask, size=bag_cap, fill_value=0)[0]
            sel_cnt = jnp.sum(row_mask, dtype=jnp.int32)  # <= bag_cap
            mask_it = jnp.arange(bag_cap, dtype=jnp.int32) < sel_cnt
            bins_it = jnp.take(bins_dev, idx, axis=1)
            nor0 = jnp.zeros(bag_cap, jnp.int32)
        else:
            bins_it, mask_it = bins_dev, row_mask
            nor0 = jnp.zeros(n, jnp.int32)
        outs = []
        for kk in range(k):
            gk = g if g.ndim == 1 else g[:, kk]
            hk = h if h.ndim == 1 else h[:, kk]
            if is_goss:
                gk = jnp.take(gk, idx) * amp_c
                hk = jnp.take(hk, idx) * amp_c
            elif bag_cap:
                gk = jnp.take(gk, idx)
                hk = jnp.take(hk, idx)
            out = _grow_tree_device_body(
                bins_it, gk, hk, mask_it, nor0,
                l1, l2, msh, mgs, fmask,
                num_bins=num_bins, max_nodes=M,
                min_data_in_leaf=config.min_data_in_leaf,
                max_depth=config.max_depth, use_mxu=use_mxu,
                has_feature_mask=has_fm, interpret=interpret,
                cat_args=cat_args)
            rows = out.pop("node_of_row")
            if is_goss or bag_cap:
                rows = _route_full(out)
            sums, feat = out["sums"], out["feature"]
            g_thr = jnp.sign(sums[:, 0]) * jnp.maximum(
                jnp.abs(sums[:, 0]) - l1, 0.0)
            val = jnp.where(feat < 0, -g_thr / (sums[:, 1] + l2), 0.0)
            if config.max_delta_step > 0:
                val = jnp.clip(val, -config.max_delta_step,
                               config.max_delta_step)
            # host-path parity: an unsplit root keeps value 0
            val = val.at[0].set(jnp.where(out["n_nodes"] > 1, val[0], 0.0))
            upd = (val * shrink)[rows]
            if k == 1:
                y_ = upd + comp
                t_ = score + y_
                score, comp = t_, y_ - (t_ - score)
            else:
                s_col, c_col = score[:, kk], comp[:, kk]
                y_ = upd + c_col
                t_ = s_col + y_
                score = score.at[:, kk].set(t_)
                comp = comp.at[:, kk].set(y_ - (t_ - s_col))
            outs.append(out)
        stacked = jax.tree.map(lambda *a: jnp.stack(a), *outs)  # [k, ...]
        return (score, comp), stacked

    score0 = jnp.asarray(scores[:, 0] if k == 1 else scores, dtype=jnp.float32)
    comp0 = jnp.zeros_like(score0)
    xs = None
    if row_masks is not None or has_fm or is_goss:
        xs = {}
        if row_masks is not None:
            xs["rm"] = jnp.asarray(row_masks)
        if has_fm:
            xs["fm"] = jnp.asarray(feat_masks)
        if is_goss:
            xs["gk"] = goss_keys
    timing = os.environ.get("MMLSPARK_TPU_GBDT_TIMING", "") not in ("", "0")
    t0 = _now() if timing else 0.0

    # Chunk the scan so one dispatch stays under the device-runtime bound
    # (~40-60s of continuous execution crashed/restarted the worker on the
    # tunnelled chip at 2M+ rows x 50 iters): bound row*iteration work per
    # dispatch; the (score, comp) carry stays device-resident across chunks,
    # so the host cost is one small fetch per chunk.
    # 6e7 row-iters ~ 12-20 s of device execution per dispatch at the r4
    # per-iteration rate — comfortably under the ~40-60 s worker crash
    # bound while paying the ~0.1 s per-dispatch fetch RTT 6x less often
    # than the old 2e7 default (tools/profile_gbdt_10m.py history)
    budget = int(os.environ.get("MMLSPARK_TPU_SCAN_CHUNK_ROWS", str(6 * 10**7)))
    ipc = max(1, min(iters, budget // max(n, 1)))
    n_chunks = -(-iters // ipc)

    carry = (score0, comp0)
    host_chunks = []
    done = 0
    while done < iters:
        # EVERY chunk runs the same static length (one compiled program): a
        # short final chunk overgrows up to ipc-1 surplus trees (same xs rows
        # repeated) that are simply dropped below — one tree of wasted
        # compute beats a second multi-second XLA compile
        xs_c = None
        if xs is not None:
            idx = np.minimum(np.arange(done, done + ipc), iters - 1)
            xs_c = {k: v[idx] for k, v in xs.items()}
        carry, ys = jax.lax.scan(body, carry, xs_c, length=ipc)
        host_chunks.append(fetch_global(ys))
        done += ipc
    host = jax.tree.map(lambda *c: np.concatenate(c, axis=0), *host_chunks) \
        if len(host_chunks) > 1 else host_chunks[0]
    host = jax.tree.map(lambda a: a[:iters], host)
    if timing:
        print(f"[gbdt-scan] exec+fetch ({n_chunks} chunk(s) of <= {ipc}) "
              f"{_now() - t0:.3f}s", flush=True)
        t0 = _now()

    for it in range(iters):
        group: List[Tree] = []
        for kk in range(k):
            nn = int(host["n_nodes"][it, kk])
            feature = host["feature"][it, kk][:nn].astype(np.int32)
            tbin = host["threshold_bin"][it, kk][:nn].astype(np.int32)
            sums = host["sums"][it, kk][:nn].astype(np.float64)
            g_thr = np.sign(sums[:, 0]) * np.maximum(
                np.abs(sums[:, 0]) - config.lambda_l1, 0.0)
            value = np.where(feature < 0,
                             -g_thr / (sums[:, 1] + config.lambda_l2), 0.0)
            if config.max_delta_step > 0:
                value = np.clip(value, -config.max_delta_step,
                                config.max_delta_step)
            value[0] = 0.0 if nn == 1 else value[0]
            cat_sets = cat_words_np = None
            if "cat_words" in host:
                from .tree import cat_sets_from_words

                cat_sets, cat_words_np = cat_sets_from_words(
                    host["cat_words"][it, kk][:nn], feature, mapper)
            threshold = build_thresholds(feature, tbin, cat_sets, mapper)
            group.append(Tree(
                feature=feature,
                threshold=threshold,
                threshold_bin=tbin,
                default_left=host["default_left"][it, kk][:nn].astype(bool),
                left=host["left"][it, kk][:nn].astype(np.int32),
                right=host["right"][it, kk][:nn].astype(np.int32),
                value=value,
                gain=host["gain"][it, kk][:nn].astype(np.float32),
                count=sums[:, 2].astype(np.int32),
                shrinkage=lr,
                weight=sums[:, 1],
                cat_sets=cat_sets,
                cat_bin_words=cat_words_np,
            ))
        booster.trees.append(group)
    if timing:
        print(f"[gbdt-scan] host tree build {_now() - t0:.3f}s", flush=True)


# ---------------------------------------------------------------------------
# Training loop
# ---------------------------------------------------------------------------


def _grad_hess_np(objective: str, scores: np.ndarray, labels: np.ndarray,
                  weights: Optional[np.ndarray], alpha: float):
    """Host mirror of grad_hess (same formulas; f32 like the device path —
    the grower consumes f32 anyway; lambdarank is device-only and gated out
    of the native path)."""
    scores = scores.astype(np.float32)
    labels = labels.astype(np.float32)
    if objective == "binary":
        p = 1.0 / (1.0 + np.exp(-scores))
        g = p - labels
        h = np.maximum(p * (1.0 - p), 1e-16)
    elif objective == "multiclass":
        m = scores - scores.max(axis=-1, keepdims=True)
        e = np.exp(m)
        p = e / e.sum(axis=-1, keepdims=True)
        yh = np.zeros_like(p)
        li = labels.astype(np.int64)
        # out-of-range labels get a zero one-hot row (jax.nn.one_hot
        # semantics — the device engine accepts them; fancy indexing
        # would crash or wrap)
        ok = (li >= 0) & (li < p.shape[-1])
        yh[np.nonzero(ok)[0], li[ok]] = 1.0
        g = p - yh
        h = np.maximum(2.0 * p * (1.0 - p), 1e-16)
    elif objective in ("regression", "regression_l2", "l2",
                       "mean_squared_error"):
        g = scores - labels
        h = np.ones_like(scores)
    elif objective in ("regression_l1", "l1", "mae"):
        g = np.sign(scores - labels)
        h = np.ones_like(scores)
    elif objective == "quantile":
        diff = scores - labels
        g = np.where(diff >= 0, 1.0 - alpha, -alpha)
        h = np.ones_like(scores)
    elif objective == "huber":
        g = np.clip(scores - labels, -alpha, alpha)
        h = np.ones_like(scores)
    elif objective == "poisson":
        g = np.exp(scores) - labels
        h = np.exp(scores)
    else:
        raise ValueError(f"Unknown objective {objective!r}")
    if weights is not None:
        w = np.asarray(weights, dtype=np.float32)
        w = w if g.ndim == 1 else w[:, None]
        g, h = g * w, h * w
    return g, h


_NATIVE_PATH_FORCING_ENVS = (
    # envs that force a specific XLA training path: honoring them means the
    # native host engine must stand aside (tests pin paths this way;
    # NO_SCAN_TRAIN explicitly selects the XLA host loop, not this engine)
    "MMLSPARK_TPU_SCAN_TRAIN", "MMLSPARK_TPU_NO_SCAN_TRAIN",
    "MMLSPARK_TPU_FUSED_TREE", "MMLSPARK_TPU_NO_FUSED_TREE",
    "MMLSPARK_TPU_HIST_EXACT")


def _native_train_ok(params: TrainParams, n: int) -> bool:
    """Route this fit to the native C++ host grower?

    The reference's engine is LightGBM's C++ core (TrainUtils.scala:170-233);
    this is its small-N equivalent: below ~MMLSPARK_TPU_NATIVE_TRAIN_MAX
    row*iteration*class work the per-dispatch overhead of any accelerator
    exceeds what one host core does outright (BENCH_gbdt_train.json: 200k
    was dispatch-bound at 0.44x sklearn through r4). Large fits keep the
    whole-run lax.scan device path. MMLSPARK_TPU_NATIVE_TRAIN=1 forces,
    =0 disables."""
    env = os.environ.get("MMLSPARK_TPU_NATIVE_TRAIN", "")
    if env in ("0", "false"):
        return False
    if params.categorical_feature or params.objective == "lambdarank":
        return False
    if params.max_bin > 255 or (params.max_bin_by_feature
                                and max(params.max_bin_by_feature) > 255):
        return False
    if any(os.environ.get(e, "") not in ("", "0")
           for e in _NATIVE_PATH_FORCING_ENVS):
        return False
    from .. import native_loader

    if not native_loader.available():
        return False
    if env in ("1", "true", "force"):
        return True
    # size budget FIRST: small fits are native on every backend, so the
    # decision must not initialize the accelerator (the whole point of
    # this engine is that the tunnel/H2D is never touched for them)
    budget = float(os.environ.get("MMLSPARK_TPU_NATIVE_TRAIN_MAX", "2e7"))
    if n * params.num_iterations * max(params.num_class, 1) <= budget:
        return True
    # above budget the device engine is the default — consulting the
    # backend here is free, those fits initialize it anyway
    try:
        import jax

        return jax.default_backend() == "cpu"
    except Exception:
        return True


def _train_native(params: TrainParams, X: np.ndarray, y: np.ndarray,
                  weights, valid, valid_groups, init_scores, init_model,
                  log) -> Optional[Booster]:
    """All-host training loop over the C++ grower (no device arrays at all).

    Mirrors the host-orchestrated loop of train() — same objectives,
    bagging/GOSS/dart/rf selection logic, early stopping, and metric
    logging — with mml_gbdt_grow_tree replacing the XLA tree grower.
    Returns None when this fit cannot run natively (mapper with >256 bins
    inherited from init_model, native lib unavailable at call time)."""
    from .. import native_loader

    n, num_f = X.shape
    k = max(params.num_class, 1)
    objective = params.objective
    rng = np.random.default_rng(params.seed or params.bagging_seed)

    if init_model is not None and init_model.bin_mapper is not None:
        mapper = init_model.bin_mapper
    else:
        mapper = BinMapper.fit(X, params.max_bin, (), seed=params.seed,
                               max_bin_by_feature=params.max_bin_by_feature)
    num_bins = mapper.max_num_bins
    if num_bins > 256:
        return None
    bins_fm = mapper.transform_fm(X, dtype=np.uint8)

    if init_scores is not None:
        base = np.zeros(k, dtype=np.float64)
        scores = np.broadcast_to(
            np.asarray(init_scores, dtype=np.float64).reshape(n, -1),
            (n, k)).copy()
    else:
        base = init_score(objective, np.asarray(y, dtype=np.float64), k,
                          alpha=params.alpha)
        scores = np.tile(base, (n, 1)).astype(np.float64)
    booster = Booster(params, mapper, base_score=base)
    if init_model is not None:
        booster.trees = [list(g) for g in init_model.trees]
        booster.base_score = init_model.base_score
        if init_model.trees:
            scores = init_model.raw_predict(
                X, num_iteration=len(init_model.trees)).reshape(n, -1)

    metric = params.metric or default_metric(objective)
    higher_better = metric in _HIGHER_BETTER
    best_val, best_iter, rounds_no_improve = \
        (-np.inf if higher_better else np.inf), -1, 0
    val_X, val_y = valid if valid is not None else (None, None)

    is_rf = params.boosting_type == "rf"
    is_dart = params.boosting_type == "dart"
    is_goss = params.boosting_type == "goss"
    lr = 1.0 if is_rf else params.learning_rate
    bag_mask = np.ones(n, dtype=bool)
    yv = np.asarray(y, dtype=np.float64)
    wv = np.asarray(weights, dtype=np.float64) if weights is not None else None

    from ..obs.metrics import TrainRecorder

    recorder = TrainRecorder("gbdt_native")
    for it in range(params.num_iterations):
        _it_t0 = _now()
        _faults.fire(_faults.TRAIN_STEP, iteration=it, engine="native")
        dropped: List[int] = []
        if is_dart and booster.trees:
            n_trees = len(booster.trees)
            if params.uniform_drop:
                drop_mask = rng.random(n_trees) < params.drop_rate
                dropped = list(np.where(drop_mask)[0][: params.max_drop])
            else:
                n_drop = min(max(1, int(n_trees * params.drop_rate)),
                             params.max_drop)
                dropped = list(rng.choice(n_trees, size=n_drop,
                                          replace=False))
            for di in dropped:
                for kk in range(k):
                    scores[:, kk] -= _tree_contrib(booster.trees[di][kk], X)

        sc = scores[:, 0] if k == 1 else scores
        g, h = _grad_hess_np(objective, sc, yv, wv, params.alpha)

        row_mask = bag_mask
        if is_goss:
            g_abs = np.abs(g)
            if g_abs.ndim == 2:
                g_abs = g_abs.sum(axis=1)
            top_n = int(n * params.top_rate)
            other_n = int(n * params.other_rate)
            # argpartition: the top-|g| SET is what GOSS needs, not its
            # order — O(n) beats the device path's full argsort here
            # (selection was ~20% of the native 200k GOSS fit)
            part = np.argpartition(-g_abs, max(top_n - 1, 0))
            row_mask = np.zeros(n, dtype=bool)
            row_mask[part[:top_n]] = True
            rest = part[top_n:]
            picked = rng.choice(len(rest), size=min(other_n, len(rest)),
                                replace=False)
            row_mask[rest[picked]] = True
            amplify = (1.0 - params.top_rate) / max(params.other_rate, 1e-12)
            amp = np.ones(n)
            amp[rest] = amplify
            g, h = g * (amp if g.ndim == 1 else amp[:, None]), \
                h * (amp if h.ndim == 1 else amp[:, None])
        elif ((params.bagging_fraction < 1.0
               or params.pos_bagging_fraction < 1.0
               or params.neg_bagging_fraction < 1.0)
              and (is_rf or params.bagging_freq > 0)
              and it % max(params.bagging_freq, 1) == 0):
            if (params.pos_bagging_fraction < 1.0
                    or params.neg_bagging_fraction < 1.0):
                pos = np.asarray(y) > 0.5
                frac = np.where(pos, params.pos_bagging_fraction,
                                params.neg_bagging_fraction)
                bag_mask = rng.random(n) < frac
            else:
                bag_mask = rng.random(n) < params.bagging_fraction
            row_mask = bag_mask

        feature_mask = None
        if params.feature_fraction < 1.0:
            m = np.zeros(num_f, dtype=bool)
            n_feat = max(1, int(num_f * params.feature_fraction))
            m[rng.choice(num_f, size=n_feat, replace=False)] = True
            feature_mask = m

        group: List[Tree] = []
        for kk in range(k):
            gk = np.ascontiguousarray(g if g.ndim == 1 else g[:, kk],
                                      dtype=np.float32)
            hk = np.ascontiguousarray(h if h.ndim == 1 else h[:, kk],
                                      dtype=np.float32)
            res = native_loader.gbdt_grow_tree(
                bins_fm, gk, hk,
                None if row_mask.all() else row_mask, feature_mask,
                num_bins=num_bins, num_leaves=params.num_leaves,
                max_depth=params.max_depth,
                min_data_in_leaf=params.min_data_in_leaf,
                min_sum_hessian=params.min_sum_hessian_in_leaf,
                min_gain_to_split=params.min_gain_to_split,
                lambda_l1=params.lambda_l1, lambda_l2=params.lambda_l2,
                max_delta_step=params.max_delta_step)
            if res is None:
                return None
            feat = res["feature"]
            thr = np.zeros(len(feat), dtype=np.float64)
            for i in np.nonzero(feat >= 0)[0]:
                thr[i] = mapper.bin_upper_value(int(feat[i]),
                                                int(res["threshold_bin"][i]))
            tree = Tree(
                feature=feat, threshold=thr,
                threshold_bin=res["threshold_bin"],
                default_left=res["default_left"], left=res["left"],
                right=res["right"], value=res["value"], gain=res["gain"],
                count=res["count"], weight=res["weight"])
            shrink = lr
            if is_dart and dropped:
                shrink = lr / (len(dropped) + lr)
            tree.shrinkage = shrink
            group.append(tree)
            scores[:, kk] += tree.value[res["leaf_of_row"]] * shrink
        if is_dart and dropped:
            factor = len(dropped) / (len(dropped) + lr)
            for di in dropped:
                for kk in range(k):
                    booster.trees[di][kk].shrinkage *= factor
                    scores[:, kk] += _tree_contrib(booster.trees[di][kk], X)
        booster.trees.append(group)

        if params.train_metric and log:
            tm = eval_metric(metric, scores[:, 0] if k == 1 else scores, yv)
            recorder.metric(f"train_{metric}", tm)
            log(f"[{it + 1}] train {metric}={tm:.6f}")
        if val_X is not None:
            val_scores = booster.raw_predict(
                val_X, num_iteration=len(booster.trees))
            m = eval_metric(metric, val_scores,
                            np.asarray(val_y, dtype=np.float64), valid_groups)
            recorder.metric(f"valid_{metric}", m)
            improved = m > best_val if higher_better else m < best_val
            if improved:
                best_val, best_iter, rounds_no_improve = \
                    m, len(booster.trees), 0
            else:
                rounds_no_improve += 1
            if log:
                log(f"[{it + 1}] valid {metric}={m:.6f}")
            if params.early_stopping_round > 0 \
                    and rounds_no_improve >= params.early_stopping_round:
                booster.best_iteration = best_iter
                if log:
                    log(f"early stopping at iteration {it + 1}, "
                        f"best {best_iter}")
                recorder.step(_now() - _it_t0, examples=n)
                break
        elif log and not params.train_metric and (it + 1) % 10 == 0:
            m = eval_metric(metric, scores[:, 0] if k == 1 else scores, yv)
            log(f"[{it + 1}] train {metric}={m:.6f}")
        recorder.step(_now() - _it_t0, examples=n)

    if is_rf and booster.trees:
        inv = 1.0 / len(booster.trees)
        for gtrees in booster.trees:
            for t in gtrees:
                t.shrinkage = inv
    return booster


def train(params: TrainParams,
          X: np.ndarray, y: np.ndarray,
          weights: Optional[np.ndarray] = None,
          groups: Optional[np.ndarray] = None,
          valid: Optional[Tuple[np.ndarray, np.ndarray]] = None,
          valid_groups: Optional[np.ndarray] = None,
          init_scores: Optional[np.ndarray] = None,
          init_model: Optional[Booster] = None,
          log: Optional[Callable[[str], None]] = None,
          mesh=None, checkpoint=None) -> Booster:
    """Full training: bin, boost, early-stop. Returns a Booster.

    ``mesh``: optional jax Mesh — rows are sharded over the ``data`` axis and the
    histogram scatter becomes a cross-shard reduction (GSPMD inserts the psum):
    the TPU equivalent of LightGBM's socket-ring data-parallel mode
    (TrainUtils.scala:383-418). Rows are padded to a shard multiple with
    zero-hessian padding so they never influence splits (empty-partition
    IgnoreStatus parity, TrainUtils.scala:332-341).

    ``checkpoint``: optional gbdt.checkpoint.CheckpointConfig — atomically
    persists the model + loop state every k iterations and resumes an
    interrupted fit from the last checkpoint, replaying the remaining
    iterations identically to an uninterrupted run (pins the fit to the
    per-iteration host-orchestrated loop; see CheckpointConfig docs).
    """
    if checkpoint is not None:
        from .checkpoint import (check_params_match, load_checkpoint,
                                 save_checkpoint)
    # native C++ host engine for small fits (and CPU-only hosts): decided
    # before ANY device work so the tunnel/H2D is never touched.
    # Checkpointed fits skip it — the native loop keeps its state in C++.
    if mesh is None and groups is None and checkpoint is None \
            and _native_train_ok(params, len(y)):
        nb = _train_native(params, X, y, weights, valid, valid_groups,
                           init_scores, init_model, log)
        if nb is not None:
            return nb

    import jax
    import jax.numpy as jnp

    from .pallas_hist import CHUNK

    # Pad rows so every device array is a CHUNK multiple (the histogram
    # kernel would otherwise jnp.pad inside jit — a whole-array copy that
    # OOMed the 10M-row bench) and, when sharded, a per-shard CHUNK
    # multiple. Padded rows: NaN features (bin 0), zero label/weight,
    # excluded from training via pad_mask (empty-partition IgnoreStatus
    # parity, TrainUtils.scala:332-341).
    shard_put = bins_put = None
    n_shards = 1
    if mesh is not None:
        from ..parallel.mesh import DATA_AXIS, data_sharding

        n_shards = int(mesh.shape.get(DATA_AXIS, 1))
    row_mult = CHUNK * max(n_shards, 1)
    pad = (-len(y)) % row_mult
    if pad:
        X = np.concatenate([X, np.full((pad, X.shape[1]), np.nan)])
        y = np.concatenate([y, np.zeros(pad)])
        if weights is not None:
            weights = np.concatenate([weights, np.zeros(pad)])
        if groups is not None:
            groups = np.concatenate([groups, np.full(pad, -1)])
    if n_shards > 1:
        from jax.sharding import NamedSharding, PartitionSpec

        from ..parallel.mesh import DATA_AXIS, data_sharding

        sharding = data_sharding(mesh)
        shard_put = lambda a: jax.device_put(a, sharding)
        # feature-major bins shard the ROW dim, which is dim 1
        bins_sharding = NamedSharding(mesh, PartitionSpec(None, DATA_AXIS))
        bins_put = lambda a: jax.device_put(a, bins_sharding)
    pad_mask = np.ones(len(y), dtype=bool)
    if pad:
        pad_mask[-pad:] = False

    n, num_f = X.shape
    n_real = int(pad_mask.sum())
    k = max(params.num_class, 1)
    objective = params.objective
    rng = np.random.default_rng(params.seed or params.bagging_seed)

    if init_model is not None and init_model.bin_mapper is not None:
        mapper = init_model.bin_mapper
    else:
        mapper = BinMapper.fit(X[:n_real], params.max_bin,
                               params.categorical_feature, seed=params.seed,
                               max_bin_by_feature=params.max_bin_by_feature)
    # the mapper (possibly inherited from init_model with a different max_bin)
    # is the sole authority on bin count — mixing in params.max_bin would corrupt
    # the flat scatter indices in compute_histogram
    num_bins = mapper.max_num_bins
    put = shard_put or jax.device_put
    put_bins = bins_put or jax.device_put
    # feature-major [F, N] device layout (column store, like LightGBM's own
    # Dataset): minor dim rows -> no XLA lane padding (an [N, 28] int32
    # array tiles 28 -> 128 lanes, a 4.6x HBM blowup at 10M rows). Bins ship
    # as uint8 when they fit (4x less H2D — 280 MB vs 1.1 GB at 10M rows
    # through the host link) and widen once on device.
    u8 = num_bins <= 256
    bin_dtype = np.uint8 if u8 else np.int32
    timing = os.environ.get("MMLSPARK_TPU_GBDT_TIMING", "") not in ("", "0")
    t_bins = _now() if timing else 0.0
    if bins_put is None and n * num_f >= 1 << 22:
        # Overlapped bin+ship: the MAIN thread bins columns (the host has
        # one core — a transform pool cannot help) while a single worker
        # thread ships each finished slab (device_put releases the GIL
        # during the tunnel write, measured full overlap: 28 slab puts ride
        # inside the binning wall clock — tools/profile_gbdt_10m.py, r4).
        import queue
        import threading

        slabs: List = [None] * num_f
        slab_q: "queue.Queue" = queue.Queue()
        worker_err: List[BaseException] = []

        def _put_worker():
            while True:
                item = slab_q.get()
                if item is None:
                    return
                fi, arr = item
                try:
                    slabs[fi] = jax.device_put(arr)
                except BaseException as e:  # surface after join, not as a
                    worker_err.append(e)    # confusing stack(None) TypeError
                    return

        th = threading.Thread(target=_put_worker, daemon=True)
        th.start()
        try:
            for f in range(num_f):
                col = mapper.transform_col(f, np.ascontiguousarray(X[:, f]))
                slab_q.put((f, col.astype(bin_dtype)))
        finally:
            slab_q.put(None)
            th.join()
        if worker_err:
            raise worker_err[0]
        bins_dev = jnp.stack(slabs, axis=0)
        if u8:
            bins_dev = _widen_bins(bins_dev)
    else:
        bins_fm = mapper.transform_fm(X, dtype=bin_dtype)
        if u8:
            bins_dev = _widen_bins(put_bins(jnp.asarray(bins_fm)))
        else:
            bins_dev = put_bins(jnp.asarray(bins_fm))
    if timing:
        print(f"[gbdt-bins] transform+ship {_now() - t_bins:.3f}s",
              flush=True)

    labels = put(jnp.asarray(y, dtype=jnp.float32))
    w_dev = put(jnp.asarray(weights, dtype=jnp.float32)) if weights is not None else None
    g_dev = put(jnp.asarray(groups, dtype=jnp.int32)) if groups is not None else None
    # lambdarank group layout is static across boosting: segment once here
    group_seg = (segment_groups(groups)
                 if groups is not None and objective == "lambdarank" else None)

    if init_scores is not None:
        # per-row init score (initScoreCol): boosting starts from it, but it is
        # NOT part of the serialized model (LightGBM init_score semantics)
        base = np.zeros(k, dtype=np.float64)
        pad_rows = n - len(init_scores)
        init_arr = np.asarray(init_scores, dtype=np.float64).reshape(len(init_scores), -1)
        if pad_rows:
            init_arr = np.concatenate([init_arr, np.zeros((pad_rows, init_arr.shape[1]))])
        scores = np.broadcast_to(init_arr, (n, k)).copy()
    else:
        base = init_score(objective, np.asarray(y[:n_real], dtype=np.float64),
                          k, alpha=params.alpha)
        scores = np.tile(base, (n, 1)).astype(np.float64)
    booster = Booster(params, mapper, base_score=base)
    if init_model is not None:
        booster.trees = [list(g) for g in init_model.trees]
        booster.base_score = init_model.base_score
        if init_model.trees:
            # seed from ALL inherited trees (they are all carried into the merged
            # model), not the early-stopped prefix
            scores = init_model.raw_predict(
                X, num_iteration=len(init_model.trees)).reshape(n, -1)

    metric = params.metric or default_metric(objective)
    higher_better = metric in _HIGHER_BETTER
    best_val = -np.inf if higher_better else np.inf
    best_iter = -1
    rounds_no_improve = 0

    val_X = val_y = None
    if valid is not None:
        val_X, val_y = valid

    config = GrowerConfig(
        num_leaves=params.num_leaves, max_depth=params.max_depth,
        min_data_in_leaf=params.min_data_in_leaf,
        min_sum_hessian_in_leaf=params.min_sum_hessian_in_leaf,
        min_gain_to_split=params.min_gain_to_split,
        lambda_l1=params.lambda_l1, lambda_l2=params.lambda_l2,
        max_delta_step=params.max_delta_step,
        cat_smooth=params.cat_smooth, cat_l2=params.cat_l2,
        max_cat_threshold=params.max_cat_threshold)

    # categorical SET splits (LightGBM num_cat machinery): features flagged
    # categorical split by sorted-gradient-prefix subsets
    cat_args = None
    if params.categorical_feature:
        cat_mask_np = np.zeros(num_f, dtype=bool)
        cat_mask_np[list(params.categorical_feature)] = True
        cat_args = (jnp.asarray(cat_mask_np), np.float32(params.cat_smooth),
                    np.float32(params.cat_l2),
                    np.int32(params.max_cat_threshold))

    is_rf = params.boosting_type == "rf"
    is_dart = params.boosting_type == "dart"
    is_goss = params.boosting_type == "goss"
    lr = 1.0 if is_rf else params.learning_rate
    bag_mask = np.ones(n, dtype=bool)  # persists across iters (bagging_freq reuse)

    # ----- checkpoint resume: restore model + loop state (scores, RNG
    # stream, bagging mask, early-stopping bookkeeping) so iterations
    # start_it..N replay the uninterrupted computation exactly
    start_it = 0
    if checkpoint is not None and checkpoint.resume:
        ck = load_checkpoint(checkpoint.path)
        if ck is not None:
            check_params_match(ck["params"], dataclasses.asdict(params),
                               checkpoint.path)
            restored = Booster.from_string(ck["model"])
            booster.trees = restored.trees
            booster.base_score = restored.base_score
            if ck["scores"].shape != (n, k):
                raise ValueError(
                    f"checkpoint {checkpoint.path!r} scores shape "
                    f"{ck['scores'].shape} does not match this dataset "
                    f"({(n, k)}); resume requires the same data and mesh")
            scores = ck["scores"]
            rng.bit_generator.state = ck["rng_state"]
            bag_mask = ck["bag_mask"].astype(bool)
            best_val = ck["best_val"]
            best_iter = ck["best_iter"]
            rounds_no_improve = ck["rounds_no_improve"]
            start_it = int(ck["iteration"])

    # whole-run fused path: every boosting iteration inside ONE lax.scan
    # dispatch — no per-tree host round trips at all
    if _scan_train_ok(params, objective, valid, log, shard_put, checkpoint):
        row_masks, feat_masks, ok = _scan_precompute_masks(
            params, rng, n, num_f, np.asarray(y), is_rf)
        if ok:
            from ..core.runtime import ensure_compile_cache

            ensure_compile_cache()
            _train_scan(params, config, booster, mapper, bins_dev, labels,
                        w_dev, scores, n, num_f, num_bins, k, lr,
                        row_masks, feat_masks, pad_mask=pad_mask,
                        cat_args=cat_args)
            if is_rf and booster.trees:
                inv = 1.0 / len(booster.trees)
                for gtrees in booster.trees:
                    for t in gtrees:
                        t.shrinkage = inv
            return booster

    # single-device accelerator fast path: keep the running scores ON DEVICE
    # (Kahan-compensated f32 — see _add_leaf_values) and update them from the
    # fused grower's device-resident row routing — no per-iter [N] score
    # upload or row fetch. Dart is excluded (it rewrites scores on host when
    # dropping/re-adding trees), the sharded path is excluded (its grower
    # already returns host rows through the per-shard kernels), and CPU keeps
    # the exact-f64 host accumulation (in-process dispatch is cheap there).
    fast_scores = (shard_put is None and not is_dart
                   and jax.default_backend() != "cpu")
    max_nodes = 2 * params.num_leaves - 1
    score_dev = comp_dev = None
    if fast_scores:
        score_dev = jax.device_put(jnp.asarray(
            scores[:, 0] if k == 1 else scores, dtype=jnp.float32))
        comp_dev = jnp.zeros_like(score_dev)

    def _host_scores():
        if not fast_scores:
            return scores
        s, c = fetch_global((score_dev, comp_dev))
        return (np.asarray(s, dtype=np.float64)
                + np.asarray(c, dtype=np.float64)).reshape(n, -1)

    from ..obs.metrics import TrainRecorder

    recorder = TrainRecorder("gbdt")
    for it in range(start_it, params.num_iterations):
        _it_t0 = _now()
        # chaos seam: a planned fault here simulates preemption mid-train
        # (the last checkpoint is on disk; resume replays from it)
        _faults.fire(_faults.TRAIN_STEP, iteration=it)
        # ----- dart: drop a subset of existing trees from the current scores
        dropped: List[int] = []
        if is_dart and booster.trees:
            n_trees = len(booster.trees)
            if params.uniform_drop:
                drop_mask = rng.random(n_trees) < params.drop_rate
                dropped = list(np.where(drop_mask)[0][: params.max_drop])
            else:
                n_drop = min(max(1, int(n_trees * params.drop_rate)), params.max_drop)
                dropped = list(rng.choice(n_trees, size=n_drop, replace=False))
            for di in dropped:
                for kk in range(k):
                    scores[:, kk] -= _tree_contrib(booster.trees[di][kk], X)

        if not fast_scores:
            score_dev = put(jnp.asarray(scores[:, 0] if k == 1 else scores,
                                        dtype=jnp.float32))
        g, h = grad_hess(objective, score_dev, labels, w_dev, params.alpha,
                         g_dev, group_segments=group_seg)

        # ----- bagging / goss row selection
        row_mask = bag_mask
        if is_goss:
            g_abs = np.abs(np.asarray(fetch_global(g)))
            if g_abs.ndim == 2:
                g_abs = g_abs.sum(axis=1)
            # pad rows sit at the end; goss ranks/samples REAL rows only
            g_abs = g_abs[:n_real]
            top_n = int(n_real * params.top_rate)
            other_n = int(n_real * params.other_rate)
            order = np.argsort(-g_abs)
            row_mask = np.zeros(n, dtype=bool)
            row_mask[order[:top_n]] = True
            rest = order[top_n:]
            picked = rng.choice(len(rest), size=min(other_n, len(rest)), replace=False)
            row_mask[rest[picked]] = True
            amplify = (1.0 - params.top_rate) / max(params.other_rate, 1e-12)
            amp = np.ones(n, dtype=np.float32)
            amp[rest] = amplify
            amp_dev = jnp.asarray(amp)
            g, h = g * (amp_dev if g.ndim == 1 else amp_dev[:, None]), \
                   h * (amp_dev if h.ndim == 1 else amp_dev[:, None])
        elif ((params.bagging_fraction < 1.0
               or params.pos_bagging_fraction < 1.0
               or params.neg_bagging_fraction < 1.0)
              and (is_rf or params.bagging_freq > 0)
              and it % max(params.bagging_freq, 1) == 0):
            # resample every bagging_freq iterations, reuse the subset in between
            if (params.pos_bagging_fraction < 1.0
                    or params.neg_bagging_fraction < 1.0):
                # class-aware bagging (binary): per-class keep fractions
                # (LightGBM pos/neg_bagging_fraction; overrides the uniform
                # fraction like LightGBM does)
                pos = np.asarray(y) > 0.5
                frac = np.where(pos, params.pos_bagging_fraction,
                                params.neg_bagging_fraction)
                bag_mask = rng.random(n) < frac
            else:
                bag_mask = rng.random(n) < params.bagging_fraction
            row_mask = bag_mask

        # ----- feature subsampling
        feature_mask = None
        if params.feature_fraction < 1.0:
            m = np.zeros(num_f, dtype=bool)
            n_feat = max(1, int(num_f * params.feature_fraction))
            m[rng.choice(num_f, size=n_feat, replace=False)] = True
            feature_mask = jnp.asarray(m)

        row_mask &= pad_mask
        mask_dev = put(jnp.asarray(row_mask))
        group: List[Tree] = []
        for kk in range(k):
            gk = g if g.ndim == 1 else g[:, kk]
            hk = h if h.ndim == 1 else h[:, kk]
            tree, leaf_of_row = grow_tree(bins_dev, gk, hk, mask_dev, num_bins,
                                          config, mapper, feature_mask,
                                          device_rows=fast_scores,
                                          cat_args=cat_args)
            shrink = lr
            if is_dart and dropped:
                shrink = lr / (len(dropped) + lr)  # dart normalization
            tree.shrinkage = shrink
            group.append(tree)
            if fast_scores:
                # rows may be host numpy if the grower fell back to the
                # per-split path (memory budget) — device scores either way
                vals = np.zeros(max(max_nodes, len(tree.value)),
                                dtype=np.float32)
                vals[: len(tree.value)] = tree.value * shrink
                score_dev, comp_dev = _add_leaf_values(
                    score_dev, comp_dev, jnp.asarray(vals),
                    jnp.asarray(leaf_of_row), kk if k > 1 else None)
            else:
                scores[:, kk] += tree.value[leaf_of_row] * shrink
        if is_dart and dropped:
            # scale dropped trees and add them back
            factor = len(dropped) / (len(dropped) + lr)
            for di in dropped:
                for kk in range(k):
                    booster.trees[di][kk].shrinkage *= factor
                    scores[:, kk] += _tree_contrib(booster.trees[di][kk], X)
        booster.trees.append(group)

        # ----- eval + early stopping
        if params.train_metric and log:
            host_sc = _host_scores()
            tm = eval_metric(metric, host_sc[:n_real, 0] if k == 1
                             else host_sc[:n_real],
                             np.asarray(y[:n_real], dtype=np.float64),
                             groups[:n_real] if groups is not None else None)
            recorder.metric(f"train_{metric}", tm)
            log(f"[{it + 1}] train {metric}={tm:.6f}")
        if val_X is not None:
            val_scores = booster.raw_predict(val_X, num_iteration=len(booster.trees))
            m = eval_metric(metric, val_scores, np.asarray(val_y, dtype=np.float64),
                            valid_groups)
            recorder.metric(f"valid_{metric}", m)
            improved = m > best_val if higher_better else m < best_val
            if improved:
                best_val, best_iter, rounds_no_improve = m, len(booster.trees), 0
            else:
                rounds_no_improve += 1
            if log:
                log(f"[{it + 1}] valid {metric}={m:.6f}")
            if params.early_stopping_round > 0 \
                    and rounds_no_improve >= params.early_stopping_round:
                booster.best_iteration = best_iter
                if log:
                    log(f"early stopping at iteration {it + 1}, best {best_iter}")
                recorder.step(_now() - _it_t0, examples=n_real)
                break
        elif log and not params.train_metric and (it + 1) % 10 == 0:
            host_sc = _host_scores()[:n_real]
            train_scores = host_sc[:, 0] if k == 1 else host_sc
            m = eval_metric(metric, train_scores,
                            np.asarray(y[:n_real], dtype=np.float64),
                            groups[:n_real] if groups is not None else None)
            log(f"[{it + 1}] train {metric}={m:.6f}")

        # ----- atomic checkpoint every k iterations (and at the end)
        if checkpoint is not None and (
                (it + 1) % max(checkpoint.every_k, 1) == 0
                or it + 1 == params.num_iterations):
            _ck_t0 = _now()
            save_checkpoint(
                checkpoint.path,
                params_dict=dataclasses.asdict(params),
                model_string=booster.to_string(),
                iteration=it + 1,
                scores=_host_scores() if fast_scores else scores,
                rng_state=rng.bit_generator.state,
                bag_mask=bag_mask,
                best_val=best_val, best_iter=best_iter,
                rounds_no_improve=rounds_no_improve)
            recorder.checkpoint(_now() - _ck_t0)
        recorder.step(_now() - _it_t0, examples=n_real)

    if is_rf and booster.trees:
        inv = 1.0 / len(booster.trees)
        for gtrees in booster.trees:
            for t in gtrees:
                t.shrinkage = inv
    return booster


def _tree_contrib(tree: Tree, X: np.ndarray) -> np.ndarray:
    from .predict import predict_single_tree

    return predict_single_tree(tree, X)


@functools.partial(__import__("jax").jit, static_argnames=("kk",))
def _add_leaf_values(score, comp, values, rows, kk=None):
    """On-device score update: score += values[rows] (column kk if multiclass).

    Kahan-compensated: ``comp`` carries the rounding residual of every prior
    add, so small per-tree updates against a large running score are not lost
    to f32 (the accumulated sum keeps ~2x24-bit effective mantissa, standing
    in for the f64 host accumulation of the non-fast path). ``values`` is
    padded to the static max-node count so every tree of a run hits the same
    compiled executable."""
    upd = values[rows]
    if kk is not None:
        s_col, c_col = score[:, kk], comp[:, kk]
        y = upd + c_col
        t = s_col + y
        return (score.at[:, kk].set(t),
                comp.at[:, kk].set(y - (t - s_col)))
    y = upd + comp
    t = score + y
    return t, y - (t - score)
