"""Tree-ensemble prediction: vectorized host path + jitted device kernel.

LGBM_BoosterPredictForMat/PredictForMatSingle parity (driven by the reference's
scoring UDFs, lightgbm/LightGBMBooster.scala:21-148). The device kernel pads all
trees into one SoA tensor and traverses every (row, tree) pair in parallel with a
bounded gather loop — no per-row JNI calls, one XLA program for the whole forest.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .tree import Tree


def predict_single_tree(tree: Tree, X: np.ndarray) -> np.ndarray:
    """Host path: [N,F] raw floats -> [N] contributions (incl. shrinkage).

    Categorical SET nodes (tree.cat_sets): LightGBM semantics — the value
    is truncated to int and tested for set membership; members go left,
    everything else (incl. NaN and unseen categories) goes right."""
    n = X.shape[0]
    node = np.zeros(n, dtype=np.int64)
    has_cat = tree.cat_sets is not None
    active = tree.feature[node] != -1
    while active.any():
        cur = node[active]
        f = tree.feature[cur]
        x = X[active, f]
        miss = np.isnan(x)
        go_left = np.where(miss, tree.default_left[cur], x <= tree.threshold[cur])
        if has_cat:
            for nid in np.unique(cur):
                cset = tree.cat_sets[nid]
                if cset is None:
                    continue
                sel = cur == nid
                xv = x[sel]
                ok = ~np.isnan(xv)
                member = np.zeros(len(xv), dtype=bool)
                member[ok] = np.isin(xv[ok].astype(np.int64), cset)
                go_left[sel] = member
        node[active] = np.where(go_left, tree.left[cur], tree.right[cur])
        active = tree.feature[node] != -1
    return tree.value[node] * tree.shrinkage


def predict_ensemble(tree_groups: List[List[Tree]], X: np.ndarray,
                     num_class: int) -> np.ndarray:
    """[iterations][class] trees -> [N, num_class] raw score deltas."""
    n = X.shape[0]
    out = np.zeros((n, num_class), dtype=np.float64)
    for group in tree_groups:
        for k, tree in enumerate(group):
            out[:, k] += predict_single_tree(tree, X)
    return out


class DeviceEnsemble:
    """All trees padded into one SoA tensor; one jitted traversal for the forest.

    Used by the model stages' transform hot path: predict cost is
    O(depth * N * T) gathers, fully parallel on device.
    """

    def __init__(self, tree_groups: List[List[Tree]], num_class: int):
        trees = [t for g in tree_groups for t in g]
        self.num_class = num_class
        self.class_of_tree = np.array(
            [k for g in tree_groups for k in range(len(g))], dtype=np.int32)
        self.num_trees = len(trees)
        if not trees:
            return
        m = max(len(t.feature) for t in trees)
        self.max_depth = 0

        def pad(vals, fill, dtype):
            out = np.full((self.num_trees, m), fill, dtype=dtype)
            for i, v in enumerate(vals):
                out[i, :len(v)] = v
            return out

        self.feature = pad([t.feature for t in trees], -1, np.int32)
        self.threshold = pad([t.threshold for t in trees], 0.0, np.float32)
        self.default_left = pad([t.default_left for t in trees], True, bool)
        self.left = pad([t.left for t in trees], 0, np.int32)
        self.right = pad([t.right for t in trees], 0, np.int32)
        self.value = pad([np.asarray(t.value) * t.shrinkage for t in trees],
                         0.0, np.float32)
        # categorical SET nodes: padded per-node value sets [T, m, S] with
        # NaN fill (== compares false) — built only when the model has any.
        # High-cardinality sets (imported LightGBM models can carry
        # thousands of categories per node) would make both the [T, m, S]
        # tensor and the per-depth-step [N, T, S] gather blow up — those
        # models take the host traversal instead (self.cat_host_fallback).
        self.cat_vals = None
        self.is_cat = None
        self.cat_host_fallback = False
        self._tree_groups = tree_groups
        if any(t.cat_sets is not None for t in trees):
            smax = max((len(s) for t in trees if t.cat_sets is not None
                        for s in t.cat_sets if s is not None), default=1)
            if smax > 256 or self.num_trees * m * smax > 1 << 27:
                self.cat_host_fallback = True
            else:
                cv = np.full((self.num_trees, m, smax), np.nan,
                             dtype=np.float32)
                ic = np.zeros((self.num_trees, m), dtype=bool)
                for i, t in enumerate(trees):
                    if t.cat_sets is None:
                        continue
                    for nid, s in enumerate(t.cat_sets):
                        if s is not None:
                            cv[i, nid, : len(s)] = s
                            ic[i, nid] = True
                self.cat_vals = cv
                self.is_cat = ic
        for t in trees:
            self.max_depth = max(self.max_depth, _tree_depth(t))
        self._jitted = None

    def _compile(self):
        import jax
        import jax.numpy as jnp

        depth = max(self.max_depth, 1)
        feature = jnp.asarray(self.feature)
        threshold = jnp.asarray(self.threshold)
        default_left = jnp.asarray(self.default_left)
        left = jnp.asarray(self.left)
        right = jnp.asarray(self.right)
        value = jnp.asarray(self.value)
        class_onehot = jax.nn.one_hot(
            jnp.asarray(self.class_of_tree), self.num_class, dtype=jnp.float32)

        cat_vals = (jnp.asarray(self.cat_vals)
                    if self.cat_vals is not None else None)
        is_cat = jnp.asarray(self.is_cat) if self.is_cat is not None else None

        def fwd(X):
            n = X.shape[0]
            t = feature.shape[0]
            node = jnp.zeros((n, t), dtype=jnp.int32)

            t_idx = jnp.arange(t, dtype=jnp.int32)[None, :]

            def body(_, node):
                # advanced-index gathers ([T, m][t, node] -> [N, T]): the
                # take_along_axis(arr[None], node[:, :, None]) form lowered
                # to a broadcast materializing [N, T, m] per field — ~2.4 GB
                # at 200k rows x 50 trees and 29x slower end to end
                # (BENCH_gbdt_train.json predict history)
                f = feature[t_idx, node]
                thr = threshold[t_idx, node]
                dl = default_left[t_idx, node]
                l = left[t_idx, node]
                r = right[t_idx, node]
                x = jnp.take_along_axis(X, jnp.maximum(f, 0), axis=1)
                miss = jnp.isnan(x)
                go_left = jnp.where(miss, dl, x <= thr)
                if cat_vals is not None:
                    # set membership (truncated-int equality; NaN pads and
                    # NaN inputs compare false -> right)
                    sv = cat_vals[t_idx, node]            # [N, T, S]
                    member = jnp.any(
                        jnp.trunc(x)[:, :, None] == sv, axis=-1)
                    icn = is_cat[t_idx, node]             # [N, T]
                    go_left = jnp.where(icn, member, go_left)
                nxt = jnp.where(go_left, l, r)
                return jnp.where(f == -1, node, nxt)

            node = jax.lax.fori_loop(0, depth, body, node)
            leaf_vals = value[t_idx, node]
            return leaf_vals @ class_onehot          # [N, num_class]

        return jax.jit(fwd)

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """[N,F] float32 -> [N, num_class] summed tree outputs (device)."""
        if self.num_trees == 0:
            return np.zeros((X.shape[0], self.num_class), dtype=np.float64)
        if self.cat_host_fallback:
            return predict_ensemble(self._tree_groups, np.asarray(X),
                                    self.num_class)
        if self._jitted is None:
            self._jitted = self._compile()
        return np.asarray(self._jitted(np.asarray(X, dtype=np.float32)),
                          dtype=np.float64)


def _tree_depth(tree: Tree) -> int:
    depth = np.zeros(len(tree.feature), dtype=np.int32)
    order = range(len(tree.feature))
    for i in order:  # parents precede children by construction
        if tree.feature[i] != -1:
            depth[tree.left[i]] = depth[i] + 1
            depth[tree.right[i]] = depth[i] + 1
    return int(depth.max()) + 1 if len(depth) else 1
