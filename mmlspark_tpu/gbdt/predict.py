"""Tree-ensemble prediction: vectorized host path + jitted device kernel.

LGBM_BoosterPredictForMat/PredictForMatSingle parity (driven by the reference's
scoring UDFs, lightgbm/LightGBMBooster.scala:21-148). The device kernel pads all
trees into one SoA tensor and traverses every (row, tree) pair in parallel with a
bounded gather loop — no per-row JNI calls, one XLA program for the whole forest.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .tree import Tree


def predict_single_tree(tree: Tree, X: np.ndarray) -> np.ndarray:
    """Host path: [N,F] raw floats -> [N] contributions (incl. shrinkage)."""
    n = X.shape[0]
    node = np.zeros(n, dtype=np.int64)
    active = tree.feature[node] != -1
    while active.any():
        cur = node[active]
        f = tree.feature[cur]
        x = X[active, f]
        miss = np.isnan(x)
        go_left = np.where(miss, tree.default_left[cur], x <= tree.threshold[cur])
        node[active] = np.where(go_left, tree.left[cur], tree.right[cur])
        active = tree.feature[node] != -1
    return tree.value[node] * tree.shrinkage


def predict_ensemble(tree_groups: List[List[Tree]], X: np.ndarray,
                     num_class: int) -> np.ndarray:
    """[iterations][class] trees -> [N, num_class] raw score deltas."""
    n = X.shape[0]
    out = np.zeros((n, num_class), dtype=np.float64)
    for group in tree_groups:
        for k, tree in enumerate(group):
            out[:, k] += predict_single_tree(tree, X)
    return out


class DeviceEnsemble:
    """All trees padded into one SoA tensor; one jitted traversal for the forest.

    Used by the model stages' transform hot path: predict cost is
    O(depth * N * T) gathers, fully parallel on device.
    """

    def __init__(self, tree_groups: List[List[Tree]], num_class: int):
        trees = [t for g in tree_groups for t in g]
        self.num_class = num_class
        self.class_of_tree = np.array(
            [k for g in tree_groups for k in range(len(g))], dtype=np.int32)
        self.num_trees = len(trees)
        if not trees:
            return
        m = max(len(t.feature) for t in trees)
        self.max_depth = 0

        def pad(vals, fill, dtype):
            out = np.full((self.num_trees, m), fill, dtype=dtype)
            for i, v in enumerate(vals):
                out[i, :len(v)] = v
            return out

        self.feature = pad([t.feature for t in trees], -1, np.int32)
        self.threshold = pad([t.threshold for t in trees], 0.0, np.float32)
        self.default_left = pad([t.default_left for t in trees], True, bool)
        self.left = pad([t.left for t in trees], 0, np.int32)
        self.right = pad([t.right for t in trees], 0, np.int32)
        self.value = pad([np.asarray(t.value) * t.shrinkage for t in trees],
                         0.0, np.float32)
        for t in trees:
            self.max_depth = max(self.max_depth, _tree_depth(t))
        self._jitted = None

    def _compile(self):
        import jax
        import jax.numpy as jnp

        depth = max(self.max_depth, 1)
        feature = jnp.asarray(self.feature)
        threshold = jnp.asarray(self.threshold)
        default_left = jnp.asarray(self.default_left)
        left = jnp.asarray(self.left)
        right = jnp.asarray(self.right)
        value = jnp.asarray(self.value)
        class_onehot = jax.nn.one_hot(
            jnp.asarray(self.class_of_tree), self.num_class, dtype=jnp.float32)

        def fwd(X):
            n = X.shape[0]
            t = feature.shape[0]
            node = jnp.zeros((n, t), dtype=jnp.int32)

            def body(_, node):
                f = jnp.take_along_axis(feature[None, :, :],
                                        node[:, :, None], axis=2)[:, :, 0]
                thr = jnp.take_along_axis(threshold[None, :, :],
                                          node[:, :, None], axis=2)[:, :, 0]
                dl = jnp.take_along_axis(default_left[None, :, :],
                                         node[:, :, None], axis=2)[:, :, 0]
                l = jnp.take_along_axis(left[None, :, :],
                                        node[:, :, None], axis=2)[:, :, 0]
                r = jnp.take_along_axis(right[None, :, :],
                                        node[:, :, None], axis=2)[:, :, 0]
                x = jnp.take_along_axis(X, jnp.maximum(f, 0), axis=1)
                miss = jnp.isnan(x)
                go_left = jnp.where(miss, dl, x <= thr)
                nxt = jnp.where(go_left, l, r)
                return jnp.where(f == -1, node, nxt)

            node = jax.lax.fori_loop(0, depth, body, node)
            leaf_vals = jnp.take_along_axis(value[None, :, :],
                                            node[:, :, None], axis=2)[:, :, 0]
            return leaf_vals @ class_onehot          # [N, num_class]

        return jax.jit(fwd)

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """[N,F] float32 -> [N, num_class] summed tree outputs (device)."""
        if self.num_trees == 0:
            return np.zeros((X.shape[0], self.num_class), dtype=np.float64)
        if self._jitted is None:
            self._jitted = self._compile()
        return np.asarray(self._jitted(np.asarray(X, dtype=np.float32)),
                          dtype=np.float64)


def _tree_depth(tree: Tree) -> int:
    depth = np.zeros(len(tree.feature), dtype=np.int32)
    order = range(len(tree.feature))
    for i in order:  # parents precede children by construction
        if tree.feature[i] != -1:
            depth[tree.left[i]] = depth[i] + 1
            depth[tree.right[i]] = depth[i] + 1
    return int(depth.max()) + 1 if len(depth) else 1
