"""Tree-ensemble prediction: vectorized host path + jitted device kernels.

LGBM_BoosterPredictForMat/PredictForMatSingle parity (driven by the reference's
scoring UDFs, lightgbm/LightGBMBooster.scala:21-148). Two device strategies:

- **GEMM forest** (default for numerical forests): tree traversal
  reformulated as matrix algebra on the MXU — the TPU-first design, since
  per-node gathers serialize badly on TPU (measured ~20k rows/s for the
  gather loop at 200k x 50 trees). Per row: comparison signs s_i = ±1 for
  every internal node of every tree (one [N, I] gather + compare), then
  ONE matmul against the ±1/0 path matrix C[i, l] (+1 left-ancestor, -1
  right-ancestor, 0 non-ancestor): a leaf l is reached iff (S @ C)[l]
  equals its path length. Leaf values arrive via a second matmul. All
  products are ±1/0 — exact in bf16 with f32 accumulation; the value
  matmul runs f32. Rows are chunked so [N, I]/[N, L] activations stay
  bounded.
- **Gather loop** (fallback): bounded per-depth gathers over the padded
  node SoA — used for categorical forests (set membership is not a sign
  comparison; small models use host traversal outright).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .tree import Tree


def predict_single_tree(tree: Tree, X: np.ndarray) -> np.ndarray:
    """Host path: [N,F] raw floats -> [N] contributions (incl. shrinkage).

    Categorical SET nodes (tree.cat_sets): LightGBM semantics — the value
    is truncated to int and tested for set membership; members go left,
    everything else (incl. NaN and unseen categories) goes right."""
    n = X.shape[0]
    node = np.zeros(n, dtype=np.int64)
    has_cat = tree.cat_sets is not None
    active = tree.feature[node] != -1
    while active.any():
        cur = node[active]
        f = tree.feature[cur]
        x = X[active, f]
        miss = np.isnan(x)
        go_left = np.where(miss, tree.default_left[cur], x <= tree.threshold[cur])
        if has_cat:
            for nid in np.unique(cur):
                cset = tree.cat_sets[nid]
                if cset is None:
                    continue
                sel = cur == nid
                xv = x[sel]
                ok = ~np.isnan(xv)
                member = np.zeros(len(xv), dtype=bool)
                member[ok] = np.isin(xv[ok].astype(np.int64), cset)
                go_left[sel] = member
        node[active] = np.where(go_left, tree.left[cur], tree.right[cur])
        active = tree.feature[node] != -1
    return tree.value[node] * tree.shrinkage


_FOREST_MEMO: dict = {}


def memoize_forest(tree_groups, tag: str, build):
    """Identity-memoized per-forest arrays for the native predict paths.

    Key: the first Tree object's id + ``tag`` (layout variant). A cache
    hit must prove the forest is the SAME sequence of Tree objects, not
    just the same head: boosters continued from one init_model share their
    prefix trees (Booster.trees copies the list but not the Tree objects),
    so two distinct forests can agree on (id(first), length, shrinkages).
    Validation therefore holds a weakref per tree and requires every
    weakref to resolve to the corresponding tree by identity (weakrefs
    also guard against id reuse after GC; Tree is an eq-dataclass and
    cannot key a WeakKeyDictionary). Per-tree shrinkage is checked too:
    the ONLY in-place Tree mutation in the codebase (dart rescales dropped
    trees' shrinkage between iterations, rf normalizes after training).
    Any new in-place mutation must extend THIS validation — it covers the
    dense and CSR layouts at once, which is why the helper is shared."""
    import weakref

    trees = [t for g in tree_groups for t in g]
    shr = tuple(float(t.shrinkage) for t in trees)
    # first+last+length in the key so prefix-sharing forests (same head,
    # different tails) cache SIMULTANEOUSLY instead of evicting each other
    key = (id(trees[0]), id(trees[-1]), len(trees), tag)
    cached = _FOREST_MEMO.get(key)
    if (cached is not None and len(cached[0]) == len(trees)
            and cached[1] == shr
            and all(r() is t for r, t in zip(cached[0], trees))):
        return cached[2]
    flat = build()
    if len(_FOREST_MEMO) >= 16:
        _FOREST_MEMO.pop(next(iter(_FOREST_MEMO)))
    _FOREST_MEMO[key] = (tuple(weakref.ref(t) for t in trees), shr, flat)
    return flat


def pad_soa(vals, fill, dtype, T: int, m: int) -> np.ndarray:
    """[T, m] padded struct-of-arrays field (shared by the device ensemble
    and the native host layouts)."""
    arr = np.full((T, m), fill, dtype=dtype)
    for i, v in enumerate(vals):
        arr[i, :len(v)] = v
    return arr


def _padded_forest_f64(tree_groups):
    """[T, m] padded SoA (f64 thresholds/values, value pre-scaled by
    shrinkage) for the native host traversal."""

    def build():
        trees = [t for g in tree_groups for t in g]
        m = max(len(t.feature) for t in trees)
        T = len(trees)
        return (pad_soa([t.feature for t in trees], -1, np.int32, T, m),
                pad_soa([t.threshold for t in trees], 0.0, np.float64, T, m),
                pad_soa([t.default_left for t in trees], True, bool, T, m),
                pad_soa([t.left for t in trees], 0, np.int32, T, m),
                pad_soa([t.right for t in trees], 0, np.int32, T, m),
                pad_soa([np.asarray(t.value) * t.shrinkage for t in trees],
                        0.0, np.float64, T, m),
                np.array([k for g in tree_groups for k in range(len(g))],
                         dtype=np.int32))

    return memoize_forest(tree_groups, "dense_f64", build)


def predict_ensemble(tree_groups: List[List[Tree]], X: np.ndarray,
                     num_class: int) -> np.ndarray:
    """[iterations][class] trees -> [N, num_class] raw score deltas.

    Native fast path (numeric forests): one C++ SoA traversal, f64
    end-to-end — bit-equal to the per-tree numpy loop below, which stays
    as the toolchain-free fallback, the categorical path, and the parity
    reference (gated equal in tests). The reference's scoring surface is
    LightGBM's C++ predict (LightGBMBooster.scala:21-148);
    MMLSPARK_TPU_NO_NATIVE_PREDICT=1 disables."""
    import os

    n = X.shape[0]
    trees = [t for g in tree_groups for t in g]
    if (trees and not any(t.cat_sets is not None for t in trees)
            and os.environ.get("MMLSPARK_TPU_NO_NATIVE_PREDICT",
                               "") in ("", "0")):
        from .. import native_loader

        flat = _padded_forest_f64(tree_groups)
        res = native_loader.forest_predict_f64(np.asarray(X), *flat,
                                               num_class)
        if res is not None:
            return res
    out = np.zeros((n, num_class), dtype=np.float64)
    for group in tree_groups:
        for k, tree in enumerate(group):
            out[:, k] += predict_single_tree(tree, X)
    return out


class DeviceEnsemble:
    """All trees padded into one SoA tensor; one jitted traversal for the forest.

    Used by the model stages' transform hot path: predict cost is
    O(depth * N * T) gathers, fully parallel on device.
    """

    def __init__(self, tree_groups: List[List[Tree]], num_class: int):
        trees = [t for g in tree_groups for t in g]
        self.num_class = num_class
        self.last_ingest_stats = None  # set by chunked ring scoring
        self.class_of_tree = np.array(
            [k for g in tree_groups for k in range(len(g))], dtype=np.int32)
        self.num_trees = len(trees)
        if not trees:
            return
        m = max(len(t.feature) for t in trees)
        self.max_depth = 0
        T = self.num_trees
        self.feature = pad_soa([t.feature for t in trees], -1, np.int32, T, m)
        self.threshold = pad_soa([t.threshold for t in trees], 0.0,
                                 np.float32, T, m)
        self.default_left = pad_soa([t.default_left for t in trees], True,
                                    bool, T, m)
        self.left = pad_soa([t.left for t in trees], 0, np.int32, T, m)
        self.right = pad_soa([t.right for t in trees], 0, np.int32, T, m)
        self.value = pad_soa(
            [np.asarray(t.value) * t.shrinkage for t in trees],
            0.0, np.float32, T, m)
        # categorical SET nodes: padded per-node value sets [T, m, S] with
        # NaN fill (== compares false) — built only when the model has any.
        # High-cardinality sets (imported LightGBM models can carry
        # thousands of categories per node) would make both the [T, m, S]
        # tensor and the per-depth-step [N, T, S] gather blow up — those
        # models take the host traversal instead (self.cat_host_fallback).
        self.cat_vals = None
        self.is_cat = None
        self.cat_host_fallback = False
        self._tree_groups = tree_groups
        if any(t.cat_sets is not None for t in trees):
            smax = max((len(s) for t in trees if t.cat_sets is not None
                        for s in t.cat_sets if s is not None), default=1)
            if smax > 256 or self.num_trees * m * smax > 1 << 27:
                self.cat_host_fallback = True
            else:
                cv = np.full((self.num_trees, m, smax), np.nan,
                             dtype=np.float32)
                ic = np.zeros((self.num_trees, m), dtype=bool)
                for i, t in enumerate(trees):
                    if t.cat_sets is None:
                        continue
                    for nid, s in enumerate(t.cat_sets):
                        if s is not None:
                            cv[i, nid, : len(s)] = s
                            ic[i, nid] = True
                self.cat_vals = cv
                self.is_cat = ic
        for t in trees:
            self.max_depth = max(self.max_depth, _tree_depth(t))
        self._jitted = None
        self._jitted_gather = None
        self._gemm = None
        if self.cat_vals is None and not self.cat_host_fallback:
            self._build_gemm(trees)

    def _build_gemm(self, trees):
        """Per-tree padded GEMM layout: comparison-sign x path-matrix
        forest evaluation (module docstring). Host-built once."""
        import os

        T = self.num_trees
        i_max = max(max((int((t.feature >= 0).sum()) for t in trees),
                        default=1), 1)
        l_max = max(max((t.num_leaves for t in trees), default=1), 1)
        if os.environ.get("MMLSPARK_TPU_NO_GEMM_PREDICT", "") not in ("", "0"):
            self._gemm = None
            return
        if T * i_max * l_max > 1 << 27:
            # imported forests can carry thousands of leaves per tree: the
            # [T, I, L] path matrix would be GBs — keep the gather kernel
            self._gemm = None
            return
        # activations scale with rows x T x (I + L) — x_sel/s [N, T, I]
        # (f32 + bf16) and z/reach [N, T, L] (f32 x2); shrink the row chunk
        # so one dispatch stays ~<=1.5 GB (a 1000-tree x 255-leaf imported
        # forest passes the path-matrix guard but costs ~3.6 MB per row)
        per_row = T * (6 * i_max + 8 * l_max)
        budget = 1.5e9
        chunk = int(budget // max(per_row, 1))
        self._gemm_row_chunk = max(256, min(self.GEMM_ROW_CHUNK,
                                            (chunk // 256) * 256))
        feat = np.zeros((T, i_max), dtype=np.int32)
        thr = np.zeros((T, i_max), dtype=np.float32)
        dl = np.zeros((T, i_max), dtype=bool)
        ivalid = np.zeros((T, i_max), dtype=np.float32)
        C = np.zeros((T, i_max, l_max), dtype=np.float32)
        plen = np.full((T, l_max), -1.0, dtype=np.float32)  # pad unreachable
        lval = np.zeros((T, l_max), dtype=np.float32)
        for ti, t in enumerate(trees):
            int_ids = np.nonzero(t.feature >= 0)[0]
            int_index = {int(nid): i for i, nid in enumerate(int_ids)}
            feat[ti, : len(int_ids)] = t.feature[int_ids]
            thr[ti, : len(int_ids)] = t.threshold[int_ids]
            dl[ti, : len(int_ids)] = t.default_left[int_ids]
            ivalid[ti, : len(int_ids)] = 1.0
            li = 0
            stack = [(0, [])]
            while stack:
                nid, path = stack.pop()
                if t.feature[nid] == -1:
                    for ii, sign in path:
                        C[ti, ii, li] = sign
                    plen[ti, li] = float(len(path))
                    lval[ti, li] = float(t.value[nid]) * t.shrinkage
                    li += 1
                else:
                    ii = int_index[int(nid)]
                    stack.append((int(t.left[nid]), path + [(ii, 1.0)]))
                    stack.append((int(t.right[nid]), path + [(ii, -1.0)]))
        self._gemm = (feat, thr, dl, ivalid, C, plen, lval)

    def _compile_gemm(self):
        import jax
        import jax.numpy as jnp

        feat_h, thr_h, dl_h, iv_h, C_h, plen_h, lval_h = self._gemm
        # ±1/0 operands are exact in bf16 (half the MXU passes); CPU XLA
        # has no bf16xbf16->f32 dot, so it keeps f32 (equally exact)
        mm_dtype = (jnp.bfloat16 if jax.default_backend() == "tpu"
                    else jnp.float32)
        feat = jnp.asarray(feat_h)
        thr = jnp.asarray(thr_h)
        dl = jnp.asarray(dl_h)
        iv = jnp.asarray(iv_h)
        Cb = jnp.asarray(C_h, dtype=mm_dtype)
        plen = jnp.asarray(plen_h)
        lval = jnp.asarray(lval_h)
        class_onehot = jax.nn.one_hot(
            jnp.asarray(self.class_of_tree), self.num_class,
            dtype=jnp.float32)

        def fwd(X):
            x_sel = X[:, feat]                       # [N, T, I] gather
            s = jnp.where(jnp.isnan(x_sel),
                          jnp.where(dl[None], 1.0, -1.0),
                          jnp.where(x_sel <= thr[None], 1.0, -1.0))
            s = (s * iv[None]).astype(mm_dtype)      # pad ints contribute 0
            # z[n,t,l] = sum_i s * C: ±1 products are exact in bf16, the
            # f32 accumulation holds small integers exactly
            z = jax.lax.dot_general(
                s, Cb, ((((2,), (1,)), ((1,), (0,)))),
                preferred_element_type=jnp.float32)  # [T, N, L]
            z = jnp.swapaxes(z, 0, 1)                # [N, T, L]
            reach = (z == plen[None]).astype(jnp.float32)
            contrib = jnp.sum(reach * lval[None], axis=2)   # [N, T]
            return contrib @ class_onehot            # [N, K]

        return jax.jit(fwd)

    def _compile(self):
        import jax
        import jax.numpy as jnp

        depth = max(self.max_depth, 1)
        feature = jnp.asarray(self.feature)
        threshold = jnp.asarray(self.threshold)
        default_left = jnp.asarray(self.default_left)
        left = jnp.asarray(self.left)
        right = jnp.asarray(self.right)
        value = jnp.asarray(self.value)
        class_onehot = jax.nn.one_hot(
            jnp.asarray(self.class_of_tree), self.num_class, dtype=jnp.float32)

        cat_vals = (jnp.asarray(self.cat_vals)
                    if self.cat_vals is not None else None)
        is_cat = jnp.asarray(self.is_cat) if self.is_cat is not None else None

        def fwd(X):
            n = X.shape[0]
            t = feature.shape[0]
            node = jnp.zeros((n, t), dtype=jnp.int32)

            t_idx = jnp.arange(t, dtype=jnp.int32)[None, :]

            def body(_, node):
                # advanced-index gathers ([T, m][t, node] -> [N, T]): the
                # take_along_axis(arr[None], node[:, :, None]) form lowered
                # to a broadcast materializing [N, T, m] per field — ~2.4 GB
                # at 200k rows x 50 trees and 29x slower end to end
                # (BENCH_gbdt_train.json predict history)
                f = feature[t_idx, node]
                thr = threshold[t_idx, node]
                dl = default_left[t_idx, node]
                l = left[t_idx, node]
                r = right[t_idx, node]
                x = jnp.take_along_axis(X, jnp.maximum(f, 0), axis=1)
                miss = jnp.isnan(x)
                go_left = jnp.where(miss, dl, x <= thr)
                if cat_vals is not None:
                    # set membership (truncated-int equality; NaN pads and
                    # NaN inputs compare false -> right)
                    sv = cat_vals[t_idx, node]            # [N, T, S]
                    member = jnp.any(
                        jnp.trunc(x)[:, :, None] == sv, axis=-1)
                    icn = is_cat[t_idx, node]             # [N, T]
                    go_left = jnp.where(icn, member, go_left)
                nxt = jnp.where(go_left, l, r)
                return jnp.where(f == -1, node, nxt)

            node = jax.lax.fori_loop(0, depth, body, node)
            leaf_vals = value[t_idx, node]
            return leaf_vals @ class_onehot          # [N, num_class]

        return jax.jit(fwd)

    # max rows per GEMM dispatch; _build_gemm shrinks it when T*(I+L) makes
    # the [N, T, I]/[N, T, L] activations large (see per_row budget there)
    GEMM_ROW_CHUNK = 1 << 16
    _gemm_row_chunk = GEMM_ROW_CHUNK

    def device_forward(self, params=None):
        """The traced forest kernel X[f32] -> [N, num_class] f32 raw scores
        for pipeline fusion, or None when only the host traversal is valid
        (empty/categorical-fallback forests). Returns the SAME jitted
        callable predict_raw dispatches — calling it inside an enclosing
        jit inlines the identical jaxpr, so a fused segment's forest
        arithmetic is bitwise-equal to the standalone path.

        ``params`` (a kernel-variant params dict, see core.kernels) selects
        the traversal implementation: ``{"impl": "gather"}`` forces the
        fori_loop gather kernel even when the GEMM path matrix is built;
        ``{"impl": "gemm"}`` (and None/default) keeps the default routing.
        Both implementations are exact — leaf values reach the output as
        one-hot products with exact-zero padding — so every variant is
        bitwise-equal; the variants differ only in compiled-program cost.
        """
        if self.num_trees == 0 or self.cat_host_fallback:
            return None
        if params and params.get("impl") == "gather":
            if self._jitted_gather is None:
                self._jitted_gather = self._compile()
            return self._jitted_gather
        if self._jitted is None:
            self._jitted = (self._compile_gemm() if self._gemm is not None
                            else self._compile())
        return self._jitted

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """[N,F] float32 -> [N, num_class] summed tree outputs (device)."""
        if self.num_trees == 0:
            return np.zeros((X.shape[0], self.num_class), dtype=np.float64)
        if self.cat_host_fallback:
            return predict_ensemble(self._tree_groups, np.asarray(X),
                                    self.num_class)
        Xf = np.asarray(X, dtype=np.float32)
        if self._gemm is not None:
            if self._jitted is None:
                self._jitted = self._compile_gemm()
            n = Xf.shape[0]
            row_chunk = self._gemm_row_chunk
            if n <= row_chunk:
                return np.asarray(self._jitted(Xf), dtype=np.float64)
            # chunked scoring rides the shared transfer ring: chunk i+1's
            # pad + H2D overlaps chunk i's forest GEMM instead of the old
            # serial dispatch-readback-dispatch loop
            import jax

            from ..parallel.batching import Batch
            from ..parallel.ingest import IngestStats, TransferRing

            def chunks():
                for r0 in range(0, n, row_chunk):
                    xc = Xf[r0: r0 + row_chunk]
                    m = len(xc)
                    if m < row_chunk:  # pad: one compiled shape
                        xc = np.pad(xc, ((0, row_chunk - m), (0, 0)),
                                    constant_values=np.nan)
                    # analysis: allow D001 -- host validity mask only
                    mask = np.zeros(row_chunk, dtype=bool)
                    mask[:m] = True
                    yield Batch({"x": xc}, mask, m)

            self.last_ingest_stats = IngestStats()
            ring = TransferRing(
                chunks(),
                put=lambda b: (jax.device_put(b.arrays["x"]), b.num_valid),
                step=lambda s: (self._jitted(s[0]), s[1]),
                fetch=lambda h: np.asarray(h[0], dtype=np.float64)[:h[1]],
                depth=2, stats=self.last_ingest_stats)
            outs = list(ring)
            return np.concatenate(outs, axis=0)
        if self._jitted is None:
            self._jitted = self._compile()
        return np.asarray(self._jitted(Xf), dtype=np.float64)


def _tree_depth(tree: Tree) -> int:
    depth = np.zeros(len(tree.feature), dtype=np.int32)
    order = range(len(tree.feature))
    for i in order:  # parents precede children by construction
        if tree.feature[i] != -1:
            depth[tree.left[i]] = depth[i] + 1
            depth[tree.right[i]] = depth[i] + 1
    return int(depth.max()) + 1 if len(depth) else 1
