"""LightGBM text-model interchange: import/export the `v3` model string.

The reference's saveNativeModel emits a real LightGBM model string that any
LightGBM runtime loads (lightgbm/LightGBMBooster.scala:96-148, persisted via
TrainUtils.scala:153-157), and loadNativeModelFromFile builds a booster from
one. This module gives the TPU engine the same interchange surface:

  - ``to_lightgbm_string(booster)``: serialize a trained Booster to the
    LightGBM `v3` text format (the format written by
    LGBM_BoosterSaveModelToString in the lightgbmlib the reference pins,
    build.sbt:27). Base scores are folded into the first iteration's leaf
    values, so ``sum of leaf outputs`` — the LightGBM prediction contract —
    reproduces this engine's raw scores exactly.
  - ``from_lightgbm_string(text)``: parse a LightGBM model string (ours or
    one produced by LightGBM itself) into a Booster that predicts with this
    engine's vectorized/jitted predict path.

Format notes (mirrors LightGBM's tree serialization):
  - Internal nodes and leaves are numbered separately; a negative child id
    ``c`` in left_child/right_child means leaf ``~c``.
  - ``decision_type`` is a bit field: bit0 = categorical, bit1 =
    default-left, bits2-3 = missing type (0=None, 1=Zero, 2=NaN).
  - Numerical rule: value <= threshold goes left; NaN goes with the default
    direction when missing type is NaN, else is coerced to 0.
  - ``leaf_value`` already includes shrinkage; prediction is a plain sum.

Categorical set splits round-trip through LightGBM's num_cat machinery:
``cat_boundaries``/``cat_threshold`` bitsets over category VALUES
(FindInBitset semantics — membership goes left, missing/unseen right), in
both directions. Linear trees and unknown versions are rejected loudly.
"""

from __future__ import annotations

import io
from typing import Dict, List, Optional, Sequence

import numpy as np

from .booster import Booster, TrainParams
from .tree import Tree

_MISSING_NAN = 2 << 2        # missing_type NaN in bits 2-3
_DEFAULT_LEFT = 2            # kDefaultLeftMask
_CATEGORICAL = 1             # kCategoricalMask


# ---------------------------------------------------------------------------
# Export
# ---------------------------------------------------------------------------


def _objective_string(params: TrainParams) -> str:
    obj = params.objective
    if obj == "binary":
        return "binary sigmoid:1"
    if obj == "multiclass":
        return f"multiclass num_class:{params.num_class}"
    if obj == "lambdarank":
        return "lambdarank"
    if obj in ("regression", "regression_l2", "l2", "mean_squared_error"):
        return "regression"
    if obj in ("regression_l1", "l1", "mae"):
        return "regression_l1"
    return obj


def _fmt(x: float) -> str:
    """LightGBM writes %.17g doubles; repr-style shortest is compatible."""
    return np.format_float_positional(
        float(x), unique=True, trim="0") if np.isfinite(x) else str(float(x))


def _tree_block(tree: Tree, index: int, fold_bias: float = 0.0) -> str:
    """Serialize one Tree to a LightGBM `Tree=N` block.

    Node mapping: our flat nodes with feature >= 0 become internal nodes
    (in node-id order, so the root stays index 0 — the same order LightGBM
    assigns, split creation order); feature == -1 nodes become leaves.
    """
    feat = tree.feature
    is_leaf = feat == -1
    n_nodes = len(feat)
    internal_ids = np.nonzero(~is_leaf)[0]
    leaf_ids = np.nonzero(is_leaf)[0]
    int_index = {int(nid): i for i, nid in enumerate(internal_ids)}
    leaf_index = {int(nid): i for i, nid in enumerate(leaf_ids)}

    def child_ref(nid: int) -> int:
        return int_index[nid] if not is_leaf[nid] else ~leaf_index[nid]

    # categorical SET splits -> LightGBM's num_cat machinery: per cat node,
    # a bitset over category VALUES (FindInBitset(cat_threshold +
    # cat_boundaries[cat_idx]) in LightGBM's CategoricalDecision)
    cat_idx_of_node = {}
    cat_boundaries = [0]
    cat_words_out: list = []
    if tree.cat_sets is not None:
        for nid in internal_ids:
            s = tree.cat_sets[int(nid)]
            if s is None:
                continue
            if (s < 0).any():
                raise ValueError(
                    "cannot serialize categorical split with negative "
                    "category values to the LightGBM format (its bitsets "
                    "are over non-negative ints); re-encode with "
                    "ValueIndexer first")
            mx = int(s.max())
            if mx >= 1 << 22:
                raise ValueError(
                    f"category value {mx} too large for a LightGBM bitset "
                    f"(> 2^22); re-encode with ValueIndexer first")
            w = np.zeros(mx // 32 + 1, dtype=np.uint32)
            np.bitwise_or.at(w, (s // 32).astype(np.int64),
                             np.uint32(1) << (s % 32).astype(np.uint32))
            cat_idx_of_node[int(nid)] = len(cat_boundaries) - 1
            cat_boundaries.append(cat_boundaries[-1] + len(w))
            cat_words_out.append(w)

    num_leaves = len(leaf_ids)
    lines = [f"Tree={index}", f"num_leaves={num_leaves}",
             f"num_cat={len(cat_words_out)}"]

    def node_weight(nid: int) -> str:
        # real hessian sums when the trainer recorded them (LightGBM uses
        # leaf_weight for refit/contrib); row counts only as legacy fallback
        if tree.weight is not None:
            return _fmt(float(tree.weight[nid]))
        return str(int(tree.count[nid]))

    if num_leaves == 1:
        # stump: LightGBM still writes one leaf_value row
        lines += [
            "split_feature=", "split_gain=", "threshold=", "decision_type=",
            "left_child=", "right_child=",
            "leaf_value=" + _fmt(tree.value[0] * tree.shrinkage + fold_bias),
            "leaf_weight=" + node_weight(0),
            "leaf_count=" + str(int(tree.count[0])),
            "internal_value=", "internal_weight=", "internal_count=",
            f"shrinkage={_fmt(tree.shrinkage)}",
        ]
        return "\n".join(lines) + "\n"

    sf, sg, th, dt, lc, rc = [], [], [], [], [], []
    for nid in internal_ids:
        sf.append(str(int(feat[nid])))
        sg.append(_fmt(float(tree.gain[nid])))
        if int(nid) in cat_idx_of_node:
            # categorical: threshold holds the cat_idx; decision_type is
            # the categorical bit (missing type None, no default-left)
            th.append(str(cat_idx_of_node[int(nid)]))
            dt.append(str(_CATEGORICAL))
        else:
            th.append(_fmt(float(tree.threshold[nid])))
            d = _MISSING_NAN | (_DEFAULT_LEFT if tree.default_left[nid]
                                else 0)
            dt.append(str(d))
        lc.append(str(child_ref(int(tree.left[nid]))))
        rc.append(str(child_ref(int(tree.right[nid]))))
    lv = [_fmt(float(tree.value[nid]) * tree.shrinkage + fold_bias)
          for nid in leaf_ids]
    lcount = [str(int(tree.count[nid])) for nid in leaf_ids]
    lw = [node_weight(int(nid)) for nid in leaf_ids]
    iv = [_fmt(0.0) for _ in internal_ids]
    iw = [node_weight(int(nid)) for nid in internal_ids]
    ic = [str(int(tree.count[nid])) for nid in internal_ids]

    lines += [
        "split_feature=" + " ".join(sf),
        "split_gain=" + " ".join(sg),
        "threshold=" + " ".join(th),
        "decision_type=" + " ".join(dt),
        "left_child=" + " ".join(lc),
        "right_child=" + " ".join(rc),
    ]
    if cat_words_out:
        lines += [
            "cat_boundaries=" + " ".join(str(b) for b in cat_boundaries),
            "cat_threshold=" + " ".join(
                str(int(w)) for ws in cat_words_out for w in ws),
        ]
    lines += [
        "leaf_value=" + " ".join(lv),
        "leaf_weight=" + " ".join(lw),
        "leaf_count=" + " ".join(lcount),
        "internal_value=" + " ".join(iv),
        "internal_weight=" + " ".join(iw),
        "internal_count=" + " ".join(ic),
        f"shrinkage={_fmt(tree.shrinkage)}",
    ]
    return "\n".join(lines) + "\n"


def to_lightgbm_string(booster: Booster,
                       feature_names: Optional[Sequence[str]] = None) -> str:
    """Serialize a Booster to the LightGBM v3 text model format."""
    params = booster.params
    k = max(params.num_class, 1)
    num_f = booster.bin_mapper.num_features if booster.bin_mapper else (
        max((int(t.feature.max()) + 1 if (t.feature >= 0).any() else 1)
            for g in booster.trees for t in g) if booster.trees else 1)
    names = list(feature_names) if feature_names else [
        f"Column_{i}" for i in range(num_f)]
    if len(names) != num_f:
        raise ValueError(f"{len(names)} feature names for {num_f} features")

    infos = []
    for i in range(num_f):
        mapper = booster.bin_mapper
        if mapper is not None and not mapper.categorical[i] \
                and len(mapper.edges[i]):
            e = mapper.edges[i]
            infos.append(f"[{_fmt(e[0])}:{_fmt(e[-1])}]")
        else:
            infos.append("none")

    blocks: List[str] = []
    idx = 0
    for it, group in enumerate(booster.trees):
        for kk, tree in enumerate(group):
            bias = float(booster.base_score[kk]) if it == 0 else 0.0
            blocks.append(_tree_block(tree, idx, fold_bias=bias))
            idx += 1

    out = io.StringIO()
    out.write("tree\n")
    out.write("version=v3\n")
    out.write(f"num_class={k}\n")
    out.write(f"num_tree_per_iteration={k}\n")
    out.write("label_index=0\n")
    out.write(f"max_feature_idx={num_f - 1}\n")
    out.write(f"objective={_objective_string(params)}\n")
    out.write("feature_names=" + " ".join(names) + "\n")
    out.write("feature_infos=" + " ".join(infos) + "\n")
    out.write("tree_sizes=" + " ".join(
        str(len(b.encode("utf-8")) + 1) for b in blocks) + "\n\n")
    for b in blocks:
        out.write(b)
        out.write("\n\n")
    out.write("end of trees\n\n")
    imp = booster.feature_importances("split") if booster.bin_mapper else None
    out.write("feature importances:\n")
    if imp is not None:
        order = np.argsort(-imp)
        for i in order:
            if imp[i] > 0:
                out.write(f"{names[i]}={int(imp[i])}\n")
    out.write("\nparameters:\n")
    out.write(f"[boosting: {params.boosting_type}]\n")
    out.write(f"[objective: {params.objective}]\n")
    out.write(f"[learning_rate: {params.learning_rate}]\n")
    out.write(f"[num_leaves: {params.num_leaves}]\n")
    out.write(f"[max_bin: {params.max_bin}]\n")
    out.write(f"[num_iterations: {params.num_iterations}]\n")
    out.write("\nend of parameters\n\n")
    out.write("pandas_categorical:null\n")
    return out.getvalue()


# ---------------------------------------------------------------------------
# Import
# ---------------------------------------------------------------------------


def _parse_header(text: str) -> Dict[str, str]:
    head: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("Tree="):
            break
        if "=" in line:
            key, val = line.split("=", 1)
            head[key] = val
    return head


def _floats(s: str) -> np.ndarray:
    return np.array([float(x) for x in s.split()] if s else [],
                    dtype=np.float64)


def _ints(s: str) -> np.ndarray:
    return np.array([int(x) for x in s.split()] if s else [], dtype=np.int64)


def _parse_tree(block: Dict[str, str]) -> Tree:
    num_leaves = int(block["num_leaves"])
    num_cat = int(block.get("num_cat", "0") or 0)
    cat_boundaries = _ints(block.get("cat_boundaries", "")) \
        if num_cat else None
    cat_threshold_words = _ints(block.get("cat_threshold", "")) \
        if num_cat else None
    if int(block.get("is_linear", "0") or 0):
        raise ValueError(
            "linear-tree models (is_linear=1) are not supported: leaves hold "
            "linear models, not constants — retrain without linear_tree")
    leaf_value = _floats(block["leaf_value"])
    leaf_count = _ints(block.get("leaf_count", "")) \
        if block.get("leaf_count") else np.zeros(num_leaves, dtype=np.int64)
    leaf_weight = _floats(block["leaf_weight"]) \
        if block.get("leaf_weight") else None
    int_weight = _floats(block["internal_weight"]) \
        if block.get("internal_weight") else None

    if num_leaves == 1:
        return Tree(
            feature=np.array([-1], dtype=np.int32),
            threshold=np.zeros(1), threshold_bin=np.zeros(1, dtype=np.int32),
            default_left=np.ones(1, dtype=bool),
            left=np.array([-1], dtype=np.int32),
            right=np.array([-1], dtype=np.int32),
            value=leaf_value[:1].astype(np.float64),
            gain=np.zeros(1, dtype=np.float32),
            count=leaf_count[:1].astype(np.int32),
            shrinkage=1.0,  # leaf_value already includes it
            weight=(leaf_weight[:1].astype(np.float64)
                    if leaf_weight is not None else None),
        )

    n_int = num_leaves - 1
    split_feature = _ints(block["split_feature"])
    threshold = _floats(block["threshold"])
    decision_type = _ints(block.get("decision_type", "")) \
        if block.get("decision_type") else np.zeros(n_int, dtype=np.int64)
    left_child = _ints(block["left_child"])
    right_child = _ints(block["right_child"])
    split_gain = _floats(block.get("split_gain", "")) \
        if block.get("split_gain") else np.zeros(n_int)
    int_count = _ints(block.get("internal_count", "")) \
        if block.get("internal_count") else np.zeros(n_int, dtype=np.int64)

    is_cat_node = (decision_type & _CATEGORICAL) != 0
    if is_cat_node.any() and (cat_boundaries is None or not len(cat_boundaries)
                              or cat_threshold_words is None
                              or not len(cat_threshold_words)):
        raise ValueError(
            "categorical decision_type bit set but the tree block carries "
            "no cat_boundaries/cat_threshold")

    # flatten: internal node i -> flat i; leaf j -> flat n_int + j
    n_nodes = n_int + num_leaves
    feature = np.full(n_nodes, -1, dtype=np.int32)
    thr = np.zeros(n_nodes, dtype=np.float64)
    dleft = np.ones(n_nodes, dtype=bool)
    left = np.full(n_nodes, -1, dtype=np.int32)
    right = np.full(n_nodes, -1, dtype=np.int32)
    value = np.zeros(n_nodes, dtype=np.float64)
    gain = np.zeros(n_nodes, dtype=np.float32)
    count = np.zeros(n_nodes, dtype=np.int32)

    def flat(c: int) -> int:
        return int(c) if c >= 0 else n_int + (~int(c))

    feature[:n_int] = split_feature
    thr[:n_int] = threshold
    # NaN routing by missing type (tree.h bits 2-3). Our predict sends NaN to
    # default_left, so translate each type into the direction NaN actually
    # takes in LightGBM: NaN type -> the stored default bit; None type ->
    # NaN is coerced to 0.0 and compared (left iff 0 <= threshold); Zero
    # type -> 0-as-missing goes the default direction, NaN included.
    missing_type = (decision_type >> 2) & 3
    stored_default = (decision_type & _DEFAULT_LEFT) != 0
    if (missing_type == 1).any():
        # Zero type: LightGBM sends exact-0.0 feature values the default
        # (missing) direction; this engine only applies the default bit to
        # NaN, so 0.0 compares against the threshold instead. Models with
        # Zero missing type typically come from sparse training data.
        import warnings

        warnings.warn(
            "importing a LightGBM model with missing_type=Zero: exact-0.0 "
            "feature values follow the threshold compare here, not the "
            "stored default direction — predictions can differ from "
            "LightGBM for rows with zero-valued features at those splits",
            RuntimeWarning, stacklevel=4)
    dleft[:n_int] = np.where(missing_type == 0, 0.0 <= threshold,
                             stored_default)
    left[:n_int] = [flat(c) for c in left_child]
    right[:n_int] = [flat(c) for c in right_child]
    gain[:n_int] = split_gain.astype(np.float32)
    count[:n_int] = int_count
    value[n_int:] = leaf_value
    count[n_int:] = leaf_count

    cat_sets = None
    if is_cat_node.any():
        cat_sets = [None] * n_nodes
        for i in np.nonzero(is_cat_node)[0]:
            ci = int(threshold[i])
            w = cat_threshold_words[
                int(cat_boundaries[ci]): int(cat_boundaries[ci + 1])]
            bits = np.unpackbits(
                w.astype(np.uint32).view(np.uint8), bitorder="little")
            cat_sets[int(i)] = np.nonzero(bits)[0].astype(np.int64)
            thr[int(i)] = 0.0           # threshold held the cat_idx
            dleft[int(i)] = False       # missing/unseen -> right
    weight = None
    if leaf_weight is not None and len(leaf_weight) == num_leaves:
        weight = np.zeros(n_nodes, dtype=np.float64)
        weight[n_int:] = leaf_weight
        if int_weight is not None and len(int_weight) == n_int:
            weight[:n_int] = int_weight
    return Tree(feature=feature, threshold=thr,
                threshold_bin=np.zeros(n_nodes, dtype=np.int32),
                default_left=dleft, left=left, right=right, value=value,
                gain=gain, count=count, shrinkage=1.0, weight=weight,
                cat_sets=cat_sets)


def parse_model_string(text: str) -> Booster:
    """Accept either model-string format: the LightGBM v3 text model (as
    written by save_native_model / any LightGBM runtime) or this engine's
    internal JSON — the reference's setModelString init-model path accepts
    its native string (LightGBMBase.scala:26-39)."""
    if is_lightgbm_string(text):
        return from_lightgbm_string(text)
    return Booster.from_string(text)


def is_lightgbm_string(text: str) -> bool:
    """True when the string looks like a LightGBM text model (vs the internal
    JSON format)."""
    head = text.lstrip()[:16].splitlines()
    return bool(head) and head[0].strip() == "tree"


def from_lightgbm_string(text: str) -> Booster:
    """Parse a LightGBM v3 text model into a Booster (predict-ready).

    Leaf values keep LightGBM semantics: the prediction is the plain sum of
    per-tree leaf outputs (shrinkage/init score already folded in), so
    ``base_score`` is zero and every imported tree has shrinkage 1.0.
    """
    if not is_lightgbm_string(text):
        raise ValueError("not a LightGBM model string (missing 'tree' magic)")
    head = _parse_header(text)
    version = head.get("version", "")
    # Version gate, explicit: v2/v3/v4 share the tree-block fields this
    # parser reads (v4 added linear trees, rejected per-tree below). An
    # unknown version means fields we have never seen — fail loudly instead
    # of silently misparsing.
    if version not in ("v2", "v3", "v4"):
        raise ValueError(
            f"unsupported LightGBM model version {version!r}: this parser "
            f"handles v2/v3/v4 text models")
    if int(head.get("linear_tree", "0") or 0):
        raise ValueError(
            "linear-tree models (linear_tree=1) are not supported: leaves "
            "hold linear models, not constants — retrain without linear_tree")
    k = int(head.get("num_class", "1"))
    obj_field = head.get("objective", "regression").split()
    objective = obj_field[0] if obj_field else "regression"
    if objective == "multiclassova":
        objective = "multiclass"

    body = text.split("end of trees")[0]
    blocks: List[Dict[str, str]] = []
    cur: Optional[Dict[str, str]] = None
    for line in body.splitlines():
        line = line.strip()
        if line.startswith("Tree="):
            cur = {}
            blocks.append(cur)
        elif cur is not None and "=" in line:
            key, val = line.split("=", 1)
            cur[key] = val
    trees = [_parse_tree(b) for b in blocks]

    if k > 1 and len(trees) % k != 0:
        raise ValueError(
            f"{len(trees)} trees is not a multiple of num_class={k}")
    groups = [trees[i: i + k] for i in range(0, len(trees), k)]

    params = TrainParams(
        objective=objective,
        num_class=k if k > 1 else 1,
        num_iterations=len(groups),
    )
    return Booster(params, bin_mapper=None, trees=groups,
                   base_score=np.zeros(max(k, 1)))
