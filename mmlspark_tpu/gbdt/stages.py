"""LightGBM-parity pipeline stages: Classifier / Regressor / Ranker (+Models).

Param surface mirrors lightgbm/LightGBMParams.scala:1-259 (camelCase names kept so
reference users find everything); fit orchestration mirrors LightGBMBase.train
(lightgbm/LightGBMBase.scala:18-192) including multi-batch incremental training via
booster merge and validation-indicator early stopping. The socket-ring/rendezvous
machinery has no equivalent here: SPMD + psum replaces it (histogram.py docstring).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import (
    ComplexParam,
    HasFeaturesCol,
    HasGroupCol,
    HasInitScoreCol,
    HasLabelCol,
    HasValidationIndicatorCol,
    HasWeightCol,
    Param,
)
from ..core.pipeline import Estimator, Model
from ..core.schema import ColType, Schema
from ..parallel.batching import stack_rows
from .booster import Booster, TrainParams, train
from .lgbm_format import parse_model_string


class _LightGBMParams(HasFeaturesCol, HasLabelCol, HasWeightCol,
                      HasValidationIndicatorCol, HasInitScoreCol):
    """Shared param surface (LightGBMParams.scala:1-259)."""

    numIterations = Param("numIterations", "Number of boosting iterations", 100,
                          lambda v: v > 0, int)
    learningRate = Param("learningRate", "Shrinkage rate", 0.1, lambda v: v > 0, float)
    numLeaves = Param("numLeaves", "Max leaves per tree", 31, lambda v: v > 1, int)
    maxBin = Param("maxBin", "Max feature bins", 255, lambda v: v > 1, int)
    maxDepth = Param("maxDepth", "Max tree depth (-1 = unlimited)", -1, ptype=int)
    minDataInLeaf = Param("minDataInLeaf", "Min rows per leaf", 20, lambda v: v >= 0, int)
    minSumHessianInLeaf = Param("minSumHessianInLeaf", "Min hessian per leaf", 1e-3,
                                ptype=float)
    minGainToSplit = Param("minGainToSplit", "Min gain to split", 0.0, ptype=float)
    lambdaL1 = Param("lambdaL1", "L1 regularization", 0.0, ptype=float)
    lambdaL2 = Param("lambdaL2", "L2 regularization", 0.0, ptype=float)
    baggingFraction = Param("baggingFraction", "Row subsample fraction", 1.0, ptype=float)
    baggingFreq = Param("baggingFreq", "Bagging frequency (0 = off)", 0, ptype=int)
    baggingSeed = Param("baggingSeed", "Bagging seed", 3, ptype=int)
    featureFraction = Param("featureFraction", "Feature subsample per tree", 1.0,
                            ptype=float)
    boostingType = Param("boostingType", "gbdt|rf|dart|goss", "gbdt",
                         lambda v: v in ("gbdt", "rf", "dart", "goss"), str)
    earlyStoppingRound = Param("earlyStoppingRound",
                               "Stop if no valid improvement for N rounds (0 = off)",
                               0, ptype=int)
    numBatches = Param("numBatches",
                       "Split data into batches, train incrementally and merge "
                       "(LightGBMBase.scala:26-39)", 0, ptype=int)
    categoricalSlotIndexes = Param("categoricalSlotIndexes",
                                   "Feature indexes treated as categorical", None,
                                   ptype=(list, tuple))
    modelString = Param("modelString", "Init model string for continued training",
                        None, ptype=str)
    boostFromAverage = Param("boostFromAverage", "Init score from label mean", True,
                             ptype=bool)
    verbosity = Param("verbosity", "Logging verbosity", -1, ptype=int)
    seed = Param("seed", "Master random seed", 0, ptype=int)
    objective = Param("objective", "Objective override", None, ptype=str)
    alpha = Param("alpha", "Quantile/huber parameter", 0.9, ptype=float)
    dropRate = Param("dropRate", "DART tree drop rate", 0.1, ptype=float)
    maxDrop = Param("maxDrop", "DART max dropped trees", 50, ptype=int)
    topRate = Param("topRate", "GOSS top-gradient keep rate", 0.2, ptype=float)
    otherRate = Param("otherRate", "GOSS random keep rate", 0.1, ptype=float)
    useBarrierExecutionMode = Param("useBarrierExecutionMode",
                                    "Gang scheduling (inherent on TPU; parity no-op)",
                                    False, ptype=bool)
    parallelism = Param("parallelism",
                        "Tree-learner parallelism (LightGBMParams.scala:13-18): "
                        "data_parallel or voting_parallel. Both run the EXACT "
                        "psum'd-histogram algorithm here — voting_parallel is "
                        "LightGBM's bandwidth approximation for slow networks, "
                        "and exact histograms over ICI collectives strictly "
                        "dominate it (same or better splits, no extra cost)",
                        "data_parallel",
                        lambda v: v in ("data_parallel", "voting_parallel"), str)
    numWorkers = Param("numWorkers", "Worker/shard count override (0 = auto)", 0,
                       ptype=int)
    metric = Param("metric", "Eval metric override (auc, binary_logloss, l1, "
                   "l2, rmse, ndcg, ...; empty = objective default)", "",
                   ptype=str)
    isProvideTrainingMetric = Param(
        "isProvideTrainingMetric",
        "Log the training metric during fit (TrainUtils.scala:194-230)",
        False, ptype=bool)
    maxDeltaStep = Param("maxDeltaStep",
                         "Clamp on |leaf output| (0 = off; LightGBM "
                         "max_delta_step, e.g. for poisson stability)",
                         0.0, ptype=float)
    posBaggingFraction = Param("posBaggingFraction",
                               "Positive-class bagging fraction (binary)",
                               1.0, ptype=float)
    negBaggingFraction = Param("negBaggingFraction",
                               "Negative-class bagging fraction (binary)",
                               1.0, ptype=float)
    maxBinByFeature = Param("maxBinByFeature",
                            "Per-feature bin caps overriding maxBin", None,
                            ptype=(list, tuple))
    categoricalSlotNames = Param(
        "categoricalSlotNames",
        "Feature slot NAMES treated as categorical, resolved against the "
        "features column's slot_names metadata (AssembleFeatures records it)",
        None, ptype=(list, tuple))
    catSmooth = Param("catSmooth",
                      "Categorical gradient-statistic smoothing "
                      "(LightGBM cat_smooth)", 10.0, ptype=float)
    catL2 = Param("catL2", "Extra L2 for categorical set splits "
                  "(LightGBM cat_l2)", 10.0, ptype=float)
    maxCatThreshold = Param("maxCatThreshold",
                            "Max categories on the left side of a set "
                            "split (LightGBM max_cat_threshold)", 32,
                            lambda v: v > 0, int)
    defaultListenPort = Param("defaultListenPort",
                              "Socket-era rendezvous port (reference "
                              "LightGBMConstants.DefaultLocalListenPort; "
                              "accepted for API parity — collectives need no "
                              "sockets here)", 12400, ptype=int)
    timeout = Param("timeout",
                    "Socket-era network timeout seconds (parity no-op)",
                    120.0, ptype=float)

    def _train_params(self, objective: str, num_class: int = 1) -> TrainParams:
        return TrainParams(
            objective=self.get("objective") or objective,
            boosting_type=self.get("boostingType"),
            num_iterations=self.get("numIterations"),
            learning_rate=self.get("learningRate"),
            num_leaves=self.get("numLeaves"),
            max_bin=self.get("maxBin"),
            max_depth=self.get("maxDepth"),
            min_data_in_leaf=self.get("minDataInLeaf"),
            min_sum_hessian_in_leaf=self.get("minSumHessianInLeaf"),
            min_gain_to_split=self.get("minGainToSplit"),
            lambda_l1=self.get("lambdaL1"),
            lambda_l2=self.get("lambdaL2"),
            bagging_fraction=self.get("baggingFraction"),
            bagging_freq=self.get("baggingFreq"),
            bagging_seed=self.get("baggingSeed"),
            feature_fraction=self.get("featureFraction"),
            early_stopping_round=self.get("earlyStoppingRound"),
            num_class=num_class,
            alpha=self.get("alpha"),
            drop_rate=self.get("dropRate"),
            max_drop=self.get("maxDrop"),
            top_rate=self.get("topRate"),
            other_rate=self.get("otherRate"),
            categorical_feature=tuple(self.get("categoricalSlotIndexes") or ()),
            cat_smooth=self.get("catSmooth"),
            cat_l2=self.get("catL2"),
            max_cat_threshold=self.get("maxCatThreshold"),
            parallelism=self.get("parallelism"),
            metric=self.get("metric") or "",
            max_delta_step=self.get("maxDeltaStep"),
            pos_bagging_fraction=self.get("posBaggingFraction"),
            neg_bagging_fraction=self.get("negBaggingFraction"),
            max_bin_by_feature=tuple(self.get("maxBinByFeature") or ()),
            train_metric=self.get("isProvideTrainingMetric"),
            seed=self.get("seed"),
        )

    def _resolve_mesh(self):
        """Worker topology shared by the dense and sparse fit paths: an
        EXPLICITLY configured mesh's data axis is the worker count
        (ClusterUtil.getNumExecutorCores parity, LightGBMBase.scala:120-128);
        numWorkers=1 forces single-device training. 'No mesh configured'
        stays single-device (MeshContext.current, not get): silently
        adopting a lazily-built all-device mesh row-shards tiny fits onto
        the per-split collective path — orders of magnitude slower than one
        device — and would span non-addressable devices multi-host."""
        if self.get("numWorkers") == 1:
            return None
        from ..parallel.mesh import DATA_AXIS, MeshContext

        try:
            candidate = MeshContext.current()
            if candidate is not None \
                    and int(candidate.shape.get(DATA_AXIS, 1)) > 1:
                return candidate
        except Exception:
            pass
        return None

    def _make_log(self):
        import logging

        return logging.getLogger("mmlspark_tpu.gbdt").info \
            if (self.get("verbosity") >= 0
                or self.get("isProvideTrainingMetric")) else None

    def _init_model(self):
        if self.get("modelString"):
            return parse_model_string(self.get("modelString"))
        return None

    def _extract(self, df: DataFrame, data=None):
        """DataFrame -> (X, y, weights, init_scores, valid_mask) numpy arrays."""
        if data is None:
            data = df.collect()
        X = stack_rows(data[self.get_or_throw("featuresCol")], np.float64)
        y = np.asarray(data[self.get_or_throw("labelCol")], dtype=np.float64)
        w = None
        if self.get("weightCol"):
            w = np.asarray(data[self.get("weightCol")], dtype=np.float64)
        init_scores = None
        if self.get("initScoreCol"):
            init_scores = np.asarray(data[self.get("initScoreCol")], dtype=np.float64)
        valid_mask = None
        if self.get("validationIndicatorCol"):
            valid_mask = np.asarray(data[self.get("validationIndicatorCol")],
                                    dtype=bool)
        return X, y, w, init_scores, valid_mask

    def _timed_fit(self, fit_fn) -> Booster:
        """Run one booster fit with fit-level observability: wall seconds
        and completed-fit count land in the obs default registry under this
        estimator's class name, next to the per-iteration
        ``mmlspark_train_*`` series the boost loops emit."""
        import time

        from ..obs.metrics import default_registry

        t0 = time.perf_counter()
        booster = fit_fn()
        reg = default_registry()
        est = type(self).__name__
        reg.gauge("mmlspark_train_fit_seconds",
                  "wall seconds of the last booster fit",
                  ("estimator",)).labels(estimator=est).set(
            time.perf_counter() - t0)
        reg.counter("mmlspark_train_fits_total", "booster fits completed",
                    ("estimator",)).labels(estimator=est).inc()
        return booster

    def _fit_booster_sparse(self, data, objective: str, num_class: int,
                            groups: Optional[np.ndarray] = None) -> Booster:
        """CSR training for sparse-row features (TextFeaturizer / VW
        featurizer output) — never densifies, so 2^18-wide hashTF spaces
        train in O(nnz) memory, with the reference's FULL sparse param
        surface (generateSparseDataset feeds the same engine with
        everything enabled, lightgbm/TrainUtils.scala:23-66): bagging,
        goss/dart/rf, feature_fraction, weights, init scores, validation +
        early stopping, ranker groups, modelString continuation, and
        numBatches incremental training."""
        from ..parallel.batching import sparse_width
        from .sparse import SparseDataset, rows_to_csr, train_sparse

        y = np.asarray(data[self.get_or_throw("labelCol")], dtype=np.float64)
        w = None
        if self.get("weightCol"):
            w = np.asarray(data[self.get("weightCol")], dtype=np.float64)
        init_scores = None
        if self.get("initScoreCol"):
            init_scores = np.asarray(data[self.get("initScoreCol")],
                                     dtype=np.float64)
        for unsupported in ("categoricalSlotNames", "categoricalSlotIndexes"):
            if self.get(unsupported):
                raise ValueError(
                    f"{unsupported} is not supported with sparse features — "
                    f"hashTF/count spaces are numeric; densify explicitly "
                    f"(FastVectorAssembler) for categorical slots")
        params = self._train_params(objective, num_class)
        col = list(data[self.get_or_throw("featuresCol")])
        width = sparse_width(col)

        valid = None
        valid_groups = None
        if self.get("validationIndicatorCol"):
            vm = np.asarray(data[self.get("validationIndicatorCol")],
                            dtype=bool)
            val_col = [v for v, m in zip(col, vm) if m]
            col = [v for v, m in zip(col, vm) if not m]
            val_y = y[vm]
            y = y[~vm]
            if w is not None:
                w = w[~vm]
            if init_scores is not None:
                init_scores = init_scores[~vm]
            if groups is not None:
                valid_groups = groups[vm]
                groups = groups[~vm]
            indptr, indices, values, _ = rows_to_csr(val_col, width)
            valid = ((indptr, indices, values), val_y)

        init = self._init_model()
        log = self._make_log()
        mesh = self._resolve_mesh()
        max_bin = min(params.max_bin, 255)
        n_batches = self.get("numBatches")
        if n_batches and n_batches > 1:
            booster = init
            bounds = np.linspace(0, len(y), n_batches + 1).astype(int)
            for b in range(n_batches):
                sl = slice(bounds[b], bounds[b + 1])
                ds = SparseDataset.from_rows(col[sl], num_features=width,
                                             max_bin=max_bin)
                booster = train_sparse(
                    params, ds, y[sl],
                    weights=w[sl] if w is not None else None,
                    groups=groups[sl] if groups is not None else None,
                    valid=valid, valid_groups=valid_groups,
                    init_scores=(init_scores[sl]
                                 if init_scores is not None else None),
                    init_model=booster, log=log, mesh=mesh)
            return booster
        ds = SparseDataset.from_rows(col, num_features=width,
                                     max_bin=max_bin)
        return train_sparse(params, ds, y, weights=w, groups=groups,
                            valid=valid, valid_groups=valid_groups,
                            init_scores=init_scores, init_model=init,
                            log=log, mesh=mesh)

    def _fit_booster(self, df: DataFrame, objective: str, num_class: int = 1,
                     groups: Optional[np.ndarray] = None) -> Booster:
        from ..parallel.batching import is_sparse_row

        data = df.collect()  # ONE materialization for sniff + either path
        fcol = data[self.get_or_throw("featuresCol")]
        first = next((v for v in fcol if v is not None), None)
        if is_sparse_row(first):
            return self._fit_booster_sparse(data, objective, num_class,
                                            groups=groups)

        X, y, w, init_scores, valid_mask = self._extract(df, data)
        params = self._train_params(objective, num_class)
        names = self.get("categoricalSlotNames")
        if names:
            slot_names = df.schema.metadata.get(
                self.get_or_throw("featuresCol"), {}).get("slot_names")
            if not slot_names:
                raise ValueError(
                    "categoricalSlotNames requires slot_names metadata on "
                    "the features column (AssembleFeatures records it); use "
                    "categoricalSlotIndexes otherwise")
            lut = {nm: i for i, nm in enumerate(slot_names)}
            missing = [nm for nm in names if nm not in lut]
            if missing:
                raise KeyError(f"categoricalSlotNames not found in "
                               f"slot_names metadata: {missing}")
            params = dataclasses.replace(
                params, categorical_feature=tuple(sorted(
                    set(params.categorical_feature)
                    | {lut[nm] for nm in names})))
        valid = None
        valid_groups = None
        if valid_mask is not None:
            valid = (X[valid_mask], y[valid_mask])
            keep = ~valid_mask
            X, y = X[keep], y[keep]
            if w is not None:
                w = w[keep]
            if init_scores is not None:
                init_scores = init_scores[keep]
            if groups is not None:
                valid_groups = groups[valid_mask]
                groups = groups[keep]
        init = self._init_model()
        log = self._make_log()
        mesh = self._resolve_mesh()
        n_batches = self.get("numBatches")
        if n_batches and n_batches > 1:
            booster = init
            bounds = np.linspace(0, len(y), n_batches + 1).astype(int)
            for b in range(n_batches):
                sl = slice(bounds[b], bounds[b + 1])
                booster = train(params, X[sl], y[sl],
                                weights=w[sl] if w is not None else None,
                                groups=groups[sl] if groups is not None else None,
                                valid=valid, valid_groups=valid_groups,
                                init_scores=init_scores[sl] if init_scores is not None else None,
                                init_model=booster, log=log, mesh=mesh)
            return booster
        return train(params, X, y, weights=w, groups=groups, valid=valid,
                     valid_groups=valid_groups, init_scores=init_scores,
                     init_model=init, log=log, mesh=mesh)


class _LightGBMModelBase(Model, HasFeaturesCol):
    """Shared scoring: features column -> raw scores via the device forest kernel."""

    model = ComplexParam("model", "Trained booster (model string)")

    def __init__(self, **kwargs):
        booster = kwargs.pop("booster", None)
        super().__init__(**kwargs)
        self._booster: Optional[Booster] = booster
        self._device_ensemble = None
        if booster is not None:
            self.set("model", booster.to_string())

    @property
    def booster(self) -> Booster:
        if self._booster is None:
            self._booster = Booster.from_string(self.get_or_throw("model"))
        return self._booster

    def _ensemble(self):
        from .predict import DeviceEnsemble

        if self._device_ensemble is None:
            b = self.booster
            n_iter = b.best_iteration if b.best_iteration > 0 else len(b.trees)
            self._device_ensemble = DeviceEnsemble(
                b.trees[:n_iter], max(b.params.num_class, 1))
        return self._device_ensemble

    def _raw_scores(self, part) -> np.ndarray:
        from ..parallel.batching import is_sparse_row

        col = part[self.get_or_throw("featuresCol")]
        first = next((v for v in col if v is not None), None)
        if is_sparse_row(first):
            # CSR predict: no densification (PredictForCSRSingle parity,
            # lightgbm/LightGBMBooster.scala:21-148)
            from .sparse import predict_csr, rows_to_csr

            b = self.booster
            n_iter = b.best_iteration if b.best_iteration > 0 \
                else len(b.trees)
            indptr, indices, values, _ = rows_to_csr(col, filter_zeros=False)
            raw = predict_csr(b.trees[:n_iter], indptr, indices, values,
                              max(b.params.num_class, 1))
            return raw + b.base_score[None, :]
        X = stack_rows(col, np.float32)
        raw = self._ensemble().predict_raw(X)
        return raw + self.booster.base_score[None, :]

    # -- reference API parity --------------------------------------------
    def save_native_model(self, path: str, overwrite: bool = True) -> None:
        """saveNativeModel parity (LightGBMClassifier.scala, emitting the
        actual LightGBM v3 text model via LightGBMBooster.scala:96-148 —
        the written file loads in any LightGBM runtime)."""
        import os

        from .lgbm_format import to_lightgbm_string

        if os.path.exists(path) and not overwrite:
            raise FileExistsError(path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            f.write(to_lightgbm_string(self.booster))

    @classmethod
    def load_native_model_from_string(cls, text: str, **kwargs):
        """Build a scoring model from a LightGBM v3 text model string
        (loadNativeModelFromString parity, LightGBMClassifier.scala)."""
        from .lgbm_format import from_lightgbm_string

        return cls(booster=from_lightgbm_string(text), **kwargs)

    @classmethod
    def load_native_model_from_file(cls, path: str, **kwargs):
        """loadNativeModelFromFile parity (LightGBMClassifier.scala)."""
        with open(path) as f:
            return cls.load_native_model_from_string(f.read(), **kwargs)

    def get_feature_importances(self, importance_type: str = "split") -> List[float]:
        return list(self.booster.feature_importances(importance_type))

    def get_model_string(self) -> str:
        return self.get_or_throw("model")

    # -- pipeline fusion ---------------------------------------------------
    def _device_scores(self):
        """(featuresCol, raw env key, traceable fn) for fusion, or None when
        the forest only has a host path (empty / categorical fallback).
        The fn inlines the SAME jitted forest kernel predict_raw uses.

        The forest traversal implementation (``forest.gemm`` vs
        ``forest.gather`` kernel variants — both exact, see
        EnsemblePredictor.device_forward) resolves from the variant registry
        at TRACE time: the executor activates the chosen variant around
        lower/compile, so each variant's program lands under its own
        ``variant=<id>;``-prefixed CompileCache key."""
        from ..core.device_stage import FusionUnsupported

        ens = self._ensemble()
        fwd = ens.device_forward()
        if fwd is None:
            return None
        feats = self.get_or_throw("featuresCol")
        raw_key = f"__gbdt_raw__{self.uid}"

        def fn(params, env):
            import jax.numpy as jnp

            from ..core import kernels as _kernels

            var = _kernels.active("forest")
            f = (ens.device_forward(var.params) if var is not None
                 else fwd) or fwd
            X = env[feats]
            if X.ndim != 2:
                raise FusionUnsupported(f"features must be [N, F], got {X.shape}")
            return {raw_key: f(X.astype(jnp.float32))}

        # CSR capability (docs/sparse.md): when the executor's layout knob
        # stages the features column as a wire triple, this body replaces
        # the [N, width] densify with a [N, U] gather of the forest's used
        # feature columns and traverses a position-remapped ensemble —
        # bitwise-equal raw scores to fn over the densified matrix (the
        # gather replicates take_along_axis's out-of-range clamp, and leaf
        # markers / GEMM pad slots stay inert under the remap).
        cell: Dict[str, Any] = {}

        def sparse_fn(params, env):
            from ..core import kernels as _kernels

            from . import pallas_sparse

            if ens.cat_vals is not None:
                # categorical SET membership reads raw category values the
                # used-feature compaction preserves, but the knob-off
                # sparse path (predict_csr) rejects categorical models —
                # keep both paths aligned
                raise FusionUnsupported("categorical splits need dense rows")
            if "remap" not in cell:
                used = pallas_sparse.used_features(ens)
                cell["remap"] = (used,
                                 pallas_sparse.remap_ensemble(ens, used))
            used, rens = cell["remap"]
            var = _kernels.active("forest")
            f = (rens.device_forward(var.params) if var is not None
                 else rens.device_forward())
            if f is None:
                raise FusionUnsupported("forest has no device path")
            x_used = pallas_sparse.csr_gather(
                env[f"{feats}:indptr"], env[f"{feats}:indices"],
                env[f"{feats}:values"], env[f"{feats}:width"], used,
                pallas=(var is not None
                        and var.params.get("csr_gather") == "pallas"))
            return {raw_key: f(x_used)}

        return feats, raw_key, fn, sparse_fn

    def _score_device_fn(self, finalize, extra_out_cols, **stitch_caps):
        """Build the terminal DeviceFn shared by the model subclasses:
        forest scores on device, f64 base-score/objective math in the
        host finalize (bitwise-identical to the unfused score()).
        ``stitch_caps`` passes through the optional transpiled-finalizer
        capability fields (device_finalize & co — see DeviceFn)."""
        from ..core.device_stage import DeviceFn

        base = self._device_scores()
        if base is None:
            return None
        feats, raw_key, fn, sparse_fn = base
        return DeviceFn(
            key=(type(self).__name__, self.uid, feats),
            in_cols=(feats,), out_cols=tuple(extra_out_cols), fn=fn,
            device_outputs=(raw_key,), finalize=finalize,
            **stitch_caps,
            # nulls/sparse rows take the unfused path (CSR predict / the
            # host error), identically to the per-stage chain — UNLESS the
            # executor's layout knob stages the features column as a CSR
            # wire triple, which this capability pair opts into
            # (docs/sparse.md; reject_sparse stays True for every other
            # sparse shape, so the knob-off path is byte-for-byte)
            null_policy="fallback", reject_sparse=True,
            sparse_cols=(feats,), sparse_fn=sparse_fn,
            terminal=True, heavy=True,
            # pod-scale planner declaration (parallel/shardplan.py): the
            # [N, F] features matrix may shard its feature dim over the
            # mesh's tensor axis (the forest kernel gathers full rows —
            # GSPMD inserts that collective; the cost model prices it)
            shard_dims={feats: 1})


# ---------------------------------------------------------------------------
# Classifier
# ---------------------------------------------------------------------------


class LightGBMClassifier(Estimator, _LightGBMParams):
    """Binary/multiclass GBDT classifier (lightgbm/LightGBMClassifier.scala)."""

    rawPredictionCol = Param("rawPredictionCol", "Raw scores column", "rawPrediction",
                             ptype=str)
    probabilityCol = Param("probabilityCol", "Probability vector column", "probability",
                           ptype=str)
    predictionCol = Param("predictionCol", "Predicted label column", "prediction",
                          ptype=str)

    def fit(self, df: DataFrame) -> "LightGBMClassificationModel":
        y = df.column(self.get_or_throw("labelCol"))
        classes = np.unique(np.asarray(y, dtype=np.float64))
        num_class = len(classes)
        if not np.array_equal(classes, np.arange(num_class)):
            raise ValueError(
                f"Labels must be 0..K-1 (got {classes[:10]}); use ValueIndexer first")
        objective = "binary" if num_class <= 2 else "multiclass"
        booster = self._timed_fit(lambda: self._fit_booster(
            df, objective, 1 if num_class <= 2 else num_class))
        return LightGBMClassificationModel(
            booster=booster,
            featuresCol=self.get("featuresCol"),
            rawPredictionCol=self.get("rawPredictionCol"),
            probabilityCol=self.get("probabilityCol"),
            predictionCol=self.get("predictionCol"),
        )


class LightGBMClassificationModel(_LightGBMModelBase):
    rawPredictionCol = Param("rawPredictionCol", "Raw scores column", "rawPrediction",
                             ptype=str)
    probabilityCol = Param("probabilityCol", "Probability vector column", "probability",
                           ptype=str)
    predictionCol = Param("predictionCol", "Predicted label column", "prediction",
                          ptype=str)

    def _score_columns(self, raw: np.ndarray) -> dict:
        """[N, K] f64 raw scores (base score included) -> output columns.
        Shared by transform() and the fused finalize so both paths run the
        identical f64 objective math."""
        if self.booster.params.objective == "binary":
            p1 = 1 / (1 + np.exp(-raw[:, 0]))
            proba = np.stack([1 - p1, p1], axis=1)
            rawcol = np.stack([-raw[:, 0], raw[:, 0]], axis=1)
        else:
            e = np.exp(raw - raw.max(axis=1, keepdims=True))
            proba = e / e.sum(axis=1, keepdims=True)
            rawcol = raw
        pred = np.argmax(proba, axis=1).astype(np.float64)
        n = len(pred)
        raw_obj = np.empty(n, dtype=object)
        proba_obj = np.empty(n, dtype=object)
        for i in range(n):
            raw_obj[i] = rawcol[i]
            proba_obj[i] = proba[i]
        return {self.get("rawPredictionCol"): raw_obj,
                self.get("probabilityCol"): proba_obj,
                self.get("predictionCol"): pred}

    def transform(self, df: DataFrame) -> DataFrame:
        def score(part):
            part.update(self._score_columns(self._raw_scores(part)))
            return part

        return df.map_partitions(score)

    def device_fn(self, schema: Schema):
        raw_key = f"__gbdt_raw__{self.uid}"
        proba_key = f"__gbdt_proba__{self.uid}"
        pred_key = f"__gbdt_pred__{self.uid}"
        binary = self.booster.params.objective == "binary"
        base = self.booster.base_score

        def finalize(outs, ctx):
            raw = np.asarray(outs[raw_key], dtype=np.float64) \
                + base[None, :]
            return self._score_columns(raw)

        def device_finalize(params, env):
            # transpiled finalizer (docs/compiler_search.md): the host f64
            # objective math re-expressed as a jittable f32 shim so the
            # probability/prediction reductions ride the fused program
            # instead of a second host pass — numeric deviation vs the f64
            # path is DECLARED via finalize_tolerance below
            import jax.numpy as jnp

            raw32 = env[raw_key] + jnp.asarray(base,
                                               dtype=jnp.float32)[None, :]
            if binary:
                p1 = 1.0 / (1.0 + jnp.exp(-raw32[:, 0]))
                proba = jnp.stack([1.0 - p1, p1], axis=1)
            else:
                e = jnp.exp(raw32 - raw32.max(axis=1, keepdims=True))
                proba = e / e.sum(axis=1, keepdims=True)
            pred = jnp.argmax(proba, axis=1).astype(jnp.float32)
            return {proba_key: proba, pred_key: pred}

        def finalize_stitched(outs, ctx):
            # rawPrediction stays BITWISE: rebuilt from the same f64 raw
            # readback the host finalize uses; only proba/pred come from
            # the device f32 shim
            raw = np.asarray(outs[raw_key], dtype=np.float64) \
                + base[None, :]
            rawcol = (np.stack([-raw[:, 0], raw[:, 0]], axis=1)
                      if binary else raw)
            proba = np.asarray(outs[proba_key], dtype=np.float64)
            pred = np.asarray(outs[pred_key], dtype=np.float64)
            n = len(pred)
            raw_obj = np.empty(n, dtype=object)
            proba_obj = np.empty(n, dtype=object)
            for i in range(n):
                raw_obj[i] = rawcol[i]
                proba_obj[i] = proba[i]
            return {self.get("rawPredictionCol"): raw_obj,
                    self.get("probabilityCol"): proba_obj,
                    self.get("predictionCol"): pred}

        return self._score_device_fn(
            finalize, (self.get("rawPredictionCol"),
                       self.get("probabilityCol"), self.get("predictionCol")),
            stitchable=True,
            device_finalize=device_finalize,
            device_finalize_outputs=(proba_key, pred_key),
            finalize_stitched=finalize_stitched,
            finalize_tolerance=1e-5)

    def transform_schema(self, schema: Schema) -> Schema:
        out = schema.copy()
        out.types[self.get("rawPredictionCol")] = ColType.VECTOR
        out.types[self.get("probabilityCol")] = ColType.VECTOR
        out.types[self.get("predictionCol")] = ColType.FLOAT64
        return out


# ---------------------------------------------------------------------------
# Regressor
# ---------------------------------------------------------------------------


class LightGBMRegressor(Estimator, _LightGBMParams):
    """GBDT regressor: l2/l1/huber/quantile/poisson objectives
    (lightgbm/LightGBMRegressor.scala)."""

    predictionCol = Param("predictionCol", "Prediction column", "prediction", ptype=str)
    applicationName = Param("applicationName", "regression|quantile|huber|poisson|mape",
                            "regression", ptype=str)

    def fit(self, df: DataFrame) -> "LightGBMRegressionModel":
        objective = self.get("objective") or {
            "regression": "regression", "quantile": "quantile",
            "huber": "huber", "poisson": "poisson",
        }.get(self.get("applicationName"), "regression")
        booster = self._timed_fit(lambda: self._fit_booster(df, objective))
        return LightGBMRegressionModel(
            booster=booster,
            featuresCol=self.get("featuresCol"),
            predictionCol=self.get("predictionCol"),
        )


class LightGBMRegressionModel(_LightGBMModelBase):
    predictionCol = Param("predictionCol", "Prediction column", "prediction", ptype=str)

    def _prediction_column(self, raw: np.ndarray) -> np.ndarray:
        """[N, 1] f64 raw (base score included) -> prediction values (shared
        by transform() and the fused finalize)."""
        raw = raw[:, 0]
        if self.booster.params.objective == "poisson":
            raw = np.exp(raw)
        return raw

    def transform(self, df: DataFrame) -> DataFrame:
        def score(part):
            part[self.get("predictionCol")] = \
                self._prediction_column(self._raw_scores(part))
            return part

        return df.map_partitions(score)

    def device_fn(self, schema: Schema):
        def finalize(outs, ctx):
            raw_key = next(iter(outs))
            raw = np.asarray(outs[raw_key], dtype=np.float64) \
                + self.booster.base_score[None, :]
            return {self.get("predictionCol"): self._prediction_column(raw)}

        return self._score_device_fn(finalize, (self.get("predictionCol"),))

    def transform_schema(self, schema: Schema) -> Schema:
        out = schema.copy()
        out.types[self.get("predictionCol")] = ColType.FLOAT64
        return out


# ---------------------------------------------------------------------------
# Ranker
# ---------------------------------------------------------------------------


class LightGBMRanker(Estimator, _LightGBMParams, HasGroupCol):
    """LambdaRank GBDT (lightgbm/LightGBMRanker.scala; group cardinality encoding
    TrainUtils.scala:82-132 — here groups are a plain column, no encoding dance)."""

    predictionCol = Param("predictionCol", "Prediction column", "prediction", ptype=str)

    def fit(self, df: DataFrame) -> "LightGBMRankerModel":
        group_col = self.get_or_throw("groupCol")
        raw_groups = df.column(group_col)
        _, groups = np.unique(np.asarray([str(g) for g in raw_groups]),
                              return_inverse=True)
        booster = self._timed_fit(lambda: self._fit_booster(
            df, "lambdarank", groups=groups.astype(np.int64)))
        return LightGBMRankerModel(
            booster=booster,
            featuresCol=self.get("featuresCol"),
            predictionCol=self.get("predictionCol"),
        )


class LightGBMRankerModel(_LightGBMModelBase):
    predictionCol = Param("predictionCol", "Prediction column", "prediction", ptype=str)

    def transform(self, df: DataFrame) -> DataFrame:
        def score(part):
            part[self.get("predictionCol")] = self._raw_scores(part)[:, 0]
            return part

        return df.map_partitions(score)

    def device_fn(self, schema: Schema):
        def finalize(outs, ctx):
            raw_key = next(iter(outs))
            raw = np.asarray(outs[raw_key], dtype=np.float64) \
                + self.booster.base_score[None, :]
            return {self.get("predictionCol"): raw[:, 0]}

        return self._score_device_fn(finalize, (self.get("predictionCol"),))

    def transform_schema(self, schema: Schema) -> Schema:
        out = schema.copy()
        out.types[self.get("predictionCol")] = ColType.FLOAT64
        return out
