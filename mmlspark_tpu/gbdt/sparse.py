"""Sparse/CSR feature path through the GBDT engine.

Reference parity: the reference trains LightGBM directly on sparse vectors —
``generateSparseDataset`` / ``LGBM_DatasetCreateFromCSRSpark``
(lightgbm/TrainUtils.scala:23-66, lightgbm/LightGBMUtils.scala:199-252) — and
predicts single sparse rows via ``PredictForCSRSingle``
(lightgbm/LightGBMBooster.scala:21-148). This module gives the TPU engine the
same capability for TextFeaturizer/VW-width feature spaces (2^18+ columns)
without ever densifying:

  - ``SparseDataset``: CSR (indptr/indices/values) + per-feature
    distinct-value binning over the nonzeros with the implicit zero as its
    own bin, laid out as a FLAT ragged bin space (per-feature offsets,
    ``total_bins = sum_f bins_f`` — LightGBM's num_total_bin layout). Memory
    is O(nnz + total_bins), never O(N * F).
  - histogram: one ``segment_sum`` over the nnz entries' flat bin ids
    (node-masked via a cheap 1-D gather of the row routing); the zero bin of
    every feature is reconstructed by subtraction from the node totals —
    LightGBM's default-bin trick, so absent entries cost nothing.
  - split finding: a single flat cumsum + vectorized gain scan over
    ``total_bins`` candidates with per-feature segment boundaries.
  - ``predict_csr``: depth-stepped traversal where each row resolves the
    split feature's value through its own CSR row (absent -> 0.0).

Trees come out as the ordinary dense ``Tree`` (raw-value thresholds), so
persistence, merge, importances, and the LightGBM text-format interchange
all work unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..parallel.mesh import fetch_global

from .tree import GrowerConfig, Tree

_MAX_SPARSE_BIN = 64  # per-feature cap: count/tf features have few levels


def rows_to_csr(col, num_features: Optional[int] = None,
                filter_zeros: bool = True):
    """Sparse-row column ({"indices","values"[,"size"]}) -> sorted CSR
    (indptr, indices, values, width). The single row-walk shared by training
    (SparseDataset.from_rows) and predict (stages._raw_scores)."""
    from ..parallel.batching import sparse_width

    width = num_features or sparse_width(col)
    indptr = np.zeros(len(col) + 1, dtype=np.int64)
    idx_parts, val_parts = [], []
    for i, v in enumerate(col):
        if v is None:
            indptr[i + 1] = indptr[i]
            continue
        idx = np.asarray(v["indices"], dtype=np.int64)
        val = np.asarray(v["values"], dtype=np.float64)
        keep = idx < width
        if filter_zeros:
            keep &= val != 0.0
        idx, val = idx[keep], val[keep]
        srt = np.argsort(idx, kind="stable")  # CSR contract: sorted rows
        idx_parts.append(idx[srt])
        val_parts.append(val[srt])
        indptr[i + 1] = indptr[i] + len(idx)
    indices = (np.concatenate(idx_parts) if idx_parts
               else np.zeros(0, dtype=np.int64))
    values = (np.concatenate(val_parts) if val_parts
              else np.zeros(0, dtype=np.float64))
    return indptr, indices, values, width


@dataclasses.dataclass
class SparseDataset:
    """CSR dataset with flat ragged binning over the nonzero values."""

    indptr: np.ndarray        # i64 [N+1]
    indices: np.ndarray       # i32 [nnz] feature ids
    values: np.ndarray        # f32 [nnz]
    num_features: int
    # binning (flat ragged layout)
    feat_offset: np.ndarray   # i64 [F+1]: feature f owns flat bins
    #                           [feat_offset[f], feat_offset[f+1])
    thresholds: np.ndarray    # f64 [total_bins]: upper value per flat bin
    zero_local: np.ndarray    # i32 [F]: local bin index holding value 0.0
    bin_of_nnz: np.ndarray    # i32 [nnz]: flat bin id per entry
    row_of_nnz: np.ndarray    # i32 [nnz]

    @property
    def num_rows(self) -> int:
        return len(self.indptr) - 1

    @property
    def total_bins(self) -> int:
        return int(self.feat_offset[-1])

    @staticmethod
    def from_rows(col, num_features: Optional[int] = None,
                  max_bin: int = _MAX_SPARSE_BIN) -> "SparseDataset":
        """Build from a sparse-row column ({"indices","values"[,"size"]})."""
        indptr, indices, values, width = rows_to_csr(col, num_features)
        return SparseDataset.from_csr(indptr, indices, values, width, max_bin)

    @staticmethod
    def from_csr(indptr, indices, values, num_features: int,
                 max_bin: int = _MAX_SPARSE_BIN) -> "SparseDataset":
        indptr = np.asarray(indptr, dtype=np.int64)
        indices = np.asarray(indices, dtype=np.int64)
        values = np.asarray(values, dtype=np.float64)
        nnz = len(indices)

        # One synthetic zero "entry" per present feature makes the implicit
        # zero an ordinary distinct value — binning, zero position, and
        # capping all handle it uniformly.
        feats_present = np.unique(indices) if nnz else np.zeros(0, np.int64)
        fs_aug = np.concatenate([indices, feats_present])
        vs_aug = np.concatenate([values, np.zeros(len(feats_present))])

        # distinct (feature, value) pairs via one lexsort; per-entry pair id
        order = np.lexsort((vs_aug, fs_aug))
        fs, vs = fs_aug[order], vs_aug[order]
        m = len(fs)
        first = np.ones(m, dtype=bool)
        if m:
            first[1:] = (fs[1:] != fs[:-1]) | (vs[1:] != vs[:-1])
        pair_of_sorted = np.cumsum(first) - 1 if m \
            else np.zeros(0, dtype=np.int64)
        df, dv = fs[first], vs[first]          # value-ascending per feature

        # stride-quantile cap: feature f with d_f distinct values uses
        # stride_f = ceil(d_f / max_bin); local bin = distinct_pos // stride
        # — an even subsample of the value range (a smallest-values prefix
        # cap mixes large values into the zero bin when negatives exist)
        d_per_feat = np.bincount(df, minlength=num_features)
        stride = np.maximum(1, -(-d_per_feat // max_bin))      # [F]
        first_pair = np.searchsorted(df, df)
        pos_in_feat = np.arange(len(df)) - first_pair
        local_of_pair = pos_in_feat // stride[df]
        bins_per_feat = np.where(d_per_feat > 0,
                                 -(-d_per_feat // stride), 0)
        feat_offset = np.zeros(num_features + 1, dtype=np.int64)
        np.cumsum(bins_per_feat, out=feat_offset[1:])
        total_bins = int(feat_offset[-1])

        # upper threshold of flat bin (f, j): midpoint between the last
        # distinct value covered by bin j and the first of bin j+1; the
        # feature's last bin is +inf
        thresholds = np.full(total_bins, np.inf)
        if len(df):
            flat_of_pair = feat_offset[df] + local_of_pair
            # boundary pairs: last pair of its bin, not last of its feature
            not_last = np.zeros(len(df), dtype=bool)
            not_last[:-1] = (df[:-1] == df[1:]) & \
                (flat_of_pair[:-1] != flat_of_pair[1:])
            b_idx = np.nonzero(not_last)[0]
            thresholds[flat_of_pair[b_idx]] = (dv[b_idx] + dv[b_idx + 1]) / 2.0

        # zero position: the synthetic zero is a distinct value of every
        # present feature; find its pair and take its local bin
        zero_local = np.zeros(num_features, dtype=np.int32)
        if len(df):
            zpair = (dv == 0.0)
            zero_local[df[zpair]] = local_of_pair[zpair].astype(np.int32)

        # flat bin per ORIGINAL nnz entry (the synthetic zeros occupy the
        # tail of the augmented arrays)
        bin_of_nnz = np.zeros(nnz, dtype=np.int64)
        if nnz:
            flat_sorted = (feat_offset[df] + local_of_pair)[pair_of_sorted]
            flat_aug = np.zeros(len(fs_aug), dtype=np.int64)
            flat_aug[order] = flat_sorted
            bin_of_nnz = flat_aug[:nnz]
        return SparseDataset(
            indptr=indptr,
            indices=indices.astype(np.int32),
            values=values.astype(np.float32),
            num_features=int(num_features),
            feat_offset=feat_offset,
            thresholds=thresholds,
            zero_local=zero_local,
            bin_of_nnz=bin_of_nnz,
            row_of_nnz=np.repeat(
                np.arange(len(indptr) - 1, dtype=np.int64),
                np.diff(indptr)).astype(np.int32),
        )

    def bin_upper_value(self, f: int, local_bin: int) -> float:
        return float(self.thresholds[int(self.feat_offset[f]) + local_bin])


# ---------------------------------------------------------------------------
# Device histogram + split finding over the flat ragged bin space
# ---------------------------------------------------------------------------


_PREFIX_BLOCK = 512


def _prefix_sum(data, int_channel=None):
    """Inclusive prefix sum of [C, n] with a LEADING zero column -> [C, n+1]
    (so ``out[:, k]`` = sum of the first k elements).

    XLA's native cumsum lowering costs ~645 ms at [3, 50M] on the chip —
    it dominates every sparse split. This is the TPU-native two-level
    scheme instead: inclusive prefixes WITHIN 512-wide blocks via one
    upper-triangular matmul on the MXU (the stream-select kernel's trick),
    plus an ordinary cumsum over the ~n/512 block sums. Also better
    precision than a flat f32 scan: within-block sums cover <= 512 values.
    Small inputs keep jnp.cumsum (cheaper to compile, equally fast).

    ``int_channel``: channel whose values are integers (the COUNT channel)
    — its prefix is ALSO returned as an exact int32 [n+1] array (blocked
    short-scan cumsum + int32 block prefix), because an f32 prefix
    silently rounds once the running total passes 2^24 (at 50M entries a
    bin's boundary difference would be off by up to ~4). Callers must take
    count DIFFERENCES from the int array — storing the int prefix back
    into the f32 result would just reintroduce the rounding. (A variant
    that removed the int channel from the f32 matmul entirely measured
    ~8% SLOWER end to end on the 1M x 2^18 bench than this shared-layout
    form — same-run A/B pending, kept the better-attested shape.)
    Return is ``cs [C, n+1]`` alone when int_channel is None, else
    ``(cs, cs_int [n+1] int32)``; per-bin count differences cast back to
    f32 stay exact below 2^24 rows per bin. SCOPE of the exactness claim:
    per-bin/per-boundary counts are int-exact at any nnz, but node-TOTAL
    counts still live in the f32 [3] sums vector (root_tot, lsum/rsum,
    Tree.count) — a node above 2^24 ROWS rounds its total to the nearest
    representable f32 (~±4 at 50M). Removing that would mean an int32
    carry through the whole grower state; at the engine's practical
    single-chip scale (<=16.7M rows per fit today) the totals are exact."""
    import jax.numpy as jnp

    c, n = data.shape
    zero = jnp.zeros((c, 1), data.dtype)
    if n < (1 << 18):
        cs = jnp.concatenate([zero, jnp.cumsum(data, axis=1)], axis=1)
        if int_channel is None:
            return cs
        xi = jnp.round(data[int_channel]).astype(jnp.int32)
        cs_i = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(xi)])
        return cs, cs_i
    B = _PREFIX_BLOCK
    import jax

    n_pad = (n + B - 1) // B * B
    x = jnp.pad(data, ((0, 0), (0, n_pad - n))).reshape(c, n_pad // B, B)
    iota = jnp.arange(B, dtype=jnp.int32)
    ut = (iota[:, None] <= iota[None, :]).astype(jnp.float32)  # [B, B]
    intra = jax.lax.dot_general(
        x, ut, (((2,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)          # [c, nb, B] inclusive
    block_excl = jnp.cumsum(intra[:, :, -1], axis=1) - intra[:, :, -1]
    cs = (intra + block_excl[:, :, None]).reshape(c, n_pad)[:, :n]
    out = jnp.concatenate([zero, cs], axis=1)
    if int_channel is None:
        return out
    xi = jnp.round(x[int_channel]).astype(jnp.int32)   # [nb, B]
    intra_i = jnp.cumsum(xi, axis=1)                   # short scans
    bsum = intra_i[:, -1]
    bexcl = jnp.cumsum(bsum) - bsum
    cs_i = (intra_i + bexcl[:, None]).reshape(n_pad)[:n]
    cs_i = jnp.concatenate([jnp.zeros(1, jnp.int32), cs_i])
    return out, cs_i


def _exact_topk_mask(key, k: int, n: int, exclude=None):
    """Boolean [n] mask of EXACTLY ``min(k, n_eligible)`` rows with the
    largest keys, ties broken toward the smallest row index — scatter-free
    (a 32-step bitwise bisection on the nonnegative-f32 int view plus an
    index bisection among threshold ties; every step is one [n]
    compare-and-reduce, ~60 cheap reduces total).

    The exact count is what makes selected-row nnz compaction safe: the
    static capacity bound (sum of the k largest row-nnz, computed on host
    at fit time) only holds if selection can never exceed k rows. The
    >=-threshold GOSS mask cannot promise that — when gradients tie (e.g.
    a constant-label stretch) it selects every tied row. LightGBM's own
    GOSS takes exactly topN by sort (GOSS bagging in its C++ engine);
    this reproduces that count without a device sort.

    ``key``: [n] f32, values >= 0 (|grad| sums / uniform draws).
    ``exclude``: optional [n] bool — ineligible rows, never selected.
    """
    import jax
    import jax.numpy as jnp

    if k <= 0:
        return jnp.zeros(n, dtype=bool)
    # uint32 order-preserving view: bitcast of a nonnegative f32 keeps the
    # sign bit clear (< 2^31), so +1 shifts every eligible key above the
    # excluded-row sentinel 0 without overflow — and keeps the bisection
    # range inside uint32 (an int32 domain of [-1, 2^31-1] overflows the
    # midpoint arithmetic)
    ik = jax.lax.bitcast_convert_type(
        jnp.abs(key.astype(jnp.float32)), jnp.uint32) + jnp.uint32(1)
    if exclude is not None:
        ik = jnp.where(exclude, jnp.uint32(0), ik)
        kk = jnp.minimum(jnp.int32(k),
                         jnp.sum((~exclude).astype(jnp.int32)))
    else:
        kk = jnp.int32(min(k, n))

    # largest t with count(ik >= t) >= kk  (count is monotone in t)
    def bis_t(_, lohi):
        lo, hi = lohi
        mid = lo + ((hi - lo + jnp.uint32(1)) >> 1)
        take = jnp.sum((ik >= mid).astype(jnp.int32)) >= kk
        return (jnp.where(take, mid, lo),
                jnp.where(take, hi, mid - jnp.uint32(1)))

    t, _ = jax.lax.fori_loop(
        0, 32, bis_t, (jnp.uint32(0), jnp.uint32(2**31 + 1)))

    gt = ik > t
    need = kk - jnp.sum(gt.astype(jnp.int32))    # ties still to take, >= 0
    tie = ik == t
    idxv = jnp.arange(n, dtype=jnp.int32)

    # smallest c with count(tie & idx < c) >= need; counts step by <= 1 per
    # c, so the count at the answer is exactly `need`
    def bis_c(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) >> 1
        ok = jnp.sum((tie & (idxv < mid)).astype(jnp.int32)) >= need
        return (jnp.where(ok, lo, mid + 1), jnp.where(ok, mid, hi))

    c, _ = jax.lax.fori_loop(
        0, 32, bis_c, (jnp.int32(0), jnp.int32(n)))
    return gt | (tie & (idxv < c))


def _entry_gh(dev, grad, hess):
    """Per-ENTRY grad/hess in bin-sorted order: gathered ONCE per
    tree/iteration. The 50M-entry random gather costs ~0.45 s on the chip
    (measured ~30 ns/element — the dominant sparse cost); grad/hess are
    loop-invariant during a tree, so only the node MASK gather stays in
    the per-split path."""
    import jax.numpy as jnp

    rows_bs = dev["row_of_nnz_bs"]
    return jnp.take(grad, rows_bs), jnp.take(hess, rows_bs)


def _flat_histogram(dev, g_bs, h_bs, node_mask_rows):
    """Nonzero-entry histogram: [3, total_bins] sums over the node's rows —
    SCATTER-FREE (the TPU has no scatter hardware; jax segment_sum lowers
    to a serialized XLA scatter that crashed the tunnelled worker at 50M
    nnz). Entries are pre-sorted by flat bin at dataset build, so the
    per-bin sums are differences of ONE masked prefix sum at the
    bin-boundary offsets: O(nnz) block-matmul scan (_prefix_sum) + O(TB)
    gathers. Per split this costs one [nnz] row-mask gather + the scan.

    ``g_bs``/``h_bs``: per-entry grad/hess from _entry_gh (hoisted out of
    the split loop — they are tree-invariant).
    ``dev["nnz_valid"]`` (optional, sharded layouts): 0/1 per BIN-SORTED
    entry — padding entries in equal-shape per-shard slices contribute
    nothing.

    ALL flat-histogram tensors are CHANNEL-MAJOR [3, nnz] / [3, TB]: the
    minor dim must be the big one — a [50M, 3] f32 array tiles 3 -> 128
    lanes on TPU, a 42x HBM blowup that tried to allocate 25.6 GB at the
    1M-row text bench (same trap the dense kernels hit in r3)."""
    import jax.numpy as jnp

    rows_bs = dev["row_of_nnz_bs"]                 # bin-sorted entry order
    m = jnp.take(node_mask_rows, rows_bs).astype(jnp.float32)
    if "nnz_valid" in dev:
        m = m * dev["nnz_valid"]
    data = jnp.stack([g_bs * m, h_bs * m, m], axis=0)   # [3, nnz]
    # hist.csr kernel variant (core/kernels.py): the same sums as a one-hot
    # MXU contraction over nnz chunks (gbdt/pallas_sparse.py). Resolved at
    # trace time; None = the default prefix-sum path, byte-for-byte.
    from .pallas_sparse import flat_hist_dispatch

    hist_p = flat_hist_dispatch(dev, data)
    if hist_p is not None:
        return hist_p
    cs, cs_i = _prefix_sum(data, int_channel=2)
    hist = (jnp.take(cs, dev["bin_end"], axis=1)
            - jnp.take(cs, dev["bin_start"], axis=1))   # [3, TB]
    # count channel: int differences (the f32 prefix rounds past 2^24)
    counts = (jnp.take(cs_i, dev["bin_end"])
              - jnp.take(cs_i, dev["bin_start"]))
    return hist.at[2].set(counts.astype(jnp.float32))


def _zero_completed(dev, flat_hist, node_totals):
    """Add the implicit-zero bin of every feature: node totals minus the
    feature's nonzero-entry sums (LightGBM's default-bin subtraction).
    Scatter-free: per-feature sums are cumsum differences at the feature
    boundaries (bins are grouped by feature in the flat space), and the
    zero-bin add is a masked gather of the per-feature deficit.
    Channel-major [3, TB] layout throughout (see _flat_histogram)."""
    import jax.numpy as jnp

    cs, cs_i = _prefix_sum(flat_hist, int_channel=2)
    feat_sums = (jnp.take(cs, dev["feat_offset_dev"][1:], axis=1)
                 - jnp.take(cs, dev["feat_offset_dev"][:-1], axis=1))
    feat_cnt = (jnp.take(cs_i, dev["feat_offset_dev"][1:])
                - jnp.take(cs_i, dev["feat_offset_dev"][:-1]))
    feat_sums = feat_sums.at[2].set(feat_cnt.astype(jnp.float32))
    zero_sums = node_totals[:, None] - feat_sums          # [3, F]
    add = jnp.where(dev["is_zero_bin"][None, :],
                    jnp.take(zero_sums, dev["feat_of_bin"], axis=1), 0.0)
    return flat_hist + add


def _find_best_split_flat(dev, hist, lambda_l1, lambda_l2, min_sum_hessian,
                          min_data_in_leaf, bin_mask=None):
    """Vectorized gain scan over ALL flat bins: candidate t at flat bin b
    sends local bins <= b left. Per-feature left-cumulative sums come from a
    global cumsum minus the feature's base — no per-feature loop.
    ``hist`` is channel-major [3, TB] (see _flat_histogram).

    ``bin_mask``: optional [TB] bool of ALLOWED candidate bins (feature
    fraction, mapped to the flat bin space by the caller)."""
    import jax.numpy as jnp

    from .histogram import _leaf_objective

    cs, cs_full_i = _prefix_sum(hist, int_channel=2)
    cs, cs_i = cs[:, 1:], cs_full_i[1:]                    # [3, TB] inclusive
    base = (jnp.take(cs, dev["feat_start_of_bin"], axis=1)
            - jnp.take(hist, dev["feat_start_of_bin"], axis=1))
    left = cs - base                                       # [3, TB] within-feature
    total = jnp.take(left, dev["feat_end_of_bin"], axis=1)
    GL, HL = left[0], left[1]
    G, H = total[0], total[1]
    # count channel in exact int32: left/right row counts feed the
    # min_data_in_leaf gates and the emitted Tree.count
    hist_cnt = jnp.round(hist[2]).astype(jnp.int32)
    base_i = (jnp.take(cs_i, dev["feat_start_of_bin"])
              - jnp.take(hist_cnt, dev["feat_start_of_bin"]))
    left_i = cs_i - base_i
    total_i = jnp.take(left_i, dev["feat_end_of_bin"])
    CL = left_i.astype(jnp.float32)
    GR, HR = G - GL, H - HL
    CR = (total_i - left_i).astype(jnp.float32)
    gain = (_leaf_objective(GL, HL, lambda_l1, lambda_l2)
            + _leaf_objective(GR, HR, lambda_l1, lambda_l2)
            - _leaf_objective(G, H, lambda_l1, lambda_l2)) * -1.0
    ok = ((CL >= min_data_in_leaf) & (CR >= min_data_in_leaf)
          & (HL >= min_sum_hessian) & (HR >= min_sum_hessian)
          & ~dev["is_last_bin"])                          # no split after last
    if bin_mask is not None:
        ok &= bin_mask
    gain = jnp.where(ok, gain, -jnp.inf)
    b = jnp.argmax(gain)
    return (b, gain[b], jnp.stack([GL[b], HL[b], CL[b]]),
            jnp.stack([GR[b], HR[b], CR[b]]))


def _row_feature_search(dev, lo0, hi0, f):
    """Vectorized lower-bound search for each row's entry of feature ``f``
    (scalar or per-row array) inside the row's feature-sorted CSR slice
    [lo0, hi0) — pure gathers, no scatter. Per-row ranges are at most
    max_row_nnz wide, so ceil(log2(max_row_nnz)) steps suffice
    (dev["route_steps"]) — at avg-50-nnz text data that is ~9 gathers
    instead of 32 (each step is a random gather from the 200 MB entry
    stream, the dominant routing cost at 50M nnz). Shared by per-split
    routing (_route_rows) and the lazy full-N traversal
    (_assign_leaves_all_rows) so the two can never desynchronize."""
    import jax
    import jax.numpy as jnp

    feats = dev["feat_of_nnz"]
    nnz = feats.shape[0]

    def step(_, lohi):
        lo, hi = lohi
        cont = lo < hi
        mid = (lo + hi) >> 1
        fm = jnp.take(feats, jnp.clip(mid, 0, nnz - 1))
        go_hi = fm < f
        new_lo = jnp.where(go_hi, mid + 1, lo)
        new_hi = jnp.where(go_hi, hi, mid)
        return (jnp.where(cont, new_lo, lo), jnp.where(cont, new_hi, hi))

    n_steps = dev.get("route_steps", 32)
    lo, _ = jax.lax.fori_loop(0, n_steps, step, (lo0, hi0))
    return lo


def _route_rows(dev, node_of_row, node_id, f, t_local, lid, rid):
    """Send the node's rows left iff value-bin <= t_local; absent entries
    carry the feature's zero bin.

    SCATTER-FREE: each row's entry of feature ``f`` (if any) is located by
    the vectorized lower-bound search of _row_feature_search — pure
    gathers over the feature-sorted entries (segment_max over 50M entries
    lowered to a serialized scatter-max that crashed the tunnelled worker
    at text scale)."""
    import jax
    import jax.numpy as jnp

    zero_goes_left = dev["zero_local_dev"][f] <= t_local
    default_child = jnp.where(zero_goes_left, lid, rid)
    in_node = node_of_row == node_id
    out = jnp.where(in_node, default_child, node_of_row)

    feats = dev["feat_of_nnz"]
    nnz = feats.shape[0]
    if "route_lo" in dev:
        # lazy/compacted mode: the routed "rows" are the SELECTED rows;
        # their CSR slices into the global entry stream were gathered at
        # compaction time (slices need not be contiguous across rows)
        lo0 = dev["route_lo"]
        hi0 = dev["route_hi"]
    else:
        indptr = dev["indptr_dev"]
        lo0 = indptr[:-1]
        hi0 = indptr[1:]

    lo = _row_feature_search(dev, lo0, hi0, f)
    pos = jnp.clip(lo, 0, nnz - 1)
    has = (lo < hi0) & (jnp.take(feats, pos) == f)
    local_bin = jnp.take(dev["bin_of_nnz"], pos) - dev["feat_offset_dev"][f]
    target = jnp.where(local_bin <= t_local, lid, rid)
    return jnp.where(in_node & has, target, out)


def _assign_leaves_all_rows(dev, tree_out, n: int):
    """Route ALL n rows through a finished tree by level-synchronous
    traversal: each level advances every row one node via ONE vectorized
    per-row binary search (the row's entry of its CURRENT node's feature —
    the search target varies per row, which the lower-bound gathers handle
    unchanged). Cost is depth x one routing pass instead of
    (num_leaves-1) x one routing pass — the lazy-routing complement: with
    per-split routing restricted to the selected rows, this single
    traversal recovers the full node assignment the score update needs.
    Absent features carry the zero bin, exactly like _route_rows."""
    import jax
    import jax.numpy as jnp

    feat = tree_out["feature"]
    tb_l = tree_out["threshold_bin"]
    li = tree_out["left"]
    ri = tree_out["right"]
    feats = dev["feat_of_nnz"]
    bins = dev["bin_of_nnz"]
    fo = dev["feat_offset_dev"]
    zl = dev["zero_local_dev"]
    nnz = feats.shape[0]
    indptr = dev["indptr_dev"]
    lo_all, hi_all = indptr[:-1], indptr[1:]

    def cond(state):
        pos, it = state
        return (it < feat.shape[0]) & jnp.any(jnp.take(feat, pos) >= 0)

    def body(state):
        pos, it = state
        f = jnp.take(feat, pos)                  # [n]; -1 at leaves
        t_loc = jnp.take(tb_l, pos)
        f_safe = jnp.maximum(f, 0)
        lo = _row_feature_search(dev, lo_all, hi_all, f_safe)
        p = jnp.clip(lo, 0, nnz - 1)
        has = (lo < hi_all) & (jnp.take(feats, p) == f_safe)
        lb = jnp.take(bins, p) - jnp.take(fo, f_safe)
        lb_eff = jnp.where(has, lb, jnp.take(zl, f_safe))
        nxt = jnp.where(lb_eff <= t_loc, jnp.take(li, pos), jnp.take(ri, pos))
        return jnp.where(f >= 0, nxt, pos), it + 1

    pos, _ = jax.lax.while_loop(
        cond, body, (jnp.zeros(n, jnp.int32), jnp.int32(0)))
    return pos


def _bin_sorted_layout(bin_of_nnz: np.ndarray, total_bins: int):
    """Host precompute for the scatter-free histogram: a stable sort of
    entries by flat bin + the per-bin [start, end) offsets into the sorted
    stream. Returns (order, bin_start [TB], bin_end [TB])."""
    order = np.argsort(bin_of_nnz, kind="stable")
    sorted_bins = bin_of_nnz[order]
    bin_start = np.searchsorted(sorted_bins, np.arange(total_bins),
                                side="left")
    bin_end = np.searchsorted(sorted_bins, np.arange(total_bins),
                              side="right")
    return order, bin_start.astype(np.int64), bin_end.astype(np.int64)


def _device_arrays(ds: SparseDataset):
    import jax.numpy as jnp

    tb = ds.total_bins
    feat_of_bin = np.repeat(np.arange(ds.num_features, dtype=np.int64),
                            np.diff(ds.feat_offset))
    feat_start = ds.feat_offset[feat_of_bin]
    feat_end = ds.feat_offset[feat_of_bin + 1] - 1
    is_last = np.arange(tb) == feat_end
    present = np.nonzero(np.diff(ds.feat_offset) > 0)[0]
    zero_flat = (ds.feat_offset[present]
                 + ds.zero_local[present]).astype(np.int64)
    is_zero_bin = np.zeros(tb, dtype=bool)
    is_zero_bin[zero_flat] = True
    order, bin_start, bin_end = _bin_sorted_layout(ds.bin_of_nnz, tb)
    return {
        "bin_of_nnz": jnp.asarray(ds.bin_of_nnz, dtype=jnp.int32),
        "feat_of_nnz": jnp.asarray(ds.indices, dtype=jnp.int32),
        "indptr_dev": jnp.asarray(ds.indptr, dtype=jnp.int32),
        # bin-sorted views for the scatter-free histogram
        "row_of_nnz_bs": jnp.asarray(ds.row_of_nnz[order]),
        "bin_start": jnp.asarray(bin_start, dtype=jnp.int32),
        "bin_end": jnp.asarray(bin_end, dtype=jnp.int32),
        "is_zero_bin": jnp.asarray(is_zero_bin),
        "feat_of_bin": jnp.asarray(feat_of_bin, dtype=jnp.int32),
        "feat_start_of_bin": jnp.asarray(feat_start, dtype=jnp.int32),
        "feat_end_of_bin": jnp.asarray(feat_end, dtype=jnp.int32),
        "is_last_bin": jnp.asarray(is_last),
        "present_feats": jnp.asarray(present, dtype=jnp.int32),
        "zero_flat": jnp.asarray(zero_flat, dtype=jnp.int32),
        "zero_local_dev": jnp.asarray(ds.zero_local, dtype=jnp.int32),
        "feat_offset_dev": jnp.asarray(ds.feat_offset, dtype=jnp.int32),
        "total_bins": tb,
        "num_features": ds.num_features,
        "route_steps": int(
            max(int(np.diff(ds.indptr).max()) if len(ds.indptr) > 1 else 1,
                1)).bit_length(),
    }


_FUSED_SPARSE_GROW_CACHE: dict = {}
_SPARSE_SCAN_CACHE: dict = {}


def _tree_from_fused_out(out_host, config: GrowerConfig,
                         thresholds: np.ndarray) -> Tree:
    """Host-side Tree build from the fused grower's fetched arrays, leaf
    values recomputed in f64 (same precision lineage as the host loop)."""
    nn = int(out_host["n_nodes"])
    feature = out_host["feature"][:nn].astype(np.int32)
    tbin = out_host["threshold_bin"][:nn].astype(np.int32)
    fbin = out_host["flat_bin"][:nn].astype(np.int64)
    sums = out_host["sums"][:nn].astype(np.float64)
    g_thr = np.sign(sums[:, 0]) * np.maximum(
        np.abs(sums[:, 0]) - config.lambda_l1, 0.0)
    value = np.where(feature < 0,
                     -g_thr / (sums[:, 1] + config.lambda_l2), 0.0)
    if config.max_delta_step > 0:
        value = np.clip(value, -config.max_delta_step, config.max_delta_step)
    value[0] = 0.0 if nn == 1 else value[0]
    threshold = np.where(feature >= 0, thresholds[fbin], 0.0)
    return Tree(
        feature=feature,
        threshold=threshold.astype(np.float64),
        threshold_bin=tbin,
        default_left=out_host["default_left"][:nn].astype(bool),
        left=out_host["left"][:nn].astype(np.int32),
        right=out_host["right"][:nn].astype(np.int32),
        value=value,
        gain=out_host["gain"][:nn].astype(np.float32),
        count=sums[:, 2].astype(np.int32),
        weight=sums[:, 1],
    )


def shard_sparse_dataset(ds: SparseDataset, n_shards: int):
    """Partition rows into ``n_shards`` contiguous, nnz-BALANCED blocks and
    build equal-shape per-shard nnz/row arrays (shard_map needs identical
    shard shapes; padding entries carry feat=-1 / nnz_valid=0 so they
    contribute nothing).

    Returns (host dict of [S, ...] arrays, row_bounds [S+1], r_max).
    nnz balancing: block boundaries at equal cumulative-nnz quantiles — the
    reference's equivalent is Spark partition sizing; here the histogram
    cost is O(local nnz), so balanced nnz = balanced step time."""
    n = ds.num_rows
    nnz = len(ds.indices)
    # boundaries: rows where cumulative nnz crosses each 1/S quantile
    targets = (np.arange(1, n_shards) * nnz) // n_shards
    bounds = np.concatenate([
        [0], np.searchsorted(ds.indptr[1:], targets, side="left") + 1, [n]])
    bounds = np.maximum.accumulate(bounds)  # monotone under empty blocks
    r_max = int(np.max(np.diff(bounds))) if n else 1
    nz_max = int(np.max(ds.indptr[bounds[1:]] - ds.indptr[bounds[:-1]])) \
        if n else 1
    nz_max = max(nz_max, 1)

    S = n_shards
    tb = ds.total_bins
    bin_sh = np.zeros((S, nz_max), dtype=np.int32)
    feat_sh = np.full((S, nz_max), -1, dtype=np.int32)
    row_bs = np.zeros((S, nz_max), dtype=np.int32)
    valid_bs = np.zeros((S, nz_max), dtype=np.float32)
    bin_start = np.zeros((S, tb), dtype=np.int32)
    bin_end = np.zeros((S, tb), dtype=np.int32)
    indptr_loc = np.zeros((S, r_max + 1), dtype=np.int32)
    row_valid = np.zeros((S, r_max), dtype=bool)
    for s in range(S):
        r0, r1 = int(bounds[s]), int(bounds[s + 1])
        e0, e1 = int(ds.indptr[r0]), int(ds.indptr[r1])
        m = e1 - e0
        bin_sh[s, :m] = ds.bin_of_nnz[e0:e1]
        feat_sh[s, :m] = ds.indices[e0:e1]
        # bin-sorted views of the REAL entries (pads stay at the tail with
        # valid 0; bin boundaries index only the sorted real stream)
        order, bs, be = _bin_sorted_layout(
            ds.bin_of_nnz[e0:e1].astype(np.int64), tb)
        row_bs[s, :m] = (ds.row_of_nnz[e0:e1] - r0)[order]
        valid_bs[s, :m] = 1.0
        bin_start[s] = bs
        bin_end[s] = be
        # local CSR offsets for the binary-search routing; empty/pad rows
        # collapse to [m, m)
        indptr_loc[s, : r1 - r0 + 1] = ds.indptr[r0: r1 + 1] - e0
        indptr_loc[s, r1 - r0 + 1:] = m
        row_valid[s, : r1 - r0] = True
    return ({"bin_of_nnz": bin_sh,
             "feat_of_nnz": feat_sh, "row_of_nnz_bs": row_bs,
             "nnz_valid": valid_bs, "bin_start": bin_start,
             "bin_end": bin_end, "indptr_dev": indptr_loc,
             "row_valid": row_valid}, bounds, r_max)


_SHARDED_SPARSE_GROW_CACHE: dict = {}


def grow_tree_sparse_sharded(ds: SparseDataset, dev, sharded, mesh,
                             grad_sh, hess_sh, row_mask_sh,
                             config: GrowerConfig, bin_mask=None
                             ) -> Tuple[Tree, np.ndarray]:
    """Row-sharded whole-tree growth: the while_loop runs per shard under
    shard_map with psum'd flat histograms — replicated split decisions,
    sharded row routing (the dense engine's _grow_tree_device_sharded, on
    CSR). One dispatch + one collective stream per tree.

    ``sharded``: device dict from shard_sparse_dataset ([S, ...] arrays,
    device_put with the shard dim split over the mesh's data axis).
    ``grad_sh``/``hess_sh``/``row_mask_sh``: [S, r_max] sharded arrays.
    """
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map_compat as shard_map

    from ..parallel.mesh import DATA_AXIS

    M = 2 * config.num_leaves - 1
    has_bm = bin_mask is not None
    tb = dev["total_bins"]
    # key carries EVERY closed-over static (tb, num_features) — all array
    # data flows through jit arguments, so a cache hit can never serve a
    # stale dataset (shape changes retrace inside the cached jit)
    key = (mesh, M, config.min_data_in_leaf, config.max_depth, has_bm,
           tb, dev["num_features"], dev.get("route_steps", 32))
    if key not in _SHARDED_SPARSE_GROW_CACHE:
        if len(_SHARDED_SPARSE_GROW_CACHE) >= 8:
            _SHARDED_SPARSE_GROW_CACHE.pop(
                next(iter(_SHARDED_SPARSE_GROW_CACHE)))
        # globals (bin layout) replicate; per-shard arrays split on dim 0;
        # static ints (segment counts) close over — they must not trace
        nf_static = dev["num_features"]
        rs_static = dev.get("route_steps", 32)
        _PER_SHARD = ("bin_of_nnz", "feat_of_nnz", "row_of_nnz_bs",
                      "nnz_valid", "bin_start", "bin_end", "indptr_dev")
        glob = {k: v for k, v in dev.items()
                if k not in _PER_SHARD + ("total_bins", "num_features",
                                          "route_steps")}

        sh_spec = P(DATA_AXIS)
        rep = P()

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=({k: sh_spec for k in _PER_SHARD},
                      sh_spec, sh_spec, sh_spec,
                      {k: rep for k in glob}, rep, rep, rep, rep, rep),
            out_specs={"node_of_row": sh_spec, "feature": rep,
                       "threshold_bin": rep, "flat_bin": rep,
                       "default_left": rep, "left": rep, "right": rep,
                       "gain": rep, "sums": rep, "n_nodes": rep},
            # like tree._grow_tree_device_sharded: the while_loop carry
            # mixes shard-varying (node_of_row) and replicated state
            check_vma=False)
        def go(shd, g, h, m, gl, bm, l1, l2, mshp, mgsp):
            dev_l = dict(gl)
            dev_l["total_bins"] = tb
            dev_l["num_features"] = nf_static
            dev_l["route_steps"] = rs_static
            for kk, v in shd.items():
                dev_l[kk] = v[0]
            g, h, m = g[0], h[0], m[0]
            mask_f = m.astype(jnp.float32)
            root_tot = jax.lax.psum(
                jnp.stack([jnp.sum(g * mask_f), jnp.sum(h * mask_f),
                           jnp.sum(mask_f)]),
                DATA_AXIS)
            out = _grow_tree_sparse_body(
                dev_l, g, h, m, jnp.zeros(g.shape[0], jnp.int32), root_tot,
                l1, l2, mshp, mgsp, bm, total_bins=tb, max_nodes=M,
                min_data_in_leaf=config.min_data_in_leaf,
                max_depth=config.max_depth, has_bin_mask=has_bm,
                psum_axis=DATA_AXIS)
            out["node_of_row"] = out["node_of_row"][None, :]
            return out

        _SHARDED_SPARSE_GROW_CACHE[key] = (jax.jit(go), glob)
    fn, glob = _SHARDED_SPARSE_GROW_CACHE[key]
    bm = bin_mask if has_bm else jnp.zeros(0, dtype=bool)
    out = fn({k: sharded[k] for k in
              ("bin_of_nnz", "feat_of_nnz", "row_of_nnz_bs",
               "nnz_valid", "bin_start", "bin_end", "indptr_dev")},
             grad_sh, hess_sh, row_mask_sh, glob, bm,
             np.float32(config.lambda_l1), np.float32(config.lambda_l2),
             np.float32(config.min_sum_hessian_in_leaf),
             np.float32(config.min_gain_to_split))
    rows_dev = out.pop("node_of_row")
    out_host = fetch_global(out)
    tree = _tree_from_fused_out(out_host, config, ds.thresholds)
    return tree, np.asarray(fetch_global(rows_dev))


def grow_tree_sparse(ds: SparseDataset, dev, grad, hess,
                     config: GrowerConfig, row_mask=None, bin_mask=None,
                     use_fused: Optional[bool] = None
                     ) -> Tuple[Tree, np.ndarray]:
    """Grow one tree over the flat sparse bins; returns (tree, leaf_of_row).

    Default (``use_fused``): the whole tree grows inside one jitted
    ``lax.while_loop`` dispatch (_grow_tree_sparse_body) — one fetch per
    tree. Fallback (state over the memory budget or explicitly disabled):
    the host-orchestrated per-split loop below.

    ``row_mask``: [N] bool device array — bagging/goss subset (histograms
    and totals are masked; routing still covers every row).
    ``bin_mask``: [TB] bool device array of allowed split bins
    (feature_fraction mapped to the flat space).
    """
    import heapq

    import jax
    import jax.numpy as jnp

    n = ds.num_rows
    if use_fused is None:
        use_fused = (_fused_sparse_enabled(2 * config.num_leaves - 1,
                                           ds.total_bins)
                     and jax.default_backend() != "cpu")
    if use_fused:
        M = 2 * config.num_leaves - 1
        has_bm = bin_mask is not None
        tb = dev["total_bins"]
        nf = dev["num_features"]
        rs = dev.get("route_steps", 32)
        # key carries every closed-over static; array data (the dev dict)
        # flows through jit arguments — no id()-keying, no pinned device
        # memory for evicted datasets (numBatches builds a fresh
        # SparseDataset per batch)
        key = (M, config.min_data_in_leaf, config.max_depth, has_bm, tb,
               nf, rs)
        if key not in _FUSED_SPARSE_GROW_CACHE:
            if len(_FUSED_SPARSE_GROW_CACHE) >= 16:
                _FUSED_SPARSE_GROW_CACHE.pop(
                    next(iter(_FUSED_SPARSE_GROW_CACHE)))

            @jax.jit
            def _go(devd, gk, hk, mask, bm, l1, l2, msh, mgs):
                devd = dict(devd)
                devd["total_bins"] = tb
                devd["num_features"] = nf
                devd["route_steps"] = rs
                mask_f = mask.astype(jnp.float32)
                root_tot = jnp.stack([jnp.sum(gk * mask_f),
                                      jnp.sum(hk * mask_f),
                                      jnp.sum(mask_f)])
                return _grow_tree_sparse_body(
                    devd, gk, hk, mask, jnp.zeros(gk.shape[0], jnp.int32),
                    root_tot, l1, l2, msh, mgs, bm, total_bins=tb,
                    max_nodes=M, min_data_in_leaf=config.min_data_in_leaf,
                    max_depth=config.max_depth, has_bin_mask=has_bm)

            _FUSED_SPARSE_GROW_CACHE[key] = _go
        mask = row_mask if row_mask is not None \
            else jnp.ones(n, dtype=bool)
        bm = bin_mask if has_bm else jnp.zeros(0, dtype=bool)
        dev_arrays = {kk_: v for kk_, v in dev.items()
                      if kk_ not in ("total_bins", "num_features",
                                     "route_steps")}
        out = _FUSED_SPARSE_GROW_CACHE[key](
            dev_arrays, mask=mask, bm=bm, gk=grad, hk=hess,
            l1=np.float32(config.lambda_l1), l2=np.float32(config.lambda_l2),
            msh=np.float32(config.min_sum_hessian_in_leaf),
            mgs=np.float32(config.min_gain_to_split))
        rows_dev = out.pop("node_of_row")
        out_host = fetch_global(out)
        tree = _tree_from_fused_out(out_host, config, ds.thresholds)
        return tree, np.asarray(fetch_global(rows_dev))

    node_of_row = jnp.zeros(n, dtype=jnp.int32)
    ones = row_mask if row_mask is not None else jnp.ones(n, dtype=bool)

    feature = [-1]
    threshold = [0.0]
    threshold_bin = [0]
    default_left = [True]
    left = [-1]
    right = [-1]
    value = [0.0]
    gains = [0.0]
    counts = [0]
    hweights = [0.0]

    def leaf_value(sums):
        g_thr = np.sign(sums[0]) * max(abs(sums[0]) - config.lambda_l1, 0.0)
        v = float(-g_thr / (sums[1] + config.lambda_l2))
        if config.max_delta_step > 0:
            v = float(np.clip(v, -config.max_delta_step,
                              config.max_delta_step))
        return v

    g_bs, h_bs = _entry_gh(dev, grad, hess)

    def node_hist(mask_rows, totals):
        flat = _flat_histogram(dev, g_bs, h_bs, mask_rows)
        return _zero_completed(dev, flat, totals)

    mask_f = ones.astype(jnp.float32)
    totals0 = jnp.stack([jnp.sum(grad * mask_f), jnp.sum(hess * mask_f),
                         jnp.sum(mask_f)])
    hist0 = node_hist(ones, totals0)
    totals0_h = np.asarray(fetch_global(totals0), np.float64)
    counts[0] = int(totals0_h[2])
    hweights[0] = float(totals0_h[1])

    def eval_split(hist):
        b, gain, lsum, rsum = _find_best_split_flat(
            dev, hist, np.float32(config.lambda_l1),
            np.float32(config.lambda_l2),
            np.float32(config.min_sum_hessian_in_leaf),
            config.min_data_in_leaf, bin_mask)
        b, gain, lsum, rsum = fetch_global((b, gain, lsum, rsum))
        f = int(np.searchsorted(ds.feat_offset, b, side="right") - 1)
        t_local = int(b - ds.feat_offset[f])
        return f, t_local, float(gain), np.asarray(lsum, np.float64), \
            np.asarray(rsum, np.float64)

    heap = []
    tiebreak = 0

    def push(node_id, depth, hist, sums):
        nonlocal tiebreak
        f, t_local, gain, lsum, rsum = eval_split(hist)
        if np.isfinite(gain) and gain > config.min_gain_to_split:
            if config.max_depth > 0 and depth >= config.max_depth:
                return
            heapq.heappush(heap, (-gain, tiebreak,
                                  (node_id, depth, hist, sums,
                                   f, t_local, lsum, rsum, gain)))
            tiebreak += 1

    push(0, 0, hist0, totals0_h)
    n_leaves = 1

    while heap and n_leaves < config.num_leaves:
        _, _, (nid, depth, hist, sums, f, t_local, lsum, rsum, gain) = \
            heapq.heappop(heap)
        lid, rid = len(feature), len(feature) + 1
        thr = ds.bin_upper_value(f, t_local)
        feature[nid] = f
        threshold[nid] = thr
        threshold_bin[nid] = t_local
        # absent==0.0 routes by value like LightGBM's sparse default bin;
        # keep dense-predict agreement: zeros follow the threshold compare
        default_left[nid] = bool(0.0 <= thr)
        left[nid], right[nid] = lid, rid
        gains[nid] = float(gain)
        value[nid] = 0.0
        for csum in (lsum, rsum):
            feature.append(-1)
            threshold.append(0.0)
            threshold_bin.append(0)
            default_left.append(True)
            left.append(-1)
            right.append(-1)
            value.append(leaf_value(csum))
            gains.append(0.0)
            counts.append(int(csum[2]))
            hweights.append(float(csum[1]))
        n_leaves += 1

        node_of_row = _route_rows(dev, node_of_row, np.int32(nid),
                                  np.int32(f), np.int32(t_local),
                                  np.int32(lid), np.int32(rid))
        small_id, big_id = (lid, rid) if lsum[2] <= rsum[2] else (rid, lid)
        small_sums = lsum if small_id == lid else rsum
        big_sums = rsum if small_id == lid else lsum
        small_hist = node_hist(ones & (node_of_row == small_id),
                               jnp.asarray(small_sums, jnp.float32))
        big_hist = hist - small_hist
        for cid, chist, csums in ((small_id, small_hist, small_sums),
                                  (big_id, big_hist, big_sums)):
            if csums[2] >= 2 * config.min_data_in_leaf:
                push(cid, depth + 1, chist, csums)

    tree = Tree(
        feature=np.asarray(feature, dtype=np.int32),
        threshold=np.asarray(threshold, dtype=np.float64),
        threshold_bin=np.asarray(threshold_bin, dtype=np.int32),
        default_left=np.asarray(default_left, dtype=bool),
        left=np.asarray(left, dtype=np.int32),
        right=np.asarray(right, dtype=np.int32),
        value=np.asarray(value, dtype=np.float64),
        gain=np.asarray(gains, dtype=np.float32),
        count=np.asarray(counts, dtype=np.int32),
        weight=np.asarray(hweights, dtype=np.float64),
    )
    return tree, np.asarray(fetch_global(node_of_row))


# ---------------------------------------------------------------------------
# Device-fused whole-tree growth + whole-run scan (the dense engine's
# booster._train_scan / tree._grow_tree_device_body, ported to the flat
# ragged bin space — one dispatch chain for the entire boosting run)
# ---------------------------------------------------------------------------

# Per-node flat-histogram state cap for the fused sparse grower:
# [2L-1, total_bins, 3] f32. Above this, the host-orchestrated per-split
# loop runs instead (its live set is the heap frontier only).
_FUSED_SPARSE_DEFAULT_BUDGET = 2 << 30


def _fused_sparse_enabled(max_nodes: int, total_bins: int) -> bool:
    import os

    if os.environ.get("MMLSPARK_TPU_NO_FUSED_TREE", "") not in ("", "0"):
        return False
    budget = int(os.environ.get("MMLSPARK_TPU_FUSED_TREE_BYTES",
                                _FUSED_SPARSE_DEFAULT_BUDGET))
    return max_nodes * total_bins * 3 * 4 <= budget


def _grow_tree_sparse_body(dev, grad, hess, row_mask, node_of_row, root_tot,
                           l1, l2, msh, mgs, bin_mask, *, total_bins: int,
                           max_nodes: int, min_data_in_leaf: int,
                           max_depth: int, has_bin_mask: bool,
                           psum_axis=None):
    """Grow one whole tree over the flat sparse bins inside a single
    ``lax.while_loop`` (the sparse analogue of tree._grow_tree_device_body).

    ``dev``: the _device_arrays dict (traced pytree — nnz/bin layouts).
    ``root_tot``: [3] f32 masked (grad, hess, count) node totals (already
    psum'd by the caller when sharded).
    ``psum_axis``: set when running per shard under shard_map with rows
    split over that mesh axis — every histogram is psum'd so all shards
    take identical split decisions while the row routing stays sharded
    (LightGBM's socket-ring data-parallel mode as one collective stream,
    TrainUtils.scala:383-418).
    Returns flat node arrays sized ``max_nodes`` plus the final row→node
    routing; node ids are assigned in split order exactly like the dense
    grower, so serialization/merge see an identical tree shape.
    """
    import jax
    import jax.numpy as jnp

    neg_inf = jnp.float32(-jnp.inf)
    M = max_nodes
    num_leaves_target = (max_nodes + 1) // 2
    bm = bin_mask if has_bin_mask else None
    g_bs, h_bs = _entry_gh(dev, grad, hess)  # tree-invariant entry gathers

    def best(hist):
        return _find_best_split_flat(dev, hist, l1, l2, msh,
                                     min_data_in_leaf, bm)

    def node_hist(mask_rows, totals):
        flat = _flat_histogram(dev, g_bs, h_bs, mask_rows)
        if psum_axis is not None:
            flat = jax.lax.psum(flat, psum_axis)
        return _zero_completed(dev, flat, totals)

    root_hist = node_hist(row_mask, root_tot)
    b0, gain0, lsum0, rsum0 = best(root_hist)
    root_ok = jnp.isfinite(gain0) & (gain0 > mgs)

    f32 = jnp.float32
    state = dict(
        node_of_row=node_of_row,
        feature=jnp.full(M, -1, jnp.int32),
        threshold_bin=jnp.zeros(M, jnp.int32),   # LOCAL bin within feature
        flat_bin=jnp.zeros(M, jnp.int32),        # flat bin (threshold lookup)
        default_left=jnp.ones(M, bool),
        left=jnp.full(M, -1, jnp.int32),
        right=jnp.full(M, -1, jnp.int32),
        gain=jnp.zeros(M, f32),
        sums=jnp.zeros((M, 3), f32).at[0].set(root_tot),
        depth=jnp.zeros(M, jnp.int32),
        hists=jnp.zeros((M, 3, total_bins), f32).at[0].set(root_hist),
        cand_gain=jnp.full(M, -jnp.inf, f32).at[0].set(
            jnp.where(root_ok, gain0, neg_inf)),
        cand_bin=jnp.zeros(M, jnp.int32).at[0].set(b0.astype(jnp.int32)),
        cand_lsum=jnp.zeros((M, 3), f32).at[0].set(lsum0),
        cand_rsum=jnp.zeros((M, 3), f32).at[0].set(rsum0),
        n_nodes=jnp.int32(1),
        n_leaves=jnp.int32(1),
    )

    def cond(st):
        return (st["n_leaves"] < num_leaves_target) \
            & (jnp.max(st["cand_gain"]) > neg_inf)

    def body(st):
        leaf = jnp.argmax(st["cand_gain"]).astype(jnp.int32)
        b = st["cand_bin"][leaf]
        f = dev["feat_of_bin"][b]
        t_local = b - dev["feat_start_of_bin"][b]
        dl = dev["zero_local_dev"][f] <= t_local   # absent (0.0) routing
        lsum = st["cand_lsum"][leaf]
        rsum = st["cand_rsum"][leaf]
        lid = st["n_nodes"]
        rid = lid + 1
        dchild = st["depth"][leaf] + 1

        node_of_row = _route_rows(dev, st["node_of_row"], leaf, f, t_local,
                                  lid, rid)

        small_is_left = lsum[2] <= rsum[2]
        small_id = jnp.where(small_is_left, lid, rid)
        big_id = jnp.where(small_is_left, rid, lid)
        small_tot = jnp.where(small_is_left, lsum, rsum)
        small_mask = row_mask & (node_of_row == small_id)
        small_hist = node_hist(small_mask, small_tot)
        big_hist = st["hists"][leaf] - small_hist
        sb, sg, sl, sr = best(small_hist)
        bb, bg, bl, br = best(big_hist)

        cg = st["cand_gain"].at[leaf].set(neg_inf)
        cb = st["cand_bin"]
        cl, cr = st["cand_lsum"], st["cand_rsum"]

        def push(arrs, nid, bsel, gsel, lsel, rsel, csum):
            cg, cb, cl, cr = arrs
            ok = jnp.isfinite(gsel) & (gsel > mgs)
            ok &= csum[2] >= 2 * min_data_in_leaf
            if max_depth > 0:
                ok &= dchild < max_depth
            return (cg.at[nid].set(jnp.where(ok, gsel, neg_inf)),
                    cb.at[nid].set(bsel.astype(jnp.int32)),
                    cl.at[nid].set(lsel), cr.at[nid].set(rsel))

        big_tot = jnp.where(small_is_left, rsum, lsum)
        arrs = push((cg, cb, cl, cr), small_id, sb, sg, sl, sr, small_tot)
        cg, cb, cl, cr = push(arrs, big_id, bb, bg, bl, br, big_tot)

        return dict(
            node_of_row=node_of_row,
            feature=st["feature"].at[leaf].set(f),
            threshold_bin=st["threshold_bin"].at[leaf].set(t_local),
            flat_bin=st["flat_bin"].at[leaf].set(b),
            default_left=st["default_left"].at[leaf].set(dl),
            left=st["left"].at[leaf].set(lid),
            right=st["right"].at[leaf].set(rid),
            gain=st["gain"].at[leaf].set(st["cand_gain"][leaf]),
            sums=st["sums"].at[lid].set(lsum).at[rid].set(rsum),
            depth=st["depth"].at[lid].set(dchild).at[rid].set(dchild),
            hists=st["hists"].at[small_id].set(small_hist)
                             .at[big_id].set(big_hist),
            cand_gain=cg, cand_bin=cb, cand_lsum=cl, cand_rsum=cr,
            n_nodes=lid + 2, n_leaves=st["n_leaves"] + 1,
        )

    out = jax.lax.while_loop(cond, body, state)
    return {k: out[k] for k in (
        "node_of_row", "feature", "threshold_bin", "flat_bin", "default_left",
        "left", "right", "gain", "sums", "n_nodes")}


def _scan_sparse_ok(params, valid, log) -> bool:
    """Whole-run-scan eligibility for the sparse path: mirrors
    booster._scan_train_ok (dart and per-iteration host eval stay on the
    host loop; lambdarank grads are group-segmented and also host-looped)."""
    import os

    import jax

    if os.environ.get("MMLSPARK_TPU_NO_SCAN_TRAIN", "") not in ("", "0"):
        return False
    if params.boosting_type == "dart" or params.objective == "lambdarank":
        return False
    if valid is not None or log is not None or params.train_metric:
        return False
    if 2 * params.num_leaves - 1 < 3:
        return False
    forced = os.environ.get("MMLSPARK_TPU_SCAN_TRAIN", "") not in ("", "0")
    if not forced and jax.default_backend() == "cpu":
        return False
    return True


def _sparse_compact_cap(params, ds, row_masks) -> tuple:
    """Static capacities ``(cap, sel_cap)`` for in-scan selected-row entry
    compaction — ``(0, 0)`` disables it.

    When a row subset is selected per iteration (GOSS / bagging / rf), the
    histogram stream is compacted to the selected rows' entries, shrinking
    every per-split cost from O(nnz) to O(selected nnz) — masking alone
    does not (the round-3 artifact's 'GOSS shows no speedup' finding:
    histogram prefix sums and mask gathers stream all nnz regardless).
    The capacity must be STATIC (the scan's shapes are fixed across
    iterations) and must bound the selected nnz of every iteration:

    - GOSS: selection is exactly top_n + other_n rows (_exact_topk_mask),
      so the sum of that many largest row-nnz is a guarantee;
    - host-precomputed bagging masks: the per-iteration selected nnz is
      known outright — take the max.

    Returns ``(cap, sel_cap)`` — the nnz capacity and the selected-ROW
    capacity. sel_cap > 0 additionally enables LAZY ROUTING: per-split
    routing runs only over the selected rows (the tree's rows), and the
    full-N node assignment the score update needs is recovered once per
    tree by level-synchronous traversal (_assign_leaves_all_rows) —
    depth routing passes instead of num_leaves-1 (at 50M nnz routing is
    ~0.3 s/split over all 1M rows, the largest per-split cost after
    compaction). MMLSPARK_TPU_NO_SPARSE_LAZY_ROUTE=1 keeps compaction but
    routes eagerly.

    Gated to TPU at real scale (compaction costs one drop-scatter +
    cumsum per iteration, ~0.85 s at 50M nnz — profitable only when the
    ~30 splits/tree each save a third of their stream costs);
    MMLSPARK_TPU_SPARSE_COMPACT=1 forces it on (tests),
    MMLSPARK_TPU_NO_SPARSE_COMPACT=1 kills it.
    """
    import os

    import jax

    if os.environ.get("MMLSPARK_TPU_NO_SPARSE_COMPACT", "") not in ("", "0"):
        return 0, 0
    n = ds.num_rows
    nnz = int(ds.indptr[-1])
    row_nnz = np.diff(ds.indptr)
    if params.boosting_type == "goss":
        k_sel = int(n * params.top_rate) + int(n * params.other_rate)
        if k_sel <= 0 or k_sel >= n:
            return 0, 0
        cap = int(np.partition(row_nnz, n - k_sel)[n - k_sel:].sum())
    elif row_masks is not None:
        k_sel = int(row_masks.sum(axis=1).max())
        cap = int((row_masks.astype(np.int64) @ row_nnz.astype(np.int64))
                  .max())
    else:
        return 0, 0
    cap = max(cap, 1)
    sel_cap = max(int(k_sel), 1)
    if os.environ.get("MMLSPARK_TPU_NO_SPARSE_LAZY_ROUTE",
                      "") not in ("", "0"):
        sel_cap = 0
    if os.environ.get("MMLSPARK_TPU_SPARSE_COMPACT", "") not in ("", "0"):
        # forced mode (tests) bypasses profitability gates, not correctness
        return cap, sel_cap
    # lazy-routing profitability: per tree, eager routing costs
    # (num_leaves-1) full-N passes; lazy costs (num_leaves-1) passes over
    # the selected fraction PLUS max_depth full-N traversal levels.
    # Leaf-wise trees on zipf-ish text data grow DEEP (measured: lazy
    # LOST ~50% at 200k x 31 leaves unbounded — depth ~ num_leaves), so
    # lazy only turns on when max_depth bounds the traversal and the
    # model says it wins with margin.
    splits = max(params.num_leaves - 1, 1)
    if params.max_depth <= 0:
        sel_cap = 0
    else:
        sel_frac = sel_cap / max(n, 1)
        if sel_frac * splits + params.max_depth >= 0.9 * splits:
            sel_cap = 0
    try:
        if jax.default_backend() != "tpu":
            return 0, 0
    except Exception:
        return 0, 0
    if nnz < 2_000_000 or cap > int(0.75 * nnz):
        return 0, 0
    return cap, sel_cap


def _train_scan_sparse(params, config: GrowerConfig, booster, ds,
                       dev, labels, w_dev, scores, k: int, lr: float,
                       row_masks, feat_masks, compact_cap: int = 0,
                       sel_cap: int = 0) -> None:
    """ALL boosting iterations in one chunked ``lax.scan`` dispatch over the
    flat sparse bin space — no per-tree host round trips (the sparse
    analogue of booster._train_scan; chunking bounds device-runtime per
    dispatch the same way)."""
    import os

    import jax
    import jax.numpy as jnp

    from .booster import grad_hess

    n = ds.num_rows
    iters = params.num_iterations
    M = 2 * config.num_leaves - 1
    tb = dev["total_bins"]
    objective = params.objective
    alpha = params.alpha
    l1 = np.float32(config.lambda_l1)
    l2 = np.float32(config.lambda_l2)
    msh = np.float32(config.min_sum_hessian_in_leaf)
    mgs = np.float32(config.min_gain_to_split)
    has_fm = feat_masks is not None
    shrink = np.float32(lr)

    # in-scan GOSS: EXACT top_n |grad| rows (_exact_topk_mask — LightGBM's
    # sorted-GOSS count semantics, needed for the static compaction bound)
    # + exactly other_n uniform draws among the rest, amplified
    is_goss = params.boosting_type == "goss"
    if is_goss:
        top_n = int(n * params.top_rate)
        other_n = int(n * params.other_rate)
        goss_amp = np.float32((1.0 - params.top_rate)
                              / max(params.other_rate, 1e-12))
        goss_keys = jax.random.split(
            jax.random.PRNGKey(params.seed or params.bagging_seed), iters)

    # The scan is wrapped in a jit whose ARGUMENTS carry every large array
    # (dev layout, labels, weights): a lax.scan traced outside jit embeds
    # closed-over device arrays as program CONSTANTS — at 50M-nnz text
    # scale that serialized ~600 MB of literals into the remote compile
    # request (observed: multi-minute compiles, then HTTP 413).
    # locals only below — closing over `dev` inside _run_chunk would pin
    # the whole dataset's device arrays in the _SPARSE_SCAN_CACHE entry
    nf_s = dev["num_features"]
    rs_s = dev.get("route_steps", 32)
    has_rm = row_masks is not None

    def _run_chunk(devd, lab, wv, carry, xs_c, ipc):
        devt = dict(devd)
        devt["total_bins"] = tb
        devt["num_features"] = nf_s
        devt["route_steps"] = rs_s

        def body(carry, xs):
            score, comp = carry
            row_mask = (xs["rm"] if has_rm
                        else jnp.ones(n, dtype=bool))
            if has_fm:
                bin_mask = jnp.take(xs["fm"], devt["feat_of_bin"])
            else:
                bin_mask = jnp.zeros(0, dtype=bool)
            g, h = grad_hess(objective, score, lab, wv, alpha)
            if is_goss:
                g_sel = jnp.abs(g) if g.ndim == 1 \
                    else jnp.sum(jnp.abs(g), axis=1)
                is_top = _exact_topk_mask(g_sel, top_n, n)
                u = jax.random.uniform(xs["gk"], (n,))
                row_mask = is_top | _exact_topk_mask(u, other_n, n,
                                                     exclude=is_top)
                amp = jnp.where(is_top, jnp.float32(1.0), goss_amp)
                g = g * (amp if g.ndim == 1 else amp[:, None])
                h = h * (amp if h.ndim == 1 else amp[:, None])

            devc = devt
            lazy = bool(compact_cap and sel_cap)
            sel_rows = sel_valid = None
            if compact_cap:
                # selected-row entry compaction: the bin-sorted stream keeps
                # its order under compaction, so the prefix-sum histogram
                # works unchanged with remapped bin boundaries
                # (cnt0[bin_start], cnt0[bin_end] — entries of bin b occupy
                # [cnt0[start_b], cnt0[end_b]) of the compacted stream).
                # Tail slots past the selected count are never read: every
                # remapped boundary is <= the selected total. Drop-scatter
                # with strictly unique indices (unselected entries get
                # distinct out-of-range slots).
                rbs = devt["row_of_nnz_bs"]
                esel = jnp.take(row_mask, rbs)
                # native 1-D int32 cumsum measures 23 ms at 50M (vs 25 ms
                # for the blocked scheme — the 645 ms pathology is the
                # 3-channel f32 case); the drop-scatter is the real cost
                cnt = jnp.cumsum(esel.astype(jnp.int32))
                nnz_i = rbs.shape[0]
                iota = jnp.arange(nnz_i, dtype=jnp.int32)
                idx = jnp.where(esel, cnt - 1, compact_cap + iota)
                rows_cmp = jnp.zeros(compact_cap, jnp.int32).at[idx].set(
                    rbs, mode="drop", unique_indices=True)
                cnt0 = jnp.concatenate([jnp.zeros(1, jnp.int32), cnt])
                bstart_c = jnp.take(cnt0, devt["bin_start"])
                bend_c = jnp.take(cnt0, devt["bin_end"])
                if lazy:
                    # lazy routing: re-parameterize the grower so its
                    # "rows" ARE the selected rows — compacted entries
                    # reference selected-row POSITIONS, per-split routing
                    # searches only the selected rows' CSR slices
                    # (route_lo/route_hi), and the full-N assignment is
                    # recovered once per tree by level traversal below
                    cnt_rows = jnp.cumsum(row_mask.astype(jnp.int32))
                    rank_of_row = cnt_rows - 1       # [N]; valid where sel
                    sel_rows = jnp.nonzero(row_mask, size=sel_cap,
                                           fill_value=0)[0]
                    sel_valid = (jnp.arange(sel_cap, dtype=jnp.int32)
                                 < cnt_rows[-1])
                    selpos = jnp.take(rank_of_row, rows_cmp)   # [cap]
                    ip = devt["indptr_dev"]
                    devc = dict(devt,
                                row_of_nnz_bs=selpos,
                                bin_start=bstart_c, bin_end=bend_c,
                                route_lo=jnp.take(ip, sel_rows),
                                route_hi=jnp.take(ip, sel_rows + 1))
                else:
                    devc = dict(devt,
                                row_of_nnz_bs=rows_cmp,
                                bin_start=bstart_c, bin_end=bend_c)

            mask_f = row_mask.astype(jnp.float32)
            outs = []
            for kk in range(k):
                gk = g if g.ndim == 1 else g[:, kk]
                hk = h if h.ndim == 1 else h[:, kk]
                root_tot = jnp.stack([jnp.sum(gk * mask_f),
                                      jnp.sum(hk * mask_f),
                                      jnp.sum(mask_f)])
                if lazy:
                    out = _grow_tree_sparse_body(
                        devc, jnp.take(gk, sel_rows), jnp.take(hk, sel_rows),
                        sel_valid, jnp.zeros(sel_cap, jnp.int32),
                        root_tot, l1, l2, msh, mgs, bin_mask, total_bins=tb,
                        max_nodes=M,
                        min_data_in_leaf=config.min_data_in_leaf,
                        max_depth=config.max_depth, has_bin_mask=has_fm)
                    out.pop("node_of_row")   # selected-row ids only
                    rows = _assign_leaves_all_rows(devt, out, n)
                else:
                    out = _grow_tree_sparse_body(
                        devc, gk, hk, row_mask, jnp.zeros(n, jnp.int32),
                        root_tot, l1, l2, msh, mgs, bin_mask, total_bins=tb,
                        max_nodes=M,
                        min_data_in_leaf=config.min_data_in_leaf,
                        max_depth=config.max_depth, has_bin_mask=has_fm)
                    rows = out.pop("node_of_row")
                sums, feat = out["sums"], out["feature"]
                g_thr = jnp.sign(sums[:, 0]) * jnp.maximum(
                    jnp.abs(sums[:, 0]) - l1, 0.0)
                val = jnp.where(feat < 0, -g_thr / (sums[:, 1] + l2), 0.0)
                if config.max_delta_step > 0:
                    val = jnp.clip(val, -config.max_delta_step,
                                   config.max_delta_step)
                val = val.at[0].set(
                    jnp.where(out["n_nodes"] > 1, val[0], 0.0))
                upd = (val * shrink)[rows]
                if k == 1:
                    y_ = upd + comp
                    t_ = score + y_
                    score, comp = t_, y_ - (t_ - score)
                else:
                    s_col, c_col = score[:, kk], comp[:, kk]
                    y_ = upd + c_col
                    t_ = s_col + y_
                    score = score.at[:, kk].set(t_)
                    comp = comp.at[:, kk].set(y_ - (t_ - s_col))
                outs.append(out)
            stacked = jax.tree.map(lambda *a: jnp.stack(a), *outs)
            return (score, comp), stacked

        return jax.lax.scan(body, carry, xs_c, length=ipc)

    # the jit wrapper is cached on its STATIC closure values — a fresh
    # jax.jit per train_sparse call recompiled the whole scan every fit
    # (~250 s at 50M-nnz scale; observed as 'warm' fits slower than cold)
    cache_key = (tb, dev["num_features"], dev.get("route_steps", 32), n,
                 iters, k, M, objective, float(alpha), float(shrink),
                 float(l1), float(l2), float(msh), float(mgs),
                 config.min_data_in_leaf, config.max_depth,
                 float(config.max_delta_step), is_goss, has_fm,
                 compact_cap, sel_cap, row_masks is not None,
                 (params.top_rate, params.other_rate,
                  params.seed or params.bagging_seed) if is_goss else None)
    if cache_key not in _SPARSE_SCAN_CACHE:
        if len(_SPARSE_SCAN_CACHE) >= 8:
            _SPARSE_SCAN_CACHE.pop(next(iter(_SPARSE_SCAN_CACHE)))
        _SPARSE_SCAN_CACHE[cache_key] = jax.jit(
            _run_chunk, static_argnames=("ipc",))
    run_chunk = _SPARSE_SCAN_CACHE[cache_key]

    score0 = jnp.asarray(scores[:, 0] if k == 1 else scores,
                         dtype=jnp.float32)
    comp0 = jnp.zeros_like(score0)
    xs = None
    if row_masks is not None or has_fm or is_goss:
        xs = {}
        if row_masks is not None:
            xs["rm"] = jnp.asarray(row_masks)
        if has_fm:
            xs["fm"] = jnp.asarray(feat_masks)
        if is_goss:
            xs["gk"] = goss_keys

    # chunk: bound device-runtime per dispatch (the tunnelled worker dies
    # past ~40-60s of continuous execution); sparse per-iter work scales
    # with nnz (histogram streams) + n (routing) + M*tb (state updates)
    per_iter = len(ds.indices) + n + M * tb // 8
    budget = int(os.environ.get("MMLSPARK_TPU_SCAN_CHUNK_ROWS",
                                str(2 * 10**7)))
    ipc = max(1, min(iters, budget // max(per_iter, 1)))

    dev_arrays = {k2: v for k2, v in dev.items()
                  if k2 not in ("total_bins", "num_features", "route_steps")}
    carry = (score0, comp0)
    host_chunks = []
    done = 0
    while done < iters:
        xs_c = None
        if xs is not None:
            idx = np.minimum(np.arange(done, done + ipc), iters - 1)
            xs_c = {kk_: v[idx] for kk_, v in xs.items()}
        carry, ys = run_chunk(dev_arrays, labels, w_dev, carry, xs_c,
                              ipc=ipc)
        host_chunks.append(fetch_global(ys))
        done += ipc
    host = jax.tree.map(lambda *c: np.concatenate(c, axis=0), *host_chunks) \
        if len(host_chunks) > 1 else host_chunks[0]
    host = jax.tree.map(lambda a: a[:iters], host)

    thresholds = ds.thresholds  # [TB] f64 upper values
    for it in range(iters):
        group: List[Tree] = []
        for kk in range(k):
            nn = int(host["n_nodes"][it, kk])
            feature = host["feature"][it, kk][:nn].astype(np.int32)
            tbin = host["threshold_bin"][it, kk][:nn].astype(np.int32)
            fbin = host["flat_bin"][it, kk][:nn].astype(np.int64)
            sums = host["sums"][it, kk][:nn].astype(np.float64)
            g_thr = np.sign(sums[:, 0]) * np.maximum(
                np.abs(sums[:, 0]) - config.lambda_l1, 0.0)
            value = np.where(feature < 0,
                             -g_thr / (sums[:, 1] + config.lambda_l2), 0.0)
            if config.max_delta_step > 0:
                value = np.clip(value, -config.max_delta_step,
                                config.max_delta_step)
            value[0] = 0.0 if nn == 1 else value[0]
            threshold = np.where(feature >= 0, thresholds[fbin], 0.0)
            group.append(Tree(
                feature=feature,
                threshold=threshold.astype(np.float64),
                threshold_bin=tbin,
                default_left=host["default_left"][it, kk][:nn].astype(bool),
                left=host["left"][it, kk][:nn].astype(np.int32),
                right=host["right"][it, kk][:nn].astype(np.int32),
                value=value,
                gain=host["gain"][it, kk][:nn].astype(np.float32),
                count=sums[:, 2].astype(np.int32),
                shrinkage=lr,
                weight=sums[:, 1],
            ))
        booster.trees.append(group)


def train_sparse(params, ds: SparseDataset, y: np.ndarray,
                 weights: Optional[np.ndarray] = None,
                 groups: Optional[np.ndarray] = None,
                 valid: Optional[Tuple] = None,
                 valid_groups: Optional[np.ndarray] = None,
                 init_scores: Optional[np.ndarray] = None,
                 init_model=None,
                 log=None,
                 mesh=None):
    """Boosting over a SparseDataset; returns an ordinary Booster.

    Carries the reference's FULL sparse param surface — in the reference,
    CSR data feeds the same native engine with everything enabled
    (generateSparseDataset → LGBM_DatasetCreateFromCSRSpark,
    lightgbm/TrainUtils.scala:23-66): bagging (incl. pos/neg and rf),
    goss, dart, feature_fraction, weights, init scores, lambdarank groups,
    validation + early stopping, and continued training (init_model).

    The no-valid/no-dart/no-lambdarank case runs the whole boosting run in
    ONE chunked lax.scan dispatch (_train_scan_sparse); everything else
    takes the host-orchestrated loop below.

    ``valid``: optional ((indptr, indices, values), y_valid) CSR holdout.
    ``mesh``: optional jax Mesh — rows are split into nnz-balanced
    contiguous blocks over the ``data`` axis and each tree grows per shard
    under shard_map with psum'd flat histograms (grow_tree_sparse_sharded):
    the CSR counterpart of the dense engine's multi-chip data-parallel
    path, replacing LightGBM's socket-ring allreduce over sparse partitions
    (TrainUtils.scala:23-66 + 383-418).
    """
    import jax
    import jax.numpy as jnp

    from .booster import (_HIGHER_BETTER, Booster, GrowerConfig,
                          _scan_precompute_masks, default_metric, eval_metric,
                          grad_hess, init_score, segment_groups)

    if params.categorical_feature:
        raise ValueError(
            "categorical_feature is not supported on the sparse path "
            "(set splits need the dense bin space; sparse features are "
            "numeric TF counts) — densify for categorical slots")
    k = max(params.num_class, 1)
    n = ds.num_rows
    dev = _device_arrays(ds)
    labels = jnp.asarray(y, dtype=jnp.float32)
    w_dev = jnp.asarray(weights, dtype=jnp.float32) \
        if weights is not None else None
    g_dev = jnp.asarray(groups, dtype=jnp.int32) \
        if groups is not None else None
    group_seg = (segment_groups(groups)
                 if groups is not None and params.objective == "lambdarank"
                 else None)
    rng = np.random.default_rng(params.seed or params.bagging_seed)

    if init_scores is not None:
        base = np.zeros(k, dtype=np.float64)
        scores = np.broadcast_to(
            np.asarray(init_scores, dtype=np.float64).reshape(n, -1),
            (n, k)).copy()
    else:
        base = init_score(params.objective, np.asarray(y, dtype=np.float64),
                          k, alpha=params.alpha)
        scores = np.tile(base, (n, 1)).astype(np.float64)
    booster = Booster(params, None, base_score=base)
    if init_model is not None:
        booster.trees = [list(g) for g in init_model.trees]
        booster.base_score = init_model.base_score
        base = booster.base_score
        if init_model.trees:
            scores = (np.tile(base, (n, 1))
                      + predict_csr(init_model.trees,
                                    ds.indptr, ds.indices, ds.values, k))

    config = GrowerConfig(
        num_leaves=params.num_leaves, max_depth=params.max_depth,
        min_data_in_leaf=params.min_data_in_leaf,
        min_sum_hessian_in_leaf=params.min_sum_hessian_in_leaf,
        min_gain_to_split=params.min_gain_to_split,
        lambda_l1=params.lambda_l1, lambda_l2=params.lambda_l2,
        max_delta_step=params.max_delta_step)

    is_rf = params.boosting_type == "rf"
    is_dart = params.boosting_type == "dart"
    is_goss = params.boosting_type == "goss"
    lr = 1.0 if is_rf else params.learning_rate

    # ----- mesh sharding context (nnz-balanced contiguous row blocks) ---
    shard_ctx = None
    if mesh is not None:
        from ..parallel.mesh import DATA_AXIS

        n_shards = int(mesh.shape.get(DATA_AXIS, 1))
        if n_shards > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            sh_host, bounds, r_max = shard_sparse_dataset(ds, n_shards)
            row_sharding = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
            sharded = {kk_: jax.device_put(jnp.asarray(v), row_sharding)
                       for kk_, v in sh_host.items()
                       if kk_ != "row_valid"}
            row_valid = sh_host["row_valid"]

            # one-time gather plan: [S, r_max] indices into a (sentinel-
            # extended) [N+1] array — per-iteration resharding is a single
            # fancy-index instead of a Python loop over shards
            pad_idx = np.full((n_shards, r_max), n, dtype=np.int64)
            for s in range(n_shards):
                ln = bounds[s + 1] - bounds[s]
                pad_idx[s, :ln] = np.arange(bounds[s], bounds[s + 1])

            def _to_shards(a, fill=0):
                ext = np.append(a, np.asarray(fill, dtype=a.dtype))
                return ext[pad_idx]

            def _from_shards(a_sh):
                return np.concatenate(
                    [a_sh[s, : bounds[s + 1] - bounds[s]]
                     for s in range(n_shards)])

            shard_ctx = (sharded, row_sharding, _to_shards, _from_shards)

    # ----- whole-run fused scan path ------------------------------------
    if (shard_ctx is None and _scan_sparse_ok(params, valid, log)
            and _fused_sparse_enabled(2 * config.num_leaves - 1,
                                      ds.total_bins)):
        row_masks, feat_masks, ok = _scan_precompute_masks(
            params, rng, n, ds.num_features, np.asarray(y), is_rf)
        if ok:
            from ..core.runtime import ensure_compile_cache

            ensure_compile_cache()
            ccap, scap = _sparse_compact_cap(params, ds, row_masks)
            _train_scan_sparse(params, config, booster, ds, dev, labels,
                               w_dev, scores, k, lr, row_masks, feat_masks,
                               compact_cap=ccap, sel_cap=scap)
            if is_rf and booster.trees:
                inv = 1.0 / len(booster.trees)
                for gtrees in booster.trees:
                    for t in gtrees:
                        t.shrinkage = inv
            return booster

    # ----- host-orchestrated loop (valid/early-stop, dart, lambdarank) --
    metric = params.metric or default_metric(params.objective)
    higher_better = metric in _HIGHER_BETTER
    best_val = -np.inf if higher_better else np.inf
    best_iter = -1
    rounds_no_improve = 0
    val_csr = val_y = None
    val_scores = None
    if valid is not None:
        val_csr, val_y = valid
        nv = len(val_csr[0]) - 1
        val_scores = np.tile(base, (nv, 1)).astype(np.float64)
        if init_model is not None and init_model.trees:
            val_scores += predict_csr(init_model.trees, *val_csr, k)

    def _csr_contrib(tree_group):
        return predict_csr([tree_group], ds.indptr, ds.indices, ds.values, k)

    bag_mask = np.ones(n, dtype=bool)
    use_fused = _fused_sparse_enabled(2 * config.num_leaves - 1,
                                      ds.total_bins)
    for it in range(params.num_iterations):
        dropped: List[int] = []
        if is_dart and booster.trees:
            n_trees = len(booster.trees)
            if params.uniform_drop:
                drop_mask = rng.random(n_trees) < params.drop_rate
                dropped = list(np.where(drop_mask)[0][: params.max_drop])
            else:
                n_drop = min(max(1, int(n_trees * params.drop_rate)),
                             params.max_drop)
                dropped = list(rng.choice(n_trees, size=n_drop,
                                          replace=False))
            for di in dropped:
                scores -= _csr_contrib(booster.trees[di])
                if val_csr is not None:
                    # keep the holdout scores in lockstep (the dropped
                    # trees are rescaled below; stale valid contributions
                    # would corrupt the early-stopping metric)
                    val_scores -= predict_csr([booster.trees[di]],
                                              *val_csr, k)

        score_dev = jnp.asarray(scores[:, 0] if k == 1 else scores,
                                dtype=jnp.float32)
        g, h = grad_hess(params.objective, score_dev, labels, w_dev,
                         params.alpha, g_dev, group_segments=group_seg)

        # bagging / goss row selection (host RNG: same draws as dense)
        row_mask = bag_mask
        if is_goss:
            g_abs = np.abs(np.asarray(fetch_global(g)))
            if g_abs.ndim == 2:
                g_abs = g_abs.sum(axis=1)
            top_n = int(n * params.top_rate)
            other_n = int(n * params.other_rate)
            order = np.argsort(-g_abs)
            row_mask = np.zeros(n, dtype=bool)
            row_mask[order[:top_n]] = True
            rest = order[top_n:]
            picked = rng.choice(len(rest), size=min(other_n, len(rest)),
                                replace=False)
            row_mask[rest[picked]] = True
            amplify = (1.0 - params.top_rate) / max(params.other_rate, 1e-12)
            amp = np.ones(n, dtype=np.float32)
            amp[rest] = amplify
            amp_dev = jnp.asarray(amp)
            g = g * (amp_dev if g.ndim == 1 else amp_dev[:, None])
            h = h * (amp_dev if h.ndim == 1 else amp_dev[:, None])
        elif ((params.bagging_fraction < 1.0
               or params.pos_bagging_fraction < 1.0
               or params.neg_bagging_fraction < 1.0)
              and (is_rf or params.bagging_freq > 0)
              and it % max(params.bagging_freq, 1) == 0):
            if (params.pos_bagging_fraction < 1.0
                    or params.neg_bagging_fraction < 1.0):
                pos = np.asarray(y) > 0.5
                frac = np.where(pos, params.pos_bagging_fraction,
                                params.neg_bagging_fraction)
                bag_mask = rng.random(n) < frac
            else:
                bag_mask = rng.random(n) < params.bagging_fraction
            row_mask = bag_mask

        bin_mask = None
        if params.feature_fraction < 1.0:
            m = np.zeros(ds.num_features, dtype=bool)
            n_feat = max(1, int(ds.num_features * params.feature_fraction))
            m[rng.choice(ds.num_features, size=n_feat, replace=False)] = True
            bin_mask = jnp.asarray(m)[dev["feat_of_bin"]]

        mask_dev = jnp.asarray(row_mask) if shard_ctx is None else None
        group: List[Tree] = []
        for kk in range(k):
            gk = g if g.ndim == 1 else g[:, kk]
            hk = h if h.ndim == 1 else h[:, kk]
            if shard_ctx is not None:
                sharded, row_sharding, _to_shards, _from_shards = shard_ctx
                gh = np.asarray(fetch_global(gk), dtype=np.float32)
                hh = np.asarray(fetch_global(hk), dtype=np.float32)
                g_sh = jax.device_put(jnp.asarray(_to_shards(gh)),
                                      row_sharding)
                h_sh = jax.device_put(jnp.asarray(_to_shards(hh)),
                                      row_sharding)
                m_sh = jax.device_put(
                    jnp.asarray(_to_shards(row_mask)
                                & sh_host["row_valid"]), row_sharding)
                tree, rows_sh = grow_tree_sparse_sharded(
                    ds, dev, sharded, mesh, g_sh, h_sh, m_sh, config,
                    bin_mask=bin_mask)
                leaf_of_row = _from_shards(rows_sh)
            else:
                tree, leaf_of_row = grow_tree_sparse(
                    ds, dev, gk, hk, config, row_mask=mask_dev,
                    bin_mask=bin_mask, use_fused=use_fused)
            shrink = lr
            if is_dart and dropped:
                shrink = lr / (len(dropped) + lr)
            tree.shrinkage = shrink
            group.append(tree)
            scores[:, kk] += tree.value[leaf_of_row] * shrink
        if is_dart and dropped:
            factor = len(dropped) / (len(dropped) + lr)
            for di in dropped:
                for kk in range(k):
                    booster.trees[di][kk].shrinkage *= factor
                scores += _csr_contrib(booster.trees[di])
                if val_csr is not None:
                    val_scores += predict_csr([booster.trees[di]],
                                              *val_csr, k)
        booster.trees.append(group)

        # eval + early stopping on the CSR holdout
        if val_csr is not None:
            val_scores += predict_csr([group], *val_csr, k)
            vs = val_scores[:, 0] if k == 1 else val_scores
            m = eval_metric(metric, vs, np.asarray(val_y, dtype=np.float64),
                            valid_groups)
            improved = m > best_val if higher_better else m < best_val
            if improved:
                best_val, best_iter, rounds_no_improve = \
                    m, len(booster.trees), 0
            else:
                rounds_no_improve += 1
            if log:
                log(f"[{it + 1}] valid {metric}={m:.6f}")
            if params.early_stopping_round > 0 \
                    and rounds_no_improve >= params.early_stopping_round:
                booster.best_iteration = best_iter
                if log:
                    log(f"early stopping at iteration {it + 1}, "
                        f"best {best_iter}")
                break
        elif log and (it + 1) % 10 == 0:
            sc = scores[:, 0] if k == 1 else scores
            m = eval_metric(metric, sc, np.asarray(y, dtype=np.float64),
                            groups)
            log(f"[{it + 1}] train {metric}={m:.6f}")

    if is_rf and booster.trees:
        inv = 1.0 / len(booster.trees)
        for gtrees in booster.trees:
            for t in gtrees:
                t.shrinkage = inv
    return booster


def _flatten_forest(tree_groups):
    """Concatenated node arrays + per-tree offsets for the C++ CSR
    traversal, memoized/validated by predict.memoize_forest (shared with
    the dense layout — one shrinkage-invalidation contract)."""
    from .predict import memoize_forest

    def build():
        feats, thrs, lefts, rights, vals_ = [], [], [], [], []
        offs, shr, cls = [0], [], []
        for group in tree_groups:
            for kcls, tree in enumerate(group):
                feats.append(np.asarray(tree.feature, dtype=np.int32))
                thrs.append(np.asarray(tree.threshold, dtype=np.float64))
                lefts.append(np.asarray(tree.left, dtype=np.int32))
                rights.append(np.asarray(tree.right, dtype=np.int32))
                vals_.append(np.asarray(tree.value, dtype=np.float64))
                offs.append(offs[-1] + len(tree.feature))
                shr.append(float(tree.shrinkage))
                cls.append(kcls)
        return (np.concatenate(feats), np.concatenate(thrs),
                np.concatenate(lefts), np.concatenate(rights),
                np.concatenate(vals_), np.asarray(offs, dtype=np.int64),
                np.asarray(shr, dtype=np.float64),
                np.asarray(cls, dtype=np.int32))

    return memoize_forest(tree_groups, "csr", build)


def _predict_csr_native(tree_groups, indptr, indices, values, n: int,
                        num_class: int):
    """Flatten the forest and call the C++ traversal
    (native_loader.csr_forest_predict); None when the library is
    unavailable so the caller keeps its numpy path."""
    from .. import native_loader

    if not any(len(g) for g in tree_groups):
        return np.zeros((n, num_class), dtype=np.float64)
    flat = _flatten_forest(tree_groups)
    return native_loader.csr_forest_predict(
        indptr, indices, values, *flat[:6], flat[6], flat[7], num_class)


def predict_csr(tree_groups: List[List[Tree]], indptr, indices, values,
                num_class: int) -> np.ndarray:
    """[CSR rows] -> [N, num_class] raw score deltas (PredictForCSRSingle
    parity, LightGBMBooster.scala:21-148 — fully vectorized over rows).

    Value lookup rides ONE global searchsorted per depth step over the
    composite (row, feature) key — CSR rows are sorted, so
    ``row * (F+1) + feature`` is globally ascending."""
    for group in tree_groups:
        for tree in group:
            if tree.cat_sets is not None:
                raise ValueError(
                    "categorical set splits cannot be evaluated on sparse "
                    "CSR rows (sparse features are numeric); densify for "
                    "categorical models")
    indptr = np.asarray(indptr, dtype=np.int64)
    indices = np.asarray(indices, dtype=np.int64)
    values = np.asarray(values, dtype=np.float64)
    n = len(indptr) - 1

    # native fast path: flattened per-row traversal in C++ (the reference's
    # predict is LightGBM's C++ core; the numpy path below stays as the
    # toolchain-free fallback and the parity reference — gated equal in
    # tests). MMLSPARK_TPU_NO_NATIVE_CSR_PREDICT=1 disables.
    import os as _os

    if _os.environ.get("MMLSPARK_TPU_NO_NATIVE_CSR_PREDICT",
                       "") in ("", "0"):
        native_out = _predict_csr_native(tree_groups, indptr, indices,
                                         values, n, num_class)
        if native_out is not None:
            return native_out

    out = np.zeros((n, num_class), dtype=np.float64)
    width = int(indices.max()) + 2 if len(indices) else 1
    row_of = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
    key = row_of * width + indices                    # globally ascending

    def lookup(rows: np.ndarray, feats: np.ndarray) -> np.ndarray:
        res = np.zeros(len(feats), dtype=np.float64)
        if not len(key):
            return res
        inr = feats < width  # features beyond the data's width are absent
        q = rows[inr] * width + feats[inr]
        pos = np.searchsorted(key, q)
        ok = (pos < len(key)) & (key[np.minimum(pos, len(key) - 1)] == q)
        sub = np.zeros(len(q), dtype=np.float64)
        sub[ok] = values[pos[ok]]
        res[inr] = sub
        return res

    all_rows = np.arange(n, dtype=np.int64)
    for group in tree_groups:
        for kcls, tree in enumerate(group):
            node = np.zeros(n, dtype=np.int64)
            active = tree.feature[node] != -1
            while active.any():
                cur = node[active]
                f = tree.feature[cur].astype(np.int64)
                x = lookup(all_rows[active], f)
                go_left = x <= tree.threshold[cur]
                node[active] = np.where(go_left, tree.left[cur],
                                        tree.right[cur])
                active = tree.feature[node] != -1
            out[:, kcls] += tree.value[node] * tree.shrinkage
    return out
