"""Sparse/CSR feature path through the GBDT engine.

Reference parity: the reference trains LightGBM directly on sparse vectors —
``generateSparseDataset`` / ``LGBM_DatasetCreateFromCSRSpark``
(lightgbm/TrainUtils.scala:23-66, lightgbm/LightGBMUtils.scala:199-252) — and
predicts single sparse rows via ``PredictForCSRSingle``
(lightgbm/LightGBMBooster.scala:21-148). This module gives the TPU engine the
same capability for TextFeaturizer/VW-width feature spaces (2^18+ columns)
without ever densifying:

  - ``SparseDataset``: CSR (indptr/indices/values) + per-feature
    distinct-value binning over the nonzeros with the implicit zero as its
    own bin, laid out as a FLAT ragged bin space (per-feature offsets,
    ``total_bins = sum_f bins_f`` — LightGBM's num_total_bin layout). Memory
    is O(nnz + total_bins), never O(N * F).
  - histogram: one ``segment_sum`` over the nnz entries' flat bin ids
    (node-masked via a cheap 1-D gather of the row routing); the zero bin of
    every feature is reconstructed by subtraction from the node totals —
    LightGBM's default-bin trick, so absent entries cost nothing.
  - split finding: a single flat cumsum + vectorized gain scan over
    ``total_bins`` candidates with per-feature segment boundaries.
  - ``predict_csr``: depth-stepped traversal where each row resolves the
    split feature's value through its own CSR row (absent -> 0.0).

Trees come out as the ordinary dense ``Tree`` (raw-value thresholds), so
persistence, merge, importances, and the LightGBM text-format interchange
all work unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .tree import GrowerConfig, Tree

_MAX_SPARSE_BIN = 64  # per-feature cap: count/tf features have few levels


def rows_to_csr(col, num_features: Optional[int] = None,
                filter_zeros: bool = True):
    """Sparse-row column ({"indices","values"[,"size"]}) -> sorted CSR
    (indptr, indices, values, width). The single row-walk shared by training
    (SparseDataset.from_rows) and predict (stages._raw_scores)."""
    from ..parallel.batching import sparse_width

    width = num_features or sparse_width(col)
    indptr = np.zeros(len(col) + 1, dtype=np.int64)
    idx_parts, val_parts = [], []
    for i, v in enumerate(col):
        if v is None:
            indptr[i + 1] = indptr[i]
            continue
        idx = np.asarray(v["indices"], dtype=np.int64)
        val = np.asarray(v["values"], dtype=np.float64)
        keep = idx < width
        if filter_zeros:
            keep &= val != 0.0
        idx, val = idx[keep], val[keep]
        srt = np.argsort(idx, kind="stable")  # CSR contract: sorted rows
        idx_parts.append(idx[srt])
        val_parts.append(val[srt])
        indptr[i + 1] = indptr[i] + len(idx)
    indices = (np.concatenate(idx_parts) if idx_parts
               else np.zeros(0, dtype=np.int64))
    values = (np.concatenate(val_parts) if val_parts
              else np.zeros(0, dtype=np.float64))
    return indptr, indices, values, width


@dataclasses.dataclass
class SparseDataset:
    """CSR dataset with flat ragged binning over the nonzero values."""

    indptr: np.ndarray        # i64 [N+1]
    indices: np.ndarray       # i32 [nnz] feature ids
    values: np.ndarray        # f32 [nnz]
    num_features: int
    # binning (flat ragged layout)
    feat_offset: np.ndarray   # i64 [F+1]: feature f owns flat bins
    #                           [feat_offset[f], feat_offset[f+1])
    thresholds: np.ndarray    # f64 [total_bins]: upper value per flat bin
    zero_local: np.ndarray    # i32 [F]: local bin index holding value 0.0
    bin_of_nnz: np.ndarray    # i32 [nnz]: flat bin id per entry
    row_of_nnz: np.ndarray    # i32 [nnz]

    @property
    def num_rows(self) -> int:
        return len(self.indptr) - 1

    @property
    def total_bins(self) -> int:
        return int(self.feat_offset[-1])

    @staticmethod
    def from_rows(col, num_features: Optional[int] = None,
                  max_bin: int = _MAX_SPARSE_BIN) -> "SparseDataset":
        """Build from a sparse-row column ({"indices","values"[,"size"]})."""
        indptr, indices, values, width = rows_to_csr(col, num_features)
        return SparseDataset.from_csr(indptr, indices, values, width, max_bin)

    @staticmethod
    def from_csr(indptr, indices, values, num_features: int,
                 max_bin: int = _MAX_SPARSE_BIN) -> "SparseDataset":
        indptr = np.asarray(indptr, dtype=np.int64)
        indices = np.asarray(indices, dtype=np.int64)
        values = np.asarray(values, dtype=np.float64)
        nnz = len(indices)

        # One synthetic zero "entry" per present feature makes the implicit
        # zero an ordinary distinct value — binning, zero position, and
        # capping all handle it uniformly.
        feats_present = np.unique(indices) if nnz else np.zeros(0, np.int64)
        fs_aug = np.concatenate([indices, feats_present])
        vs_aug = np.concatenate([values, np.zeros(len(feats_present))])

        # distinct (feature, value) pairs via one lexsort; per-entry pair id
        order = np.lexsort((vs_aug, fs_aug))
        fs, vs = fs_aug[order], vs_aug[order]
        m = len(fs)
        first = np.ones(m, dtype=bool)
        if m:
            first[1:] = (fs[1:] != fs[:-1]) | (vs[1:] != vs[:-1])
        pair_of_sorted = np.cumsum(first) - 1 if m \
            else np.zeros(0, dtype=np.int64)
        df, dv = fs[first], vs[first]          # value-ascending per feature

        # stride-quantile cap: feature f with d_f distinct values uses
        # stride_f = ceil(d_f / max_bin); local bin = distinct_pos // stride
        # — an even subsample of the value range (a smallest-values prefix
        # cap mixes large values into the zero bin when negatives exist)
        d_per_feat = np.bincount(df, minlength=num_features)
        stride = np.maximum(1, -(-d_per_feat // max_bin))      # [F]
        first_pair = np.searchsorted(df, df)
        pos_in_feat = np.arange(len(df)) - first_pair
        local_of_pair = pos_in_feat // stride[df]
        bins_per_feat = np.where(d_per_feat > 0,
                                 -(-d_per_feat // stride), 0)
        feat_offset = np.zeros(num_features + 1, dtype=np.int64)
        np.cumsum(bins_per_feat, out=feat_offset[1:])
        total_bins = int(feat_offset[-1])

        # upper threshold of flat bin (f, j): midpoint between the last
        # distinct value covered by bin j and the first of bin j+1; the
        # feature's last bin is +inf
        thresholds = np.full(total_bins, np.inf)
        if len(df):
            flat_of_pair = feat_offset[df] + local_of_pair
            # boundary pairs: last pair of its bin, not last of its feature
            not_last = np.zeros(len(df), dtype=bool)
            not_last[:-1] = (df[:-1] == df[1:]) & \
                (flat_of_pair[:-1] != flat_of_pair[1:])
            b_idx = np.nonzero(not_last)[0]
            thresholds[flat_of_pair[b_idx]] = (dv[b_idx] + dv[b_idx + 1]) / 2.0

        # zero position: the synthetic zero is a distinct value of every
        # present feature; find its pair and take its local bin
        zero_local = np.zeros(num_features, dtype=np.int32)
        if len(df):
            zpair = (dv == 0.0)
            zero_local[df[zpair]] = local_of_pair[zpair].astype(np.int32)

        # flat bin per ORIGINAL nnz entry (the synthetic zeros occupy the
        # tail of the augmented arrays)
        bin_of_nnz = np.zeros(nnz, dtype=np.int64)
        if nnz:
            flat_sorted = (feat_offset[df] + local_of_pair)[pair_of_sorted]
            flat_aug = np.zeros(len(fs_aug), dtype=np.int64)
            flat_aug[order] = flat_sorted
            bin_of_nnz = flat_aug[:nnz]
        return SparseDataset(
            indptr=indptr,
            indices=indices.astype(np.int32),
            values=values.astype(np.float32),
            num_features=int(num_features),
            feat_offset=feat_offset,
            thresholds=thresholds,
            zero_local=zero_local,
            bin_of_nnz=bin_of_nnz,
            row_of_nnz=np.repeat(
                np.arange(len(indptr) - 1, dtype=np.int64),
                np.diff(indptr)).astype(np.int32),
        )

    def bin_upper_value(self, f: int, local_bin: int) -> float:
        return float(self.thresholds[int(self.feat_offset[f]) + local_bin])


# ---------------------------------------------------------------------------
# Device histogram + split finding over the flat ragged bin space
# ---------------------------------------------------------------------------


def _flat_histogram(dev, grad, hess, node_mask_rows):
    """Nonzero-entry histogram: [total_bins, 3] sums over the node's rows.

    One 1-D gather (row routing mask at the nnz entries) + one segment_sum —
    O(nnz) work regardless of F (LightGBM's per-feature nnz iteration,
    TrainUtils.scala:23-66, as one vectorized pass)."""
    import jax.numpy as jnp
    import jax.ops

    m = jnp.take(node_mask_rows, dev["row_of_nnz"]).astype(jnp.float32)
    g = jnp.take(grad, dev["row_of_nnz"]) * m
    h = jnp.take(hess, dev["row_of_nnz"]) * m
    data = jnp.stack([g, h, m], axis=-1)
    return jax.ops.segment_sum(data, dev["bin_of_nnz"],
                               num_segments=dev["total_bins"])


def _zero_completed(dev, flat_hist, node_totals):
    """Add the implicit-zero bin of every feature: node totals minus the
    feature's nonzero-entry sums (LightGBM's default-bin subtraction)."""
    import jax.numpy as jnp
    import jax.ops

    feat_sums = jax.ops.segment_sum(flat_hist, dev["feat_of_bin"],
                                    num_segments=dev["num_features"])
    zero_sums = node_totals[None, :] - feat_sums          # [F, 3]
    return flat_hist.at[dev["zero_flat"]].add(
        jnp.take(zero_sums, dev["present_feats"], axis=0))


def _find_best_split_flat(dev, hist, lambda_l1, lambda_l2, min_sum_hessian,
                          min_data_in_leaf):
    """Vectorized gain scan over ALL flat bins: candidate t at flat bin b
    sends local bins <= b left. Per-feature left-cumulative sums come from a
    global cumsum minus the feature's base — no per-feature loop."""
    import jax.numpy as jnp

    from .histogram import _leaf_objective

    cs = jnp.cumsum(hist, axis=0)                          # [TB, 3]
    base = cs[dev["feat_start_of_bin"]] - hist[dev["feat_start_of_bin"]]
    left = cs - base                                       # [TB, 3] within-feature
    total = left[dev["feat_end_of_bin"]]                   # node totals per bin's feat
    GL, HL, CL = left[:, 0], left[:, 1], left[:, 2]
    G, H, C = total[:, 0], total[:, 1], total[:, 2]
    GR, HR, CR = G - GL, H - HL, C - CL
    gain = (_leaf_objective(GL, HL, lambda_l1, lambda_l2)
            + _leaf_objective(GR, HR, lambda_l1, lambda_l2)
            - _leaf_objective(G, H, lambda_l1, lambda_l2)) * -1.0
    ok = ((CL >= min_data_in_leaf) & (CR >= min_data_in_leaf)
          & (HL >= min_sum_hessian) & (HR >= min_sum_hessian)
          & ~dev["is_last_bin"])                          # no split after last
    gain = jnp.where(ok, gain, -jnp.inf)
    b = jnp.argmax(gain)
    return (b, gain[b], jnp.stack([GL[b], HL[b], CL[b]]),
            jnp.stack([GR[b], HR[b], CR[b]]))


def _route_rows(dev, node_of_row, node_id, f, t_local, lid, rid):
    """Send the node's rows left iff value-bin <= t_local; absent entries
    carry the feature's zero bin.

    A row owns at most ONE entry of feature f (CSR distinct indices), so a
    segment_max over per-entry corrections (sentinel -1 elsewhere) resolves
    the override without duplicate-index scatter races."""
    import jax.numpy as jnp
    import jax.ops

    zero_goes_left = dev["zero_local_dev"][f] <= t_local
    default_child = jnp.where(zero_goes_left, lid, rid)
    in_node = node_of_row == node_id
    out = jnp.where(in_node, default_child, node_of_row)
    # entries of feature f override the default for their rows
    local_bin = dev["bin_of_nnz"] - dev["feat_offset_dev"][dev["feat_of_nnz"]]
    is_f = dev["feat_of_nnz"] == f
    target = jnp.where(local_bin <= t_local, lid, rid)
    rows = dev["row_of_nnz"]
    per_entry = jnp.where(is_f & jnp.take(in_node, rows), target,
                          jnp.int32(-1))
    correction = jax.ops.segment_max(per_entry, rows,
                                     num_segments=node_of_row.shape[0])
    return jnp.where(correction >= 0, correction, out)


def _device_arrays(ds: SparseDataset):
    import jax.numpy as jnp

    tb = ds.total_bins
    feat_of_bin = np.repeat(np.arange(ds.num_features, dtype=np.int64),
                            np.diff(ds.feat_offset))
    feat_start = ds.feat_offset[feat_of_bin]
    feat_end = ds.feat_offset[feat_of_bin + 1] - 1
    is_last = np.arange(tb) == feat_end
    present = np.nonzero(np.diff(ds.feat_offset) > 0)[0]
    zero_flat = (ds.feat_offset[present]
                 + ds.zero_local[present]).astype(np.int64)
    return {
        "row_of_nnz": jnp.asarray(ds.row_of_nnz),
        "bin_of_nnz": jnp.asarray(ds.bin_of_nnz, dtype=jnp.int32),
        "feat_of_nnz": jnp.asarray(ds.indices, dtype=jnp.int32),
        "feat_of_bin": jnp.asarray(feat_of_bin, dtype=jnp.int32),
        "feat_start_of_bin": jnp.asarray(feat_start, dtype=jnp.int32),
        "feat_end_of_bin": jnp.asarray(feat_end, dtype=jnp.int32),
        "is_last_bin": jnp.asarray(is_last),
        "present_feats": jnp.asarray(present, dtype=jnp.int32),
        "zero_flat": jnp.asarray(zero_flat, dtype=jnp.int32),
        "zero_local_dev": jnp.asarray(ds.zero_local, dtype=jnp.int32),
        "feat_offset_dev": jnp.asarray(ds.feat_offset, dtype=jnp.int32),
        "total_bins": tb,
        "num_features": ds.num_features,
    }


def grow_tree_sparse(ds: SparseDataset, dev, grad, hess,
                     config: GrowerConfig) -> Tuple[Tree, np.ndarray]:
    """Leaf-wise growth over the flat sparse bins (host-orchestrated loop;
    each split = one histogram segment_sum + one flat gain scan)."""
    import heapq

    import jax
    import jax.numpy as jnp

    n = ds.num_rows
    node_of_row = jnp.zeros(n, dtype=jnp.int32)
    ones = jnp.ones(n, dtype=bool)

    feature = [-1]
    threshold = [0.0]
    threshold_bin = [0]
    default_left = [True]
    left = [-1]
    right = [-1]
    value = [0.0]
    gains = [0.0]
    counts = [0]
    hweights = [0.0]

    def leaf_value(sums):
        g_thr = np.sign(sums[0]) * max(abs(sums[0]) - config.lambda_l1, 0.0)
        v = float(-g_thr / (sums[1] + config.lambda_l2))
        if config.max_delta_step > 0:
            v = float(np.clip(v, -config.max_delta_step,
                              config.max_delta_step))
        return v

    def node_hist(mask_rows, totals):
        flat = _flat_histogram(dev, grad, hess, mask_rows)
        return _zero_completed(dev, flat, totals)

    totals0 = jnp.stack([jnp.sum(grad), jnp.sum(hess),
                         jnp.asarray(float(n), jnp.float32)])
    hist0 = node_hist(ones, totals0)
    counts[0] = n
    hweights[0] = float(jax.device_get(totals0)[1])

    def eval_split(hist):
        b, gain, lsum, rsum = _find_best_split_flat(
            dev, hist, np.float32(config.lambda_l1),
            np.float32(config.lambda_l2),
            np.float32(config.min_sum_hessian_in_leaf),
            config.min_data_in_leaf)
        b, gain, lsum, rsum = jax.device_get((b, gain, lsum, rsum))
        f = int(np.searchsorted(ds.feat_offset, b, side="right") - 1)
        t_local = int(b - ds.feat_offset[f])
        return f, t_local, float(gain), np.asarray(lsum, np.float64), \
            np.asarray(rsum, np.float64)

    heap = []
    tiebreak = 0

    def push(node_id, depth, hist, sums):
        nonlocal tiebreak
        f, t_local, gain, lsum, rsum = eval_split(hist)
        if np.isfinite(gain) and gain > config.min_gain_to_split:
            if config.max_depth > 0 and depth >= config.max_depth:
                return
            heapq.heappush(heap, (-gain, tiebreak,
                                  (node_id, depth, hist, sums,
                                   f, t_local, lsum, rsum, gain)))
            tiebreak += 1

    push(0, 0, hist0, np.asarray(jax.device_get(totals0), np.float64))
    n_leaves = 1

    while heap and n_leaves < config.num_leaves:
        _, _, (nid, depth, hist, sums, f, t_local, lsum, rsum, gain) = \
            heapq.heappop(heap)
        lid, rid = len(feature), len(feature) + 1
        thr = ds.bin_upper_value(f, t_local)
        feature[nid] = f
        threshold[nid] = thr
        threshold_bin[nid] = t_local
        # absent==0.0 routes by value like LightGBM's sparse default bin;
        # keep dense-predict agreement: zeros follow the threshold compare
        default_left[nid] = bool(0.0 <= thr)
        left[nid], right[nid] = lid, rid
        gains[nid] = float(gain)
        value[nid] = 0.0
        for csum in (lsum, rsum):
            feature.append(-1)
            threshold.append(0.0)
            threshold_bin.append(0)
            default_left.append(True)
            left.append(-1)
            right.append(-1)
            value.append(leaf_value(csum))
            gains.append(0.0)
            counts.append(int(csum[2]))
            hweights.append(float(csum[1]))
        n_leaves += 1

        node_of_row = _route_rows(dev, node_of_row, np.int32(nid),
                                  np.int32(f), np.int32(t_local),
                                  np.int32(lid), np.int32(rid))
        small_id, big_id = (lid, rid) if lsum[2] <= rsum[2] else (rid, lid)
        small_sums = lsum if small_id == lid else rsum
        big_sums = rsum if small_id == lid else lsum
        small_hist = node_hist(node_of_row == small_id,
                               jnp.asarray(small_sums, jnp.float32))
        big_hist = hist - small_hist
        for cid, chist, csums in ((small_id, small_hist, small_sums),
                                  (big_id, big_hist, big_sums)):
            if csums[2] >= 2 * config.min_data_in_leaf:
                push(cid, depth + 1, chist, csums)

    tree = Tree(
        feature=np.asarray(feature, dtype=np.int32),
        threshold=np.asarray(threshold, dtype=np.float64),
        threshold_bin=np.asarray(threshold_bin, dtype=np.int32),
        default_left=np.asarray(default_left, dtype=bool),
        left=np.asarray(left, dtype=np.int32),
        right=np.asarray(right, dtype=np.int32),
        value=np.asarray(value, dtype=np.float64),
        gain=np.asarray(gains, dtype=np.float32),
        count=np.asarray(counts, dtype=np.int32),
        weight=np.asarray(hweights, dtype=np.float64),
    )
    return tree, np.asarray(jax.device_get(node_of_row))


def train_sparse(params, ds: SparseDataset, y: np.ndarray,
                 weights: Optional[np.ndarray] = None):
    """Boosting over a SparseDataset; returns an ordinary Booster.

    Supports the elementwise objectives (binary/regression families);
    bagging/goss/dart fall back to their dense-path semantics later if
    needed — the text-pipeline parity target is plain gbdt
    (docs/lightgbm.md text scenarios)."""
    import jax
    import jax.numpy as jnp

    from .booster import (Booster, GrowerConfig, default_metric, grad_hess,
                          init_score)

    if params.boosting_type != "gbdt":
        raise ValueError("sparse training supports boosting_type='gbdt'")
    k = max(params.num_class, 1)
    n = ds.num_rows
    dev = _device_arrays(ds)
    labels = jnp.asarray(y, dtype=jnp.float32)
    w_dev = jnp.asarray(weights, dtype=jnp.float32) \
        if weights is not None else None

    base = init_score(params.objective, np.asarray(y, dtype=np.float64), k,
                      alpha=params.alpha)
    scores = np.tile(base, (n, 1)).astype(np.float64)
    booster = Booster(params, None, base_score=base)
    config = GrowerConfig(
        num_leaves=params.num_leaves, max_depth=params.max_depth,
        min_data_in_leaf=params.min_data_in_leaf,
        min_sum_hessian_in_leaf=params.min_sum_hessian_in_leaf,
        min_gain_to_split=params.min_gain_to_split,
        lambda_l1=params.lambda_l1, lambda_l2=params.lambda_l2,
        max_delta_step=params.max_delta_step)

    for _ in range(params.num_iterations):
        score_dev = jnp.asarray(scores[:, 0] if k == 1 else scores,
                                dtype=jnp.float32)
        g, h = grad_hess(params.objective, score_dev, labels, w_dev,
                         params.alpha)
        group: List[Tree] = []
        for kk in range(k):
            gk = g if g.ndim == 1 else g[:, kk]
            hk = h if h.ndim == 1 else h[:, kk]
            tree, leaf_of_row = grow_tree_sparse(ds, dev, gk, hk, config)
            tree.shrinkage = params.learning_rate
            group.append(tree)
            scores[:, kk] += tree.value[leaf_of_row] * params.learning_rate
        booster.trees.append(group)
    return booster


def predict_csr(tree_groups: List[List[Tree]], indptr, indices, values,
                num_class: int) -> np.ndarray:
    """[CSR rows] -> [N, num_class] raw score deltas (PredictForCSRSingle
    parity, LightGBMBooster.scala:21-148 — fully vectorized over rows).

    Value lookup rides ONE global searchsorted per depth step over the
    composite (row, feature) key — CSR rows are sorted, so
    ``row * (F+1) + feature`` is globally ascending."""
    indptr = np.asarray(indptr, dtype=np.int64)
    indices = np.asarray(indices, dtype=np.int64)
    values = np.asarray(values, dtype=np.float64)
    n = len(indptr) - 1
    out = np.zeros((n, num_class), dtype=np.float64)
    width = int(indices.max()) + 2 if len(indices) else 1
    row_of = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
    key = row_of * width + indices                    # globally ascending

    def lookup(rows: np.ndarray, feats: np.ndarray) -> np.ndarray:
        res = np.zeros(len(feats), dtype=np.float64)
        if not len(key):
            return res
        inr = feats < width  # features beyond the data's width are absent
        q = rows[inr] * width + feats[inr]
        pos = np.searchsorted(key, q)
        ok = (pos < len(key)) & (key[np.minimum(pos, len(key) - 1)] == q)
        sub = np.zeros(len(q), dtype=np.float64)
        sub[ok] = values[pos[ok]]
        res[inr] = sub
        return res

    all_rows = np.arange(n, dtype=np.int64)
    for group in tree_groups:
        for kcls, tree in enumerate(group):
            node = np.zeros(n, dtype=np.int64)
            active = tree.feature[node] != -1
            while active.any():
                cur = node[active]
                f = tree.feature[cur].astype(np.int64)
                x = lookup(all_rows[active], f)
                go_left = x <= tree.threshold[cur]
                node[active] = np.where(go_left, tree.left[cur],
                                        tree.right[cur])
                active = tree.feature[node] != -1
            out[:, kcls] += tree.value[node] * tree.shrinkage
    return out
