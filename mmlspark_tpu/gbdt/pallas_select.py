"""Pallas TPU kernel for masked row compaction (stream select).

The tier-compacted histogram path (tree.py small_child_hist) needs the rows
of a boolean mask gathered to the front of a static-capacity buffer. XLA's
``jnp.nonzero(size=cap)`` lowers to a full-width cumsum + scatter — measured
~56 ms at 3.2M rows on the chip, paid once per tiered split, which makes
row compaction (not the histogram kernel) the largest per-split cost of
GBDT training (reference analogue: LightGBM's DataPartition::Split, which
is a cache-local CPU pass).

This kernel reformulates compaction the same way pallas_hist.py
reformulates the histogram scatter: as **one-hot contractions on the MXU**
over feature-major inputs.

Per row tile of CHUNK columns (grid is 1-D over tiles, executed in order):

1. within-tile exclusive prefix of the mask — a [1, CHUNK] x [CHUNK, CHUNK]
   strict-upper-triangular matmul (0/1 operands, f32 accumulate: exact);
2. transposed one-hot W[p, i] = (prefix[i] == p) & mask[i];
3. compacted tile = V @ W^T on the MXU, where V = [bins; grad; hess] is the
   [F+2, CHUNK] channel-major value block. One-hot rows pass values through
   untouched (products are v*1 and v*0 with f32 accumulation), so grad/hess
   come out bit-exact and bins cast back to uint8 losslessly;
4. the tile lands in the output at the tile's global offset (exclusive
   cumsum of per-tile counts, computed by the XLA wrapper and handed to the
   kernel via scalar prefetch) with a dynamic-slice DMA. Tiles overlap the
   previous tile's invalid tail; the grid's sequential order makes the
   overwrite well-defined, and rows past the total count are masked by the
   caller's validity mask (histogram vals are pre-masked; garbage bins fall
   outside the one-hot range).

Row order is preserved (stable within tiles, tiles in order), so histogram
summation order matches the nonzero+gather path bit-for-bit — verified by
an exact-equality unit test in interpret mode.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_hist import _round_up  # shared: keep rounding rules in one place

CHUNK = 1024


def _select_kernel(offs_ref, bins_ref, g_ref, h_ref, m_ref,
                   out_ref, s_ref, sem, *, nf: int, chunk: int,
                   c_pad: int):
    j = pl.program_id(0)
    off = offs_ref[j]

    m = m_ref[...].astype(jnp.float32)                       # [1, CHUNK]
    # 1. exclusive prefix within the tile: pos[i] = sum_{i'<i} m[i']
    iota0 = jax.lax.broadcasted_iota(jnp.int32, (chunk, chunk), 0)
    iota1 = jax.lax.broadcasted_iota(jnp.int32, (chunk, chunk), 1)
    upper = (iota0 < iota1).astype(jnp.float32)              # [i', i]
    pos = jax.lax.dot_general(                               # [1, CHUNK] f32
        m, upper, dimension_numbers=(((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)

    # 2. transposed one-hot: W[p, i] = (pos[i] == p) & m[i]
    pos_b = jnp.broadcast_to(pos.astype(jnp.int32), (chunk, chunk))
    sel = jnp.broadcast_to(m, (chunk, chunk)) > 0.0
    wt = ((pos_b == iota0) & sel).astype(jnp.float32)        # [p, i]

    # 3. compacted tile on the MXU: [p, i] x [C, i] -> [p, C] (row-major:
    # the tile then lands with a major-dim dynamic offset, the layout the
    # DMA engine slices without minor-dim tiling constraints)
    v = jnp.concatenate(
        [bins_ref[...].astype(jnp.float32),                  # int32 bins
         g_ref[...].astype(jnp.float32),
         h_ref[...].astype(jnp.float32),
         # lane padding: HBM minor dims are (1,128)-tiled, so the output
         # carries c_pad >= 128 channels; surplus lanes are zeros
         jnp.zeros((c_pad - nf - 2, chunk), jnp.float32)], axis=0)
    # wt is exactly 0/1 (bf16-exact), so out = wt@v_hi + wt@v_mid + wt@v_lo
    # with the classic 3-term bf16 split of v reconstructs every selected
    # f32 bit-exactly (each product is v_term*1 or *0; accumulation is f32)
    # in 3 single-pass bf16 matmuls — Mosaic has no per-operand precision,
    # and HIGHEST on both operands would cost 6 passes
    wt_bf = wt.astype(jnp.bfloat16)
    v_hi = v.astype(jnp.bfloat16)
    r = v - v_hi.astype(jnp.float32)
    v_mid = r.astype(jnp.bfloat16)
    v_lo = (r - v_mid.astype(jnp.float32)).astype(jnp.bfloat16)
    dn = (((1,), (1,)), ((), ()))
    acc = jax.lax.dot_general(wt_bf, v_hi, dn,
                              preferred_element_type=jnp.float32)
    acc += jax.lax.dot_general(wt_bf, v_mid, dn,
                               preferred_element_type=jnp.float32)
    acc += jax.lax.dot_general(wt_bf, v_lo, dn,
                               preferred_element_type=jnp.float32)
    s_ref[...] = acc                                         # [CHUNK, c_pad]

    # 4. land the tile at its global offset (sequential grid: later tiles
    # overwrite this tile's invalid tail)
    cp = pltpu.make_async_copy(
        s_ref, out_ref.at[pl.ds(off, chunk), :], sem)
    cp.start()
    cp.wait()


def select_rows(bins_fm, grad, hess, mask, cap: int, interpret: bool = False):
    """Compact the masked rows of feature-major data to the buffer front.

    bins_fm: [F, N] int (bin ids, exact through f32 for num_bins <= 2^24 —
    the engine caps bins at 65535), grad/hess: [N] f32, mask: [N] bool,
    cap: static output width (caller guarantees mask.sum() <= cap; rows
    beyond the count are zero).
    Returns (bins_c [F, cap] int32, grad_c [cap] f32, hess_c [cap] f32).

    The row-tile width (the Tuner's ``select.c*`` kernel variants) resolves
    from the variant registry OUTSIDE the jit boundary — it is a static arg
    of the jitted body, so resolving inside would freeze the first call's
    value into the cache. Compaction is exact at every tile width: each
    selected row is written exactly once by pass-through one-hot products.
    """
    from ..core import kernels as _kernels

    chunk = int(_kernels.active_param("select", "chunk", CHUNK))
    return _select_rows(bins_fm, grad, hess, mask, cap, interpret, chunk)


@functools.partial(jax.jit,
                   static_argnames=("cap", "interpret", "chunk"))
def _select_rows(bins_fm, grad, hess, mask, cap: int, interpret: bool = False,
                 chunk: int = CHUNK):
    f, n = bins_fm.shape
    n_pad = _round_up(max(n, 1), chunk)
    n_tiles = n_pad // chunk
    cap_pad = _round_up(cap, chunk) + chunk  # slack: every tile writes chunk
    c_pad = _round_up(f + 2, 128)            # HBM minor-dim (1,128) tiling

    m2 = jnp.pad(mask, (0, n_pad - n)).astype(jnp.float32).reshape(1, n_pad)
    bins_p = jnp.pad(bins_fm, ((0, 0), (0, n_pad - n)))
    g2 = jnp.pad(grad.astype(jnp.float32), (0, n_pad - n)).reshape(1, n_pad)
    h2 = jnp.pad(hess.astype(jnp.float32), (0, n_pad - n)).reshape(1, n_pad)

    counts = m2.reshape(n_tiles, chunk).sum(axis=1).astype(jnp.int32)
    offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1]])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((f, chunk), lambda j, offs: (0, j)),
            pl.BlockSpec((1, chunk), lambda j, offs: (0, j)),
            pl.BlockSpec((1, chunk), lambda j, offs: (0, j)),
            pl.BlockSpec((1, chunk), lambda j, offs: (0, j)),
        ],
        out_specs=[
            # HBM explicitly: ANY may place small tiers in VMEM, where
            # dynamic slicing of the tiled memref is not lowerable; the DMA
            # engine slices the HBM case without tiling constraints
            pl.BlockSpec(memory_space=pltpu.HBM),
        ],
        scratch_shapes=[
            pltpu.VMEM((chunk, c_pad), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    out = pl.pallas_call(
        functools.partial(_select_kernel, nf=f, chunk=chunk, c_pad=c_pad),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((cap_pad, c_pad), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * n_pad * chunk * (f + 3),
            bytes_accessed=bins_p.size * bins_p.dtype.itemsize
            + (f + 8) * n_pad * 4,
            transcendentals=0,
        ),
    )(offs, bins_p, g2, h2, m2)[0]
    # rows in [count+CHUNK, cap) are never written by any tile: scrub the
    # uninitialized HBM tail (recycled buffers can hold NaN/Inf bit
    # patterns, and downstream masking is multiplicative — NaN*0=NaN would
    # poison whole histograms)
    total = jnp.sum(counts)
    valid = jnp.arange(cap, dtype=jnp.int32) < total
    # feature-major views: one small XLA transpose ([cap, F] f32 ~ 0.1 ms at
    # tier caps) + lossless int cast (bin ids <= 65535 are exact in f32)
    bins_c = jnp.where(valid[None, :], out[:cap, :f].T, 0.0).astype(jnp.int32)
    return (bins_c, jnp.where(valid, out[:cap, f], 0.0),
            jnp.where(valid, out[:cap, f + 1], 0.0))


def use_select(n_rows: int = 0, interpret: bool = False) -> bool:
    """Dispatch gate: on for TPU (or interpret mode, for tests) when the
    mask width reaches MMLSPARK_TPU_SELECT_MIN_ROWS (default 500k);
    MMLSPARK_TPU_NO_PALLAS_SELECT=1 kills it.

    Measured (chained methodology, quiet machine): standalone the kernel
    beats XLA's cumsum+scatter+gathers 2.6x at 3.2M rows (40 vs 106 ms);
    in-situ inside the whole-run training scan at 2M-row GOSS (617k mask
    width) it wins 28.3-29.0 s vs 31.0-35.1 s over repeated A/B. Below
    ~500k widths the kernel's per-tile fixed costs (sync DMA latency,
    ~7 us/tile) erase the win, so small fits keep the XLA path.
    Methodology scar, recorded on purpose: an earlier gate required uint8
    bins, which the engine widens to int32 on device — the gate was dead,
    and an A/B 'regression' attributed to the kernel was pure tunnel
    variance. The current gate is proven live by a dispatch-count spy in
    test_select_tier_growth_matches_xla_path."""
    if os.environ.get("MMLSPARK_TPU_NO_PALLAS_SELECT", "") not in ("", "0"):
        return False
    min_rows = int(os.environ.get("MMLSPARK_TPU_SELECT_MIN_ROWS",
                                  str(500_000)))
    if n_rows and n_rows < min_rows:
        return False
    if interpret:
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False
