"""Gradient-boosted decision trees, TPU-native (LightGBM-on-Spark parity).

The reference wraps LightGBM's C++ core (histogram GBDT with socket-ring allreduce,
SURVEY §2.1/§3.2). This package re-implements the algorithm TPU-first:

  - quantile feature binning (binning.py; LGBM_DatasetCreateFromMat equivalent)
  - binned histogram accumulation + split finding as jitted XLA kernels
    (histogram.py) with a Pallas MXU one-hot-contraction kernel for the hot
    scatter on TPU (pallas_hist.py, ~13x over the XLA scatter lowering;
    BENCH_hist.json)
  - leaf-wise tree growth with the parent-minus-sibling histogram subtraction
    trick (tree.py; LightGBM's core data structure)
  - boosting loop with gbdt/rf/dart/goss variants, binary/multiclass/regression/
    ranking objectives, early stopping, continued training (booster.py;
    LGBM_BoosterUpdateOneIter parity)
  - data-parallel training: per-shard histograms psum'd over the mesh data axis —
    the socket-ring allreduce collapses into one XLA collective (distributed.py)
  - pipeline stages with the reference's param surface (stages.py;
    LightGBMClassifier/Regressor/Ranker, lightgbm/LightGBMParams.scala:1-259)
"""

from .binning import BinMapper
from .booster import Booster, TrainParams
from .stages import (
    LightGBMClassificationModel,
    LightGBMClassifier,
    LightGBMRanker,
    LightGBMRankerModel,
    LightGBMRegressionModel,
    LightGBMRegressor,
)

__all__ = [
    "BinMapper", "Booster", "LightGBMClassificationModel", "LightGBMClassifier",
    "LightGBMRanker", "LightGBMRankerModel", "LightGBMRegressionModel",
    "LightGBMRegressor", "TrainParams",
]
