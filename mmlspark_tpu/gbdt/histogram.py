"""Histogram accumulation and split finding — the GBDT hot kernels.

LightGBM's C++ core spends its time in exactly two loops (driven from the
reference via LGBM_BoosterUpdateOneIter, lightgbm/TrainUtils.scala:170-233):
binned histogram construction and best-split search. Here both are jitted XLA
kernels over static [N,F] / [F,B] shapes:

  - ``compute_histogram``: masked scatter-add of (grad, hess, count) into
    [F, B, 3]. On TPU the hot path dispatches to the Pallas one-hot-matmul
    kernel in pallas_hist.py (the scatter reformulated as an MXU contraction
    with a VMEM-resident accumulator); elsewhere it falls back to the XLA
    ``at[].add`` scatter below.
  - ``find_best_split``: vectorized gain scan over all (feature, bin) candidates
    with L1/L2 regularization, min-data / min-hessian constraints, and learned
    missing-value default direction — one argmax on device, no per-feature host
    loop.

Data-parallel training: when ``bins``/``grad``/``hess`` are sharded over the mesh
data axis, the scatter-add is a contraction over rows, so GSPMD inserts the
cross-shard psum automatically — the C++ socket-ring allreduce
(TrainUtils.scala:383-418) becomes one XLA collective.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import numpy as np


class SplitInfo(NamedTuple):
    feature: np.ndarray       # i32 scalar
    bin: np.ndarray           # i32 scalar: rows with bin <= this go left
    gain: np.ndarray          # f32 scalar
    default_left: np.ndarray  # bool scalar: where missing (bin 0) goes
    left_sum: np.ndarray      # [3] (grad, hess, count)
    right_sum: np.ndarray     # [3]
    # categorical set split (LightGBM num_cat machinery): bin-space bitset —
    # bin b goes LEFT iff bit b is set. All-zero words = numerical split
    # (bin 0 / missing can never be a member, so it naturally routes right).
    cat_words: np.ndarray = np.zeros(8, dtype=np.uint32)  # [ceil(B/32)] u32


def compute_histogram(bins_fm, grad, hess, row_mask, num_bins: int):
    """[F,N] feature-major int bins + per-row grad/hess + row mask ->
    [F, num_bins, 3] sums.

    Feature-major is the canonical device layout (LightGBM's own column
    store): the minor dim is rows, so no XLA lane padding and contiguous
    per-feature reads. On TPU, dispatches to the Pallas MXU kernel
    (pallas_hist.py): per-shard kernel + psum under shard_map when rows are
    sharded over a mesh axis, plain kernel on single-device inputs. Falls
    back to the XLA scatter for CPU/GPU, traced inputs, and shardings the
    kernel doesn't handle.
    """
    from . import pallas_hist

    out = pallas_hist.dispatch(bins_fm, grad, hess, row_mask, num_bins)
    if out is not None:
        return out
    return compute_histogram_xla(bins_fm, grad, hess, row_mask, num_bins)


@functools.partial(
    __import__("jax").jit, static_argnames=("num_bins",))
def compute_histogram_xla(bins_fm, grad, hess, row_mask, num_bins: int):
    """XLA ``at[].add`` scatter lowering (CPU/GPU fallback + parity reference).
    Takes the canonical feature-major [F, N] layout."""
    import jax.numpy as jnp

    f, n = bins_fm.shape
    m = row_mask.astype(jnp.float32)
    vals = jnp.stack([grad * m, hess * m, m], axis=-1)          # [N, 3]
    vals = jnp.broadcast_to(vals[None, :, :], (f, n, 3))        # [F, N, 3]
    feat_offset = jnp.arange(f, dtype=jnp.int32) * num_bins
    flat_idx = (bins_fm.astype(jnp.int32)
                + feat_offset[:, None]).reshape(-1)             # [F*N]
    hist = jnp.zeros((f * num_bins, 3), dtype=jnp.float32)
    hist = hist.at[flat_idx].add(vals.reshape(-1, 3))
    return hist.reshape(f, num_bins, 3)


def _leaf_objective(G, H, l1, l2):
    """-0.5 * T(G)^2 / (H + l2), T = soft-threshold by l1 (LightGBM's GetLeafGain)."""
    import jax.numpy as jnp

    t = jnp.sign(G) * jnp.maximum(jnp.abs(G) - l1, 0.0)
    return -0.5 * t * t / (H + l2)


def leaf_output(G, H, l1, l2):
    """Optimal leaf value -T(G)/(H + l2) (LightGBM's CalculateSplittedLeafOutput)."""
    import jax.numpy as jnp

    t = jnp.sign(G) * jnp.maximum(jnp.abs(G) - l1, 0.0)
    return -t / (H + l2)


def _cat_best_subset(hist, lambda_l1, lambda_l2, min_sum_hessian,
                     min_data_in_leaf: int, cat_smooth, cat_l2,
                     max_cat_threshold):
    """Per-feature best categorical SET split (LightGBM's sorted-by-
    gradient-statistic category partitioning): categories sorted by
    G/(H + cat_smooth), best prefix of the sorted order goes left.

    Returns (gain [F], words [F, CW] u32 bin-bitsets, left_sum [F, 3]).
    The missing bin (0) is never a member — missing categoricals route
    right, LightGBM's convention for the 'other' bucket."""
    import jax.numpy as jnp

    f, b, _ = hist.shape
    cw = (b + 31) // 32
    vb = hist[:, 1:, :]                                    # [F, B-1, 3]
    cnt = vb[..., 2]
    present = cnt > 0.0
    n_present = jnp.sum(present, axis=1)                   # [F]
    ratio = vb[..., 0] / (vb[..., 1] + cat_smooth)
    ratio = jnp.where(present, ratio, jnp.inf)             # absent: sort last
    order = jnp.argsort(ratio, axis=1)                     # [F, B-1]
    sh = jnp.take_along_axis(vb, order[..., None], axis=1)
    cum = jnp.cumsum(sh, axis=1)                           # [F, B-1, 3]
    total = hist.sum(axis=1)                               # [F, 3] (node totals)
    G, H, C = total[0, 0], total[0, 1], total[0, 2]
    l2c = lambda_l2 + cat_l2
    GL, HL, CL = cum[..., 0], cum[..., 1], cum[..., 2]
    GR, HR, CR = G - GL, H - HL, C - CL
    gain = (_leaf_objective(GL, HL, lambda_l1, l2c)
            + _leaf_objective(GR, HR, lambda_l1, l2c)
            - _leaf_objective(G, H, lambda_l1, l2c)) * -1.0
    k = jnp.arange(1, b, dtype=jnp.int32)[None, :]         # prefix sizes
    ok = ((CL >= min_data_in_leaf) & (CR >= min_data_in_leaf)
          & (HL >= min_sum_hessian) & (HR >= min_sum_hessian)
          & (k <= max_cat_threshold) & (k <= n_present[:, None]))
    gain = jnp.where(ok, gain, -jnp.inf)
    ki = jnp.argmax(gain, axis=1)                          # [F]
    gain_f = jnp.take_along_axis(gain, ki[:, None], axis=1)[:, 0]
    lsum_f = jnp.take_along_axis(cum, ki[:, None, None], axis=1)[:, 0, :]
    # membership back in ORIGINAL bin positions: sorted position <= ki
    member_sorted = (jnp.arange(b - 1)[None, :] <= ki[:, None])
    inv = jnp.argsort(order, axis=1)
    member = jnp.take_along_axis(member_sorted, inv, axis=1)  # [F, B-1]
    member_full = jnp.concatenate(
        [jnp.zeros((f, 1), bool), member], axis=1)         # bin 0 never
    pad = cw * 32 - b
    if pad:
        member_full = jnp.pad(member_full, ((0, 0), (0, pad)))
    bits = member_full.reshape(f, cw, 32).astype(jnp.uint32)
    words = jnp.sum(bits << jnp.arange(32, dtype=jnp.uint32)[None, None, :],
                    axis=2, dtype=jnp.uint32)              # [F, CW]
    return gain_f, words, lsum_f


@functools.partial(
    __import__("jax").jit,
    static_argnames=("min_data_in_leaf",))
def find_best_split(hist, lambda_l1, lambda_l2, min_sum_hessian,
                    min_data_in_leaf: int, feature_mask=None, cat_info=None):
    """Best (feature, bin, missing-direction) over a [F,B,3] histogram.

    Threshold semantics: candidate t sends bins 1..t left, bins t+1.. right; the
    missing bin (0) is tried on both sides and the better direction is kept
    (LightGBM's default-direction learning).

    ``cat_info``: optional (cat_mask [F] bool, cat_smooth, cat_l2,
    max_cat_threshold) — features flagged categorical are split by SET
    membership (sorted-gradient-prefix subsets, _cat_best_subset) instead
    of an ordered threshold; the winning split's bitset rides
    SplitInfo.cat_words (all-zero for numerical winners).
    """
    import jax.numpy as jnp

    f, b, _ = hist.shape
    cw = (b + 31) // 32
    miss = hist[:, 0, :]                          # [F,3] missing-bin sums
    cum = jnp.cumsum(hist[:, 1:, :], axis=1)      # [F,B-1,3] cumulative over value bins
    total = cum[:, -1, :] + miss                  # [F,3] node totals (same for all f)
    G, H, C = total[0, 0], total[0, 1], total[0, 2]

    # candidate thresholds t = 1..B-1 (cum index 0..B-2); left-without-missing sums:
    GL0, HL0, CL0 = cum[..., 0], cum[..., 1], cum[..., 2]     # [F,B-1]

    def gains(GL, HL, CL):
        GR, HR, CR = G - GL, H - HL, C - CL
        gain = (_leaf_objective(GL, HL, lambda_l1, lambda_l2)
                + _leaf_objective(GR, HR, lambda_l1, lambda_l2)
                - _leaf_objective(G, H, lambda_l1, lambda_l2)) * -1.0
        ok = ((CL >= min_data_in_leaf) & (CR >= min_data_in_leaf)
              & (HL >= min_sum_hessian) & (HR >= min_sum_hessian))
        return jnp.where(ok, gain, -jnp.inf)

    gain_right = gains(GL0, HL0, CL0)                               # missing -> right
    gain_left = gains(GL0 + miss[:, None, 0], HL0 + miss[:, None, 1],
                      CL0 + miss[:, None, 2])                       # missing -> left
    best_dir_left = gain_left >= gain_right
    gain = jnp.maximum(gain_left, gain_right)                       # [F,B-1]

    if cat_info is None:
        if feature_mask is not None:
            gain = jnp.where(feature_mask[:, None], gain, -jnp.inf)
        flat = jnp.argmax(gain)
        bf = flat // (b - 1)
        bt = flat % (b - 1) + 1                   # threshold bin (1-indexed)
        best_gain = gain.reshape(-1)[flat]
        dleft = best_dir_left.reshape(-1)[flat]
        lsum = cum[bf, bt - 1, :] + jnp.where(dleft, miss[bf], 0.0)
        rsum = total[bf] - lsum
        return SplitInfo(bf.astype(jnp.int32), bt.astype(jnp.int32),
                         best_gain, dleft, lsum, rsum,
                         jnp.zeros(cw, dtype=jnp.uint32))

    cat_mask, cat_smooth, cat_l2, max_cat_threshold = cat_info
    cat_gain, cat_words, cat_lsum = _cat_best_subset(
        hist, lambda_l1, lambda_l2, min_sum_hessian, min_data_in_leaf,
        cat_smooth, cat_l2, max_cat_threshold)
    # per-feature numerical best
    num_ki = jnp.argmax(gain, axis=1)                               # [F]
    num_gain = jnp.take_along_axis(gain, num_ki[:, None], axis=1)[:, 0]
    num_dir = jnp.take_along_axis(best_dir_left, num_ki[:, None],
                                  axis=1)[:, 0]
    num_lsum = (jnp.take_along_axis(cum, num_ki[:, None, None],
                                    axis=1)[:, 0, :]
                + jnp.where(num_dir[:, None], miss, 0.0))
    gain_f = jnp.where(cat_mask, cat_gain, num_gain)
    if feature_mask is not None:
        gain_f = jnp.where(feature_mask, gain_f, -jnp.inf)
    bf = jnp.argmax(gain_f)
    is_cat = cat_mask[bf]
    best_gain = gain_f[bf]
    bt = jnp.where(is_cat, 0, num_ki[bf] + 1)
    dleft = jnp.where(is_cat, False, num_dir[bf])
    lsum = jnp.where(is_cat, cat_lsum[bf], num_lsum[bf])
    rsum = total[bf] - lsum
    words = jnp.where(is_cat, cat_words[bf],
                      jnp.zeros(cw, dtype=jnp.uint32))
    return SplitInfo(bf.astype(jnp.int32), bt.astype(jnp.int32),
                     best_gain, dleft, lsum, rsum, words)


def find_best_split_pair(hist_pair, lambda_l1, lambda_l2, min_sum_hessian,
                         min_data_in_leaf: int, feature_mask=None,
                         cat_info=None):
    """Best splits for TWO sibling histograms stacked [2, F, B, 3] in one
    vectorized evaluation (the per-split while body evaluated each child
    separately — at large N the duplicated cumsum/gain kernels were a
    measurable share of the split cost)."""
    import jax

    def one(h):
        return find_best_split(h, lambda_l1, lambda_l2, min_sum_hessian,
                               min_data_in_leaf, feature_mask, cat_info)

    return jax.vmap(one)(hist_pair)


@functools.partial(
    __import__("jax").jit,
    static_argnames=("num_bins", "min_data_in_leaf", "use_mxu",
                     "has_feature_mask"))
def fused_split_step(bins_fm, grad, hess, row_mask, node_of_row, parent_hist,
                     feature, threshold_bin, default_left, node_id,
                     left_id, right_id, small_id,
                     lambda_l1, lambda_l2, min_sum_hessian,
                     feature_mask, *, num_bins: int, min_data_in_leaf: int,
                     use_mxu: bool, has_feature_mask: bool,
                     cat_words=None, cat_info=None):
    """ONE dispatch for a whole split iteration: route the parent's rows to
    the children, scatter the smaller child's histogram, derive the sibling
    by subtraction, and evaluate both children's best splits.

    grow_tree previously issued 4-5 separate device calls per split (each a
    blocking round trip — ~90ms through a tunnelled chip, XLA dispatch cost
    locally), which made end-to-end training dispatch-bound
    (BENCH_gbdt_train.json). Fusing keeps one round trip per split; the host
    fetches only the two SplitInfos.

    ``use_mxu``: lower the histogram through the Pallas MXU kernel (TPU,
    single-device) instead of the XLA scatter.
    """
    import jax.numpy as jnp

    bins_col = jnp.take(bins_fm, feature, axis=0)
    if cat_words is not None:
        node_of_row = partition_rows_cat(bins_col, node_of_row, node_id,
                                         threshold_bin, default_left,
                                         left_id, right_id, cat_words)
    else:
        node_of_row = partition_rows(bins_col, node_of_row, node_id,
                                     threshold_bin, default_left,
                                     left_id, right_id)
    small_mask = row_mask & (node_of_row == small_id)
    if use_mxu:
        from .pallas_hist import compute_histogram_mxu

        small_hist = compute_histogram_mxu(bins_fm, grad, hess, small_mask,
                                           num_bins)
    else:
        small_hist = compute_histogram_xla(bins_fm, grad, hess, small_mask,
                                           num_bins)
    big_hist = subtract_histogram(parent_hist, small_hist)
    fm = feature_mask if has_feature_mask else None
    split_small = find_best_split(small_hist, lambda_l1, lambda_l2,
                                  min_sum_hessian, min_data_in_leaf, fm,
                                  cat_info)
    split_big = find_best_split(big_hist, lambda_l1, lambda_l2,
                                min_sum_hessian, min_data_in_leaf, fm,
                                cat_info)
    return node_of_row, small_hist, big_hist, split_small, split_big


@__import__("jax").jit
def partition_rows(bins_col, node_of_row, node_id, threshold_bin, default_left,
                   left_id, right_id):
    """Route rows of ``node_id`` to children: bin<=t (or missing per default) left."""
    import jax.numpy as jnp

    in_node = node_of_row == node_id
    is_missing = bins_col == 0
    go_left = jnp.where(is_missing, default_left, bins_col <= threshold_bin)
    return jnp.where(in_node, jnp.where(go_left, left_id, right_id), node_of_row)


@__import__("jax").jit
def partition_rows_cat(bins_col, node_of_row, node_id, threshold_bin,
                       default_left, left_id, right_id, cat_words):
    """Cat-aware routing: when ``cat_words`` is non-zero the split is a
    SET — bin b goes left iff bit b is set (bin 0 never is, so missing
    routes right); all-zero words fall back to the threshold rule."""
    import jax.numpy as jnp

    in_node = node_of_row == node_id
    is_cat = jnp.any(cat_words != 0)
    bits = (jnp.take(cat_words, bins_col >> 5)
            >> (bins_col & 31).astype(jnp.uint32)) & 1
    is_missing = bins_col == 0
    go_left = jnp.where(
        is_cat, bits == 1,
        jnp.where(is_missing, default_left, bins_col <= threshold_bin))
    return jnp.where(in_node, jnp.where(go_left, left_id, right_id),
                     node_of_row)


@__import__("jax").jit
def subtract_histogram(parent, child):
    """Sibling histogram by subtraction (LightGBM's halving trick). Grad sums may
    be legitimately negative; only counts/hessians are clamped against tiny
    float cancellation."""
    import jax.numpy as jnp

    diff = parent - child
    return diff.at[..., 1:].set(jnp.maximum(diff[..., 1:], 0.0))


def total_sums(grad, hess, row_mask):
    import jax.numpy as jnp

    m = row_mask.astype(jnp.float32)
    return jnp.stack([jnp.sum(grad * m), jnp.sum(hess * m), jnp.sum(m)])
