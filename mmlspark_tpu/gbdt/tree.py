"""Leaf-wise (best-first) tree growth over binned features.

LightGBM's core algorithm (the reference drives it as a black box through
LGBM_BoosterUpdateOneIter, TrainUtils.scala:170-233): grow the leaf with the
largest split gain until ``num_leaves``, computing each split from per-leaf
histograms, with the parent-minus-sibling subtraction trick so each level costs
one scatter pass over the smaller child only.

Two growth paths, identical semantics:

- **Device-fused (default)**: the ENTIRE tree grows inside one jitted
  ``lax.while_loop`` — the best-first heap is an argmax over per-leaf candidate
  gains, node state lives in flat device arrays, and each iteration routes rows
  + scatters the small child's histogram (Pallas MXU kernel on TPU) + derives
  the sibling by subtraction + evaluates both children's splits. One dispatch
  and one host fetch per TREE; the old per-split orchestration cost ~31
  blocking round trips per tree and was dispatch-bound end-to-end
  (BENCH_gbdt_train.json).
  Row-sharded (multi-chip) inputs take the same fused path per shard under
  ``shard_map`` with psum'd histograms — replicated split decisions, sharded
  row routing (LightGBM's socket-ring allreduce as one collective stream).
- **Host-orchestrated**: one fused dispatch per split (histogram.py kernels
  with static shapes). The fallback when the per-node histogram buffer would
  exceed the memory budget (MMLSPARK_TPU_FUSED_TREE_BYTES), on CPU (cheap
  in-process dispatch), or when MMLSPARK_TPU_NO_FUSED_TREE=1 forces it.

Trees are stored as flat arrays (SoA) for vectorized prediction: no pointer
chasing, predict is a gather loop over depth (predict_trees in booster.py).
"""

from __future__ import annotations

import dataclasses
import functools
import heapq
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..parallel.mesh import fetch_global

from . import histogram as H

# Per-node histogram buffer cap for the device-fused grower: [2L-1, F, B, 3] f32.
# Above this, fall back to per-split host orchestration (whose live set is the
# heap frontier only).
_FUSED_TREE_DEFAULT_BUDGET = 2 << 30


def _fused_tree_enabled(max_nodes: int, num_f: int, num_bins: int) -> bool:
    if os.environ.get("MMLSPARK_TPU_NO_FUSED_TREE", "") not in ("", "0"):
        return False
    budget = int(os.environ.get("MMLSPARK_TPU_FUSED_TREE_BYTES",
                                _FUSED_TREE_DEFAULT_BUDGET))
    if max_nodes * num_f * num_bins * 3 * 4 > budget:
        return False
    if os.environ.get("MMLSPARK_TPU_FUSED_TREE", "") not in ("", "0"):
        return True  # forced on (tests exercise the fused path on CPU)
    # default: accelerators only — the fused win is removing per-split
    # dispatch round trips, which in-process CPU dispatch barely pays
    # (measured: TPU 200s -> 27s, CPU 8.3s -> 11.9s on the training bench)
    try:
        import jax

        return jax.default_backend() != "cpu"
    except Exception:
        return False


@dataclasses.dataclass
class Tree:
    """Flat decision tree. Node 0 is the root; feature == -1 marks a leaf."""

    feature: np.ndarray        # i32 [nodes], -1 for leaves
    threshold: np.ndarray      # f64 [nodes], raw-value threshold (<= goes left)
    threshold_bin: np.ndarray  # i32 [nodes]
    default_left: np.ndarray   # bool [nodes], missing direction
    left: np.ndarray           # i32 [nodes]
    right: np.ndarray          # i32 [nodes]
    value: np.ndarray          # f64 [nodes], leaf output (0 for internal)
    gain: np.ndarray           # f32 [nodes], split gain (0 for leaves)
    count: np.ndarray          # i32 [nodes], training rows through the node
    shrinkage: float = 1.0
    weight: Optional[np.ndarray] = None  # f64 [nodes], hessian sums (None: legacy)
    # categorical SET splits (LightGBM num_cat machinery): for a cat split
    # node, membership sends a row LEFT. Two views of the same set:
    #   cat_sets       — per node: sorted int64 category VALUES (raw-float
    #                    predict + LightGBM interchange), None elsewhere
    #   cat_bin_words  — [nodes, CW] u32 bitset over BIN ids (binned
    #                    routing/predict; None for imported models with no
    #                    bin mapper)
    cat_sets: Optional[list] = None
    cat_bin_words: Optional[np.ndarray] = None

    @property
    def num_leaves(self) -> int:
        return int((self.feature == -1).sum())

    def is_cat_node(self, nid: int) -> bool:
        return (self.cat_sets is not None
                and self.cat_sets[nid] is not None) or (
            self.cat_bin_words is not None
            and bool(self.cat_bin_words[nid].any()))

    def to_dict(self) -> dict:
        d = {
            "feature": self.feature.tolist(),
            "threshold": self.threshold.tolist(),
            "threshold_bin": self.threshold_bin.tolist(),
            "default_left": self.default_left.tolist(),
            "left": self.left.tolist(),
            "right": self.right.tolist(),
            "value": self.value.tolist(),
            "gain": self.gain.tolist(),
            "count": self.count.tolist(),
            "shrinkage": self.shrinkage,
        }
        if self.weight is not None:
            d["weight"] = self.weight.tolist()
        if self.cat_sets is not None:
            d["cat_sets"] = [s.tolist() if s is not None else None
                             for s in self.cat_sets]
        if self.cat_bin_words is not None:
            d["cat_bin_words"] = self.cat_bin_words.tolist()
        return d

    @staticmethod
    def from_dict(d: dict) -> "Tree":
        cat_sets = None
        if d.get("cat_sets") is not None:
            cat_sets = [np.asarray(s, dtype=np.int64) if s is not None
                        else None for s in d["cat_sets"]]
        return Tree(
            feature=np.asarray(d["feature"], dtype=np.int32),
            threshold=np.asarray(d["threshold"], dtype=np.float64),
            threshold_bin=np.asarray(d["threshold_bin"], dtype=np.int32),
            default_left=np.asarray(d["default_left"], dtype=bool),
            left=np.asarray(d["left"], dtype=np.int32),
            right=np.asarray(d["right"], dtype=np.int32),
            value=np.asarray(d["value"], dtype=np.float64),
            gain=np.asarray(d["gain"], dtype=np.float32),
            count=np.asarray(d["count"], dtype=np.int32),
            shrinkage=float(d.get("shrinkage", 1.0)),
            weight=(np.asarray(d["weight"], dtype=np.float64)
                    if d.get("weight") is not None else None),
            cat_sets=cat_sets,
            cat_bin_words=(np.asarray(d["cat_bin_words"], dtype=np.uint32)
                           if d.get("cat_bin_words") is not None else None),
        )


@dataclasses.dataclass
class GrowerConfig:
    num_leaves: int = 31
    max_depth: int = -1                 # -1 = unlimited (bounded by num_leaves)
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    max_delta_step: float = 0.0         # clamp |leaf value| (0 = off)
    # categorical set-split controls (LightGBM defaults)
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_threshold: int = 32


class _Node:
    __slots__ = ("id", "depth", "hist", "sums", "split")

    def __init__(self, id, depth, hist, sums, split):
        self.id = id
        self.depth = depth
        self.hist = hist
        self.sums = sums      # np [3]: grad, hess, count
        self.split = split    # SplitInfo (host numpy) or None


def _grow_tree_device_body(bins_fm, grad, hess, row_mask, node_of_row,
                           lambda_l1, lambda_l2, min_sum_hessian,
                           min_gain_to_split, feature_mask, *, num_bins: int,
                           max_nodes: int, min_data_in_leaf: int,
                           max_depth: int, use_mxu: bool,
                           has_feature_mask: bool, psum_axis=None,
                           interpret: bool = False, cat_args=None):
    """Grow one whole tree inside a single jitted ``lax.while_loop``.

    The best-first heap becomes an argmax over ``cand_gain`` (−inf marks
    non-splittable/already-split nodes); ties resolve to the lowest node id.
    NOTE: the host path's heapq breaks exact-gain ties by push order, which
    is small-child-first — NOT always the lower node id — so two candidates
    with bit-identical gains can pop in a different order there. Gains are
    f32 sums of distinct data, so real datasets hit this with probability ~0;
    everywhere else node ids are assigned in split order exactly as the host
    path does, and both paths produce identical trees.

    Returns flat node arrays sized ``max_nodes`` (= 2*num_leaves−1), the
    per-node (grad, hess, count) sums for host-side f64 leaf values, the final
    row→node routing, and ``n_nodes``. One dispatch, one fetch, per tree.

    ``psum_axis``: when set, this body is running per-shard under shard_map
    with rows split over that mesh axis — every histogram/total is psum'd so
    all shards make identical (replicated) split decisions while the row
    routing stays sharded. This is LightGBM's socket-ring data-parallel mode
    as one collective (TrainUtils.scala:383-418).
    """
    import jax
    import jax.numpy as jnp

    from . import pallas_select

    if use_mxu:
        from .pallas_hist import compute_histogram_mxu

        def base_hist(b, g, h, m, nb):
            return compute_histogram_mxu(b, g, h, m, nb, interpret=interpret)
    else:
        base_hist = H.compute_histogram_xla
    if psum_axis is None:
        hist_fn = base_hist
    else:
        def hist_fn(b, g, h, m, nb):
            return jax.lax.psum(base_hist(b, g, h, m, nb), psum_axis)

    # Small-child row compaction: the histogram kernel is row-streaming
    # bound (~2 MXU cycles per row*feature regardless of mask), so scanning
    # all N rows for every split wastes ~(N/|child|)x. Tiered static
    # capacities keep shapes XLA-compilable: pick the smallest tier >= the
    # child's row count, compact its row ids (one cumsum), and histogram
    # only that buffer. Total rows streamed per tree drops from ~2L*N to
    # ~3.5N (measured 8x on the 200k bench). Disabled under psum (a traced
    # switch would diverge across shards and deadlock the collective) and
    # via MMLSPARK_TPU_NO_GATHER_HIST=1 (exact-order parity for tests: the
    # compacted f32 summation order differs by ulps from the full scan).
    # Tier compaction engine: XLA's nonzero(size)+gather is a full-width
    # cumsum + scatter + 3 gathers (~106 ms at 3.2M rows on the chip, per
    # tiered split); the Pallas stream-select kernel does the same
    # compaction as one-hot MXU contractions + offset DMA writes in ~40 ms,
    # preserving row order so histogram summation is bit-identical.
    use_sel = (use_mxu
               and pallas_select.use_select(int(bins_fm.shape[1]),
                                            interpret=interpret))

    gather_caps: Tuple[int, ...] = ()
    if psum_axis is None and os.environ.get(
            "MMLSPARK_TPU_NO_GATHER_HIST", "") in ("", "0"):
        n_rows = int(bins_fm.shape[1])
        caps = []
        # Tier start (r4 profile, tools/profile_gbdt_10m.py): with the
        # stream-select kernel the compaction pass streams rows ~5x cheaper
        # than the histogram kernel (~12.5 vs ~59 ms per 1M rows at F=28),
        # so compacting pays for EVERY small child — tiers start at n/2
        # (small children are always <= n/2). The XLA nonzero+gather
        # fallback is only profitable well below n/4 (axis-1 gather ~19 ms
        # per n/2 rows at N=1M), so it keeps the old n/8 start. The select
        # buffer is [cap, 128ch] f32; the n/2 tier is capped to a 4 GB
        # budget (bins + buffers must fit 15.75 GB HBM at the 10M bench).
        top_div = 2 if use_sel else 8
        max_tiers = 7 if use_sel else 5
        c = (n_rows // top_div + 511) // 512 * 512
        while c * 132 * 4 > (4 << 30):   # select-buffer HBM budget
            c = (c // 2 + 511) // 512 * 512
        while c >= max(4096, n_rows // 128) and len(caps) < max_tiers:
            caps.append(c)
            c = (c // 2 + 511) // 512 * 512
        if caps:
            gather_caps = tuple(caps)

    def small_child_hist(small_mask, small_cnt):
        """Histogram of the masked rows, streaming only a tier-sized
        compacted buffer when the tiers are enabled."""
        if not gather_caps:
            return hist_fn(bins_fm, grad, hess, small_mask, num_bins)

        def make_branch(cap):
            def br(_):
                valid = jnp.arange(cap, dtype=jnp.int32) < small_cnt
                if use_sel:
                    # safe: the tier switch picks cap >= small_cnt, so the
                    # kernel's offset writes stay inside its slack
                    b_c, g_c, h_c = pallas_select.select_rows(
                        bins_fm, grad, hess, small_mask, cap,
                        interpret=interpret)
                    return base_hist(b_c, g_c, h_c, valid, num_bins)
                idx = jnp.nonzero(small_mask, size=cap, fill_value=0)[0]
                return base_hist(jnp.take(bins_fm, idx, axis=1),
                                 jnp.take(grad, idx), jnp.take(hess, idx),
                                 valid, num_bins)
            return br

        def full(_):
            return hist_fn(bins_fm, grad, hess, small_mask, num_bins)

        # caps are descending; choose the smallest tier that fits (small
        # children are always <= N/2, so tier 0 is a guaranteed fallback)
        branches = [full] + [make_branch(c) for c in gather_caps]
        tidx = jnp.int32(1)
        for i, cap in enumerate(gather_caps[1:], 2):
            tidx = jnp.where(small_cnt <= cap, jnp.int32(i), tidx)
        tidx = jnp.where(small_cnt <= gather_caps[0], tidx, jnp.int32(0))
        return jax.lax.switch(tidx, branches, None)

    fm = feature_mask if has_feature_mask else None
    neg_inf = jnp.float32(-jnp.inf)
    M = max_nodes
    CW = (num_bins + 31) // 32
    num_leaves_target = (max_nodes + 1) // 2
    # cat_args: (cat_mask [F] bool, cat_smooth, cat_l2, max_cat_threshold)
    # — None keeps every compiled graph identical to the numerical-only one
    cat_info = cat_args

    def best(hist):
        return H.find_best_split(hist, lambda_l1, lambda_l2, min_sum_hessian,
                                 min_data_in_leaf, fm, cat_info)

    root_hist = hist_fn(bins_fm, grad, hess, row_mask, num_bins)
    root_sums = H.total_sums(grad, hess, row_mask)
    if psum_axis is not None:
        root_sums = jax.lax.psum(root_sums, psum_axis)
    s0 = best(root_hist)
    # host parity: the root is pushed without the 2*min_data_in_leaf check
    # (find_best_split already enforces per-side constraints), and the
    # max_depth guard can never block depth 0
    root_ok = jnp.isfinite(s0.gain) & (s0.gain > min_gain_to_split)

    f32 = jnp.float32
    state = dict(
        node_of_row=node_of_row,
        feature=jnp.full(M, -1, jnp.int32),
        threshold_bin=jnp.zeros(M, jnp.int32),
        default_left=jnp.ones(M, bool),
        left=jnp.full(M, -1, jnp.int32),
        right=jnp.full(M, -1, jnp.int32),
        gain=jnp.zeros(M, f32),
        sums=jnp.zeros((M, 3), f32).at[0].set(root_sums),
        depth=jnp.zeros(M, jnp.int32),
        hists=jnp.zeros((M,) + root_hist.shape, f32).at[0].set(root_hist),
        cand_gain=jnp.full(M, -jnp.inf, f32).at[0].set(
            jnp.where(root_ok, s0.gain, neg_inf)),
        cand_feature=jnp.zeros(M, jnp.int32).at[0].set(s0.feature),
        cand_bin=jnp.zeros(M, jnp.int32).at[0].set(s0.bin),
        cand_dleft=jnp.zeros(M, bool).at[0].set(s0.default_left),
        cand_lsum=jnp.zeros((M, 3), f32).at[0].set(s0.left_sum),
        cand_rsum=jnp.zeros((M, 3), f32).at[0].set(s0.right_sum),
        n_nodes=jnp.int32(1),
        n_leaves=jnp.int32(1),
    )
    if cat_info is not None:
        state["cat_words"] = jnp.zeros((M, CW), jnp.uint32)
        state["cand_cwords"] = jnp.zeros((M, CW), jnp.uint32) \
            .at[0].set(s0.cat_words)

    def cond(st):
        return (st["n_leaves"] < num_leaves_target) \
            & (jnp.max(st["cand_gain"]) > neg_inf)

    def body(st):
        leaf = jnp.argmax(st["cand_gain"]).astype(jnp.int32)
        f = st["cand_feature"][leaf]
        t = st["cand_bin"][leaf]
        dl = st["cand_dleft"][leaf]
        lsum = st["cand_lsum"][leaf]
        rsum = st["cand_rsum"][leaf]
        lid = st["n_nodes"]
        rid = lid + 1
        dchild = st["depth"][leaf] + 1

        if cat_info is not None:
            node_of_row = H.partition_rows_cat(
                jnp.take(bins_fm, f, axis=0), st["node_of_row"], leaf, t,
                dl, lid, rid, st["cand_cwords"][leaf])
        else:
            node_of_row = H.partition_rows(
                jnp.take(bins_fm, f, axis=0), st["node_of_row"], leaf, t,
                dl, lid, rid)

        small_is_left = lsum[2] <= rsum[2]
        small_id = jnp.where(small_is_left, lid, rid)
        big_id = jnp.where(small_is_left, rid, lid)
        small_mask = row_mask & (node_of_row == small_id)
        # exact int count (the f32 sums channel saturates past 2^24 rows)
        small_cnt = jnp.sum(small_mask, dtype=jnp.int32)
        small_hist = small_child_hist(small_mask, small_cnt)
        big_hist = H.subtract_histogram(st["hists"][leaf], small_hist)
        s_pair = H.find_best_split_pair(
            jnp.stack([small_hist, big_hist]), lambda_l1, lambda_l2,
            min_sum_hessian, min_data_in_leaf, fm, cat_info)
        s_small = jax.tree.map(lambda x: x[0], s_pair)
        s_big = jax.tree.map(lambda x: x[1], s_pair)

        cg = st["cand_gain"].at[leaf].set(neg_inf)
        cf, cb, cd = st["cand_feature"], st["cand_bin"], st["cand_dleft"]
        cl, cr = st["cand_lsum"], st["cand_rsum"]
        cwd = st["cand_cwords"] if cat_info is not None else None

        def push(arrs, nid, s, csum):
            cg, cf, cb, cd, cl, cr, cwd = arrs
            ok = jnp.isfinite(s.gain) & (s.gain > min_gain_to_split)
            ok &= csum[2] >= 2 * min_data_in_leaf
            if max_depth > 0:
                ok &= dchild < max_depth
            if cwd is not None:
                cwd = cwd.at[nid].set(s.cat_words)
            return (cg.at[nid].set(jnp.where(ok, s.gain, neg_inf)),
                    cf.at[nid].set(s.feature), cb.at[nid].set(s.bin),
                    cd.at[nid].set(s.default_left),
                    cl.at[nid].set(s.left_sum), cr.at[nid].set(s.right_sum),
                    cwd)

        small_sums = jnp.where(small_is_left, lsum, rsum)
        big_sums = jnp.where(small_is_left, rsum, lsum)
        arrs = push((cg, cf, cb, cd, cl, cr, cwd), small_id, s_small,
                    small_sums)
        cg, cf, cb, cd, cl, cr, cwd = push(arrs, big_id, s_big, big_sums)

        out = dict(
            node_of_row=node_of_row,
            feature=st["feature"].at[leaf].set(f),
            threshold_bin=st["threshold_bin"].at[leaf].set(t),
            default_left=st["default_left"].at[leaf].set(dl),
            left=st["left"].at[leaf].set(lid),
            right=st["right"].at[leaf].set(rid),
            gain=st["gain"].at[leaf].set(st["cand_gain"][leaf]),
            sums=st["sums"].at[lid].set(lsum).at[rid].set(rsum),
            depth=st["depth"].at[lid].set(dchild).at[rid].set(dchild),
            hists=st["hists"].at[small_id].set(small_hist)
                             .at[big_id].set(big_hist),
            cand_gain=cg, cand_feature=cf, cand_bin=cb, cand_dleft=cd,
            cand_lsum=cl, cand_rsum=cr,
            n_nodes=lid + 2, n_leaves=st["n_leaves"] + 1,
        )
        if cat_info is not None:
            out["cat_words"] = st["cat_words"].at[leaf].set(
                st["cand_cwords"][leaf])
            out["cand_cwords"] = cwd
        return out

    out = jax.lax.while_loop(cond, body, state)
    keys = ["node_of_row", "feature", "threshold_bin", "default_left",
            "left", "right", "gain", "sums", "n_nodes"]
    if cat_info is not None:
        keys.append("cat_words")
    return {k: out[k] for k in keys}


@functools.partial(
    __import__("jax").jit,
    static_argnames=("num_bins", "max_nodes", "min_data_in_leaf", "max_depth",
                     "use_mxu", "has_feature_mask"))
def _grow_tree_device(bins, grad, hess, row_mask, node_of_row,
                      lambda_l1, lambda_l2, min_sum_hessian, min_gain_to_split,
                      feature_mask, cat_args=None, *, num_bins: int,
                      max_nodes: int, min_data_in_leaf: int, max_depth: int,
                      use_mxu: bool, has_feature_mask: bool):
    return _grow_tree_device_body(
        bins, grad, hess, row_mask, node_of_row, lambda_l1, lambda_l2,
        min_sum_hessian, min_gain_to_split, feature_mask, num_bins=num_bins,
        max_nodes=max_nodes, min_data_in_leaf=min_data_in_leaf,
        max_depth=max_depth, use_mxu=use_mxu,
        has_feature_mask=has_feature_mask, cat_args=cat_args)


_SHARDED_GROW_CACHE: Dict[Tuple, Any] = {}


def _grow_tree_device_sharded(bins, grad, hess, row_mask, node_of_row,
                              lambda_l1, lambda_l2, min_sum_hessian,
                              min_gain_to_split, feature_mask, *,
                              num_bins: int, max_nodes: int,
                              min_data_in_leaf: int, max_depth: int,
                              has_feature_mask: bool, cat_args=None):
    """Row-sharded whole-tree growth: the while_loop runs per shard under
    shard_map with psum'd histograms/totals, so every shard takes identical
    split decisions (replicated tree arrays) while ``node_of_row`` stays
    sharded. One dispatch + one collective stream per tree instead of
    one host round trip per split."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map_compat as shard_map
    from . import pallas_hist

    sh = bins.sharding
    mesh, row_axes = sh.mesh, sh.spec[1]  # bins_fm [F, N]: rows on dim 1
    # interpret mode: CPU tests of the psum'd-Pallas branch production TPU
    # meshes take (shared parser: pallas_hist.interpret_mode)
    interpret = pallas_hist.interpret_mode()
    use_mxu = pallas_hist.use_pallas() or interpret
    has_cat = cat_args is not None
    key = (mesh, row_axes, num_bins, max_nodes, min_data_in_leaf, max_depth,
           has_feature_mask, use_mxu, interpret, has_cat)
    if key not in _SHARDED_GROW_CACHE:
        if len(_SHARDED_GROW_CACHE) >= 16:  # bound compiled-program memory
            _SHARDED_GROW_CACHE.pop(next(iter(_SHARDED_GROW_CACHE)))
        row_spec = P(row_axes)
        rep = P()
        cat_spec = (rep,) * 4 if has_cat else None

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(sh.spec, row_spec, row_spec, row_spec, row_spec,
                      rep, rep, rep, rep, rep, cat_spec),
            out_specs=dict(
                {"node_of_row": row_spec, "feature": rep,
                 "threshold_bin": rep, "default_left": rep, "left": rep,
                 "right": rep, "gain": rep, "sums": rep, "n_nodes": rep},
                **({"cat_words": rep} if has_cat else {})),
            check_vma=False)  # pallas_call can't declare varying-mesh-axes
        def go(b, g, h, m, rows, l1, l2, msh, mgs, fm, ca):
            return _grow_tree_device_body(
                b, g, h, m, rows, l1, l2, msh, mgs, fm, num_bins=num_bins,
                max_nodes=max_nodes, min_data_in_leaf=min_data_in_leaf,
                max_depth=max_depth, use_mxu=use_mxu,
                has_feature_mask=has_feature_mask, psum_axis=row_axes,
                interpret=interpret, cat_args=ca)

        _SHARDED_GROW_CACHE[key] = jax.jit(go)
    return _SHARDED_GROW_CACHE[key](
        bins, grad, hess, row_mask, node_of_row,
        np.float32(lambda_l1), np.float32(lambda_l2),
        np.float32(min_sum_hessian), np.float32(min_gain_to_split),
        feature_mask, cat_args)


def cat_sets_from_words(words: np.ndarray, feature: np.ndarray,
                        bin_mapper) -> Tuple[Optional[list],
                                             Optional[np.ndarray]]:
    """[nodes, CW] u32 bin-bitsets -> (per-node sorted category-VALUE sets,
    the words themselves) — None/None when no node has a set."""
    if words is None or not words.any():
        return None, None
    nn = len(feature)
    sets: list = [None] * nn
    for nid in range(nn):
        w = words[nid]
        if not w.any():
            continue
        bits = np.unpackbits(w.view(np.uint8), bitorder="little")
        bins_in = np.nonzero(bits)[0]            # bin ids (>= 1 by invariant)
        cats = bin_mapper.categories[int(feature[nid])]
        sets[nid] = np.sort(cats[bins_in - 1]).astype(np.int64)
    return sets, words.astype(np.uint32)


def build_thresholds(feature, tbin, cat_sets, bin_mapper) -> np.ndarray:
    """Raw-value thresholds per node: the bin's upper value for numerical
    splits, 0.0 for leaves AND categorical set nodes (their routing is the
    membership set, not a threshold). Single source for the fused-grower
    and whole-run-scan tree builders."""
    return np.array(
        [bin_mapper.bin_upper_value(int(f), int(t))
         if f >= 0 and (cat_sets is None or cat_sets[i] is None) else 0.0
         for i, (f, t) in enumerate(zip(feature, tbin))], dtype=np.float64)


def _grow_tree_fused(bins_dev, grad, hess, row_mask, num_bins: int,
                     config: GrowerConfig, bin_mapper, feature_mask,
                     node_of_row, device_rows: bool = False,
                     row_sharded: bool = False,
                     cat_args=None) -> Tuple[Tree, np.ndarray]:
    """Host wrapper for the one-dispatch-per-tree device grower.

    ``device_rows``: return the row→leaf routing as the device array instead
    of fetching it (the booster's on-device score update wants it resident).
    ``row_sharded``: rows are split over a mesh axis — use the shard_map
    variant with psum'd histograms.
    """
    import jax

    from . import pallas_hist

    fm = feature_mask if feature_mask is not None else np.zeros(0, dtype=bool)
    common = dict(
        num_bins=num_bins, max_nodes=2 * config.num_leaves - 1,
        min_data_in_leaf=config.min_data_in_leaf, max_depth=config.max_depth,
        has_feature_mask=feature_mask is not None)
    if row_sharded:
        dev_out = _grow_tree_device_sharded(
            bins_dev, grad, hess, row_mask, node_of_row,
            config.lambda_l1, config.lambda_l2,
            config.min_sum_hessian_in_leaf, config.min_gain_to_split,
            fm, cat_args=cat_args, **common)
    else:
        dev_out = _grow_tree_device(
            bins_dev, grad, hess, row_mask, node_of_row,
            np.float32(config.lambda_l1), np.float32(config.lambda_l2),
            np.float32(config.min_sum_hessian_in_leaf),
            np.float32(config.min_gain_to_split), fm, cat_args,
            use_mxu=pallas_hist.use_mxu_single_device(bins_dev), **common)
    rows_dev = dev_out.pop("node_of_row")
    out = fetch_global(dev_out)

    nn = int(out["n_nodes"])
    feature = out["feature"][:nn].astype(np.int32)
    tbin = out["threshold_bin"][:nn].astype(np.int32)
    sums = out["sums"][:nn].astype(np.float64)
    # leaf values on host in f64, the same formula + precision lineage as the
    # per-split path (which fetches f32 SplitInfo sums and computes in f64)
    g_thr = np.sign(sums[:, 0]) * np.maximum(
        np.abs(sums[:, 0]) - config.lambda_l1, 0.0)
    value = np.where(feature < 0,
                     -g_thr / (sums[:, 1] + config.lambda_l2), 0.0)
    if config.max_delta_step > 0:
        value = np.clip(value, -config.max_delta_step, config.max_delta_step)
    # host-path parity: values are assigned at child creation only, so an
    # unsplit root keeps 0.0 (it is never anyone's child)
    value[0] = 0.0 if nn == 1 else value[0]
    cat_sets = cat_words_np = None
    if "cat_words" in out:
        cat_sets, cat_words_np = cat_sets_from_words(
            out["cat_words"][:nn], feature, bin_mapper)
    threshold = build_thresholds(feature, tbin, cat_sets, bin_mapper)
    tree = Tree(
        feature=feature,
        threshold=threshold,
        threshold_bin=tbin,
        default_left=out["default_left"][:nn].astype(bool),
        left=out["left"][:nn].astype(np.int32),
        right=out["right"][:nn].astype(np.int32),
        value=value,
        gain=out["gain"][:nn].astype(np.float32),
        count=sums[:, 2].astype(np.int32),
        weight=sums[:, 1],
        cat_sets=cat_sets,
        cat_bin_words=cat_words_np,
    )
    if device_rows:
        return tree, rows_dev
    return tree, np.asarray(fetch_global(rows_dev))


def grow_tree(bins_fm, grad, hess, row_mask, num_bins: int,
              config: GrowerConfig, bin_mapper, feature_mask=None,
              node_of_row=None, device_rows: bool = False,
              cat_args=None) -> Tuple[Tree, np.ndarray]:
    """Grow one tree; returns (tree, leaf_node_of_row).

    ``bins_fm``: [F,N] int (device, FEATURE-MAJOR — the canonical column-store
    layout: minor dim rows avoids XLA lane padding; LightGBM stores features
    column-wise the same way). ``grad``/``hess``: [N] f32 (device).
    ``row_mask``: [N] bool — bagging/goss row subset. ``feature_mask``: [F] bool.
    ``leaf_node_of_row`` maps every (masked-in) row to its final node id, so the
    booster can update scores with one gather instead of re-predicting.
    """
    import jax
    import jax.numpy as jnp

    from . import pallas_hist

    num_f, n = bins_fm.shape
    if node_of_row is None:
        node_of_row = jnp.zeros(n, dtype=jnp.int32)

    # routing, decided ONCE (invariant over the loop): the default on
    # accelerators grows the WHOLE tree in one device dispatch — per-shard
    # under shard_map with psum'd histograms when rows are sharded over a
    # mesh axis, plain when single-device. Fallback (memory budget exceeded
    # or MMLSPARK_TPU_NO_FUSED_TREE=1): host-orchestrated per-split calls,
    # whose compute_histogram dispatch runs the per-shard Pallas kernel +
    # psum for sharded inputs.
    row_sharded = bool(pallas_hist._row_sharded_spec(bins_fm))
    use_mxu = pallas_hist.use_mxu_single_device(bins_fm)

    if _fused_tree_enabled(2 * config.num_leaves - 1, num_f, num_bins):
        return _grow_tree_fused(bins_fm, grad, hess, row_mask, num_bins,
                                config, bin_mapper, feature_mask, node_of_row,
                                device_rows=device_rows,
                                row_sharded=row_sharded, cat_args=cat_args)

    # growable node storage (host lists; frozen to arrays at the end)
    feature = [-1]
    threshold = [0.0]
    threshold_bin = [0]
    default_left = [True]
    left = [-1]
    right = [-1]
    value = [0.0]
    gains = [0.0]
    counts = [0]
    hweights = [0.0]
    cw = (num_bins + 31) // 32
    node_cat_words = [np.zeros(cw, dtype=np.uint32)]

    def eval_node(hist) -> Tuple[Optional[H.SplitInfo], np.ndarray]:
        split = H.find_best_split(
            hist, config.lambda_l1, config.lambda_l2,
            config.min_sum_hessian_in_leaf, config.min_data_in_leaf,
            feature_mask, cat_args)
        return fetch_global(split)

    root_hist = H.compute_histogram(bins_fm, grad, hess, row_mask, num_bins)
    root_sums = np.asarray(fetch_global(
        H.total_sums(grad, hess, row_mask)), dtype=np.float64)
    counts[0] = int(root_sums[2])
    hweights[0] = float(root_sums[1])
    root_split = eval_node(root_hist)

    heap: List[Tuple[float, int, _Node]] = []
    tiebreak = 0

    def push(node: _Node):
        nonlocal tiebreak
        if node.split is not None and np.isfinite(node.split.gain) \
                and node.split.gain > config.min_gain_to_split:
            if config.max_depth > 0 and node.depth >= config.max_depth:
                return
            heapq.heappush(heap, (-float(node.split.gain), tiebreak, node))
            tiebreak += 1

    push(_Node(0, 0, root_hist, root_sums, root_split))
    n_leaves = 1

    while heap and n_leaves < config.num_leaves:
        _, _, node = heapq.heappop(heap)
        s = node.split
        f, t = int(s.feature), int(s.bin)
        lid, rid = len(feature), len(feature) + 1
        words = np.asarray(s.cat_words, dtype=np.uint32)
        is_cat_split = bool(words.any())

        # record the split on the parent
        feature[node.id] = f
        threshold[node.id] = 0.0 if is_cat_split \
            else bin_mapper.bin_upper_value(f, t)
        threshold_bin[node.id] = t
        default_left[node.id] = bool(s.default_left)
        left[node.id] = lid
        right[node.id] = rid
        gains[node.id] = float(s.gain)
        value[node.id] = 0.0
        node_cat_words[node.id] = words

        lsum = np.asarray(s.left_sum, dtype=np.float64)
        rsum = np.asarray(s.right_sum, dtype=np.float64)
        for sums in (lsum, rsum):
            feature.append(-1)
            threshold.append(0.0)
            threshold_bin.append(0)
            default_left.append(True)
            left.append(-1)
            right.append(-1)
            g_thr = np.sign(sums[0]) * max(abs(sums[0]) - config.lambda_l1, 0.0)
            v = float(-g_thr / (sums[1] + config.lambda_l2))
            if config.max_delta_step > 0:
                v = float(np.clip(v, -config.max_delta_step,
                                  config.max_delta_step))
            value.append(v)
            gains.append(0.0)
            counts.append(int(sums[2]))
            hweights.append(float(sums[1]))
            node_cat_words.append(np.zeros(cw, dtype=np.uint32))

        n_leaves += 1
        small_id, big_id = (lid, rid) if lsum[2] <= rsum[2] else (rid, lid)
        small_sums = lsum if small_id == lid else rsum
        big_sums = rsum if small_id == lid else lsum

        if row_sharded:
            # multi-call path: compute_histogram dispatches to the per-shard
            # Pallas kernel + psum (the fused jit's in-graph scatter would
            # lose ~13x and can OOM at large N — pallas_hist.py:30-35)
            node_of_row = H.partition_rows_cat(
                bins_fm[f], node_of_row, node.id,
                np.int32(t), bool(s.default_left), np.int32(lid),
                np.int32(rid), words) if is_cat_split else H.partition_rows(
                bins_fm[f], node_of_row, node.id,
                np.int32(t), bool(s.default_left), np.int32(lid),
                np.int32(rid))
            small_mask = row_mask & (node_of_row == small_id)
            small_hist = H.compute_histogram(bins_fm, grad, hess,
                                             small_mask, num_bins)
            big_hist = H.subtract_histogram(node.hist, small_hist)
            split_small = eval_node(small_hist)
            split_big = eval_node(big_hist)
        else:
            # fused split iteration: route rows + scatter the smaller
            # child's histogram + sibling subtraction + both children's
            # split evals in ONE device dispatch (H.fused_split_step — the
            # loop used to be dispatch-bound at 4-5 round trips per split)
            node_of_row, small_hist, big_hist, split_small, split_big = \
                H.fused_split_step(
                    bins_fm, grad, hess, row_mask, node_of_row, node.hist,
                    np.int32(f), np.int32(t), bool(s.default_left),
                    np.int32(node.id), np.int32(lid), np.int32(rid),
                    np.int32(small_id),
                    config.lambda_l1, config.lambda_l2,
                    config.min_sum_hessian_in_leaf,
                    feature_mask if feature_mask is not None
                    else np.zeros(0, dtype=bool),
                    num_bins=num_bins,
                    min_data_in_leaf=config.min_data_in_leaf,
                    use_mxu=use_mxu,
                    has_feature_mask=feature_mask is not None,
                    cat_words=words if cat_args is not None else None,
                    cat_info=cat_args)
            split_small, split_big = fetch_global((split_small, split_big))

        for cid, chist, csplit, csums in (
                (small_id, small_hist, split_small, small_sums),
                (big_id, big_hist, split_big, big_sums)):
            if csums[2] >= 2 * config.min_data_in_leaf:
                push(_Node(cid, node.depth + 1, chist, csums, csplit))

    words_arr = np.stack(node_cat_words)
    cat_sets, cat_words_np = cat_sets_from_words(
        words_arr, np.asarray(feature, dtype=np.int32), bin_mapper)
    tree = Tree(
        feature=np.asarray(feature, dtype=np.int32),
        threshold=np.asarray(threshold, dtype=np.float64),
        threshold_bin=np.asarray(threshold_bin, dtype=np.int32),
        default_left=np.asarray(default_left, dtype=bool),
        left=np.asarray(left, dtype=np.int32),
        right=np.asarray(right, dtype=np.int32),
        value=np.asarray(value, dtype=np.float64),
        gain=np.asarray(gains, dtype=np.float32),
        count=np.asarray(counts, dtype=np.int32),
        weight=np.asarray(hweights, dtype=np.float64),
        cat_sets=cat_sets,
        cat_bin_words=cat_words_np,
    )
    return tree, np.asarray(fetch_global(node_of_row))


def predict_tree_binned(tree: Tree, bins: np.ndarray) -> np.ndarray:
    """Evaluate one tree on binned features (host reference path for tests)."""
    n = bins.shape[0]
    out = np.zeros(n, dtype=np.float64)
    node = np.zeros(n, dtype=np.int64)
    active = tree.feature[node] != -1
    while active.any():
        cur = node[active]
        f = tree.feature[cur]
        b = bins[active, f]
        t = tree.threshold_bin[cur]
        go_left = np.where(b == 0, tree.default_left[cur], b <= t)
        if tree.cat_bin_words is not None:
            w = tree.cat_bin_words[cur]                     # [A, CW]
            bit = (w[np.arange(len(b)), b >> 5] >> (b & 31).astype(
                np.uint32)) & 1
            go_left = np.where(w.any(axis=1), bit == 1, go_left)
        node[active] = np.where(go_left, tree.left[cur], tree.right[cur])
        active = tree.feature[node] != -1
    out = tree.value[node] * tree.shrinkage
    return out
