"""Leaf-wise (best-first) tree growth over binned features.

LightGBM's core algorithm (the reference drives it as a black box through
LGBM_BoosterUpdateOneIter, TrainUtils.scala:170-233): grow the leaf with the
largest split gain until ``num_leaves``, computing each split from per-leaf
histograms, with the parent-minus-sibling subtraction trick so each level costs
one scatter pass over the smaller child only.

Host Python orchestrates; every inner computation (histogram scatter, split scan,
row partition) is a jitted kernel from histogram.py with static shapes, so the
whole growth loop compiles to a handful of cached XLA executables.

Trees are stored as flat arrays (SoA) for vectorized prediction: no pointer
chasing, predict is a gather loop over depth (predict_trees in booster.py).
"""

from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import histogram as H


@dataclasses.dataclass
class Tree:
    """Flat decision tree. Node 0 is the root; feature == -1 marks a leaf."""

    feature: np.ndarray        # i32 [nodes], -1 for leaves
    threshold: np.ndarray      # f64 [nodes], raw-value threshold (<= goes left)
    threshold_bin: np.ndarray  # i32 [nodes]
    default_left: np.ndarray   # bool [nodes], missing direction
    left: np.ndarray           # i32 [nodes]
    right: np.ndarray          # i32 [nodes]
    value: np.ndarray          # f64 [nodes], leaf output (0 for internal)
    gain: np.ndarray           # f32 [nodes], split gain (0 for leaves)
    count: np.ndarray          # i32 [nodes], training rows through the node
    shrinkage: float = 1.0

    @property
    def num_leaves(self) -> int:
        return int((self.feature == -1).sum())

    def to_dict(self) -> dict:
        return {
            "feature": self.feature.tolist(),
            "threshold": self.threshold.tolist(),
            "threshold_bin": self.threshold_bin.tolist(),
            "default_left": self.default_left.tolist(),
            "left": self.left.tolist(),
            "right": self.right.tolist(),
            "value": self.value.tolist(),
            "gain": self.gain.tolist(),
            "count": self.count.tolist(),
            "shrinkage": self.shrinkage,
        }

    @staticmethod
    def from_dict(d: dict) -> "Tree":
        return Tree(
            feature=np.asarray(d["feature"], dtype=np.int32),
            threshold=np.asarray(d["threshold"], dtype=np.float64),
            threshold_bin=np.asarray(d["threshold_bin"], dtype=np.int32),
            default_left=np.asarray(d["default_left"], dtype=bool),
            left=np.asarray(d["left"], dtype=np.int32),
            right=np.asarray(d["right"], dtype=np.int32),
            value=np.asarray(d["value"], dtype=np.float64),
            gain=np.asarray(d["gain"], dtype=np.float32),
            count=np.asarray(d["count"], dtype=np.int32),
            shrinkage=float(d.get("shrinkage", 1.0)),
        )


@dataclasses.dataclass
class GrowerConfig:
    num_leaves: int = 31
    max_depth: int = -1                 # -1 = unlimited (bounded by num_leaves)
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0


class _Node:
    __slots__ = ("id", "depth", "hist", "sums", "split")

    def __init__(self, id, depth, hist, sums, split):
        self.id = id
        self.depth = depth
        self.hist = hist
        self.sums = sums      # np [3]: grad, hess, count
        self.split = split    # SplitInfo (host numpy) or None


def grow_tree(bins_dev, grad, hess, row_mask, num_bins: int,
              config: GrowerConfig, bin_mapper, feature_mask=None,
              node_of_row=None) -> Tuple[Tree, np.ndarray]:
    """Grow one tree; returns (tree, leaf_node_of_row).

    ``bins_dev``: [N,F] int32 (device). ``grad``/``hess``: [N] f32 (device).
    ``row_mask``: [N] bool — bagging/goss row subset. ``feature_mask``: [F] bool.
    ``leaf_node_of_row`` maps every (masked-in) row to its final node id, so the
    booster can update scores with one gather instead of re-predicting.
    """
    import jax
    import jax.numpy as jnp

    from . import pallas_hist

    n, num_f = bins_dev.shape
    if node_of_row is None:
        node_of_row = jnp.zeros(n, dtype=jnp.int32)

    # routing for the per-split histogram, decided ONCE (invariant over the
    # loop): row-sharded inputs keep the multi-call path whose
    # compute_histogram dispatch runs the per-shard Pallas kernel + psum
    # (the in-jit XLA scatter both loses ~13x and can OOM at large N);
    # everything else takes the fused one-dispatch step.
    row_sharded = bool(pallas_hist._row_sharded_spec(bins_dev))
    use_mxu = pallas_hist.use_mxu_single_device(bins_dev)

    # growable node storage (host lists; frozen to arrays at the end)
    feature = [-1]
    threshold = [0.0]
    threshold_bin = [0]
    default_left = [True]
    left = [-1]
    right = [-1]
    value = [0.0]
    gains = [0.0]
    counts = [0]

    def eval_node(hist) -> Tuple[Optional[H.SplitInfo], np.ndarray]:
        split = H.find_best_split(
            hist, config.lambda_l1, config.lambda_l2,
            config.min_sum_hessian_in_leaf, config.min_data_in_leaf,
            feature_mask)
        return jax.device_get(split)

    root_hist = H.compute_histogram(bins_dev, grad, hess, row_mask, num_bins)
    root_sums = np.asarray(jax.device_get(
        H.total_sums(grad, hess, row_mask)), dtype=np.float64)
    counts[0] = int(root_sums[2])
    root_split = eval_node(root_hist)

    heap: List[Tuple[float, int, _Node]] = []
    tiebreak = 0

    def push(node: _Node):
        nonlocal tiebreak
        if node.split is not None and np.isfinite(node.split.gain) \
                and node.split.gain > config.min_gain_to_split:
            if config.max_depth > 0 and node.depth >= config.max_depth:
                return
            heapq.heappush(heap, (-float(node.split.gain), tiebreak, node))
            tiebreak += 1

    push(_Node(0, 0, root_hist, root_sums, root_split))
    n_leaves = 1

    while heap and n_leaves < config.num_leaves:
        _, _, node = heapq.heappop(heap)
        s = node.split
        f, t = int(s.feature), int(s.bin)
        lid, rid = len(feature), len(feature) + 1

        # record the split on the parent
        feature[node.id] = f
        threshold[node.id] = bin_mapper.bin_upper_value(f, t)
        threshold_bin[node.id] = t
        default_left[node.id] = bool(s.default_left)
        left[node.id] = lid
        right[node.id] = rid
        gains[node.id] = float(s.gain)
        value[node.id] = 0.0

        lsum = np.asarray(s.left_sum, dtype=np.float64)
        rsum = np.asarray(s.right_sum, dtype=np.float64)
        for sums in (lsum, rsum):
            feature.append(-1)
            threshold.append(0.0)
            threshold_bin.append(0)
            default_left.append(True)
            left.append(-1)
            right.append(-1)
            g_thr = np.sign(sums[0]) * max(abs(sums[0]) - config.lambda_l1, 0.0)
            value.append(float(-g_thr / (sums[1] + config.lambda_l2)))
            gains.append(0.0)
            counts.append(int(sums[2]))

        n_leaves += 1
        small_id, big_id = (lid, rid) if lsum[2] <= rsum[2] else (rid, lid)
        small_sums = lsum if small_id == lid else rsum
        big_sums = rsum if small_id == lid else lsum

        if row_sharded:
            # multi-call path: compute_histogram dispatches to the per-shard
            # Pallas kernel + psum (the fused jit's in-graph scatter would
            # lose ~13x and can OOM at large N — pallas_hist.py:30-35)
            node_of_row = H.partition_rows(
                bins_dev[:, f], node_of_row, node.id,
                np.int32(t), bool(s.default_left), np.int32(lid),
                np.int32(rid))
            small_mask = row_mask & (node_of_row == small_id)
            small_hist = H.compute_histogram(bins_dev, grad, hess,
                                             small_mask, num_bins)
            big_hist = H.subtract_histogram(node.hist, small_hist)
            split_small = eval_node(small_hist)
            split_big = eval_node(big_hist)
        else:
            # fused split iteration: route rows + scatter the smaller
            # child's histogram + sibling subtraction + both children's
            # split evals in ONE device dispatch (H.fused_split_step — the
            # loop used to be dispatch-bound at 4-5 round trips per split)
            node_of_row, small_hist, big_hist, split_small, split_big = \
                H.fused_split_step(
                    bins_dev, grad, hess, row_mask, node_of_row, node.hist,
                    np.int32(f), np.int32(t), bool(s.default_left),
                    np.int32(node.id), np.int32(lid), np.int32(rid),
                    np.int32(small_id),
                    config.lambda_l1, config.lambda_l2,
                    config.min_sum_hessian_in_leaf,
                    feature_mask if feature_mask is not None
                    else np.zeros(0, dtype=bool),
                    num_bins=num_bins,
                    min_data_in_leaf=config.min_data_in_leaf,
                    use_mxu=use_mxu,
                    has_feature_mask=feature_mask is not None)
            split_small, split_big = jax.device_get((split_small, split_big))

        for cid, chist, csplit, csums in (
                (small_id, small_hist, split_small, small_sums),
                (big_id, big_hist, split_big, big_sums)):
            if csums[2] >= 2 * config.min_data_in_leaf:
                push(_Node(cid, node.depth + 1, chist, csums, csplit))

    tree = Tree(
        feature=np.asarray(feature, dtype=np.int32),
        threshold=np.asarray(threshold, dtype=np.float64),
        threshold_bin=np.asarray(threshold_bin, dtype=np.int32),
        default_left=np.asarray(default_left, dtype=bool),
        left=np.asarray(left, dtype=np.int32),
        right=np.asarray(right, dtype=np.int32),
        value=np.asarray(value, dtype=np.float64),
        gain=np.asarray(gains, dtype=np.float32),
        count=np.asarray(counts, dtype=np.int32),
    )
    return tree, np.asarray(jax.device_get(node_of_row))


def predict_tree_binned(tree: Tree, bins: np.ndarray) -> np.ndarray:
    """Evaluate one tree on binned features (host reference path for tests)."""
    n = bins.shape[0]
    out = np.zeros(n, dtype=np.float64)
    node = np.zeros(n, dtype=np.int64)
    active = tree.feature[node] != -1
    while active.any():
        f = tree.feature[node[active]]
        b = bins[active, f]
        t = tree.threshold_bin[node[active]]
        go_left = np.where(b == 0, tree.default_left[node[active]], b <= t)
        node[active] = np.where(go_left, tree.left[node[active]],
                                tree.right[node[active]])
        active = tree.feature[node] != -1
    out = tree.value[node] * tree.shrinkage
    return out
