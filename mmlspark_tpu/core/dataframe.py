"""Partitioned columnar DataFrame — the distributed-data substrate.

The reference runs on Spark DataFrames; every stage is column-to-column over partitioned
data (SURVEY §1). This module provides the TPU-native substrate: a table is a list of
*partitions*, each partition a dict of equal-length numpy column arrays. Partitions map
onto input shards of a device mesh's data axis; numeric columns convert zero-copy into
device arrays, and the minibatcher (parallel/batching.py) handles static-shape padding.

Design choices vs Spark:
  - Eager, host-resident numpy columns (Arrow-compatible layout). Stage graphs in the
    reference are eager too (each transform materializes); laziness lives in XLA, where
    per-stage jitted fns fuse, not in the table layer.
  - ``map_partitions`` is the single distribution primitive, exactly like the reference's
    universal ``df.mapPartitions`` pattern (SURVEY §1 "key structural fact").
  - Ragged/object columns (strings, images, variable-length vectors) are object arrays;
    fixed-width numeric matrices stay dense 2-D.
"""

from __future__ import annotations

import math
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from .schema import ColType, Schema, infer_coltype

Partition = Dict[str, np.ndarray]


def _as_column(values: Any, n: Optional[int] = None) -> np.ndarray:
    """Normalize per-row values into a column array (object array when ragged)."""
    if isinstance(values, np.ndarray):
        if values.dtype.kind in ("U", "S"):
            return values.astype(object)
        return values
    values = list(values)
    if n is not None and len(values) != n:
        raise ValueError(f"Column length {len(values)} != partition length {n}")
    probe = next((v for v in values if v is not None), None)
    if values and isinstance(probe, (np.ndarray, dict, bytes, bytearray, str, list, tuple)):
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            out[i] = np.asarray(v) if isinstance(v, (list, tuple)) else v
        return out
    arr = np.asarray(values)
    if arr.dtype.kind in ("U", "S"):
        return arr.astype(object)
    return arr


def _partition_len(part: Partition) -> int:
    for v in part.values():
        return len(v)
    return 0


class DataFrame:
    """Immutable partitioned columnar table."""

    def __init__(self, partitions: List[Partition], schema: Optional[Schema] = None):
        self._partitions = [dict(p) for p in partitions]
        names: List[str] = list(self._partitions[0]) if self._partitions else (
            schema.names if schema else [])
        for p in self._partitions:
            if list(p) != names:
                raise ValueError(f"Inconsistent partition columns: {list(p)} vs {names}")
        if schema is None:
            types: Dict[str, str] = {}
            for name in names:
                col = next((p[name] for p in self._partitions if len(p[name])), None)
                types[name] = infer_coltype(col) if col is not None else ColType.OBJECT
            schema = Schema(types)
        self._schema = schema

    # -- construction ----------------------------------------------------
    @staticmethod
    def from_dict(data: Dict[str, Any], num_partitions: int = 1) -> "DataFrame":
        cols = {k: _as_column(v) for k, v in data.items()}
        lens = {k: len(v) for k, v in cols.items()}
        if len(set(lens.values())) > 1:
            raise ValueError(f"Column lengths differ: {lens}")
        df = DataFrame([cols])
        return df.repartition(num_partitions) if num_partitions > 1 else df

    @staticmethod
    def from_rows(rows: Sequence[Dict[str, Any]], num_partitions: int = 1) -> "DataFrame":
        if not rows:
            return DataFrame([])
        names = list(rows[0])
        return DataFrame.from_dict(
            {n: [r.get(n) for r in rows] for n in names}, num_partitions)

    @staticmethod
    def from_pandas(pdf, num_partitions: int = 1) -> "DataFrame":
        return DataFrame.from_dict(
            {c: pdf[c].to_numpy() for c in pdf.columns}, num_partitions)

    @staticmethod
    def empty(columns: Sequence[str]) -> "DataFrame":
        return DataFrame([{c: np.empty(0, dtype=object) for c in columns}])

    # -- basic properties ------------------------------------------------
    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def columns(self) -> List[str]:
        return list(self._schema.names)

    @property
    def num_partitions(self) -> int:
        return len(self._partitions)

    @property
    def partitions(self) -> List[Partition]:
        return self._partitions

    def count(self) -> int:
        return sum(_partition_len(p) for p in self._partitions)

    def __len__(self) -> int:
        return self.count()

    def is_empty(self) -> bool:
        return self.count() == 0

    # -- materialization -------------------------------------------------
    def collect(self) -> Partition:
        """Concatenate all partitions into one column dict."""
        if not self._partitions:
            return {}
        out: Partition = {}
        for name in self.columns:
            cols = [p[name] for p in self._partitions if len(p[name])]
            if not cols:
                out[name] = np.empty(0, dtype=object)
            elif any(c.dtype == object for c in cols):
                out[name] = np.concatenate([c.astype(object) for c in cols])
            else:
                out[name] = np.concatenate(cols)
        return out

    def column(self, name: str) -> np.ndarray:
        self._schema.require(name)
        return self.collect()[name]

    def rows(self) -> List[Dict[str, Any]]:
        data = self.collect()
        names = self.columns
        return [{n: data[n][i] for n in names} for i in range(len(self))]

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame({k: list(v) for k, v in self.collect().items()})

    def head(self, n: int = 5) -> List[Dict[str, Any]]:
        return self.limit(n).rows()

    def show(self, n: int = 10) -> None:
        for row in self.head(n):
            print({k: (f"<{type(v).__name__}>" if isinstance(v, (np.ndarray, bytes, dict))
                       else v) for k, v in row.items()})

    # -- columnar ops ----------------------------------------------------
    def select(self, *names: str) -> "DataFrame":
        names = tuple(n for group in names for n in
                      (group if isinstance(group, (list, tuple)) else [group]))
        for n in names:
            self._schema.require(n)
        parts = [{n: p[n] for n in names} for p in self._partitions]
        import copy as _c
        return DataFrame(parts, Schema({n: self._schema[n] for n in names},
                                       {n: _c.deepcopy(self._schema.metadata[n]) for n in names
                                        if n in self._schema.metadata}))

    def drop(self, *names: str) -> "DataFrame":
        keep = [c for c in self.columns if c not in names]
        return self.select(*keep)

    def with_column(self, name: str, fn_or_values: Union[Callable[[Partition], Any], Any]
                    ) -> "DataFrame":
        """Add/replace a column.

        ``fn_or_values`` is either a callable mapping a partition dict to per-row values,
        or a full-length array of values (split across partitions by position).
        """
        if callable(fn_or_values):
            parts = []
            for p in self._partitions:
                vals = _as_column(fn_or_values(p), _partition_len(p))
                q = dict(p)
                q[name] = vals
                parts.append(q)
        else:
            vals = _as_column(fn_or_values)
            if len(vals) != self.count():
                raise ValueError(f"Values length {len(vals)} != row count {self.count()}")
            parts, off = [], 0
            for p in self._partitions:
                n = _partition_len(p)
                q = dict(p)
                q[name] = vals[off:off + n]
                parts.append(q)
                off += n
        return self._carry_meta(DataFrame(parts))

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        self._schema.require(old)
        parts = [{(new if k == old else k): v for k, v in p.items()}
                 for p in self._partitions]
        return self._carry_meta(DataFrame(parts), rename={old: new})

    def map_rows(self, name: str, fn: Callable[[Dict[str, Any]], Any]) -> "DataFrame":
        """Add a column computed row-by-row (UDF parity). Prefer vectorized with_column."""
        def part_fn(p: Partition) -> List[Any]:
            n = _partition_len(p)
            return [fn({k: p[k][i] for k in p}) for i in range(n)]
        return self.with_column(name, part_fn)

    # -- row ops ---------------------------------------------------------
    def filter(self, predicate: Callable[[Partition], np.ndarray]) -> "DataFrame":
        """Keep rows where ``predicate(partition)`` (a boolean mask per partition) is True."""
        parts = []
        for p in self._partitions:
            mask = np.asarray(predicate(p), dtype=bool)
            parts.append({k: v[mask] for k, v in p.items()})
        return DataFrame(parts, self._schema.copy())

    def limit(self, n: int) -> "DataFrame":
        parts, remaining = [], n
        for p in self._partitions:
            if remaining <= 0:
                break
            take = min(remaining, _partition_len(p))
            parts.append({k: v[:take] for k, v in p.items()})
            remaining -= take
        return DataFrame(parts or [{c: np.empty(0, dtype=object) for c in self.columns}],
                         self._schema.copy())

    def union(self, other: "DataFrame") -> "DataFrame":
        if self.columns != other.columns:
            raise ValueError(f"Union columns mismatch: {self.columns} vs {other.columns}")
        return self._carry_meta(DataFrame(self._partitions + other._partitions))

    def sort(self, *by: str, ascending: bool = True) -> "DataFrame":
        data = self.collect()
        order = np.lexsort(tuple(data[c] for c in reversed(by)))
        if not ascending:
            order = order[::-1]
        return DataFrame([{k: v[order] for k, v in data.items()}], self._schema.copy())

    def sample(self, fraction: float, seed: int = 0) -> "DataFrame":
        rng = np.random.default_rng(seed)
        return self.filter(lambda p: rng.random(_partition_len(p)) < fraction)

    def random_split(self, weights: Sequence[float], seed: int = 0
                     ) -> List["DataFrame"]:
        total = float(sum(weights))
        bounds = np.cumsum([w / total for w in weights])
        rng = np.random.default_rng(seed)
        draws = [rng.random(_partition_len(p)) for p in self._partitions]
        out = []
        lo = 0.0
        for hi in bounds:
            parts = []
            for p, d in zip(self._partitions, draws):
                mask = (d >= lo) & (d < hi)
                parts.append({k: v[mask] for k, v in p.items()})
            out.append(DataFrame(parts, self._schema.copy()))
            lo = hi
        return out

    def dropna(self, subset: Optional[Sequence[str]] = None) -> "DataFrame":
        cols = list(subset) if subset else self.columns

        def mask(p: Partition) -> np.ndarray:
            n = _partition_len(p)
            keep = np.ones(n, dtype=bool)
            for c in cols:
                v = p[c]
                if v.dtype == object:
                    keep &= np.array([x is not None for x in v], dtype=bool)
                elif v.dtype.kind == "f":
                    keep &= (~np.isnan(v) if v.ndim == 1 else
                             ~np.isnan(v).any(axis=tuple(range(1, v.ndim))))
            return keep
        return self.filter(mask)

    # -- partitioning ----------------------------------------------------
    def repartition(self, n: int) -> "DataFrame":
        """Evenly re-split rows into ``n`` partitions (round-robin by contiguous chunks)."""
        if n <= 0:
            raise ValueError("num partitions must be positive")
        data = self.collect()
        total = len(next(iter(data.values()))) if data else 0
        bounds = [round(i * total / n) for i in range(n + 1)]
        parts = [{k: v[bounds[i]:bounds[i + 1]] for k, v in data.items()}
                 for i in range(n)]
        return DataFrame(parts, self._schema.copy())

    def coalesce(self, n: int) -> "DataFrame":
        """Reduce partition count without a full shuffle (merge adjacent partitions)."""
        if n >= self.num_partitions:
            return self
        groups = np.array_split(np.arange(self.num_partitions), n)
        parts = []
        for g in groups:
            merged: Partition = {}
            for name in self.columns:
                cols = [self._partitions[i][name] for i in g]
                obj = any(c.dtype == object for c in cols)
                merged[name] = (np.concatenate([c.astype(object) for c in cols])
                                if obj else np.concatenate(cols))
            parts.append(merged)
        return DataFrame(parts, self._schema.copy())

    def map_partitions(self, fn: Callable[[Partition], Partition],
                       retries: Optional[int] = None) -> "DataFrame":
        """THE distribution primitive (reference: df.mapPartitions everywhere, SURVEY §1).

        ``retries``: re-run ``fn`` on a fresh copy of a partition that raised —
        the recovery story Spark's task retry gave the reference for free
        (default spark.task.maxFailures=4 attempts; here default 0, or the
        MMLSPARK_TPU_TASK_RETRIES env). Each attempt receives a fresh dict, so
        column REBINDING never leaks between attempts (in-place ndarray writes
        would — treat partition arrays as immutable, as stage code here does);
        the last failure re-raises with the partition index attached.
        """
        if retries is None:
            retries = int(os.environ.get("MMLSPARK_TPU_TASK_RETRIES", "0"))
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        out = []
        for pi, p in enumerate(self._partitions):
            for attempt in range(retries + 1):
                try:
                    out.append(fn(dict(p)))
                    break
                except Exception as e:
                    if attempt >= retries:
                        # the ORIGINAL exception propagates (type, attrs,
                        # errno, args all intact); the partition context
                        # rides along as a note (add_note is 3.11+; on 3.10
                        # we drop the note rather than mask the exception)
                        note = (f"[map_partitions] partition {pi} failed "
                                f"after {attempt + 1} attempt(s)")
                        if hasattr(e, "add_note"):
                            e.add_note(note)
                        raise
        return self._carry_meta(DataFrame(out))

    def partition_by_key(self, key: str, n: Optional[int] = None) -> "DataFrame":
        """Hash-partition rows by a key column (shuffle)."""
        n = n or self.num_partitions
        data = self.collect()
        keys = data[key]
        hashes = np.array([_stable_hash(k) % n for k in keys])
        parts = [{c: v[hashes == i] for c, v in data.items()} for i in range(n)]
        return DataFrame(parts, self._schema)

    def cache(self) -> "DataFrame":
        return self  # eager: already materialized

    def _carry_meta(self, new_df: "DataFrame", rename: Optional[Dict[str, str]] = None
                    ) -> "DataFrame":
        """Copy per-column metadata (categorical levels etc.) onto a derived frame."""
        import copy as _c
        for name, meta in self._schema.metadata.items():
            tgt = (rename or {}).get(name, name)
            if meta and tgt in new_df._schema.types:
                new_df._schema.metadata[tgt] = _c.deepcopy(meta)
        return new_df

    # -- sugar (FluentAPI parity: core/spark/FluentAPI.scala:13-30) ------
    def ml_transform(self, stage) -> "DataFrame":
        return stage.transform(self)

    def ml_fit(self, estimator):
        return estimator.fit(self)

    def __repr__(self) -> str:
        return (f"DataFrame(rows={self.count()}, partitions={self.num_partitions}, "
                f"schema={self._schema.types})")


def _stable_hash(key: Any) -> int:
    """Process-stable key hash for shuffles (builtin hash() is salted per process)."""
    import zlib
    if isinstance(key, (int, np.integer)):
        return int(key) & 0x7FFFFFFF
    if isinstance(key, bytes):
        return zlib.crc32(key)
    return zlib.crc32(str(key).encode("utf-8"))
