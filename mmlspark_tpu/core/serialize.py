"""Stage persistence: params + complex values to a directory tree.

Re-design of the reference's persistence stack:
  - org/apache/spark/ml/Serializer.scala:1-203  — type-directed complex-param writers
  - org/apache/spark/ml/ComplexParamsSerializer.scala:1-181 — ComplexParamsWritable/Readable
  - core/serialize/ConstructorWriter.scala:23-60 — models serialized by constructor args

Layout (per stage):
    <path>/metadata.json            {"class": ..., "params": {...}, "timestamp": ...}
    <path>/complex/<param>/         one subdir per complex param, type-tagged payload
    <path>/stages/<i>_<name>/       nested stages (Pipeline / PipelineModel)

Complex payload types handled: numpy arrays (npz), jax arrays (npz via host copy),
pytrees of arrays (flattened npz + treedef json), DataFrames (npz of object columns via
pickle fallback), nested stages (recursive), plain picklable objects (pkl; last resort).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import time
from typing import Any, Dict, List

import numpy as np

from .dataframe import DataFrame
from .params import Params


def _is_jax_array(v: Any) -> bool:
    try:
        import jax
        return isinstance(v, jax.Array)
    except Exception:
        return False


def _write_json(path: str, obj: Any) -> None:
    with open(path, "w") as f:
        json.dump(obj, f, indent=1, default=_json_default)


def _json_default(o: Any):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"Not JSON serializable: {type(o)}")


def _save_value(value: Any, path: str) -> Dict[str, Any]:
    """Save one complex value under ``path``; return its type-tag manifest."""
    os.makedirs(path, exist_ok=True)
    from .pipeline import PipelineStage
    if isinstance(value, PipelineStage):
        save_stage(value, os.path.join(path, "stage"), overwrite=True)
        return {"kind": "stage"}
    if isinstance(value, DataFrame):
        with open(os.path.join(path, "df.pkl"), "wb") as f:
            pickle.dump(value.partitions, f)
        return {"kind": "dataframe"}
    if isinstance(value, np.ndarray) and value.dtype != object:
        np.savez(os.path.join(path, "array.npz"), arr=value)
        return {"kind": "ndarray"}
    if _is_jax_array(value):
        np.savez(os.path.join(path, "array.npz"), arr=np.asarray(value))
        return {"kind": "jax_array"}
    if isinstance(value, bytes):
        with open(os.path.join(path, "blob.bin"), "wb") as f:
            f.write(value)
        return {"kind": "bytes"}
    if isinstance(value, str):
        with open(os.path.join(path, "text.txt"), "w") as f:
            f.write(value)
        return {"kind": "str"}
    # pytree of arrays?
    try:
        import jax
        leaves, treedef = jax.tree.flatten(value)
        if leaves and all(isinstance(l, (np.ndarray,)) or _is_jax_array(l)
                          or isinstance(l, (int, float)) for l in leaves):
            np.savez(os.path.join(path, "tree.npz"),
                     **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)})
            with open(os.path.join(path, "treedef.pkl"), "wb") as f:
                pickle.dump(treedef, f)
            return {"kind": "pytree", "num_leaves": len(leaves)}
    except Exception:
        pass
    with open(os.path.join(path, "value.pkl"), "wb") as f:
        pickle.dump(value, f)
    return {"kind": "pickle"}


def _load_value(manifest: Dict[str, Any], path: str) -> Any:
    kind = manifest["kind"]
    if kind == "stage":
        return load_stage(os.path.join(path, "stage"))
    if kind == "dataframe":
        with open(os.path.join(path, "df.pkl"), "rb") as f:
            return DataFrame(pickle.load(f))
    if kind in ("ndarray", "jax_array"):
        with np.load(os.path.join(path, "array.npz")) as z:
            return z["arr"]
    if kind == "bytes":
        with open(os.path.join(path, "blob.bin"), "rb") as f:
            return f.read()
    if kind == "str":
        with open(os.path.join(path, "text.txt")) as f:
            return f.read()
    if kind == "pytree":
        with np.load(os.path.join(path, "tree.npz")) as z:
            leaves = [z[f"leaf_{i}"] for i in range(manifest["num_leaves"])]
        with open(os.path.join(path, "treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        import jax
        return jax.tree.unflatten(treedef, leaves)
    if kind == "pickle":
        with open(os.path.join(path, "value.pkl"), "rb") as f:
            return pickle.load(f)
    raise ValueError(f"Unknown complex value kind {kind!r}")


def save_stage(stage: "Params", path: str, overwrite: bool = True) -> None:
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        shutil.rmtree(path)
    os.makedirs(path)

    meta: Dict[str, Any] = {
        "class": f"{type(stage).__module__}.{type(stage).__name__}",
        "timestamp": time.time(),
        "params": stage.simple_params(),
        "complex": {},
    }
    complex_params = stage.complex_params()
    if complex_params:
        cdir = os.path.join(path, "complex")
        for name, value in complex_params.items():
            meta["complex"][name] = _save_value(value, os.path.join(cdir, name))

    # nested stage lists (Pipeline/PipelineModel constructor args — ConstructorWritable parity)
    stages = getattr(stage, "_stages", None)
    if stages is not None:
        meta["num_stages"] = len(stages)
        for i, s in enumerate(stages):
            save_stage(s, os.path.join(path, "stages", f"{i:03d}_{type(s).__name__}"))

    _write_json(os.path.join(path, "metadata.json"), meta)


def load_stage(path: str) -> Any:
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    from .pipeline import get_stage_class
    cls = get_stage_class(meta["class"])

    kwargs: Dict[str, Any] = {}
    if "num_stages" in meta:
        sdir = os.path.join(path, "stages")
        names = sorted(os.listdir(sdir)) if os.path.isdir(sdir) else []
        kwargs["stages"] = [load_stage(os.path.join(sdir, n)) for n in names]

    stage = cls(**kwargs) if kwargs else cls()
    for k, v in meta["params"].items():
        stage.set(k, v)
    for name, manifest in meta.get("complex", {}).items():
        stage.set(name, _load_value(manifest, os.path.join(path, "complex", name)))
    return stage
