"""Process-level JAX runtime knobs shared by the hot entry points.

The reference ships AOT-compiled native engines (LightGBM/VW/CNTK pay their
compile cost at build time); the XLA equivalent is the persistent compilation
cache — first-ever run of a program shape pays the compile, every later
process reuses it. Enabled lazily from the training/serving entry points so
importing the package never touches jax config.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("mmlspark_tpu.runtime")

_cache_enabled = False


def ensure_compile_cache() -> None:
    """Enable JAX's persistent compilation cache (idempotent).

    Opt out with MMLSPARK_TPU_COMPILE_CACHE=0; override the directory with
    MMLSPARK_TPU_COMPILE_CACHE_DIR (default ~/.cache/mmlspark_tpu/xla).
    """
    global _cache_enabled
    if _cache_enabled:
        return
    _cache_enabled = True
    if os.environ.get("MMLSPARK_TPU_COMPILE_CACHE", "1") in ("0", "false"):
        return
    path = os.environ.get("MMLSPARK_TPU_COMPILE_CACHE_DIR") or os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.join(os.path.expanduser("~"), ".cache")),
        "mmlspark_tpu", "xla")
    try:
        import jax

        if jax.default_backend() == "cpu":
            # CPU AOT cache entries warn (and can SIGILL) across machine
            # feature sets, and CPU compiles are cheap — accelerators only
            return
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # jax too old / read-only fs: non-fatal
        log.debug("compilation cache unavailable: %s", e)
